"""galaxylint framework: pluggable AST checkers, pragmas, committed baseline.

Flow: walk the package tree (tests/ and __pycache__/ excluded), parse each
file once, run every registered checker (per-file `check` plus cross-file
`finalize`), then suppress findings through two mechanisms:

- **pragmas** — `# galaxylint: disable=<rule>[,rule...] -- <justification>`
  on the offending line (or `disable-file=` on any line of the file).  A
  pragma WITHOUT a justification suppresses nothing and is itself a finding,
  and a pragma naming a rule that never fires there is a `pragma-unknown`
  finding: suppressions must say why, and must suppress something real.
- **baseline** — `devtools/baseline.json`, the committed grandfather list.
  Entries key on (rule, path, enclosing qualname, stripped line text) so they
  survive line drift; every entry carries a one-line `why`.  An entry that no
  longer matches anything is a `baseline-stale` finding, so the baseline can
  only shrink.

Exit status 0 means zero unsuppressed findings — the `make lint` CI gate.
"""

from __future__ import annotations

import argparse
import ast
import dataclasses
import json
import os
import re
import sys
from typing import Dict, Iterable, List, Optional, Tuple

PRAGMA_RE = re.compile(
    r"#\s*galaxylint:\s*(disable(?:-file)?)=([\w,\-]+)(?:\s*--\s*(\S.*))?")

SEVERITIES = ("error", "warn")


@dataclasses.dataclass
class Finding:
    rule: str
    path: str            # repo-relative, e.g. galaxysql_tpu/server/session.py
    line: int
    severity: str        # error | warn
    message: str
    qualname: str = ""   # enclosing Class.function scope
    line_text: str = ""  # stripped source line (the drift-stable baseline key)
    suppressed: str = "" # "" | "pragma" | "baseline"

    def key(self) -> Tuple[str, str, str, str]:
        return (self.rule, self.path, self.qualname, self.line_text)

    def render(self) -> str:
        sup = f" [suppressed:{self.suppressed}]" if self.suppressed else ""
        where = f" ({self.qualname})" if self.qualname else ""
        return (f"{self.path}:{self.line}: [{self.severity}] {self.rule}: "
                f"{self.message}{where}{sup}")


class Module:
    """One parsed source file plus its pragma table and scope map."""

    def __init__(self, relpath: str, src: str):
        self.relpath = relpath
        self.src = src
        self.lines = src.splitlines()
        self.tree = ast.parse(src)
        # line -> (set(rules), justification or None)
        self.pragmas: Dict[int, Tuple[set, Optional[str]]] = {}
        self.file_pragmas: Dict[str, Optional[str]] = {}
        for i, text in enumerate(self.lines, 1):
            m = PRAGMA_RE.search(text)
            if not m:
                continue
            kind, rules, why = m.group(1), m.group(2), m.group(3)
            ruleset = {r.strip() for r in rules.split(",") if r.strip()}
            if kind == "disable-file":
                for r in ruleset:
                    self.file_pragmas[r] = why
            else:
                self.pragmas[i] = (ruleset, why)
        self._scopes: List[Tuple[int, int, str]] = []
        self._index_scopes(self.tree, [])

    def _index_scopes(self, node: ast.AST, stack: List[str]):
        for child in ast.iter_child_nodes(node):
            if isinstance(child, (ast.FunctionDef, ast.AsyncFunctionDef,
                                  ast.ClassDef)):
                qual = stack + [child.name]
                end = getattr(child, "end_lineno", child.lineno) or child.lineno
                self._scopes.append((child.lineno, end, ".".join(qual)))
                self._index_scopes(child, qual)
            else:
                self._index_scopes(child, stack)

    def qualname_at(self, line: int) -> str:
        best = ""
        best_span = None
        for lo, hi, qual in self._scopes:
            if lo <= line <= hi:
                span = hi - lo
                if best_span is None or span < best_span:
                    best, best_span = qual, span
        return best

    def line_text(self, line: int) -> str:
        if 1 <= line <= len(self.lines):
            return self.lines[line - 1].strip()
        return ""


class Project:
    """Everything a cross-file `finalize` pass may need."""

    def __init__(self, root: str, modules: List[Module], test_text: str):
        self.root = root
        self.modules = modules
        self.test_text = test_text
        self.package_text = "\n".join(m.src for m in modules)


class Checker:
    """Base class: one lint pass, possibly emitting several rule names."""

    rules: Tuple[str, ...] = ()
    description = ""

    def check(self, mod: Module) -> Iterable[Finding]:
        return ()

    def finalize(self, project: Project) -> Iterable[Finding]:
        return ()

    def finding(self, mod: Module, line: int, message: str, rule: str = "",
                severity: str = "error") -> Finding:
        return Finding(rule or self.rules[0], mod.relpath, line, severity,
                       message, qualname=mod.qualname_at(line),
                       line_text=mod.line_text(line))


# -- tree walking -------------------------------------------------------------

def find_root(start: Optional[str] = None) -> str:
    """The repo root: the directory containing the galaxysql_tpu package."""
    here = start or os.path.dirname(os.path.dirname(os.path.dirname(
        os.path.abspath(__file__))))
    return here


def iter_sources(root: str, paths: Optional[List[str]] = None
                 ) -> List[Tuple[str, str]]:
    """(relpath, source) for every package file in scope.  tests/ and
    __pycache__/ never participate in tree walks."""
    out = []
    if paths:
        targets = [os.path.join(root, p) if not os.path.isabs(p) else p
                   for p in paths]
    else:
        targets = [os.path.join(root, "galaxysql_tpu")]
    for target in targets:
        if os.path.isfile(target):
            files = [target]
        else:
            files = []
            for dirpath, dirnames, filenames in os.walk(target):
                dirnames[:] = [d for d in dirnames
                               if d != "__pycache__" and d != "tests"]
                for fn in sorted(filenames):
                    if fn.endswith(".py"):
                        files.append(os.path.join(dirpath, fn))
        for f in sorted(files):
            rel = os.path.relpath(f, root)
            if "__pycache__" in rel or rel.startswith("tests" + os.sep):
                continue
            with open(f, "r", encoding="utf-8") as fh:
                out.append((rel.replace(os.sep, "/"), fh.read()))
    return out


def load_test_text(root: str) -> str:
    tdir = os.path.join(root, "tests")
    chunks = []
    if os.path.isdir(tdir):
        for fn in sorted(os.listdir(tdir)):
            if fn.endswith(".py"):
                with open(os.path.join(tdir, fn), "r", encoding="utf-8") as fh:
                    chunks.append(fh.read())
    return "\n".join(chunks)


# -- baseline -----------------------------------------------------------------

BASELINE_PATH = os.path.join(os.path.dirname(os.path.abspath(__file__)),
                             "baseline.json")


def load_baseline(path: str) -> List[dict]:
    if not os.path.exists(path):
        return []
    with open(path, "r", encoding="utf-8") as fh:
        data = json.load(fh)
    return list(data.get("entries", []))


def save_baseline(path: str, entries: List[dict]):
    entries = sorted(entries, key=lambda e: (e["path"], e["rule"],
                                             e["qualname"], e["line_text"]))
    with open(path, "w", encoding="utf-8") as fh:
        json.dump({"comment": "galaxylint grandfathered findings — every "
                              "entry carries a one-line justification; "
                              "stale entries fail the lint run",
                   "entries": entries}, fh, indent=1)
        fh.write("\n")


# -- the run ------------------------------------------------------------------

def run_checkers(modules: List[Module], project: Project,
                 checkers=None) -> List[Finding]:
    from galaxysql_tpu.devtools.checkers import ALL_CHECKERS
    findings: List[Finding] = []
    for ck in (checkers if checkers is not None else ALL_CHECKERS):
        for mod in modules:
            findings.extend(ck.check(mod))
        findings.extend(ck.finalize(project))
    return findings


def apply_pragmas(findings: List[Finding], modules: List[Module]
                  ) -> List[Finding]:
    """Suppress pragma'd findings.  Pragma hygiene is enforced
    unconditionally: a pragma without a justification is a pragma-justify
    finding, and a pragma naming a rule that never fires on its line (typo,
    or the finding was fixed) is a pragma-unknown finding — a suppression
    that suppresses nothing must not look like safety."""
    by_path = {m.relpath: m for m in modules}
    out: List[Finding] = []
    # pass 1: what actually fired, per (path, line) and per path
    fired_line: Dict[Tuple[str, int], set] = {}
    fired_file: Dict[str, set] = {}
    for f in findings:
        fired_line.setdefault((f.path, f.line), set()).add(f.rule)
        fired_file.setdefault(f.path, set()).add(f.rule)
    # pass 2: suppression
    for f in findings:
        mod = by_path.get(f.path)
        if mod is not None:
            if f.rule in mod.file_pragmas:
                if mod.file_pragmas[f.rule]:
                    f.suppressed = "pragma"
            else:
                pr = mod.pragmas.get(f.line)
                if pr is not None and f.rule in pr[0] and pr[1]:
                    f.suppressed = "pragma"
        out.append(f)
    # pass 3: pragma hygiene (independent of whether anything fired)
    for mod in modules:
        for line, (rules, why) in mod.pragmas.items():
            if not why:
                out.append(Finding(
                    "pragma-justify", mod.relpath, line, "error",
                    "suppression without a justification (use `# galaxylint: "
                    "disable=<rule> -- <one-line why>`)",
                    qualname=mod.qualname_at(line),
                    line_text=mod.line_text(line)))
            for r in rules - fired_line.get((mod.relpath, line), set()):
                out.append(Finding(
                    "pragma-unknown", mod.relpath, line, "error",
                    f"pragma disables {r!r} but no such finding fires on "
                    f"this line (typo, or the finding was fixed — delete "
                    f"the pragma)", qualname=mod.qualname_at(line),
                    line_text=mod.line_text(line)))
        for r, why in mod.file_pragmas.items():
            if not why:
                out.append(Finding(
                    "pragma-justify", mod.relpath, 1, "error",
                    f"file-level disable={r} has no justification "
                    f"(add `-- why`)"))
            if r not in fired_file.get(mod.relpath, set()):
                out.append(Finding(
                    "pragma-unknown", mod.relpath, 1, "error",
                    f"file-level pragma disables {r!r} but no such finding "
                    f"fires anywhere in this file — delete it"))
    return out


def apply_baseline(findings: List[Finding], entries: List[dict]
                   ) -> List[Finding]:
    index: Dict[Tuple[str, str, str, str], dict] = {}
    for e in entries:
        index[(e["rule"], e["path"], e.get("qualname", ""),
               e.get("line_text", ""))] = e
    matched = set()
    for f in findings:
        if f.suppressed:
            continue
        e = index.get(f.key())
        if e is not None:
            matched.add(id(e))
            if e.get("why"):
                f.suppressed = "baseline"
            # an unjustified baseline entry suppresses nothing
    out = list(findings)
    for e in entries:
        if not e.get("why"):
            out.append(Finding("baseline-justify", e["path"], 0, "error",
                               f"baseline entry for {e['rule']} has no "
                               f"justification", qualname=e.get("qualname", ""),
                               line_text=e.get("line_text", "")))
        elif id(e) not in matched:
            out.append(Finding("baseline-stale", e["path"], 0, "error",
                               f"baseline entry no longer matches anything "
                               f"(rule={e['rule']}, scope="
                               f"{e.get('qualname', '')!r}) — delete it",
                               qualname=e.get("qualname", ""),
                               line_text=e.get("line_text", "")))
    return out


def collect(root: Optional[str] = None, paths: Optional[List[str]] = None,
            baseline_path: Optional[str] = None, checkers=None
            ) -> List[Finding]:
    """Full pipeline: walk -> check -> pragmas -> baseline.  Returns EVERY
    finding; unsuppressed ones are the failures."""
    root = root or find_root()
    modules = []
    for rel, src in iter_sources(root, paths):
        modules.append(Module(rel, src))
    project = Project(root, modules, load_test_text(root))
    findings = run_checkers(modules, project, checkers)
    findings = apply_pragmas(findings, modules)
    entries = load_baseline(baseline_path or BASELINE_PATH)
    findings = apply_baseline(findings, entries)
    return findings


def lint_source(src: str, relpath: str = "galaxysql_tpu/fixture.py",
                checkers=None, test_text: str = "") -> List[Finding]:
    """Lint a source string (the test-fixture entry point).  Pragmas apply;
    no baseline."""
    mod = Module(relpath, src)
    project = Project("", [mod], test_text)
    findings = run_checkers([mod], project, checkers)
    return apply_pragmas(findings, [mod])


def main(argv: Optional[List[str]] = None) -> int:
    ap = argparse.ArgumentParser(
        prog="galaxylint",
        description="repo-specific concurrency/jit/typed-error/hygiene lint")
    ap.add_argument("paths", nargs="*", help="files or dirs (default: the "
                    "whole galaxysql_tpu package)")
    ap.add_argument("--baseline", default=None, help="baseline json path")
    ap.add_argument("--update-baseline", action="store_true",
                    help="add currently-unsuppressed findings to the baseline")
    ap.add_argument("--why", default="", help="justification recorded for "
                    "entries added by --update-baseline")
    ap.add_argument("--list-rules", action="store_true")
    ap.add_argument("--show-suppressed", action="store_true")
    args = ap.parse_args(argv)

    from galaxysql_tpu.devtools.checkers import ALL_CHECKERS
    if args.list_rules:
        for ck in ALL_CHECKERS:
            for r in ck.rules:
                print(f"{r}: {ck.description}")
        print("pragma-justify: suppression pragmas must carry a one-line why")
        print("pragma-unknown: a pragma must suppress a finding that "
              "actually fires there")
        print("baseline-justify/baseline-stale: baseline entries must be "
              "justified and must still match")
        return 0

    baseline_path = args.baseline or BASELINE_PATH
    findings = collect(paths=args.paths or None, baseline_path=baseline_path)
    open_findings = [f for f in findings if not f.suppressed]

    if args.update_baseline:
        if not args.why:
            print("--update-baseline requires --why (every baseline entry "
                  "carries a justification)", file=sys.stderr)
            return 2
        entries = load_baseline(baseline_path)
        known = {(e["rule"], e["path"], e.get("qualname", ""),
                  e.get("line_text", "")) for e in entries}
        added = 0
        for f in open_findings:
            if f.rule in ("baseline-stale", "baseline-justify",
                          "pragma-justify"):
                continue  # meta-findings are never grandfathered
            if f.key() in known:
                continue
            known.add(f.key())
            entries.append({"rule": f.rule, "path": f.path,
                            "qualname": f.qualname, "line_text": f.line_text,
                            "why": args.why})
            added += 1
        save_baseline(baseline_path, entries)
        print(f"baseline: {added} entr{'y' if added == 1 else 'ies'} added")
        return 0

    shown = findings if args.show_suppressed else open_findings
    for f in sorted(shown, key=lambda f: (f.path, f.line, f.rule)):
        print(f.render())
    n_sup = sum(1 for f in findings if f.suppressed)
    print(f"galaxylint: {len(open_findings)} finding(s), "
          f"{n_sup} suppressed (pragma/baseline)")
    return 1 if open_findings else 0


if __name__ == "__main__":
    sys.exit(main())
