"""dead-failpoint / metric-orphan: chaos + observability hygiene.

Cross-file passes (they run in `finalize`, over the whole project):

- **dead-failpoint**: an `FP_*` key defined in the package but never armed
  by any test is dead chaos coverage — the failure path it guards is never
  exercised, which is exactly how exactly-once/recovery bugs hide.  Tests
  count as coverage by NAME (symbol or string literal) anywhere under
  tests/.
- **metric-orphan**: a module-level process-shared metric constant
  (`NAME = Counter/Gauge/Histogram(...)`) must be BOTH updated somewhere
  (`.inc/.observe/.set/.dec` — otherwise it's a dead gauge lying on every
  dashboard) and surfaced (referenced by a module that adopts metrics into
  the instance registry — otherwise it's invisible to SHOW METRICS,
  information_schema.metrics, and Prometheus).  Registry-created metrics
  (`registry.counter(...)`) auto-surface and are exempt.
"""

from __future__ import annotations

import ast
import re
from typing import List

from galaxysql_tpu.devtools.lint import Checker, Finding, Project

_FP_NAME = re.compile(r"^FP_[A-Z0-9_]+$")
_METRIC_CTORS = ("Counter", "Gauge", "Histogram")


class HygieneChecker(Checker):
    rules = ("dead-failpoint", "metric-orphan")
    description = ("FP_* keys never armed by any test; process-shared "
                   "metrics never updated or never adopted/surfaced")

    def finalize(self, project: Project):
        findings: List[Finding] = []
        findings.extend(self._dead_failpoints(project))
        findings.extend(self._metric_orphans(project))
        return findings

    def _dead_failpoints(self, project: Project):
        findings = []
        for mod in project.modules:
            for node in ast.iter_child_nodes(mod.tree):
                if not isinstance(node, ast.Assign):
                    continue
                for tgt in node.targets:
                    if isinstance(tgt, ast.Name) and _FP_NAME.match(tgt.id) \
                            and isinstance(node.value, ast.Constant) \
                            and isinstance(node.value.value, str):
                        # word-boundary match: FP_RPC_DELAY must not count
                        # as covered because tests arm FP_RPC_DELAY_MS
                        if not re.search(rf"\b{tgt.id}\b",
                                         project.test_text):
                            findings.append(self.finding(
                                mod, node.lineno,
                                f"fail point {tgt.id} is never armed by any "
                                f"test: dead chaos coverage — the failure "
                                f"path it guards is never exercised",
                                rule="dead-failpoint"))
        return findings

    def _metric_orphans(self, project: Project):
        findings = []
        # modules that adopt process-shared metrics into a registry
        adopters = [m for m in project.modules if ".adopt(" in m.src]
        for mod in project.modules:
            for node in ast.iter_child_nodes(mod.tree):
                if not isinstance(node, ast.Assign) or \
                        not isinstance(node.value, ast.Call):
                    continue
                fn = node.value.func
                ctor = fn.id if isinstance(fn, ast.Name) else (
                    fn.attr if isinstance(fn, ast.Attribute) else "")
                if ctor not in _METRIC_CTORS:
                    continue
                for tgt in node.targets:
                    if not isinstance(tgt, ast.Name):
                        continue
                    name = tgt.id
                    updated = re.search(
                        rf"\b{name}\.(inc|observe|observe_many|set|dec)\b",
                        project.package_text)
                    if not updated:
                        findings.append(self.finding(
                            mod, node.lineno,
                            f"metric {name} is registered but never "
                            f"updated anywhere — a dead metric lying on "
                            f"every dashboard", rule="metric-orphan"))
                    surfaced = any(re.search(rf"\b{name}\b", a.src)
                                   for a in adopters if a is not mod) or \
                        re.search(rf"adopt\(\s*{name}\b", mod.src)
                    if not surfaced:
                        findings.append(self.finding(
                            mod, node.lineno,
                            f"process-shared metric {name} is never adopted "
                            f"into an instance registry — invisible to SHOW "
                            f"METRICS / information_schema.metrics / "
                            f"Prometheus", rule="metric-orphan"))
        return findings
