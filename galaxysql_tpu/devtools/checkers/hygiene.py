"""dead-failpoint / metric-orphan: chaos + observability hygiene.

Cross-file passes (they run in `finalize`, over the whole project):

- **dead-failpoint**: an `FP_*` key defined in the package but never armed
  by any test is dead chaos coverage — the failure path it guards is never
  exercised, which is exactly how exactly-once/recovery bugs hide.  Tests
  count as coverage by NAME (symbol or string literal) anywhere under
  tests/.
- **metric-orphan**: a module-level process-shared metric constant
  (`NAME = Counter/Gauge/Histogram(...)`) must be BOTH updated somewhere
  (`.inc/.observe/.set/.dec` — otherwise it's a dead gauge lying on every
  dashboard) and surfaced (referenced by a module that adopts metrics into
  the instance registry — otherwise it's invisible to SHOW METRICS,
  information_schema.metrics, and Prometheus).  Registry-created metrics
  (`registry.counter(...)`) auto-surface and are exempt.
- **event-untested**: every typed journal event kind published anywhere in
  the package (a string-literal first argument to `publish(...)`) must be
  named by at least one test — an alert nobody has ever armed or asserted
  is an alert that silently rots (the SLO plane's slo_burn/metric_anomaly
  events are load-bearing precisely because tests drive them).
- **histogram-unsampled**: every process-shared histogram adopted into the
  registry must be named by a test so its expansion (`<name>_p99` etc.)
  provably appears in a metric-history sample — otherwise the SLO plane's
  windows can lose an input without any test noticing.
- **event-uncorrelated**: publish sites for flight-recorder TRIGGER kinds
  (slo_burn, plan_regression, breaker_open, admission_reject,
  columnar_tail_failed, metric_anomaly) must pass a correlation key —
  `trace_id=` or `digest=` — or carry a justified pragma: an incident
  bundle captured off an uncorrelated trigger cannot implicate the
  statement that caused it, so the recorder degrades to guesswork.
"""

from __future__ import annotations

import ast
import re
from typing import List

from galaxysql_tpu.devtools.lint import Checker, Finding, Project

_FP_NAME = re.compile(r"^FP_[A-Z0-9_]+$")
_METRIC_CTORS = ("Counter", "Gauge", "Histogram")


class HygieneChecker(Checker):
    rules = ("dead-failpoint", "metric-orphan", "event-untested",
             "histogram-unsampled", "event-uncorrelated")
    description = ("FP_* keys never armed by any test; process-shared "
                   "metrics never updated or never adopted/surfaced; "
                   "journal event kinds / adopted histograms never "
                   "exercised by any test; trigger-kind events published "
                   "without a trace_id/digest correlation key")

    # event kinds the flight recorder treats as incident triggers
    # (server/flight_recorder.py EVENT_TRIGGERS + the reject-storm kind)
    TRIGGER_KINDS = frozenset({
        "slo_burn", "plan_regression", "breaker_open", "admission_reject",
        "columnar_tail_failed", "metric_anomaly"})

    def finalize(self, project: Project):
        findings: List[Finding] = []
        findings.extend(self._dead_failpoints(project))
        findings.extend(self._metric_orphans(project))
        findings.extend(self._untested_events(project))
        findings.extend(self._unsampled_histograms(project))
        findings.extend(self._uncorrelated_events(project))
        return findings

    def _dead_failpoints(self, project: Project):
        findings = []
        for mod in project.modules:
            for node in ast.iter_child_nodes(mod.tree):
                if not isinstance(node, ast.Assign):
                    continue
                for tgt in node.targets:
                    if isinstance(tgt, ast.Name) and _FP_NAME.match(tgt.id) \
                            and isinstance(node.value, ast.Constant) \
                            and isinstance(node.value.value, str):
                        # word-boundary match: FP_RPC_DELAY must not count
                        # as covered because tests arm FP_RPC_DELAY_MS
                        if not re.search(rf"\b{tgt.id}\b",
                                         project.test_text):
                            findings.append(self.finding(
                                mod, node.lineno,
                                f"fail point {tgt.id} is never armed by any "
                                f"test: dead chaos coverage — the failure "
                                f"path it guards is never exercised",
                                rule="dead-failpoint"))
        return findings

    def _metric_orphans(self, project: Project):
        findings = []
        # modules that adopt process-shared metrics into a registry
        adopters = [m for m in project.modules if ".adopt(" in m.src]
        for mod in project.modules:
            for node in ast.iter_child_nodes(mod.tree):
                if not isinstance(node, ast.Assign) or \
                        not isinstance(node.value, ast.Call):
                    continue
                fn = node.value.func
                ctor = fn.id if isinstance(fn, ast.Name) else (
                    fn.attr if isinstance(fn, ast.Attribute) else "")
                if ctor not in _METRIC_CTORS:
                    continue
                for tgt in node.targets:
                    if not isinstance(tgt, ast.Name):
                        continue
                    name = tgt.id
                    updated = re.search(
                        rf"\b{name}\.(inc|observe|observe_many|set|dec)\b",
                        project.package_text)
                    if not updated:
                        findings.append(self.finding(
                            mod, node.lineno,
                            f"metric {name} is registered but never "
                            f"updated anywhere — a dead metric lying on "
                            f"every dashboard", rule="metric-orphan"))
                    surfaced = any(re.search(rf"\b{name}\b", a.src)
                                   for a in adopters if a is not mod) or \
                        re.search(rf"adopt\(\s*{name}\b", mod.src)
                    if not surfaced:
                        findings.append(self.finding(
                            mod, node.lineno,
                            f"process-shared metric {name} is never adopted "
                            f"into an instance registry — invisible to SHOW "
                            f"METRICS / information_schema.metrics / "
                            f"Prometheus", rule="metric-orphan"))
        return findings

    def _untested_events(self, project: Project):
        """Every string-literal kind passed to `publish(...)` anywhere in
        the package must appear (word-boundary) somewhere under tests/.
        Variable kinds can't be checked statically and are skipped."""
        findings = []
        seen = set()  # report each kind once, at its first publish site
        for mod in project.modules:
            for node in ast.walk(mod.tree):
                if not isinstance(node, ast.Call) or not node.args:
                    continue
                fn = node.func
                fname = fn.id if isinstance(fn, ast.Name) else (
                    fn.attr if isinstance(fn, ast.Attribute) else "")
                if fname != "publish":
                    continue
                arg = node.args[0]
                if not (isinstance(arg, ast.Constant)
                        and isinstance(arg.value, str)):
                    continue
                kind = arg.value
                if kind in seen:
                    continue
                seen.add(kind)
                if not re.search(rf"\b{re.escape(kind)}\b",
                                 project.test_text):
                    findings.append(self.finding(
                        mod, node.lineno,
                        f"journal event kind '{kind}' is published here but "
                        f"never named by any test — an alert nobody has "
                        f"armed or asserted silently rots",
                        rule="event-untested"))
        return findings

    def _uncorrelated_events(self, project: Project):
        """Every publish site whose string-literal kind is a flight-recorder
        TRIGGER must pass `trace_id=` or `digest=` (the incident bundle's
        implication keys).  Sites with genuinely no query context
        (background loops) carry a justified pragma instead.  Unlike
        event-untested this reports every SITE, not each kind once — each
        uncorrelated publish degrades a different trigger path."""
        findings = []
        for mod in project.modules:
            for node in ast.walk(mod.tree):
                if not isinstance(node, ast.Call) or not node.args:
                    continue
                fn = node.func
                fname = fn.id if isinstance(fn, ast.Name) else (
                    fn.attr if isinstance(fn, ast.Attribute) else "")
                if fname != "publish":
                    continue
                arg = node.args[0]
                if not (isinstance(arg, ast.Constant)
                        and isinstance(arg.value, str)) or \
                        arg.value not in self.TRIGGER_KINDS:
                    continue
                keys = {kw.arg for kw in node.keywords if kw.arg}
                has_splat = any(kw.arg is None for kw in node.keywords)
                if keys & {"trace_id", "digest"} or has_splat:
                    continue  # **kwargs splats can't be checked statically
                findings.append(self.finding(
                    mod, node.lineno,
                    f"trigger-kind event '{arg.value}' is published without "
                    f"a trace_id/digest correlation key — the flight "
                    f"recorder cannot implicate the statement behind this "
                    f"incident", rule="event-uncorrelated"))
        return findings

    def _unsampled_histograms(self, project: Project):
        """Every module-level `NAME = Histogram("metric", ...)` must have
        its METRIC NAME (the ctor's string argument, not the Python
        symbol) appear in tests/ — the SLO-plane suite asserts each one's
        `<name>_p99` expansion lands in a history sample."""
        findings = []
        for mod in project.modules:
            for node in ast.iter_child_nodes(mod.tree):
                if not isinstance(node, ast.Assign) or \
                        not isinstance(node.value, ast.Call):
                    continue
                fn = node.value.func
                ctor = fn.id if isinstance(fn, ast.Name) else (
                    fn.attr if isinstance(fn, ast.Attribute) else "")
                if ctor != "Histogram" or not node.value.args:
                    continue
                arg = node.value.args[0]
                if not (isinstance(arg, ast.Constant)
                        and isinstance(arg.value, str)):
                    continue
                metric = arg.value
                if not re.search(rf"\b{re.escape(metric)}\b",
                                 project.test_text):
                    findings.append(self.finding(
                        mod, node.lineno,
                        f"histogram '{metric}' is never named by any test — "
                        f"nothing proves its quantile expansion reaches a "
                        f"metric-history sample",
                        rule="histogram-unsampled"))
        return findings
