"""jit-raw / pallas-raw / jit-device-sync: the `global_jit` discipline.

Every perf PR re-proves the same invariants with dispatch-count guards;
these passes mechanize them:

- **jit-raw**: a bare `jax.jit(...)` call OUTSIDE a builder passed to
  `global_jit` compiles a program that is invisible to the process-wide LRU
  (no cross-execution reuse, no compile-span accounting, no retrace
  counting) — a plan-cache hit would still pay a full retrace.  A `jax.jit`
  is legal only inside a function whose name is passed to `global_jit` in
  the same module (the `def build(): ... return jax.jit(run)` idiom) or in a
  lambda written directly into a `global_jit(...)` argument.
- **pallas-raw**: `pl.pallas_call(...)` constructs a kernel program with the
  exact same escape hazard — same rule shape: legal only inside a
  `global_jit` builder, so Pallas kernels are cached per static shape and
  counted like every other program (kernels/pallas_join.py idiom).
- **jit-device-sync**: `.item()` / `.block_until_ready()` on the default
  query path forces a host<->device sync per call.  Flagged in the hot-path
  layers (exec/, kernels/, parallel/, chunk/, server/, storage/) unless the
  enclosing scope is profiling/bench/EXPLAIN machinery (allowlisted by
  qualname pattern), where the sync is the point.
"""

from __future__ import annotations

import ast
import re
from typing import List, Set

from galaxysql_tpu.devtools.lint import Checker, Module

HOT_PREFIXES = ("galaxysql_tpu/exec/", "galaxysql_tpu/kernels/",
                "galaxysql_tpu/parallel/", "galaxysql_tpu/chunk/",
                "galaxysql_tpu/server/", "galaxysql_tpu/storage/")

# scopes where a device sync is the feature, not a leak: profiling, EXPLAIN
# ANALYZE, benchmarks, tracing/telemetry observation hooks
ALLOW_QUAL = re.compile(
    r"explain|profil|bench|analyz|stats|trace|observe|debug|telemetry",
    re.IGNORECASE)


def _is_jax_jit(call: ast.Call) -> bool:
    f = call.func
    return (isinstance(f, ast.Attribute) and f.attr == "jit"
            and isinstance(f.value, ast.Name) and f.value.id == "jax")


def _is_pallas_call(call: ast.Call) -> bool:
    f = call.func
    return (isinstance(f, ast.Attribute) and f.attr == "pallas_call"
            and isinstance(f.value, ast.Name) and f.value.id == "pl")


def _is_global_jit(call: ast.Call) -> bool:
    f = call.func
    if isinstance(f, ast.Name):
        return f.id == "global_jit"
    return isinstance(f, ast.Attribute) and f.attr == "global_jit"


class JitDisciplineChecker(Checker):
    rules = ("jit-raw", "pallas-raw", "jit-device-sync")
    description = ("raw jax.jit / pl.pallas_call outside a global_jit "
                   "builder closure; device-sync primitives on the hot path "
                   "outside profiling/bench scopes")

    def check(self, mod: Module):
        findings = []
        findings.extend(self._check_raw_jit(mod))
        if mod.relpath.startswith(HOT_PREFIXES):
            findings.extend(self._check_device_sync(mod))
        return findings

    # -- jit-raw / pallas-raw ------------------------------------------------

    def _check_raw_jit(self, mod: Module):
        builder_names: Set[str] = set()
        allowed_lambdas: Set[int] = set()
        for node in ast.walk(mod.tree):
            if isinstance(node, ast.Call) and _is_global_jit(node):
                args = list(node.args) + [kw.value for kw in node.keywords]
                for a in args:
                    if isinstance(a, ast.Name):
                        builder_names.add(a.id)
                for a in args:
                    for sub in ast.walk(a):
                        if isinstance(sub, ast.Lambda):
                            allowed_lambdas.add(id(sub))

        findings = []

        def in_builder(stack: List[ast.AST]) -> bool:
            for s in stack:
                if isinstance(s, (ast.FunctionDef,
                                  ast.AsyncFunctionDef)) and \
                        s.name in builder_names:
                    return True
                if isinstance(s, ast.Lambda) and id(s) in allowed_lambdas:
                    return True
            return False

        def walk(node: ast.AST, stack: List[ast.AST]):
            for child in ast.iter_child_nodes(node):
                if isinstance(child, ast.Call) and _is_jax_jit(child) \
                        and not in_builder(stack):
                    findings.append(self.finding(
                        mod, child.lineno,
                        "raw jax.jit outside a global_jit builder "
                        "closure: the program escapes the process-wide "
                        "LRU, retrace accounting, and compile spans",
                        rule="jit-raw"))
                if isinstance(child, ast.Call) and _is_pallas_call(child) \
                        and not in_builder(stack):
                    findings.append(self.finding(
                        mod, child.lineno,
                        "raw pl.pallas_call outside a global_jit builder "
                        "closure: the kernel program escapes the "
                        "process-wide LRU, retrace accounting, and compile "
                        "spans",
                        rule="pallas-raw"))
                walk(child, stack + [child])

        walk(mod.tree, [])
        return findings

    # -- jit-device-sync -----------------------------------------------------

    def _check_device_sync(self, mod: Module):
        findings = []
        for node in ast.walk(mod.tree):
            if not isinstance(node, ast.Call):
                continue
            f = node.func
            if not isinstance(f, ast.Attribute):
                continue
            if f.attr not in ("item", "block_until_ready"):
                continue
            qual = mod.qualname_at(node.lineno)
            if ALLOW_QUAL.search(qual or ""):
                continue
            findings.append(self.finding(
                mod, node.lineno,
                f".{f.attr}() forces a host<->device sync; on the default "
                f"query path every call stalls the dispatch pipeline "
                f"(profiling/bench scopes are allowlisted by name)",
                rule="jit-device-sync", severity="warn"))
        return findings
