"""Checker registry: the four repo-specific galaxylint passes.

Adding a pass = subclass `devtools.lint.Checker`, implement `check`
(per-file) and/or `finalize` (cross-file), list it here.
"""

from galaxysql_tpu.devtools.checkers.lock_order import LockOrderChecker
from galaxysql_tpu.devtools.checkers.jit_discipline import JitDisciplineChecker
from galaxysql_tpu.devtools.checkers.typed_errors import TypedErrorChecker
from galaxysql_tpu.devtools.checkers.hygiene import HygieneChecker

ALL_CHECKERS = [
    LockOrderChecker(),
    JitDisciplineChecker(),
    TypedErrorChecker(),
    HygieneChecker(),
]
