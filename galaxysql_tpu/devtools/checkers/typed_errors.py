"""swallow / untyped-raise: the typed-error wire contract.

The engine's error taxonomy (`utils/errors`, PR 8/12) guarantees that every
failure a client or operator sees is TYPED — carries (errno, sqlstate),
survives the wire, rides error spans, counts in metrics.  Two ways code
quietly breaks that contract on the wire/exec ramps (net/, server/, txn/):

- **swallow**: an `except Exception` (or bare `except:`) whose handler does
  NOTHING — only pass/continue/constant-return/constant-assign, never
  referencing the caught exception, no re-raise, no journal event, no typed
  translation.  The failure evaporates: no event, no counter, no trace.
- **untyped-raise**: `raise Exception/ValueError/RuntimeError(...)` where
  the `utils/errors` taxonomy is the contract — the wire layer renders
  errno 1105 "unknown error" and the client learns nothing.

Handlers that DO something (fall back with a recorded value, publish an
event, translate, re-raise) are not findings.  Deliberate silent drops
(close-path socket errors) and intra-module control-flow raises (the group
fallback RuntimeErrors the flush catches) carry pragmas with justification.
"""

from __future__ import annotations

import ast
from typing import List

from galaxysql_tpu.devtools.lint import Checker, Module

RAMP_PREFIXES = ("galaxysql_tpu/net/", "galaxysql_tpu/server/",
                 "galaxysql_tpu/txn/")

UNTYPED = {"Exception", "ValueError", "RuntimeError", "TypeError",
           "KeyError", "OSError", "IOError"}


def _is_broad(handler: ast.ExceptHandler) -> bool:
    t = handler.type
    if t is None:
        return True  # bare except:
    if isinstance(t, ast.Name):
        return t.id in ("Exception", "BaseException")
    return False


def _names_in(node: ast.AST) -> set:
    return {n.id for n in ast.walk(node) if isinstance(n, ast.Name)}


def _is_trivial_stmt(stmt: ast.stmt, exc_name: str) -> bool:
    """True when the statement neither records, translates, re-raises nor
    even references the caught exception."""
    if isinstance(stmt, (ast.Pass, ast.Continue, ast.Break)):
        return True
    if isinstance(stmt, ast.Return):
        v = stmt.value
        if v is None or isinstance(v, ast.Constant):
            return True
        if isinstance(v, (ast.List, ast.Tuple, ast.Dict)) and \
                not any(isinstance(x, ast.Call) for x in ast.walk(v)) and \
                (not exc_name or exc_name not in _names_in(v)):
            return True
        return False
    if isinstance(stmt, (ast.Assign, ast.AnnAssign, ast.AugAssign)):
        val = getattr(stmt, "value", None)
        if val is None:
            return True
        if any(isinstance(x, ast.Call) for x in ast.walk(val)):
            return False
        if exc_name and exc_name in _names_in(val):
            return False
        return True
    return False


class TypedErrorChecker(Checker):
    rules = ("swallow", "untyped-raise")
    description = ("silent except-Exception swallows and untyped raises on "
                   "the wire/exec ramps (utils/errors is the contract)")

    def check(self, mod: Module):
        if not mod.relpath.startswith(RAMP_PREFIXES):
            return []
        findings: List[ast.AST] = []
        for node in ast.walk(mod.tree):
            if isinstance(node, ast.ExceptHandler) and _is_broad(node):
                exc_name = node.name or ""
                if all(_is_trivial_stmt(s, exc_name) for s in node.body):
                    findings.append(self.finding(
                        mod, node.lineno,
                        "except Exception swallows silently: no re-raise, "
                        "no journal event, no typed translation — the "
                        "failure leaves no trace anywhere",
                        rule="swallow"))
            elif isinstance(node, ast.Raise):
                exc = node.exc
                if isinstance(exc, ast.Call) and \
                        isinstance(exc.func, ast.Name) and \
                        exc.func.id in UNTYPED:
                    findings.append(self.finding(
                        mod, node.lineno,
                        f"raise {exc.func.id} on a wire/exec ramp: the "
                        f"utils/errors taxonomy is the contract (clients "
                        f"see errno 1105 'unknown error' otherwise)",
                        rule="untyped-raise"))
        return findings
