"""lock-order / lock-blocking: the static half of the lockdep story.

Extracts the lock-nesting graph from `with <lock>:` blocks across the
concurrency-bearing layers (storage/, server/, txn/, exec/, meta/) with one
level of call-graph propagation (a call made while holding L, to a
same-module function that itself acquires M, contributes the edge L -> M),
then checks:

- **lock-order**: edges that invert the canonical rank order
  `append_lock/columnar (0) -> partition (1) -> store/metadb (2)`, or nest
  two locks of the same unordered class (two partition locks held together
  have no declared intra-class order).  The columnar tailer lock
  (ColumnarReplicaManager._lock) ranks with append_lock: seeding snapshots
  partitions and persistence writes metadb while holding it, never the
  reverse — the query path reads tier snapshots lock-free.
- **lock-blocking**: blocking operations — worker RPC (`.request`), metadb
  IO, `time.sleep`, device syncs (`.block_until_ready()`, `.item()`) —
  executed while a HOT lock (append_lock, partition) is held.  Hot locks sit
  on the DML flush path; anything slow under them convoys every writer.

Lock classes are inferred from the `with` expression: the attribute name and
its receiver (`store.append_lock` -> append_lock, `p.lock` / `self.lock`
inside class Partition -> partition, MetaDb's `self._lock` -> metadb).
Unrecognized `*lock*` attributes become class-scoped nodes (`Owner._lock`) —
they participate in the graph but carry no rank.  Condition variables are
excluded: `wait()` releases, so nesting proves nothing.
"""

from __future__ import annotations

import ast
from typing import Dict, List, Optional, Set, Tuple

from galaxysql_tpu.devtools.lint import Checker, Finding, Module

SCOPE_PREFIXES = ("galaxysql_tpu/storage/", "galaxysql_tpu/server/",
                  "galaxysql_tpu/txn/", "galaxysql_tpu/exec/",
                  "galaxysql_tpu/meta/")

RANKS = {"append_lock": 0, "columnar": 0, "partition": 1, "store": 2,
         "metadb": 2}
HOT = ("append_lock", "partition")

_PARTITION_RECVS = {"p", "part", "partition", "pt"}
_METADB_RECVS = {"metadb", "db"}


def _recv_chain(expr: ast.AST) -> str:
    if isinstance(expr, ast.Name):
        return expr.id
    if isinstance(expr, ast.Attribute):
        base = _recv_chain(expr.value)
        return f"{base}.{expr.attr}" if base else expr.attr
    if isinstance(expr, ast.Call):
        return _recv_chain(expr.func)
    return ""


def lock_name(expr: ast.AST, class_name: str) -> Optional[str]:
    """Canonical lock class for a with-item expression, or None when the
    expression is not a lock (spans, errstate, device contexts...)."""
    if isinstance(expr, ast.Attribute):
        attr = expr.attr
        recv = _recv_chain(expr.value)
    elif isinstance(expr, ast.Name):
        attr, recv = expr.id, ""
    else:
        return None
    low = attr.lower()
    if "cond" in low:
        return None  # condition vars: wait() releases, nesting proves nothing
    if "lock" not in low and low not in ("_mu", "mu", "_bk_lock"):
        return None
    if attr == "append_lock":
        return "append_lock"
    base = recv.split(".")[-1] if recv else ""
    if attr == "lock":
        if base in _PARTITION_RECVS:
            return "partition"
        if base == "self" and class_name == "Partition":
            return "partition"
        if base in ("instance", "inst") or (base == "self"
                                            and class_name == "Instance"):
            return "instance"
        if base in ("store", "gstore", "tstore"):
            return "store"
    if attr in ("lock", "_lock"):
        if base in _METADB_RECVS or (base == "self" and class_name == "MetaDb"):
            return "metadb"
        if base == "self" and class_name == "ColumnarReplicaManager":
            return "columnar"
    owner = base if base not in ("self", "") else (class_name or "module")
    return f"{owner}.{attr}"


def _blocking_op(call: ast.Call) -> Optional[str]:
    f = call.func
    if not isinstance(f, ast.Attribute):
        return None
    attr = f.attr
    recv = _recv_chain(f.value)
    base = recv.split(".")[-1] if recv else ""
    if attr == "sleep" and base in ("time", "_time", "_t"):
        return "time.sleep"
    if attr == "request" and base not in ("self",):
        return "worker RPC (.request)"
    if attr == "block_until_ready":
        return "device sync (block_until_ready)"
    if "metadb" in recv and attr in (
            "execute", "executemany", "executescript", "commit", "tx_log_put",
            "tx_log_put_many", "kv_put", "write_events", "put", "delete"):
        return f"metadb IO ({attr})"
    return None


class _Edge:
    __slots__ = ("a", "b", "line", "via", "same_expr")

    def __init__(self, a, b, line, via="", same_expr=False):
        self.a, self.b, self.line, self.via = a, b, line, via
        self.same_expr = same_expr


class LockOrderChecker(Checker):
    rules = ("lock-order", "lock-blocking")
    description = ("static lock-nesting graph vs the canonical "
                   "append_lock -> partition -> store/metadb order, plus "
                   "blocking ops under hot locks")

    def check(self, mod: Module):
        if not mod.relpath.startswith(SCOPE_PREFIXES):
            return []
        findings: List[Finding] = []
        # pass 1: per top-level function — lexical edges, blocking ops,
        # call sites under held locks, and each function's own acquisitions
        func_acquires: Dict[str, Set[str]] = {}
        call_sites: List[Tuple[List[str], str, int]] = []
        edges: List[_Edge] = []

        def scan(node: ast.AST, held: List[Tuple[str, str]], class_name: str,
                 acquires: Set[str]):
            for child in ast.iter_child_nodes(node):
                if isinstance(child, ast.With):
                    names: List[Tuple[str, str]] = []
                    for item in child.items:
                        nm = lock_name(item.context_expr, class_name)
                        if nm is None:
                            continue
                        expr_text = ast.dump(item.context_expr)
                        for prev_nm, prev_expr in held + names:
                            edges.append(_Edge(
                                prev_nm, nm, child.lineno,
                                same_expr=(prev_expr == expr_text)))
                        names.append((nm, expr_text))
                        acquires.add(nm)
                    scan(child, held + names, class_name, acquires)
                    continue
                if isinstance(child, ast.Call):
                    if held:
                        op = _blocking_op(child)
                        hot = [h for h, _ in held if h in HOT]
                        if op is not None and hot:
                            findings.append(self.finding(
                                mod, child.lineno,
                                f"{op} under hot lock "
                                f"'{hot[-1]}' — blocking work on the write "
                                f"hot path convoys every writer",
                                rule="lock-blocking", severity="warn"))
                        callee = ""
                        if isinstance(child.func, ast.Name):
                            callee = child.func.id
                        elif isinstance(child.func, ast.Attribute) and \
                                isinstance(child.func.value, ast.Name) and \
                                child.func.value.id == "self":
                            callee = child.func.attr
                        if callee:
                            call_sites.append(
                                ([h for h, _ in held], callee, child.lineno))
                    scan(child, held, class_name, acquires)
                    continue
                if isinstance(child, (ast.FunctionDef, ast.AsyncFunctionDef)):
                    # nested defs run later, not under the current holds
                    sub: Set[str] = set()
                    scan(child, [], class_name, sub)
                    func_acquires.setdefault(child.name, set()).update(sub)
                    acquires.update(sub)  # conservative: builder runs inline
                    continue
                scan(child, held, class_name, acquires)

        def top(node: ast.AST, class_name: str):
            for child in ast.iter_child_nodes(node):
                if isinstance(child, ast.ClassDef):
                    top(child, child.name)
                elif isinstance(child, (ast.FunctionDef,
                                        ast.AsyncFunctionDef)):
                    acq: Set[str] = set()
                    scan(child, [], class_name, acq)
                    func_acquires.setdefault(child.name, set()).update(acq)

        top(mod.tree, "")

        # pass 2: one level of call-graph propagation (same module only)
        for held, callee, line in call_sites:
            for m in func_acquires.get(callee, ()):
                for h in held:
                    if h != m:
                        edges.append(_Edge(h, m, line, via=callee))

        # pass 3: judge the edges
        seen: Set[Tuple[str, str, int]] = set()
        for e in edges:
            key = (e.a, e.b, e.line)
            if key in seen:
                continue
            seen.add(key)
            via = f" (via call to {e.via}())" if e.via else ""
            if e.a == e.b:
                if e.same_expr or e.via:
                    continue  # re-entrant same instance (RLock) — legal
                findings.append(self.finding(
                    mod, e.line,
                    f"two '{e.a}' locks held together{via} — no intra-class "
                    f"order is declared for this lock class",
                    rule="lock-order"))
                continue
            ra, rb = RANKS.get(e.a), RANKS.get(e.b)
            if ra is not None and rb is not None and ra > rb:
                findings.append(self.finding(
                    mod, e.line,
                    f"lock-order inversion: '{e.b}' (rank {rb}) acquired "
                    f"while holding '{e.a}' (rank {ra}){via}; canonical "
                    f"order is append_lock -> partition -> store/metadb",
                    rule="lock-order"))
        return findings
