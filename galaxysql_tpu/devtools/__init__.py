"""galaxylint: repo-specific static analysis + the runtime lockdep witness.

The reference system ships correctness *tooling*, not just correctness
(FastChecker, `executor/fastchecker/FastChecker.java` — ported in
`utils/fastchecker.py` for data consistency).  This package is the same shape
of tooling for the ENGINE'S OWN CODE: the hand-enforced invariants that used
to live in comments and reviewer memory (the append_lock-before-partition-lock
ordering, the `global_jit` zero-retrace discipline, the typed-error wire
contract, failpoint/metrics hygiene) are mechanized as AST passes so the next
PR can't silently regress them.

Entry point: `python -m galaxysql_tpu.devtools.lint` (the `make lint` target).
The runtime half lives in `utils/lockdep.py`.
"""
