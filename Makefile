# Convenience targets; CI drives the same commands.

PY ?= python

# galaxylint: the repo-specific static-analysis suite (lock-order vs the
# canonical append_lock -> partition -> store/metadb order + blocking ops
# under hot locks, raw-jax.jit / device-sync jit discipline, typed-error
# wire-contract swallows and untyped raises, failpoint/metrics hygiene).
# Exits 0 only with ZERO unsuppressed findings; suppressions live as
# justified `# galaxylint: disable=<rule> -- why` pragmas or justified
# entries in galaxysql_tpu/devtools/baseline.json (stale entries fail).
lint:
	$(PY) -m galaxysql_tpu.devtools.lint

# lint smoke: the lint marker suite — per-rule positive/negative fixtures,
# pragma/baseline round-trips, the whole-tree zero-findings self-run, and
# the runtime lockdep witness incl. the FP_LOCK_INVERT seeded inversion
lint-smoke:
	JAX_PLATFORMS=cpu $(PY) -m pytest tests/ -q -m lint -p no:cacheprovider

# full tier-1 gate (ROADMAP.md)
tier1:
	JAX_PLATFORMS=cpu $(PY) -m pytest tests/ -q -m 'not slow' \
		--continue-on-collection-errors -p no:cacheprovider

# fast fusion smoke: TPC-H Q1/Q3 (+ SSB/TPC-DS fixtures) through BOTH the
# fused and unfused execution paths, asserting identical results — guards the
# pipeline segment fuser without paying for the whole suite
fusion-smoke:
	JAX_PLATFORMS=cpu $(PY) -m pytest tests/ -q -m fusion -p no:cacheprovider

# fast observability smoke: EXPLAIN ANALYZE actual-rows vs result
# cardinalities, SHOW FULL STATS / information_schema.metrics round-trips,
# web /metrics + /query/<trace_id>, and the no-profiling hot-path guard
# (zero extra device dispatches vs the PR-1 fused baseline)
obs-smoke:
	JAX_PLATFORMS=cpu $(PY) -m pytest tests/ -q -m observability -p no:cacheprovider

# fast runtime-filter smoke: filter value semantics (empty build, NULL keys,
# bloom FP tolerance), planner annotation + hint gating, and result
# equivalence with RUNTIME_FILTER(OFF) on TPC-H Q3/Q5/Q9/Q18 + SSB Q2.1 on
# both the local engine and the 8-device mesh
rf-smoke:
	JAX_PLATFORMS=cpu $(PY) -m pytest tests/ -q -m runtime_filter -p no:cacheprovider

# fast fragment-cache smoke: cache-on (warm, second execution) vs
# FRAGMENT_CACHE(OFF) equivalence on TPC-H Q3/Q5/Q9 + SSB Q2.1 on both the
# local engine and the 8-device mesh, plus the invalidation edges (DML/DDL
# version bumps, txn-local writes, flashback, cross-coordinator SyncBus)
cache-smoke:
	JAX_PLATFORMS=cpu $(PY) -m pytest tests/ -q -m fragment_cache -p no:cacheprovider

# fast tracing smoke: TPC-H Q5 with tracing on vs off (bit-identical results,
# unchanged dispatch count when off), span-tree shape (operators, fused
# segments, MPP shard subtrees, worker graft), compile events, and a
# well-formed Chrome-trace JSON from /trace/<trace_id>
trace-smoke:
	JAX_PLATFORMS=cpu $(PY) -m pytest tests/ -q -m tracing -p no:cacheprovider

# overload smoke: the resource-governance plane under sustained load — the
# workload-class admission gate (AIMD per-class limits, deadline-aware
# shedding, typed ServerOverloadError with retry-after), memory-pressure
# tiers (fragment-cache shrink, CRITICAL AP refusal + largest-query revoke),
# retry budgets + worker slow-drain backpressure piggyback, the CCL SQL
# surface (CREATE/DROP CCL_RULE) and CclManager concurrency stress, and the
# end-to-end proof: TP keeps bounded p99 and nonzero goodput while an AP
# flood sheds typed, with zero hangs and bit-identical admitted results
overload-smoke:
	JAX_PLATFORMS=cpu $(PY) -m pytest tests/ -q -m overload -p no:cacheprovider

# overload bench: closed-loop TP point serving with and without a concurrent
# AP flood (admission on), reporting TP QPS/p99 deltas + shed rate
bench-overload:
	JAX_PLATFORMS=cpu $(PY) bench.py --overload-only

bench:
	$(PY) bench.py

# fast batching smoke: the batching marker suite (batched vs sequential
# bit-identical results under 100+ concurrent sessions, poisoned-key error
# isolation, snapshot/txn bypass edges, static-bucket retrace guard) plus the
# closed-loop multi-session serving bench (QPS/chip + p99, batching on vs off)
# (GALAXYSQL_LOCKDEP=1: every concurrency test doubles as a lock-order
# proof — the runtime witness fails loudly on any acquisition-graph cycle)
batch-smoke:
	JAX_PLATFORMS=cpu GALAXYSQL_LOCKDEP=1 $(PY) -m pytest tests/ -q -m batching -p no:cacheprovider
	JAX_PLATFORMS=cpu BENCH_BATCH_SESSIONS=100,1000 $(PY) bench.py --batch-only

# DML batching smoke: the dml_batch marker suite (batched vs sequential
# bit-identical table state under 100+ concurrent write sessions, poison-key
# error isolation, own-txn bypass, read-your-writes after async GSI apply,
# replica reply-leg-drop exactly-once, group commit, CDC coalescing +
# replay equivalence, the hatch trio, steady-state retrace/dispatch guards)
# (GALAXYSQL_LOCKDEP=1: the lockdep witness rides every write-path test)
dml-smoke:
	JAX_PLATFORMS=cpu GALAXYSQL_LOCKDEP=1 $(PY) -m pytest tests/ -q -m dml_batch -p no:cacheprovider

# DML bench: closed-loop point-DML + mixed read/write serving, DML batching
# on vs off (BENCH json lines on stdout; BENCH_DML_SESSIONS=64,256 default)
bench-dml:
	JAX_PLATFORMS=cpu $(PY) bench.py --dml-only

# chaos smoke: the fault-injection suite over a real worker subprocess —
# retry transparency + dedupe-window exactly-once (reply-leg drop), circuit
# breaker open/half-open/closed, MAX_EXECUTION_TIME deadline kills, sync-epoch
# cache healing, XA crash-restart recovery, replica read failover, and the
# fixed-seed fault-schedule matrix driving TPC-H Q5 + concurrent point DML
# (bit-identical-or-typed-error, zero hangs, zero double-applies)
# (GALAXYSQL_LOCKDEP=1: fault-schedule concurrency doubles as a lock proof)
chaos-smoke:
	JAX_PLATFORMS=cpu GALAXYSQL_LOCKDEP=1 $(PY) -m pytest tests/ -q -m chaos -p no:cacheprovider

# skew smoke: heavy-hitter hybrid joins + salted aggregation vs SKEW(OFF)
# bit-identical across the Zipf theta sweep (8-virtual-device mesh), both
# hybrid orientations, stats-drift deactivation, fragment-cache rekeying on
# hot-key-set change, the hatch trio, and shard-skew observability surfaces
skew-smoke:
	JAX_PLATFORMS=cpu $(PY) -m pytest tests/ -q -m skew -p no:cacheprovider

# skew bench: Zipf theta sweep on the Q9-like join family, skew-on vs
# skew-off, 8 virtual devices (BENCH json lines on stdout)
bench-skew:
	JAX_PLATFORMS=cpu $(PY) bench.py --skew-only

# workload-insight smoke: statement-digest aggregation (exec/error counts,
# windows, digest stability across literals), the event journal, slow-log
# digest linkage, SHOW/information_schema/web/Prometheus surfaces, the
# plan-regression sentinel end-to-end, summary-on-vs-off bit-identical
# results, race-free concurrent aggregation, and the zero-extra-dispatch /
# zero-device-sync hot-path guard
summary-smoke:
	JAX_PLATFORMS=cpu $(PY) -m pytest tests/ -q -m summary -p no:cacheprovider

# rebalance smoke: partition-granular elasticity — SPLIT/MERGE/MOVE PARTITION
# end-to-end (bucket-map conversion routing identity, shadow backfill + CDC
# catchup + FastChecker verify + TSO-fenced cutover), crash-resume from every
# checkpoint, verify-mismatch rollback restoring the source byte-identically,
# the open-transaction cutover drain, the heat-driven balancer policy with
# its admission-pressure yield, and the SHOW REBALANCE surfaces
# (GALAXYSQL_LOCKDEP=1: the move path's partition/router lock choreography
# doubles as a lock-order proof)
rebalance-smoke:
	JAX_PLATFORMS=cpu GALAXYSQL_LOCKDEP=1 $(PY) -m pytest tests/ -q -m rebalance -p no:cacheprovider

# rebalance chaos: crash schedules at EVERY job state transition (task
# boundaries, mid-backfill chunk, mid-catchup page, inside the cutover before
# and after the swap) with DML racing the move and readers watching —
# bit-identical-or-typed-error, zero lost/duplicated acked writes, and
# crash-resume completing from the last checkpoint (or undo restoring the
# source exactly)
chaos-rebalance:
	JAX_PLATFORMS=cpu GALAXYSQL_LOCKDEP=1 $(PY) -m pytest tests/ -q -m rebalance_chaos -p no:cacheprovider

# rebalance bench: closed-loop point serving measured quiesced vs during a
# live SPLIT (rebalance-while-serving QPS dip + p99; BENCH json on stdout)
bench-rebalance:
	JAX_PLATFORMS=cpu $(PY) bench.py --rebalance-only

# kernel smoke: the kernel-tier matrix — Pallas join/agg vs reference
# bit-identity (NULL keys, empty build, duplicate keys, overflow-ladder
# doubling, both hybrid orientations, TPC-H Q5/Q9 on-vs-off), the
# escape-hatch trio proven structurally off-path, and the persistent AOT
# compile cache (restart round trip with zero steady retraces, corrupted
# entries recompiling, metrics/EXPLAIN surfaces)
kernel-smoke:
	JAX_PLATFORMS=cpu $(PY) -m pytest tests/ -q -m kernel -p no:cacheprovider

# kernel bench: Pallas-vs-reference join/agg rows/s (interpret mode on CPU —
# the honest number until a TPU answers) + the AOT compile-cache cold-vs-warm
# restart compile_ms comparison (BENCH json on stdout)
bench-kernels:
	JAX_PLATFORMS=cpu $(PY) bench.py --kernels-only

# self-heal smoke: the quarantine state machine end-to-end — a genuine
# stats-driven join-order regression auto-rolls-back, verifies over
# PLAN_HEAL_VERIFY_EXECS executions, and promotes (bit-identical results,
# one plan_rollback + one plan_promoted per episode); plus stats-drift
# repair, flap damping / HEAL_FAILED park + ANALYZE re-arm, probation
# resuming across a coordinator restart, the ENABLE_PLAN_AUTOHEAL /
# GALAXYSQL_PLAN_AUTOHEAL=0 detect-only hatches, and the surfaces parity
heal-smoke:
	JAX_PLATFORMS=cpu $(PY) -m pytest tests/ -q -m selfheal -p no:cacheprovider

# SLO-plane smoke: the slo marker suite — deterministic burn/recover under
# an injected latency failpoint (fast+slow window burn, slo_burn/critical,
# /health degraded, recovery re-arm), compile-retrace anomaly detection,
# CREATE/DROP SLO restart persistence, the SHOW/info-schema/web surfaces,
# and the zero-dispatch / zero-transfer sampler guards
slo-smoke:
	JAX_PLATFORMS=cpu $(PY) -m pytest tests/ -q -m slo -p no:cacheprovider

# SLO bench: steady-state serving snapshotted through the metric history
# (slo_snapshot: history-derived qps + p99 + burn state) and the sampler
# overhead measurement — closed-loop QPS with the history/SLO tick on vs
# hatched off (target: <= 3% delta; BENCH json on stdout)
bench-slo:
	JAX_PLATFORMS=cpu $(PY) bench.py --slo-only

# incident flight-recorder smoke: the incident marker suite — tail-sampled
# trace retention (slow/shed/error tails kept at sample_rate=0, phase
# breakdown on every root span), the injected-burn end-to-end (one bundle,
# implicated digest, retained trace + metric window + admission state),
# cooldown dedupe, SHOW INCIDENTS / info-schema / web surfaces, the
# router-hop trace graft over a real subprocess peer, and the hot-path
# guard (unchanged dispatch counts, zero steady retraces, sampling on)
incident-smoke:
	JAX_PLATFORMS=cpu $(PY) -m pytest tests/ -q -m incident -p no:cacheprovider

# tracing-overhead bench: 32-session batched-serving closed loop with
# always-on tail-sampled tracing vs GALAXYSQL_TRACING=0 — overhead target
# <= 3%, dispatch counts unchanged, steady retraces 0 (BENCH_r14.json)
bench-tracing:
	JAX_PLATFORMS=cpu $(PY) bench.py --tracing-only

# serving-tier smoke: the router marker suite — consistent-hash affinity,
# session pinning + typed-once failover, cluster-wide admission gossip,
# placement-driven locality, SHOW COORDINATORS / SHOW CLUSTER surfaces,
# the hatch trio, and the coordinator-kill chaos test over real
# subprocesses.  Lockdep-armed: router/gossip paths hold instance locks.
scaleout-smoke:
	JAX_PLATFORMS=cpu GALAXYSQL_LOCKDEP=1 $(PY) -m pytest tests/ -q \
		-m router -p no:cacheprovider

# serving-tier curve: 1/2/4 coordinator subprocesses over one shared
# metadb, closed-loop point workload through the front router — aggregate
# QPS, p99, affinity hit rate, gossip staleness into BENCH_r12.json
bench-scaleout:
	JAX_PLATFORMS=cpu $(PY) bench.py --scaleout-only

# columnar HTAP replica: CDC-tailed delta+base tier bit-identical to the
# row store at arbitrary watermarks, crash-resume, compaction vs racing
# writes, DDL-mid-tail reseed, routing gates + hatch trio, SHOW/info-schema
# surfaces.  Lockdep-armed: the tailer holds the columnar lock over
# partition snapshots and metadb persistence.
columnar-smoke:
	JAX_PLATFORMS=cpu GALAXYSQL_LOCKDEP=1 $(PY) -m pytest tests/ -q \
		-m columnar -p no:cacheprovider

# HTAP curve: columnar replica vs row store rows/s on AP scans at SF0.2
# under sustained DML, plus freshness-lag series — into BENCH_r13.json
bench-htap:
	JAX_PLATFORMS=cpu $(PY) bench.py --htap-only

.PHONY: tier1 fusion-smoke obs-smoke rf-smoke cache-smoke trace-smoke bench \
	batch-smoke chaos-smoke skew-smoke bench-skew summary-smoke heal-smoke \
	overload-smoke bench-overload dml-smoke bench-dml lint lint-smoke \
	rebalance-smoke chaos-rebalance bench-rebalance kernel-smoke \
	bench-kernels slo-smoke bench-slo scaleout-smoke bench-scaleout \
	columnar-smoke bench-htap incident-smoke bench-tracing
