"""TPC-DS 10-query differential suite vs sqlite3 (BASELINE config #5).

Exercises the SQL surface the subset needs: CTEs, ROLLUP, star joins, CASE
aggregates, substr predicates, IN lists — results must match sqlite on the same
generated data (float tolerance for decimal/avg columns)."""

import math
import sqlite3

import pytest

from galaxysql_tpu.server.instance import Instance
from galaxysql_tpu.server.session import Session
from galaxysql_tpu.storage import tpcds

SF = 0.003


@pytest.fixture(scope="module")
def env():
    data = tpcds.generate(SF)
    inst = Instance()
    s = Session(inst)
    s.execute("CREATE DATABASE tpcds; USE tpcds")
    for t in tpcds.TABLE_ORDER:
        s.execute(tpcds.TPCDS_DDL[t])
        inst.store("tpcds", t).insert_pylists(data[t], inst.tso.next_timestamp())
    s.execute("ANALYZE TABLE " + ", ".join(tpcds.TABLE_ORDER))

    db = sqlite3.connect(":memory:")
    for t in tpcds.TABLE_ORDER:
        cols = list(data[t].keys())
        decls = []
        for c in cols:
            v = data[t][c][0] if data[t][c] else 0
            decls.append(f"{c} {'TEXT' if isinstance(v, str) else 'NUMERIC'}")
        db.execute(f"CREATE TABLE {t} ({', '.join(decls)})")
        rows = list(zip(*[data[t][c] for c in cols]))
        db.executemany(f"INSERT INTO {t} VALUES ({','.join('?' * len(cols))})",
                       rows)
    db.commit()
    yield s, db
    s.close()
    db.close()


def norm(v):
    if v is None:
        return None
    if isinstance(v, float):
        return v
    return v


def assert_rows_match(got, want):
    assert len(got) == len(want), f"{len(got)} rows vs sqlite {len(want)}"
    for i, (a, b) in enumerate(zip(got, want)):
        assert len(a) == len(b)
        for x, y in zip(a, b):
            if isinstance(x, float) or isinstance(y, float):
                if x is None or y is None:
                    assert x is None and y is None, f"row {i}: {a} vs {b}"
                else:
                    assert math.isclose(float(x), float(y), rel_tol=1e-6,
                                        abs_tol=1e-6), f"row {i}: {a} vs {b}"
            else:
                assert norm(x) == norm(y), f"row {i}: {a} vs {b}"


# sqlite has no ROLLUP: expand to the equivalent UNION ALL of grouping levels
_Q22_CORE = """
    SELECT {k1} AS i_product_name, {k2} AS i_brand, {k3} AS i_class,
           {k4} AS i_category, avg(inv_quantity_on_hand) AS qoh
    FROM inventory, date_dim, item
    WHERE inv_date_sk = d_date_sk AND inv_item_sk = i_item_sk
      AND d_month_seq BETWEEN 1200 AND 1211 {group}
"""
_Q27_CORE = """
    SELECT {k1} AS i_item_id, {k2} AS s_state, avg(ss_quantity) AS agg1,
           avg(ss_list_price) AS agg2, avg(ss_coupon_amt) AS agg3,
           avg(ss_sales_price) AS agg4
    FROM store_sales, customer_demographics, date_dim, store, item
    WHERE ss_sold_date_sk = d_date_sk AND ss_item_sk = i_item_sk
      AND ss_store_sk = s_store_sk AND ss_cdemo_sk = cd_demo_sk
      AND cd_gender = 'M' AND cd_marital_status = 'S'
      AND cd_education_status = 'College' AND d_year = 2002
      AND s_state IN ('TN', 'SD') {group}
"""


def _rollup_union(core: str, keys):
    parts = []
    for lvl in range(len(keys), -1, -1):
        subs = {f"k{i + 1}": (k if i < lvl else "NULL")
                for i, k in enumerate(keys)}
        grp = ("GROUP BY " + ", ".join(keys[:lvl])) if lvl else ""
        parts.append(core.format(group=grp, **subs))
    return " UNION ALL ".join(parts)


SQLITE_OVERRIDES = {
    "q22": _rollup_union(_Q22_CORE, ["i_product_name", "i_brand", "i_class",
                                     "i_category"]) +
           " ORDER BY qoh, i_product_name, i_brand, i_class, i_category "
           "LIMIT 100",
    "q27": _rollup_union(_Q27_CORE, ["i_item_id", "s_state"]) +
           " ORDER BY i_item_id, s_state LIMIT 100",
}


@pytest.mark.parametrize("qid", sorted(tpcds.QUERIES))
def test_tpcds_matches_sqlite(env, qid):
    s, db = env
    sql = tpcds.QUERIES[qid]
    got = [tuple(r) for r in s.execute(sql).rows]
    want = [tuple(r) for r in db.execute(SQLITE_OVERRIDES.get(qid, sql)).fetchall()]
    assert_rows_match(got, want)
