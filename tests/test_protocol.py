"""Wire-protocol integration: real sockets, real MySQL packets, full server stack.

Reference analog: `MockServer` protocol-level tests (SURVEY.md §4 server tests), but
against the actual engine rather than a mock executor.
"""

import asyncio
import threading

import pytest

from galaxysql_tpu.net.client import MiniClient, MySQLError
from galaxysql_tpu.net.server import MySQLServer
from galaxysql_tpu.server.instance import Instance


@pytest.fixture(scope="module")
def server():
    inst = Instance()
    srv = MySQLServer(inst, port=0, users={"root": "", "alice": "secret"})
    loop = asyncio.new_event_loop()
    started = threading.Event()

    def run():
        asyncio.set_event_loop(loop)
        loop.run_until_complete(srv.start())
        started.set()
        loop.run_forever()

    t = threading.Thread(target=run, daemon=True)
    t.start()
    started.wait(10)
    yield srv
    loop.call_soon_threadsafe(loop.stop)


@pytest.fixture()
def client(server):
    c = MiniClient("127.0.0.1", server.port)
    yield c
    c.close()


class TestProtocol:
    def test_handshake_and_ping(self, client):
        assert client.server_version.startswith("8.0")
        assert client.ping()

    def test_auth_password(self, server):
        c = MiniClient("127.0.0.1", server.port, user="alice", password="secret")
        assert c.ping()
        c.close()
        with pytest.raises(MySQLError) as ei:
            MiniClient("127.0.0.1", server.port, user="alice", password="wrong")
        assert ei.value.errno == 1045
        with pytest.raises(MySQLError):
            MiniClient("127.0.0.1", server.port, user="nobody")

    def test_query_roundtrip(self, client):
        client.query("CREATE DATABASE IF NOT EXISTS wire")
        client.query("USE wire")
        client.query("CREATE TABLE IF NOT EXISTS t (id BIGINT PRIMARY KEY, "
                     "name VARCHAR(20), amount DECIMAL(10,2), d DATE)")
        client.query("TRUNCATE TABLE t")
        client.query("INSERT INTO t VALUES (1,'ann',3.50,'2024-01-05'),"
                     "(2,NULL,NULL,NULL)")
        names, rows = client.query("SELECT id, name, amount, d FROM t ORDER BY id")
        assert names == ["id", "name", "amount", "d"]
        assert rows == [("1", "ann", "3.5", "2024-01-05"), ("2", None, None, None)]

    def test_error_packet(self, client):
        client.query("CREATE DATABASE IF NOT EXISTS wire")
        client.query("USE wire")
        with pytest.raises(MySQLError) as ei:
            client.query("SELECT * FROM does_not_exist")
        assert ei.value.errno == 1146
        # connection stays usable after an error
        assert client.query("SELECT 1 AS x")[1] == [("1",)]

    def test_multi_statement(self, client):
        client.query("CREATE DATABASE IF NOT EXISTS wire; USE wire")
        results = client.query_all(
            "CREATE TABLE IF NOT EXISTS m (a BIGINT); TRUNCATE TABLE m; "
            "INSERT INTO m VALUES (7); SELECT a FROM m")
        # EVERY statement's result arrives (SERVER_MORE_RESULTS_EXISTS chain)
        assert len(results) == 4
        assert results[-1][1] == [("7",)]
        assert results[0] == ([], []) and results[2] == ([], [])
        # and the convenience API returns the last
        assert client.query("SELECT 1; SELECT 2")[1] == [("2",)]

    def test_connect_with_database(self, server):
        c0 = MiniClient("127.0.0.1", server.port)
        c0.query("CREATE DATABASE IF NOT EXISTS withdb")
        c0.close()
        c = MiniClient("127.0.0.1", server.port, database="withdb")
        assert c.query("SELECT database() AS d")[1] == [("withdb",)]
        c.close()

    def test_prepared_statements_binary(self, client):
        client.query("CREATE DATABASE IF NOT EXISTS wire; USE wire")
        client.query("CREATE TABLE IF NOT EXISTS p (id BIGINT, v DOUBLE, "
                     "s VARCHAR(10)); TRUNCATE TABLE p")
        sid = client.prepare("INSERT INTO p VALUES (?, ?, ?)")
        client.execute(sid, [1, 2.5, "xy"])
        client.execute(sid, [2, None, None])
        sid2 = client.prepare("SELECT id, v, s FROM p WHERE id >= ? ORDER BY id")
        names, rows = client.execute(sid2, [1])
        assert names == ["id", "v", "s"]
        assert rows[0] == (1, 2.5, "xy")
        assert rows[1] == (2, None, None)

    def test_show_via_wire(self, client):
        names, rows = client.query("SHOW DATABASES")
        assert names == ["Database"]
        assert any("information_schema" in r for r in rows)

    def test_concurrent_sessions(self, server):
        results = []

        def worker(i):
            c = MiniClient("127.0.0.1", server.port)
            c.query("CREATE DATABASE IF NOT EXISTS wire")
            c.query("USE wire")
            _, rows = c.query(f"SELECT {i} + 1 AS v")
            results.append(rows[0][0])
            c.close()

        threads = [threading.Thread(target=worker, args=(i,)) for i in range(6)]
        for t in threads:
            t.start()
        for t in threads:
            t.join(30)
        assert sorted(results) == [str(i + 1) for i in range(6)]


class TestReviewRegressions:
    def test_group_order_ordinals_survive_parameterization(self, client):
        client.query("CREATE DATABASE IF NOT EXISTS wire; USE wire")
        client.query("CREATE TABLE IF NOT EXISTS ordi (a BIGINT, b BIGINT); "
                     "TRUNCATE TABLE ordi")
        client.query("INSERT INTO ordi VALUES (1, 10), (1, 20), (2, 5)")
        names, rows = client.query(
            "SELECT a, SUM(b) FROM ordi GROUP BY 1 ORDER BY 2 DESC")
        assert rows == [("1", "30"), ("2", "5")]

    def test_stmt_execute_reuses_cached_types(self, server):
        # craft a second COM_STMT_EXECUTE with new_params_bound_flag = 0
        import struct
        from galaxysql_tpu.net import packets as P
        c = MiniClient("127.0.0.1", server.port)
        c.query("CREATE DATABASE IF NOT EXISTS wire; USE wire")
        c.query("CREATE TABLE IF NOT EXISTS pt (a BIGINT); TRUNCATE TABLE pt")
        c.query("INSERT INTO pt VALUES (1), (2), (3)")
        sid = c.prepare("SELECT a FROM pt WHERE a = ? ORDER BY a")
        assert c.execute(sid, [2])[1] == [(2,)]
        # manual re-execute: null bitmap, flag=0, no types, value only
        payload = (bytes([P.COM_STMT_EXECUTE]) + struct.pack("<IBI", sid, 0, 1) +
                   b"\x00" + b"\x00" + struct.pack("<q", 3))
        c._command(payload)
        names, rows = c._read_result(binary=True)
        assert rows == [(3,)]
        c.close()

    def test_question_mark_inside_string_literal(self, client):
        client.query("CREATE DATABASE IF NOT EXISTS wire; USE wire")
        client.query("CREATE TABLE IF NOT EXISTS qs (s VARCHAR(10)); "
                     "TRUNCATE TABLE qs")
        sid = client.prepare("INSERT INTO qs VALUES ('who?')")
        client.execute(sid, [])
        assert client.query("SELECT s FROM qs")[1] == [("who?",)]

    def test_missing_params_proper_error(self, client):
        client.query("CREATE DATABASE IF NOT EXISTS wire; USE wire")
        client.query("CREATE TABLE IF NOT EXISTS mp (a BIGINT)")
        sid = client.prepare("SELECT a FROM mp WHERE a = ?")
        with pytest.raises(MySQLError) as ei:
            # send an execute claiming zero params for a 1-param statement
            import struct
            from galaxysql_tpu.net import packets as P
            payload = bytes([P.COM_STMT_EXECUTE]) + struct.pack("<IBI", sid, 0, 1)
            client._command(payload)
            client._read_result(binary=True)
        assert ei.value.errno != 0


class TestCompressedProtocol:
    def test_compressed_roundtrip(self, server):
        """CLIENT_COMPRESS framing: commands and resultsets ride zlib frames
        (small frames verbatim with uncompressed-len 0, MySQL semantics)."""
        from galaxysql_tpu.net.client import MiniClient
        host, port = "127.0.0.1", server.port
        c = MiniClient(host, port, compress=True)
        c.query_all("CREATE DATABASE IF NOT EXISTS zc; USE zc")
        c.query("CREATE TABLE IF NOT EXISTS t (a BIGINT, s VARCHAR(64))")
        big = "x" * 60
        vals = ",".join(f"({i}, '{big}')" for i in range(500))
        c.query(f"INSERT INTO t VALUES {vals}")
        names, rows = c.query("SELECT a, s FROM t ORDER BY a")
        assert len(rows) == 500 and rows[0] == ("0", big) or rows[0][1] == big
        # an uncompressed client sees the same data on the same server
        c2 = MiniClient(host, port)
        c2.query("USE zc")
        _, rows2 = c2.query("SELECT count(*) FROM t")
        assert rows2[0][0] in (500, "500")
        c.close()
        c2.close()


@pytest.fixture(scope="module")
def tls_server(tmp_path_factory):
    """A server with a self-signed cert (TLS upgrade, net/ssl analog)."""
    import subprocess
    d = tmp_path_factory.mktemp("tls")
    cert, key = str(d / "cert.pem"), str(d / "key.pem")
    subprocess.run(["openssl", "req", "-x509", "-newkey", "rsa:2048",
                    "-nodes", "-keyout", key, "-out", cert, "-days", "1",
                    "-subj", "/CN=localhost"], check=True,
                   capture_output=True)
    inst = Instance()
    srv = MySQLServer(inst, port=0, users={"root": ""},
                      ssl_certfile=cert, ssl_keyfile=key)
    loop = asyncio.new_event_loop()
    started = threading.Event()

    def run():
        asyncio.set_event_loop(loop)
        loop.run_until_complete(srv.start())
        started.set()
        loop.run_forever()

    t = threading.Thread(target=run, daemon=True)
    t.start()
    started.wait(10)
    yield srv
    loop.call_soon_threadsafe(loop.stop)


class TestTls:
    def test_tls_handshake_and_query(self, tls_server):
        c = MiniClient("127.0.0.1", tls_server.port, use_ssl=True,
               timeout=120.0)
        try:
            assert c.ping()
            c.query("CREATE DATABASE IF NOT EXISTS enc")
            c.query("USE enc")
            c.query("CREATE TABLE s (id INT, v VARCHAR(10))")
            c.query("INSERT INTO s VALUES (1, 'hush')")
            names, rows = c.query("SELECT v FROM s WHERE id = 1")
            assert rows == [("hush",)]
        finally:
            c.close()

    def test_plaintext_still_works_on_tls_server(self, tls_server):
        c = MiniClient("127.0.0.1", tls_server.port)
        try:
            assert c.ping()
        finally:
            c.close()


class TestBinlogDump:
    def test_stream_changes(self, server):
        c = MiniClient("127.0.0.1", server.port)
        try:
            c.query("CREATE DATABASE IF NOT EXISTS bl")
            c.query("USE bl")
            c.query("CREATE TABLE ev (id INT, v VARCHAR(10))")
            c.query("INSERT INTO ev VALUES (1, 'a'), (2, 'b')")
            c.query("DELETE FROM ev WHERE id = 1")
            events = c.binlog_dump(0)
            mine = [e for e in events if e["table"] == "ev"]
            kinds = [e["kind"] for e in mine]
            assert "insert" in {k.lower() for k in kinds}, mine
            assert any("delete" in k.lower() for k in kinds), mine
            # resume from the last watermark: nothing new
            last = max(e["seq"] for e in events)
            assert c.binlog_dump(last) == []
            # new change appears after the watermark
            c.query("INSERT INTO ev VALUES (3, 'c')")
            tail = c.binlog_dump(last)
            assert any(e["table"] == "ev" for e in tail)
        finally:
            c.close()
