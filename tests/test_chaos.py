"""Chaos harness: fault-tolerant distributed execution under injected faults.

The FailPoint framework (utils/failpoint.py) gained network-plane keys —
FP_RPC_DROP / FP_RPC_DELAY_MS / FP_RPC_FAIL_N (coordinator-side, op-scoped)
and FP_WORKER_CRASH (worker-side, armed remotely via the `failpoint` sync
action) — and this suite drives the coordinator<->worker plane through them:

- retries are transparent for retry-safe ops and NEVER double-apply DML (the
  worker's uid dedupe window replays the recorded result on a reconnect retry
  — the reply-leg-drop test is the exactly-once proof),
- the circuit breaker opens on consecutive failures, fast-fails typed while
  open, and half-open ping probes close it when the worker returns,
- MAX_EXECUTION_TIME deadlines kill queries TYPED at drain/RPC boundaries,
- a worker that missed a SyncBus broadcast heals its caches at next contact
  (sync-epoch gap detection),
- a worker crash between XA prepare and commit resolves exactly once after
  restart (recover_remote),
- replica reads fail over within the statement and fenced/stale replicas are
  excluded from routing,
- TPC-H Q5 with a worker-resident dimension + concurrent point DML returns
  bit-identical results or a typed error under randomized fault schedules —
  zero hangs (every run is wall-clock bounded), zero double-applies.
"""

import os
import socket
import struct
import subprocess
import sys
import tempfile
import threading
import time

import pytest

from galaxysql_tpu.net import dn
from galaxysql_tpu.server.instance import Instance
from galaxysql_tpu.server.session import Session
from galaxysql_tpu.storage import tpch
from galaxysql_tpu.storage.tpch_queries import QUERIES
from galaxysql_tpu.utils import errors
from galaxysql_tpu.utils.failpoint import (FAIL_POINTS, FP_RPC_DELAY_MS,
                                           FP_RPC_DROP, FP_RPC_FAIL_N,
                                           FP_WORKER_CRASH)
from galaxysql_tpu.utils.metrics import (RPC_RETRIES, SYNC_FAILURES,
                                         WORKER_FAILOVERS)

pytestmark = pytest.mark.chaos

REPO_ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))

# every chaos run is wall-clock bounded: a hang is a FAILURE, not a stall
RUN_BOUND_S = 120.0


def bounded(fn, timeout_s: float = RUN_BOUND_S):
    """Run fn on a DAEMON thread; raise if it neither returns nor raises
    within the bound (the suite's zero-hang enforcement).  A pool context
    manager would defeat the purpose: its shutdown joins the hung thread."""
    result: dict = {}

    def run():
        try:
            result["v"] = fn()
        except BaseException as exc:  # noqa: BLE001 — re-raised below
            result["e"] = exc

    t = threading.Thread(target=run, daemon=True)
    t.start()
    t.join(timeout_s)
    if t.is_alive():
        raise AssertionError(f"hang: call exceeded {timeout_s}s bound")
    if "e" in result:
        raise result["e"]
    return result.get("v")


@pytest.fixture(autouse=True)
def _clean_failpoints():
    FAIL_POINTS.clear()
    yield
    FAIL_POINTS.clear()


class WorkerHarness:
    """Spawn/kill/restart a real worker subprocess (same port across
    restarts so attached WorkerClients reconnect transparently)."""

    def __init__(self, init_sql: str = "", data_dir=None):
        self.init_sql = init_sql
        self.data_dir = data_dir
        self.port = 0
        self.proc = None
        self._stderr = tempfile.NamedTemporaryFile(
            mode="w", prefix="chaos-worker-", suffix=".log", delete=False)
        self.spawn()

    def spawn(self):
        env = dict(os.environ, JAX_PLATFORMS="cpu")
        cmd = [sys.executable, "-m", "galaxysql_tpu.net.worker",
               "--port", str(self.port), "--platform", "cpu"]
        if self.data_dir:
            cmd += ["--data-dir", self.data_dir]
        if self.init_sql and (self.data_dir is None or self.port == 0):
            # with a data_dir the bootstrap state persists across restarts
            cmd += ["--init-sql", self.init_sql]
        self.proc = subprocess.Popen(
            cmd, cwd=REPO_ROOT, stdout=subprocess.PIPE, stderr=self._stderr,
            env=env, text=True)
        line = self.proc.stdout.readline()
        if not line.startswith("WORKER_READY"):
            raise AssertionError(
                f"worker failed to start: {line!r} "
                f"(stderr: {self._stderr.name})")
        self.port = int(line.split()[1])

    def kill(self):
        if self.proc is not None and self.proc.poll() is None:
            self.proc.kill()
            self.proc.wait()

    def restart(self):
        self.kill()
        self.spawn()

    def wait_dead(self, timeout_s: float = 10.0):
        self.proc.wait(timeout=timeout_s)

    def close(self):
        self.kill()
        try:
            self._stderr.close()
        except Exception:
            pass

    @property
    def addr(self):
        return ("127.0.0.1", self.port)


def _region_init_sql() -> str:
    d = tpch.generate(0.01)["region"]
    rows = ", ".join(
        f"({k}, '{n}', '{c}')" for k, n, c in
        zip(d["r_regionkey"], d["r_name"], d["r_comment"]))
    return (
        "CREATE DATABASE w; USE w; "
        "CREATE TABLE kv (k BIGINT PRIMARY KEY, v BIGINT); "
        "INSERT INTO kv VALUES (1, 10), (2, 20), (3, 30); "
        "CREATE DATABASE tpch; USE tpch; "
        + tpch.TPCH_DDL["region"].strip() + "; "
        f"INSERT INTO region VALUES {rows}")


@pytest.fixture(scope="module")
def primary():
    h = WorkerHarness(init_sql=_region_init_sql())
    yield h
    h.close()


@pytest.fixture()
def kv_env(primary):
    """Coordinator with the primary worker's w.kv attached as a remote
    table.  Function-scoped: breaker/fence state never leaks across tests."""
    inst = Instance()
    s = Session(inst)
    s.execute("CREATE DATABASE w")
    s.execute("USE w")
    inst.attach_remote_table("w", "kv", *primary.addr)
    yield s, inst, primary
    s.close()


# -- unit layer: framing, retry policy, failpoints, SyncBus ------------------


class TestFraming:
    def _corrupt(self, payload: bytes):
        a, b = socket.socketpair()
        try:
            a.sendall(payload)
            with pytest.raises(errors.ProtocolError):
                dn.recv_msg(b)
        finally:
            a.close()
            b.close()

    def test_header_length_capped(self):
        # a corrupt/hostile 4-byte prefix must raise typed, not allocate GBs
        self._corrupt(struct.pack(">I", (1 << 31) - 1) + b"x" * 64)

    def test_array_count_capped(self):
        import json
        hb = json.dumps({"n_arrays": 1 << 30}).encode()
        self._corrupt(struct.pack(">I", len(hb)) + hb)

    def test_array_name_length_capped(self):
        import json
        hb = json.dumps({"n_arrays": 1}).encode()
        self._corrupt(struct.pack(">I", len(hb)) + hb +
                      struct.pack(">I", 1 << 24) + b"y" * 64)

    def test_clean_roundtrip_still_works(self):
        import numpy as np
        a, b = socket.socketpair()
        try:
            dn.send_msg(a, {"op": "x"}, {"d": np.arange(4)})
            hdr, arrs = dn.recv_msg(b)
            assert hdr["op"] == "x" and list(arrs["d"]) == [0, 1, 2, 3]
        finally:
            a.close()
            b.close()


class TestRetryPolicy:
    def test_classification(self):
        rs = dn._retry_safe
        assert rs({"op": "ping"})
        assert rs({"op": "exec_plan", "fragment": {}})
        assert rs({"op": "sync", "action": "x"})
        assert rs({"op": "xa_commit", "xid": "g1"})
        assert rs({"op": "exec_sql", "sql": "SELECT 1"})
        assert rs({"op": "exec_sql", "sql": "  /* hint */ select k from t"})
        # writes are retry-safe ONLY with an idempotency token / idem flag
        assert not rs({"op": "exec_sql", "sql": "INSERT INTO t VALUES (1)"})
        assert rs({"op": "exec_sql", "sql": "INSERT INTO t VALUES (1)",
                   "uid": "cn:1"})
        assert rs({"op": "exec_sql", "sql": "CREATE TABLE IF NOT EXISTS t",
                   "idem": True})
        assert not rs({"op": "dml", "sql": "UPDATE t SET v = 1"})
        assert rs({"op": "dml", "sql": "UPDATE t SET v = 1", "uid": "cn:2"})

    def test_rpc_spec_op_scoping_and_budget(self):
        FAIL_POINTS.arm(FP_RPC_DROP, {"op": "dml", "leg": "reply", "n": 2})
        assert FAIL_POINTS.rpc_spec(FP_RPC_DROP, "exec_plan") is None
        assert FAIL_POINTS.rpc_spec(FP_RPC_DROP, "dml")["leg"] == "reply"
        assert FAIL_POINTS.rpc_spec(FP_RPC_DROP, "dml")["leg"] == "reply"
        assert FAIL_POINTS.rpc_spec(FP_RPC_DROP, "dml") is None  # exhausted
        FAIL_POINTS.clear()
        FAIL_POINTS.arm(FP_RPC_FAIL_N, "exec_sql")  # bare-op form
        assert FAIL_POINTS.rpc_spec(FP_RPC_FAIL_N, "exec_sql") == {}
        assert FAIL_POINTS.rpc_spec(FP_RPC_FAIL_N, "dml") is None


class _StubWorker:
    def __init__(self, delay_s=0.0, fail=False):
        self.delay_s = delay_s
        self.fail = fail
        self.calls = 0

    def sync_action(self, action, payload):
        self.calls += 1
        if self.delay_s:
            time.sleep(self.delay_s)
        if self.fail:
            raise ConnectionError("stub down")
        return {"ok": True}


class TestSyncBusBroadcast:
    def test_parallel_fanout_and_failure_isolation(self):
        bus = dn.SyncBus(origin="cn-test")
        slow = [_StubWorker(delay_s=0.25) for _ in range(3)]
        dead = _StubWorker(fail=True)
        for w in slow + [dead]:
            bus.attach(w)
        f0 = SYNC_FAILURES.value
        t0 = time.perf_counter()
        out = bus.broadcast("invalidate_plan_cache", {})
        wall = time.perf_counter() - t0
        assert len(out) == 4
        assert sum(1 for r in out if r.get("ok")) == 3
        assert SYNC_FAILURES.value == f0 + 1
        # serial would be >= 0.75s; parallel is one slowest-worker delay
        assert wall < 0.6, f"broadcast not parallel: {wall:.3f}s"
        assert bus.epoch == 1

    def test_epoch_monotonic(self):
        bus = dn.SyncBus(origin="cn-test")
        for _ in range(3):
            bus.broadcast("invalidate_plan_cache", {})
        assert bus.epoch == 3


class TestBreakerUnit:
    def _dead_port(self):
        s = socket.socket()
        s.bind(("127.0.0.1", 0))
        port = s.getsockname()[1]
        s.close()
        return port

    def test_open_fastfail_and_reopen_on_failed_probe(self):
        c = dn.WorkerClient("127.0.0.1", self._dead_port(), timeout=0.5,
                            max_retries=2, retry_backoff_ms=5,
                            failure_threshold=3, cooldown_ms=250)
        with pytest.raises(errors.WorkerUnavailableError):
            c.request({"op": "ping"})
        assert c.breaker_state() == "open"  # 3 attempts = threshold
        t0 = time.perf_counter()
        with pytest.raises(errors.WorkerUnavailableError):
            c.request({"op": "ping"})
        assert time.perf_counter() - t0 < 0.2  # fast-fail, no socket touch
        time.sleep(0.3)  # cooldown elapses -> half-open probe (fails)
        with pytest.raises(errors.WorkerUnavailableError):
            c.request({"op": "ping"})
        assert c.breaker_state() == "open"
        snap = c.breaker_snapshot()
        assert snap["opens"] >= 1 and snap["failures"] >= 3


# -- integration layer: a real worker process under faults -------------------


class TestRetriesAndDedupe:
    def test_transparent_retry_on_transient_failures(self, kv_env):
        s, inst, w = kv_env
        r0 = RPC_RETRIES.value
        FAIL_POINTS.arm(FP_RPC_FAIL_N, {"op": "exec_plan", "n": 2})
        rows = bounded(lambda: s.execute(
            "SELECT k, v FROM kv ORDER BY k").rows)
        assert rows == [(1, 10), (2, 20), (3, 30)]
        assert RPC_RETRIES.value >= r0 + 2

    def test_exhausted_retries_fail_typed_not_hang(self, kv_env):
        s, inst, w = kv_env
        FAIL_POINTS.arm(FP_RPC_FAIL_N, {"op": "exec_plan", "n": 50})
        with pytest.raises(errors.TddlError):
            bounded(lambda: s.execute("SELECT k FROM kv"))
        FAIL_POINTS.clear()
        inst.ha.fence_worker(w.addr, False)  # cleanup: unfence for peers

    def test_dml_reply_drop_applies_exactly_once(self, kv_env):
        """THE exactly-once proof: the reply leg of a dml drops AFTER the
        worker executed it; the coordinator's retry re-sends the same uid and
        the worker's dedupe window replays the recorded result instead of
        double-applying."""
        s, inst, w = kv_env
        client = inst.workers[w.addr]
        stats0 = client.sync_action("worker_stats", {})
        FAIL_POINTS.arm(FP_RPC_DROP, {"op": "dml", "leg": "reply", "n": 1})
        rs = bounded(lambda: s.execute("INSERT INTO kv VALUES (777, 7)"))
        assert rs.affected == 1
        FAIL_POINTS.clear()
        try:
            rows = s.execute("SELECT count(*) FROM kv WHERE k = 777").rows
            assert rows == [(1,)], "retried DML double-applied!"
            stats1 = client.sync_action("worker_stats", {})
            assert stats1["dedupe_hits"] >= stats0["dedupe_hits"] + 1
        finally:
            s.execute("DELETE FROM kv WHERE k = 777")

    def test_ambiguous_primary_failure_aborts_explicit_txn(self, kv_env):
        """A primary DML whose every reply is lost POST-send has an UNKNOWN
        outcome: the explicit transaction must roll back (a later COMMIT
        could otherwise persist a write the client was told failed)."""
        s, inst, w = kv_env
        s.execute("BEGIN")
        # reply-leg drops: the worker EXECUTES the statement, the
        # coordinator never learns — the genuinely ambiguous class
        FAIL_POINTS.arm(FP_RPC_DROP, {"op": "dml", "leg": "reply", "n": 50})
        with pytest.raises(errors.TransactionError):
            bounded(lambda: s.execute("INSERT INTO kv VALUES (666, 6)"))
        FAIL_POINTS.clear()
        assert s.txn is None  # txn aborted, not left half-applied
        s.execute("COMMIT")   # no-op: nothing to persist
        # the failed attempts correctly tripped the breaker; recover it
        assert inst.workers[w.addr].ping()
        # the rollback undid the branch the worker had (ambiguously) applied
        assert s.execute(
            "SELECT count(*) FROM kv WHERE k = 666").rows == [(0,)]

    def test_presend_primary_failure_keeps_txn(self, kv_env):
        """A pre-send failure (nothing ever hit the wire) has a KNOWN
        outcome: statement-scoped error, the explicit txn survives — and a
        later COMMIT must not trip over a phantom branch registration."""
        s, inst, w = kv_env
        s.execute("BEGIN")
        FAIL_POINTS.arm(FP_RPC_FAIL_N, {"op": "dml", "n": 50})
        with pytest.raises(errors.TddlError) as ei:
            bounded(lambda: s.execute("INSERT INTO kv VALUES (667, 6)"))
        FAIL_POINTS.clear()
        assert not isinstance(ei.value, errors.TransactionError)
        assert s.txn is not None, "provably-unapplied failure killed the txn"
        assert inst.workers[w.addr].ping()  # failures tripped the breaker
        # the surviving txn keeps working against the recovered worker and
        # COMMITs cleanly (the never-opened branch was unregistered)
        s.execute("INSERT INTO kv VALUES (668, 8)")
        s.execute("COMMIT")
        try:
            assert s.execute("SELECT count(*) FROM kv "
                             "WHERE k IN (667, 668)").rows == [(1,)]
            assert s.execute(
                "SELECT v FROM kv WHERE k = 668").rows == [(8,)]
        finally:
            s.execute("DELETE FROM kv WHERE k = 668")

    def test_worker_reported_error_keeps_txn_alive(self, kv_env):
        """A worker-REPORTED statement error has a KNOWN outcome (nothing
        applied): MySQL statement-scoped semantics — the explicit txn
        survives, unlike the ambiguous transport-death case above."""
        s, inst, w = kv_env
        s.execute("BEGIN")
        s.execute("INSERT INTO kv VALUES (901, 1)")
        with pytest.raises(errors.TddlError):
            # worker-side bind error: column count mismatch
            bounded(lambda: s.execute("INSERT INTO kv VALUES (902)"))
        assert s.txn is not None, "statement error must not kill the txn"
        s.execute("ROLLBACK")
        assert s.execute(
            "SELECT count(*) FROM kv WHERE k = 901").rows == [(0,)]

    def test_dml_without_faults_unaffected(self, kv_env):
        s, inst, w = kv_env
        s.execute("INSERT INTO kv VALUES (888, 8)")
        try:
            assert s.execute(
                "SELECT v FROM kv WHERE k = 888").rows == [(8,)]
        finally:
            s.execute("DELETE FROM kv WHERE k = 888")


class TestDeadlines:
    def test_worker_aborts_past_deadline_fragment(self, kv_env):
        s, inst, w = kv_env
        client = inst.workers[w.addr]
        with pytest.raises(errors.QueryTimeoutError):
            client.request({"op": "exec_plan",
                            "fragment": {"schema": "w", "table": "kv",
                                         "columns": ["k"]},
                            "deadline_ms": 0})

    def test_deadline_during_rpc_dies_typed(self, kv_env):
        s, inst, w = kv_env
        s.execute("SET MAX_EXECUTION_TIME = 60")
        FAIL_POINTS.arm(FP_RPC_DELAY_MS, {"op": "exec_plan", "ms": 200})
        with pytest.raises(errors.QueryTimeoutError):
            bounded(lambda: s.execute("SELECT k FROM kv"))
        FAIL_POINTS.clear()
        s.execute("SET MAX_EXECUTION_TIME = 0")
        # typed death is observable: the timeout counter moved
        assert inst.metrics.counter("query_timeouts").value >= 1

    def test_dml_hint_deadline(self, kv_env):
        """The MAX_EXECUTION_TIME hint binds DML too: an expired deadline
        kills the shipped statement typed, before anything applies."""
        s, inst, w = kv_env
        FAIL_POINTS.arm(FP_RPC_DELAY_MS, {"op": "dml", "ms": 200})
        with pytest.raises(errors.QueryTimeoutError):
            bounded(lambda: s.execute(
                "/*+TDDL: MAX_EXECUTION_TIME(50)*/ "
                "INSERT INTO kv VALUES (555, 5)"))
        FAIL_POINTS.clear()
        assert s.execute(
            "SELECT count(*) FROM kv WHERE k = 555").rows == [(0,)]

    def test_breaker_hatch_applies_to_attached_workers(self, kv_env):
        """SET GLOBAL BREAKER_*/RPC_* must retune ALREADY-attached workers
        (the client reads the bound config live)."""
        s, inst, w = kv_env
        client = inst.workers[w.addr]
        assert client.failure_threshold == 3 and client.max_retries == 2
        s.execute("SET GLOBAL BREAKER_FAILURE_THRESHOLD = 7")
        s.execute("SET GLOBAL RPC_MAX_RETRIES = 5")
        try:
            assert client.failure_threshold == 7
            assert client.max_retries == 5
        finally:
            s.execute("SET GLOBAL BREAKER_FAILURE_THRESHOLD = 3")
            s.execute("SET GLOBAL RPC_MAX_RETRIES = 2")

    def test_hint_overrides_session_param(self, kv_env):
        s, inst, w = kv_env
        FAIL_POINTS.arm(FP_RPC_DELAY_MS, {"op": "exec_plan", "ms": 200})
        with pytest.raises(errors.QueryTimeoutError):
            bounded(lambda: s.execute(
                "/*+TDDL: MAX_EXECUTION_TIME(50)*/ SELECT k FROM kv"))
        FAIL_POINTS.clear()
        # no hint, no param: the same delayed scan completes fine
        FAIL_POINTS.arm(FP_RPC_DELAY_MS, {"op": "exec_plan", "ms": 60, "n": 1})
        assert len(bounded(lambda: s.execute("SELECT k FROM kv").rows)) == 3


class TestBreakerIntegration:
    def test_breaker_trips_fastfails_and_recovers(self):
        h = WorkerHarness(init_sql="CREATE DATABASE w; USE w; "
                          "CREATE TABLE t (a BIGINT PRIMARY KEY)")
        inst = Instance()
        s = Session(inst)
        try:
            s.execute("CREATE DATABASE w")
            s.execute("USE w")
            inst.attach_remote_table("w", "t", *h.addr)
            client = inst.workers[h.addr]
            client.timeout = 2.0
            assert s.execute("SELECT a FROM t").rows == []
            h.kill()
            h.wait_dead()
            with pytest.raises(errors.TddlError):
                bounded(lambda: s.execute("SELECT a FROM t"))
            assert client.breaker_state() == "open"
            # open breaker: fast typed failure, no connect timeout paid
            t0 = time.perf_counter()
            with pytest.raises(errors.WorkerUnavailableError):
                client.request({"op": "exec_plan", "fragment": {}})
            assert time.perf_counter() - t0 < 0.2
            h.restart()
            time.sleep(client.cooldown_s + 0.05)
            # half-open probe closes the breaker and the query serves again
            inst.ha.fence_worker(h.addr, False)
            assert bounded(lambda: s.execute("SELECT a FROM t").rows) == []
            assert client.breaker_state() == "closed"
            row = [r for r in s.execute("SHOW WORKERS").rows
                   if r[1] == h.addr[1]][0]
            assert row[2] == "closed" and row[7] >= 1  # breaker_opens
        finally:
            s.close()
            h.close()


class TestSyncEpochHealing:
    def test_missed_broadcast_heals_at_next_contact(self, kv_env):
        s, inst, w = kv_env
        client = inst.workers[w.addr]
        # establish the epoch plane on the worker
        inst.sync_bus.broadcast("invalidate_plan_cache", {})
        st0 = client.sync_action("worker_stats", {})
        # the worker misses this broadcast (every delivery attempt drops)
        FAIL_POINTS.arm(FP_RPC_DROP, {"op": "sync", "leg": "request", "n": 10})
        out = inst.sync_bus.broadcast("invalidate_fragment_cache",
                                      {"schema": "w", "table": "kv"})
        assert not out[0].get("ok")
        FAIL_POINTS.clear()
        # the failed deliveries tripped the breaker (correctly); a ping probe
        # closes it — pings carry no epoch, so the gap is still unhealed
        assert client.ping()
        # next DATA request carries the advanced epoch -> the worker detects
        # the gap and wholesale-invalidates its caches
        assert len(s.execute("SELECT k FROM kv").rows) == 3
        st1 = client.sync_action("worker_stats", {})
        assert st1["heals"] >= st0["heals"] + 1
        assert st1["sync_epochs"][inst.node_id] == inst.sync_bus.epoch


class TestXaCrashRecovery:
    def test_worker_crash_between_prepare_and_commit_resolves_once(
            self, tmp_path):
        """Satellite: kill the worker between XA prepare and commit, restart
        it, and recover_remote() resolves the branch exactly once."""
        h = WorkerHarness(
            init_sql="CREATE DATABASE w; USE w; "
                     "CREATE TABLE t (a BIGINT PRIMARY KEY, b BIGINT)",
            data_dir=str(tmp_path / "wdata"))
        inst = Instance()
        s = Session(inst)
        try:
            s.execute("CREATE DATABASE w")
            s.execute("USE w")
            inst.attach_remote_table("w", "t", *h.addr)
            client = inst.workers[h.addr]
            client.timeout = 5.0
            s.execute("BEGIN")
            s.execute("INSERT INTO t VALUES (1, 100)")
            # the worker exits hard when xa_commit arrives: prepare has
            # succeeded (durably), the commit point gets logged, the commit
            # apply never lands -> branch in doubt
            client.sync_action("failpoint", {"key": FP_WORKER_CRASH,
                                             "value": {"op": "xa_commit"}})
            with pytest.raises(errors.TransactionError) as ei:
                bounded(lambda: s.execute("COMMIT"))
            assert getattr(ei.value, "commit_ts", None) or \
                "in doubt" in str(ei.value)
            h.wait_dead()
            h.restart()
            # the failed commit opened the client breaker; an idle box
            # restarts the worker inside cooldown_s and recover_remote()
            # skips open-breaker workers by design — wait out the cooldown
            # so the half-open probe can close it
            time.sleep(client.cooldown_s + 0.05)
            out = bounded(lambda: inst.xa_coordinator.recover_remote())
            assert any(v == "committed" for v in out.values()), out
            inst.ha.fence_worker(h.addr, False)
            assert bounded(lambda: s.execute(
                "SELECT count(*), sum(b) FROM t").rows) == [(1, 100)]
            # second recovery pass: nothing left in doubt (exactly once)
            assert bounded(lambda: inst.xa_coordinator.recover_remote()) == {}
        finally:
            s.close()
            h.close()


class TestReplicaFailover:
    def test_read_failover_stale_exclusion_and_rebuild(self, primary):
        """Satellite: a dead replica read fails over WITHIN the statement;
        fenced/stale replicas are excluded from routing; attach_replica's
        backfill-needed detection still holds after failover."""
        rep = WorkerHarness()
        inst = Instance()
        s = Session(inst)
        try:
            s.execute("CREATE DATABASE w")
            s.execute("USE w")
            inst.attach_remote_table("w", "kv", *primary.addr)
            # huge weight: reads deterministically route to the replica
            inst.attach_replica("w", "kv", *rep.addr, weight=10 ** 6)
            # replica serves (and holds a backfilled copy)
            _c0, _t, rdata, _v = inst.workers[rep.addr].execute(
                "SELECT count(*) FROM kv", "w")
            assert int(next(iter(rdata.values()))[0]) == 3
            rep.kill()
            rep.wait_dead()
            inst.workers[rep.addr].timeout = 2.0
            f0 = WORKER_FAILOVERS.value
            # the read hits the dead replica and fails over mid-statement
            rows = bounded(lambda: s.execute(
                "SELECT k, v FROM kv ORDER BY k").rows)
            assert rows == [(1, 10), (2, 20), (3, 30)]
            assert WORKER_FAILOVERS.value >= f0 + 1
            assert inst.ha.worker_fenced(rep.addr)
            # a write marks the fenced replica STALE (excluded until rebuilt)
            s.execute("INSERT INTO kv VALUES (40, 400)")
            tm = inst.catalog.table("w", "kv")
            entry = [r for r in tm.replicas
                     if (r["host"], r["port"]) == rep.addr][0]
            assert entry["stale"] is True
            # stale replicas refuse re-attach without an explicit rebuild
            with pytest.raises(errors.TddlError):
                inst.attach_replica("w", "kv", *rep.addr)
            # restart empty -> backfill=True rebuilds and re-registers
            rep.restart()
            inst.ha.fence_worker(rep.addr, False)
            inst.workers[rep.addr].ping()  # close the breaker
            inst.attach_replica("w", "kv", *rep.addr, weight=10 ** 6,
                                backfill=True)
            assert entry["stale"] is False
            rows = bounded(lambda: s.execute(
                "SELECT k, v FROM kv ORDER BY k").rows)
            assert rows == [(1, 10), (2, 20), (3, 30), (40, 400)]
            _c, _t2, rdata, _v2 = inst.workers[rep.addr].execute(
                "SELECT count(*) FROM kv", "w")
            assert int(next(iter(rdata.values()))[0]) == 4
            s.execute("DELETE FROM kv WHERE k = 40")
        finally:
            s.close()
            rep.close()


# -- the randomized chaos matrix: TPC-H Q5 + concurrent point DML ------------


@pytest.fixture(scope="module")
def q5_env(primary):
    """TPC-H SF0.01 with `region` living on the worker: Q5's fragments span
    both processes, so RPC faults hit a real distributed query."""
    data = tpch.generate(0.01)
    inst = Instance()
    s = Session(inst)
    s.execute("CREATE DATABASE tpch")
    s.execute("USE tpch")
    for t in tpch.TABLE_ORDER:
        if t == "region":
            continue
        s.execute(tpch.TPCH_DDL[t])
        inst.store("tpch", t).insert_pylists(data[t],
                                             inst.tso.next_timestamp())
    s.execute("ANALYZE TABLE " + ", ".join(
        t for t in tpch.TABLE_ORDER if t != "region"))
    inst.attach_remote_table("tpch", "region", *primary.addr)
    s.execute("CREATE DATABASE w")  # for the concurrent-DML sessions
    yield s, inst, primary
    s.close()


# fixed fault-schedule matrix (the make chaos-smoke seed set): each entry is
# (name, [(key, value)...], q5_may_fail_typed)
SCHEDULES = [
    ("clean", [], False),
    ("fail1-plan", [(FP_RPC_FAIL_N, {"op": "exec_plan", "n": 1})], False),
    ("drop-reply-plan", [(FP_RPC_DROP,
                          {"op": "exec_plan", "leg": "reply", "n": 1})],
     False),
    ("delay-plan", [(FP_RPC_DELAY_MS, {"op": "exec_plan", "ms": 30, "n": 2})],
     False),
    ("drop-reply-dml", [(FP_RPC_DROP,
                         {"op": "dml", "leg": "reply", "n": 2})], False),
    ("hard-down", [(FP_RPC_FAIL_N, {"op": "exec_plan", "n": 50})], True),
]


class TestQ5ChaosMatrix:
    def _dml_storm(self, inst, base_key: int, n: int, acked: list):
        ses = Session(inst)
        ses.execute("USE w")
        try:
            for i in range(n):
                k = base_key + i
                try:
                    ses.execute(f"INSERT INTO kv VALUES ({k}, {k})")
                    acked.append(k)
                except errors.TddlError:
                    pass  # typed failure under faults is within contract
        finally:
            ses.close()

    @pytest.mark.parametrize(
        "name,faults,may_fail",
        SCHEDULES, ids=[sc[0] for sc in SCHEDULES])
    def test_q5_under_faults(self, q5_env, name, faults, may_fail):
        s, inst, w = q5_env
        inst.attach_remote_table("w", "kv", *w.addr)
        baseline = bounded(lambda: s.execute(QUERIES[5]).rows)
        assert baseline, "Q5 baseline empty — fixture broken"
        base_key = 10_000 + abs(hash(name)) % 1_000_000
        acked: list = []
        for key, value in faults:
            FAIL_POINTS.arm(key, value)
        t = threading.Thread(target=self._dml_storm,
                             args=(inst, base_key, 10, acked), daemon=True)
        t.start()
        try:
            rows = bounded(lambda: s.execute(QUERIES[5]).rows)
            assert rows == baseline, f"{name}: result drift under faults"
        except errors.TddlError:
            assert may_fail, f"{name}: unexpected typed failure"
        finally:
            t.join(timeout=RUN_BOUND_S)
            assert not t.is_alive(), f"{name}: DML storm hung"
            FAIL_POINTS.clear()
            inst.ha.fence_worker(w.addr, False)
            inst.workers[w.addr].ping()
        # exactly-once audit on the worker itself: every acked key exists
        # exactly once, no key double-applied
        cols, _t, data, _v = inst.workers[w.addr].execute(
            f"SELECT k, count(*) FROM kv WHERE k >= {base_key} "
            f"AND k < {base_key + 10} GROUP BY k", "w")
        got = dict(zip(data[cols[0]].tolist(), data[cols[1]].tolist()))
        assert all(c == 1 for c in got.values()), f"double-apply: {got}"
        for k in acked:
            assert got.get(k) == 1, f"acked key {k} missing/duplicated"
        # cleanup for the next schedule
        ses = Session(inst)
        ses.execute("USE w")
        ses.execute(f"DELETE FROM kv WHERE k >= {base_key} "
                    f"AND k < {base_key + 10}")
        ses.close()
