"""Index access paths: point-get via sorted key index, PointPlan fast path,
covering-GSI routing.

Reference analog: `DirectShardingKeyTableOperation` point plans chosen at
`polardbx-optimizer/.../core/planner/Planner.java:914,1864` and the XPlan
key-Get conversion (`RelToXPlanConverter.java:41-111`); GSI selection by the
CBO (SURVEY.md App.D).
"""

import threading

import numpy as np
import pytest

from galaxysql_tpu.server.instance import Instance
from galaxysql_tpu.server.session import Session


@pytest.fixture()
def sess():
    inst = Instance()
    s = Session(inst)
    s.execute("CREATE DATABASE apx")
    s.execute("USE apx")
    s.execute("""
        CREATE TABLE t (
            id BIGINT NOT NULL PRIMARY KEY,
            k  INT NOT NULL,
            v  VARCHAR(20),
            amt DECIMAL(12,2)
        ) PARTITION BY HASH(id) PARTITIONS 4
    """)
    rows = ", ".join(f"({i}, {i % 97}, 'v{i % 13}', {i}.25)"
                     for i in range(1, 2001))
    s.execute(f"INSERT INTO t (id, k, v, amt) VALUES {rows}")
    return inst, s


def test_point_eq_marks_scan_and_matches_full_scan(sess):
    inst, s = sess
    r = s.execute("SELECT amt FROM t WHERE id = 1234")
    assert r.rows == [(1234.25,)]
    # the scan trace records the index path, not a full partition scan
    assert any("point" in t for t in s.last_trace), s.last_trace


def test_point_plan_registered_and_reused(sess):
    inst, s = sess
    s.execute("SELECT amt FROM t WHERE id = 10")
    before = inst.counters.get("point_plan_queries", 0)
    r = s.execute("SELECT amt FROM t WHERE id = 11")
    assert r.rows == [(11.25,)]
    assert inst.counters.get("point_plan_queries", 0) == before + 1
    # NULL key matches nothing (SQL eq semantics)
    assert s.execute("SELECT amt FROM t WHERE id = 999999").rows == []


def test_point_plan_sees_own_txn_and_invalidates_on_ddl(sess):
    inst, s = sess
    s.execute("SELECT amt FROM t WHERE id = 42")  # register
    s.execute("BEGIN")
    s.execute("UPDATE t SET amt = 777.77 WHERE id = 42")
    assert s.execute("SELECT amt FROM t WHERE id = 42").rows == [(777.77,)]
    s.execute("ROLLBACK")
    assert s.execute("SELECT amt FROM t WHERE id = 42").rows == [(42.25,)]
    # another session must NOT see uncommitted changes through the fast path
    s2 = Session(inst, schema="apx")
    s.execute("BEGIN")
    s.execute("UPDATE t SET amt = 888.88 WHERE id = 42")
    assert s2.execute("SELECT amt FROM t WHERE id = 42").rows == [(42.25,)]
    s.execute("COMMIT")
    assert s2.execute("SELECT amt FROM t WHERE id = 42").rows == [(888.88,)]
    # DDL invalidates the cached point plan (schema_version keyed)
    s.execute("ALTER TABLE t ADD COLUMN extra INT")
    assert s.execute("SELECT amt FROM t WHERE id = 42").rows == [(888.88,)]


def test_point_on_string_key(sess):
    inst, s = sess
    s.execute("""
        CREATE TABLE su (name VARCHAR(30) NOT NULL PRIMARY KEY, n INT)
        PARTITION BY HASH(name) PARTITIONS 4
    """)
    s.execute("INSERT INTO su VALUES ('alpha', 1), ('beta', 2), ('gamma', 3)")
    assert s.execute("SELECT n FROM su WHERE name = 'beta'").rows == [(2,)]
    assert s.execute("SELECT n FROM su WHERE name = 'absent'").rows == []


def test_key_index_append_tail_and_lane_replacement(sess):
    inst, s = sess
    store = inst.store("apx", "t")
    # warm the index, then append new rows: the unsorted tail must be probed
    s.execute("SELECT amt FROM t WHERE id = 1")
    s.execute("INSERT INTO t (id, k, v, amt) VALUES (5001, 1, 'x', 9.99)")
    assert s.execute("SELECT amt FROM t WHERE id = 5001").rows == [(9.99,)]
    # column DDL replaces lanes -> indexes invalidate, lookups stay correct
    s.execute("ALTER TABLE t ADD COLUMN c2 INT DEFAULT 7")
    assert s.execute("SELECT amt, c2 FROM t WHERE id = 5001").rows == [(9.99, 7)]


def test_covering_gsi_route(sess):
    inst, s = sess
    s.execute("CREATE GLOBAL INDEX g_k ON t (k) COVERING (amt)")
    r = s.execute("EXPLAIN SELECT amt FROM t WHERE k = 55")
    plan_text = "\n".join(x[0] for x in r.rows)
    assert "t$g_k" in plan_text, plan_text
    got = sorted(s.execute("SELECT amt FROM t WHERE k = 55").rows)
    expect = sorted((i + 0.25,) for i in range(1, 2001) if i % 97 == 55)
    assert got == expect
    # non-covering reference keeps the base table
    r2 = s.execute("EXPLAIN SELECT v FROM t WHERE k = 55")
    assert "t$g_k" not in "\n".join(x[0] for x in r2.rows)


def test_gsi_route_correct_under_concurrent_dml(sess):
    inst, s = sess
    s.execute("CREATE GLOBAL INDEX g_k2 ON t (k) COVERING (amt)")
    stop = threading.Event()
    errors = []

    def writer():
        w = Session(inst, schema="apx")
        i = 10000
        try:
            while not stop.is_set():
                w.execute(f"INSERT INTO t (id, k, v, amt) "
                          f"VALUES ({i}, 55, 'w', 1.00)")
                w.execute(f"DELETE FROM t WHERE id = {i}")
                i += 1
        except Exception as e:  # pragma: no cover
            errors.append(e)

    th = threading.Thread(target=writer)
    th.start()
    try:
        base = sorted((i + 0.25,) for i in range(1, 2001) if i % 97 == 55)
        for _ in range(30):
            got = s.execute("SELECT amt FROM t WHERE k = 55").rows
            # every surviving row from the stable population must be present;
            # transient writer rows (amt=1.00) may appear and are fine
            stable = sorted(r for r in got if r != (1.0,))
            assert stable == base, (len(stable), len(base))
    finally:
        stop.set()
        th.join()
    assert not errors


def test_low_ndv_index_lead_not_point_routed(sess):
    """Equality on a 3-value local-index lead must NOT take the candidate
    path after ANALYZE: rows/NDV says it returns a third of the table."""
    inst, s = sess
    s.execute("CREATE INDEX i_low ON t (k)")  # local index on k (97 values)
    s.execute("CREATE TABLE lowt (id BIGINT PRIMARY KEY, st INT) "
              "PARTITION BY HASH(id) PARTITIONS 2")
    rows = ", ".join(f"({i}, {i % 3})" for i in range(1, 1001))
    s.execute(f"INSERT INTO lowt VALUES {rows}")
    s.execute("CREATE INDEX i_st ON lowt (st)")
    s.execute("ANALYZE TABLE lowt")
    from galaxysql_tpu.plan import logical as L
    plan = inst.planner.plan_select("SELECT id FROM lowt WHERE st = 1",
                                    "apx", [], s)
    scan = next(n for n in L.walk(plan.rel) if isinstance(n, L.Scan))
    # NDV=3 over 1000 rows -> est 333 candidates; under the 65536 guard the
    # point path IS still taken — verify the guard math flips for big tables
    # by checking the estimate feeds workload classification
    from galaxysql_tpu.plan.planner import scanned_rows_estimate
    est = scanned_rows_estimate(plan.rel)
    if scan.point_eq is not None:
        assert est >= 1000 / 3 - 1  # rows/NDV, not the flat point constant
    assert sorted(s.execute("SELECT id FROM lowt WHERE st = 1").rows)[:3] == \
        [(1,), (4,), (7,)]


def test_native_join_null_and_multikey():
    from galaxysql_tpu import native
    # NULL keys never match: both sides carry a null slot
    bk = np.array([1, 2, 3, 0], dtype=np.int64)
    bl = np.array([True, True, True, False])
    t = native.join_build_k1(bk, bl)
    pk = np.array([0, 2, 99], dtype=np.int64)
    b, p = native.join_probe_k1(pk, np.ones(3, bool), t)
    assert sorted(zip(p.tolist(), b.tolist())) == [(1, 1)]
    # generic (hash-combined) path: two key lanes
    h1 = native.hash_combine(None, np.array([1, 1, 2], np.int64), None)
    h1 = native.hash_combine(h1, np.array([7, 8, 7], np.int64), None)
    t2 = native.join_build(h1, np.ones(3, bool))
    h2 = native.hash_combine(None, np.array([1, 2], np.int64), None)
    h2 = native.hash_combine(h2, np.array([8, 7], np.int64), None)
    b2, p2 = native.join_probe(h2, np.ones(2, bool), h1, t2)
    assert sorted(zip(p2.tolist(), b2.tolist())) == [(0, 1), (1, 2)]
