"""Cold archive: TTL rows move to parquet; scans union hot + cold transparently."""

import numpy as np
import pytest

from galaxysql_tpu.server.instance import Instance
from galaxysql_tpu.server.session import Session
from galaxysql_tpu.types import temporal


@pytest.fixture()
def session(tmp_path):
    inst = Instance()
    inst.archive.directory = str(tmp_path / "arch")
    s = Session(inst)
    s.execute("CREATE DATABASE c; USE c")
    yield s
    s.close()


class TestArchive:
    def load(self, s, n=1000):
        s.execute("CREATE TABLE ev (id BIGINT, d DATE, tag VARCHAR(8), v BIGINT) "
                  "PARTITION BY HASH(id) PARTITIONS 4")
        base = temporal.parse_date("2020-01-01")
        store = s.instance.store("c", "ev")
        store.insert_arrays({
            "id": np.arange(n),
            "d": base + np.arange(n) % 400,          # dates spread over 400 days
            "tag": ["a" if i % 2 else "b" for i in range(n)],
            "v": np.arange(n) * 10,
        }, s.instance.tso.next_timestamp())
        s.execute("ANALYZE TABLE ev")
        return store, base

    def test_archive_and_transparent_scan(self, session):
        s = session
        store, base = self.load(s)
        before = s.execute("SELECT count(*), sum(v) FROM ev").rows
        cutoff = base + 200
        n = s.instance.archive.archive_older_than(s.instance, "c", "ev", "d", cutoff)
        assert n > 0
        # hot store shrank...
        assert store.row_count() == 1000 - n
        import os
        files = s.instance.archive.files_for("c.ev")
        assert files and os.path.getsize(files[0]) > 0
        # ...but queries still see everything (hot + cold union)
        after = s.execute("SELECT count(*), sum(v) FROM ev").rows
        assert after == before
        # filters and string predicates work over archived rows
        r1 = s.execute("SELECT count(*) FROM ev WHERE tag = 'a'").rows
        assert r1 == [(500,)]
        assert any("scan-archive" in t for t in s.last_trace)

    def test_archive_idempotent_rerun(self, session):
        s = session
        store, base = self.load(s, n=200)
        cutoff = base + 100
        n1 = s.instance.archive.archive_older_than(s.instance, "c", "ev", "d", cutoff)
        n2 = s.instance.archive.archive_older_than(s.instance, "c", "ev", "d", cutoff)
        assert n1 > 0 and n2 == 0  # nothing left to archive
        assert s.execute("SELECT count(*) FROM ev").rows == [(200,)]

    def test_archive_readable_by_parquet_tools(self, session):
        import pyarrow.parquet as pq
        s = session
        store, base = self.load(s, n=100)
        s.instance.archive.archive_older_than(s.instance, "c", "ev", "d",
                                              base + 1000)
        # one file per partition (written under the partition lock)
        tabs = [pq.read_table(f) for f in s.instance.archive.files_for("c.ev")]
        assert sum(t.num_rows for t in tabs) == 100
        for t in tabs:
            assert set(t.column_names) == {"id", "d", "tag", "v"}
            assert t.column("tag").to_pylist()[0] in ("a", "b")


class TestArchiveCrashSafety:
    def test_registry_survives_restart(self, tmp_path):
        d = str(tmp_path / "data")
        inst = Instance(data_dir=d)
        s = Session(inst)
        s.execute("CREATE DATABASE c; USE c")
        s.execute("CREATE TABLE ev (id BIGINT, d DATE)")
        base = temporal.parse_date("2020-01-01")
        inst.store("c", "ev").insert_arrays(
            {"id": np.arange(100), "d": base + np.arange(100)},
            inst.tso.next_timestamp())
        n = inst.archive.archive_older_than(inst, "c", "ev", "d", base + 50)
        assert n == 50
        inst.save()
        s.close()
        inst2 = Instance(data_dir=d)
        s2 = Session(inst2, "c")
        assert s2.execute("SELECT count(*) FROM ev").rows == [(100,)]
        assert inst2.archive.files_for("c.ev")
        s2.close()

    def test_pending_with_commit_point_promotes_on_boot(self, tmp_path):
        """Crash between the tx-log commit point and the LIVE manifest flip:
        boot must promote the PENDING file and re-commit the hot-store stamps
        (file and store always agree with the logged decision)."""
        d = str(tmp_path / "data")
        inst = Instance(data_dir=d)
        s = Session(inst)
        s.execute("CREATE DATABASE c; USE c")
        s.execute("CREATE TABLE ev (id BIGINT, d DATE)")
        base = temporal.parse_date("2020-01-01")
        inst.store("c", "ev").insert_arrays(
            {"id": np.arange(100), "d": base + np.arange(100)},
            inst.tso.next_timestamp())
        n = inst.archive.archive_older_than(inst, "c", "ev", "d", base + 50)
        assert n == 50
        # simulate the crash window: demote the manifest to PENDING + tx log
        # rewound to COMMITTED, stamps rewound to the provisional intent
        rows = inst.metadb.query(
            "SELECT path, arc_txn, archive_ts FROM archive_files")
        for path, arc_txn, ats in rows:
            inst.metadb.execute(
                "UPDATE archive_files SET state='PENDING' WHERE path=?", (path,))
            inst.metadb.tx_log_put(arc_txn, "COMMITTED", ats)
            for p in inst.store("c", "ev").partitions:
                mine = p.end_ts == ats
                p.end_ts[mine] = -arc_txn
        inst.save()
        s.close()
        inst2 = Instance(data_dir=d)
        s2 = Session(inst2, "c")
        # no lost rows, no duplicates: 50 hot + 50 archived exactly once
        assert s2.execute("SELECT count(*) FROM ev").rows == [(100,)]
        states = {st for (st,) in inst2.metadb.query(
            "SELECT state FROM archive_files")}
        assert states == {"LIVE"}
        s2.close()

    def test_snapshot_never_double_counts(self, session):
        s = session
        inst = s.instance
        s.execute("CREATE TABLE sn (id BIGINT, d DATE)")
        base = temporal.parse_date("2020-01-01")
        inst.store("c", "sn").insert_arrays(
            {"id": np.arange(10), "d": base + np.arange(10)},
            inst.tso.next_timestamp())
        s.execute("BEGIN")  # snapshot taken before archival
        assert s.execute("SELECT count(*) FROM sn").rows == [(10,)]
        s2 = Session(inst, "c")
        inst.archive.archive_older_than(inst, "c", "sn", "d", base + 100)
        # old-snapshot txn: still 10, not 20 (hot copies visible, archive skipped)
        assert s.execute("SELECT count(*) FROM sn").rows == [(10,)]
        s.execute("COMMIT")
        assert s.execute("SELECT count(*) FROM sn").rows == [(10,)]
        s2.close()

    def test_null_ttl_never_archives(self, session):
        s = session
        inst = s.instance
        s.execute("CREATE TABLE nl (id BIGINT, d DATE)")
        s.execute("INSERT INTO nl VALUES (1, '2000-01-01'), (2, NULL)")
        base = temporal.parse_date("2020-01-01")
        n = inst.archive.archive_older_than(inst, "c", "nl", "d", base)
        assert n == 1  # only the dated row; NULL never expires
        assert s.execute("SELECT count(*) FROM nl WHERE d IS NULL").rows == [(1,)]


class TestSargPruning:
    def test_minmax_stats_skip_refuted_files(self, tmp_path):
        """Parquet min-max stats prune whole archive files against scan SARGs
        (OSSTableScanExec.java:45-61 analog); pruning never changes results."""
        import numpy as np
        from galaxysql_tpu.server.instance import Instance
        from galaxysql_tpu.server.session import Session
        inst = Instance()
        s = Session(inst)
        s.execute("CREATE DATABASE ar")
        s.execute("USE ar")
        s.execute("CREATE TABLE ev (id BIGINT PRIMARY KEY, d DATE, v BIGINT)")
        from galaxysql_tpu.types import temporal
        today = temporal.days_from_civil(2026, 7, 29)
        store = inst.store("ar", "ev")
        # two disjoint archive epochs: ids 0..99 (old), 100..199 (older still)
        for base, age in ((0, 400), (100, 800)):
            store.insert_pylists(
                {"id": list(range(base, base + 100)),
                 "d": [temporal.format_date(today - age)] * 100,
                 "v": [base] * 100},
                inst.tso.next_timestamp())
            n = inst.archive.archive_older_than(inst, "ar", "ev", "d",
                                                today - age + 1)
            assert n == 100
        am = inst.archive
        before = am.pruned_files
        # id >= 150 refutes the first file (ids 0..99) by its max stat
        r = s.execute("SELECT count(*) FROM ev WHERE id >= 150")
        assert r.rows == [(50,)]
        assert am.pruned_files > before  # at least one file skipped
        # unconstrained scan still sees every archived row
        assert s.execute("SELECT count(*) FROM ev").rows == [(200,)]
        s.close()
