"""Flashback snapshot reads (AS OF TSO) and user-level named locks (GET_LOCK).

Reference analogs: `polardbx-optimizer/src/test/java/.../planner/flashback/`
(the MVCC+TSO engine makes historical reads nearly free) and
`polardbx-common/.../common/lock/LockingFunctionManager.java`.
"""

import threading
import time

import pytest

from galaxysql_tpu.server.instance import Instance
from galaxysql_tpu.server.session import Session


@pytest.fixture()
def session():
    inst = Instance()
    s = Session(inst)
    s.execute("CREATE DATABASE f")
    s.execute("USE f")
    yield s
    s.close()


class TestFlashback:
    def test_as_of_returns_old_snapshot(self, session):
        session.execute("CREATE TABLE t (id BIGINT PRIMARY KEY, v BIGINT)")
        session.execute("INSERT INTO t VALUES (1, 10), (2, 20)")
        ts1 = session.instance.tso.next_timestamp()
        session.execute("UPDATE t SET v = 99 WHERE id = 1")
        session.execute("DELETE FROM t WHERE id = 2")
        session.execute("INSERT INTO t VALUES (3, 30)")
        # current state
        assert session.execute("SELECT id, v FROM t ORDER BY id").rows == \
            [(1, 99), (3, 30)]
        # historical state at ts1
        assert session.execute(
            f"SELECT id, v FROM t AS OF TSO {ts1} ORDER BY id").rows == \
            [(1, 10), (2, 20)]

    def test_as_of_with_alias_and_filter(self, session):
        session.execute("CREATE TABLE u (id BIGINT, v VARCHAR(8))")
        session.execute("INSERT INTO u VALUES (1, 'old')")
        ts1 = session.instance.tso.next_timestamp()
        session.execute("UPDATE u SET v = 'new' WHERE id = 1")
        r = session.execute(
            f"SELECT x.v FROM u AS OF TSO {ts1} x WHERE x.id = 1")
        assert r.rows == [("old",)]
        assert session.execute("SELECT v FROM u").rows == [("new",)]

    def test_as_of_ignores_own_txn_writes(self, session):
        session.execute("CREATE TABLE w (id BIGINT)")
        session.execute("INSERT INTO w VALUES (1)")
        ts1 = session.instance.tso.next_timestamp()
        session.execute("BEGIN")
        session.execute("INSERT INTO w VALUES (2)")
        # txn read sees own write; flashback read does not
        assert len(session.execute("SELECT id FROM w").rows) == 2
        assert len(session.execute(
            f"SELECT id FROM w AS OF TSO {ts1}").rows) == 1
        session.execute("ROLLBACK")

    def test_as_of_on_view_or_cte_refuses(self, session):
        # silent wrong-snapshot results are worse than refusal (review finding)
        session.execute("CREATE TABLE vt (id BIGINT)")
        session.execute("CREATE VIEW vv AS SELECT id FROM vt")
        from galaxysql_tpu.utils import errors as E
        with pytest.raises(E.NotSupportedError):
            session.execute("SELECT * FROM vv AS OF TSO 5")
        with pytest.raises(E.NotSupportedError):
            session.execute(
                "WITH c AS (SELECT id FROM vt) SELECT * FROM c AS OF TSO 5")


class TestGetLock:
    def test_acquire_release(self, session):
        assert session.execute("SELECT GET_LOCK('m', 0)").rows == [(1,)]
        assert session.execute("SELECT IS_FREE_LOCK('m')").rows == [(0,)]
        assert session.execute("SELECT IS_USED_LOCK('m')").rows == \
            [(session.conn_id,)]
        assert session.execute("SELECT RELEASE_LOCK('m')").rows == [(1,)]
        assert session.execute("SELECT IS_FREE_LOCK('m')").rows == [(1,)]
        # releasing a lock nobody holds -> NULL
        assert session.execute("SELECT RELEASE_LOCK('m')").rows == [(None,)]

    def test_reentrant_same_session(self, session):
        assert session.execute("SELECT GET_LOCK('r', 0)").rows == [(1,)]
        assert session.execute("SELECT GET_LOCK('r', 0)").rows == [(1,)]
        assert session.execute("SELECT RELEASE_LOCK('r')").rows == [(1,)]
        # still held (count 2 -> 1)
        assert session.execute("SELECT IS_FREE_LOCK('r')").rows == [(0,)]
        assert session.execute("SELECT RELEASE_LOCK('r')").rows == [(1,)]
        assert session.execute("SELECT IS_FREE_LOCK('r')").rows == [(1,)]

    def test_blocks_across_sessions(self, session):
        s2 = Session(session.instance, schema="f")
        assert session.execute("SELECT GET_LOCK('b', 0)").rows == [(1,)]
        # a second session times out while the first holds it
        assert s2.execute("SELECT GET_LOCK('b', 0.1)").rows == [(0,)]
        # other-session release returns 0 (not the owner)
        assert s2.execute("SELECT RELEASE_LOCK('b')").rows == [(0,)]

        got = []

        def waiter():
            got.append(s2.execute("SELECT GET_LOCK('b', 5)").rows[0][0])

        thr = threading.Thread(target=waiter)
        thr.start()
        time.sleep(0.2)
        assert not got  # still blocked
        session.execute("SELECT RELEASE_LOCK('b')")
        thr.join(5)
        assert got == [1]  # woke up and acquired
        s2.close()

    def test_session_close_releases(self, session):
        s2 = Session(session.instance, schema="f")
        assert s2.execute("SELECT GET_LOCK('c', 0)").rows == [(1,)]
        s2.close()
        assert session.execute("SELECT GET_LOCK('c', 0.5)").rows == [(1,)]
