"""TPC-H differential test: every query runs on both galaxysql_tpu and sqlite3 over the
same generated data; results must match (with float tolerance).

This is the engine's correctness anchor — the analog of the reference's TPC-H planner
golden suite (SURVEY.md §4), but checking *results*, which a from-scratch engine needs
more than plan shapes.
"""

import math
import re
import sqlite3

import numpy as np
import pytest

from galaxysql_tpu.server.instance import Instance
from galaxysql_tpu.server.session import Session
from galaxysql_tpu.storage import tpch
from galaxysql_tpu.storage.tpch_queries import QUERIES
from galaxysql_tpu.types import temporal

SF = 0.01


@pytest.fixture(scope="module")
def env():
    data = tpch.generate(SF)

    inst = Instance()
    s = Session(inst)
    s.execute("CREATE DATABASE tpch")
    s.execute("USE tpch")
    for t in tpch.TABLE_ORDER:
        s.execute(tpch.TPCH_DDL[t])
        store = inst.store("tpch", t)
        store.insert_pylists(data[t], inst.tso.next_timestamp())
    s.execute("ANALYZE TABLE " + ", ".join(tpch.TABLE_ORDER))

    db = sqlite3.connect(":memory:")
    db.create_function("year_of", 1, lambda d: temporal.civil_from_days(int(d))[0])
    for t in tpch.TABLE_ORDER:
        cols = list(data[t].keys())
        decls = []
        for c in cols:
            v = data[t][c][0] if data[t][c] else 0
            decls.append(f"{c} {'TEXT' if isinstance(v, str) else 'NUMERIC'}")
        db.execute(f"CREATE TABLE {t} ({', '.join(decls)})")
        rows = list(zip(*[data[t][c] for c in cols]))
        db.executemany(f"INSERT INTO {t} VALUES ({','.join('?' * len(cols))})", rows)
    db.commit()
    yield s, db
    s.close()
    db.close()


_DATE_ARITH = re.compile(
    r"date\s+'(\d{4}-\d{2}-\d{2})'(?:\s*([+-])\s*interval\s+'(\d+)'\s+(day|month|year))?",
    re.IGNORECASE)
_EXTRACT = re.compile(r"extract\s*\(\s*year\s+from\s+([a-z0-9_.]+)\s*\)", re.IGNORECASE)


def to_sqlite(q: str) -> str:
    def fold(m):
        days = temporal.parse_date(m.group(1))
        if m.group(2):
            n = int(m.group(3))
            if m.group(2) == "-":
                n = -n
            unit = m.group(4).lower()
            if unit == "day":
                days += n
            elif unit == "month":
                days = temporal.add_interval_months(days, n)
            else:
                days = temporal.add_interval_months(days, n * 12)
        return str(days)

    q = _DATE_ARITH.sub(fold, q)
    q = _EXTRACT.sub(r"year_of(\1)", q)

    # constant decimal arithmetic: sqlite uses binary float64 (0.06 + 0.01 =
    # 0.06999...), while MySQL/our engine use exact decimals; fold to exact values
    def dec_fold(m):
        from decimal import Decimal
        a, op, b = Decimal(m.group(1)), m.group(2), Decimal(m.group(3))
        return str(a + b if op == "+" else a - b)

    q = re.sub(r"(\d+\.\d+)\s*([+-])\s*(\d+\.\d+)", dec_fold, q)
    return q


def normalize(rows, has_order):
    out = []
    for r in rows:
        nr = []
        for v in r:
            if isinstance(v, float):
                nr.append(round(v, 2))
            elif isinstance(v, str) and re.fullmatch(r"\d{4}-\d{2}-\d{2}", v):
                nr.append(temporal.parse_date(v))  # date as days for comparison
            else:
                nr.append(v)
        out.append(tuple(nr))
    if not has_order:
        out.sort(key=lambda r: tuple(str(x) for x in r))
    return out


def rows_close(a, b):
    if len(a) != len(b):
        return False, f"row count {len(a)} != {len(b)}"
    for i, (ra, rb) in enumerate(zip(a, b)):
        if len(ra) != len(rb):
            return False, f"row {i} arity"
        for j, (va, vb) in enumerate(zip(ra, rb)):
            if va is None and vb is None:
                continue
            if isinstance(va, (int, float)) and isinstance(vb, (int, float)):
                tol = max(abs(float(vb)) * 1e-4, 0.02)
                if not math.isclose(float(va), float(vb), abs_tol=tol):
                    return False, f"row {i} col {j}: {va} != {vb}"
            elif va != vb:
                return False, f"row {i} col {j}: {va!r} != {vb!r}"
    return True, ""


ORDERED = {1, 2, 3, 4, 5, 7, 8, 9, 10, 11, 12, 13, 15, 16, 18, 20, 21, 22}


@pytest.mark.parametrize("qid", sorted(QUERIES))
def test_query(env, qid):
    session, db = env
    q = QUERIES[qid]
    mine = session.execute(q)
    theirs = db.execute(to_sqlite(q)).fetchall()
    a = normalize(mine.rows, qid in ORDERED)
    b = normalize(theirs, qid in ORDERED)
    # dates come back as 'yyyy-mm-dd' from our engine, ints from sqlite: normalize
    # handled above.  Compare.
    okk, msg = rows_close(a, b)
    if not okk and qid in ORDERED:
        # ties in ORDER BY keys may legitimately reorder; retry order-insensitive
        okk, msg = rows_close(sorted(a, key=lambda r: tuple(str(x) for x in r)),
                              sorted(b, key=lambda r: tuple(str(x) for x in r)))
    assert okk, f"Q{qid}: {msg}\nmine: {a[:5]}\nsqlite: {b[:5]}"
