"""Cross-query fragment cache: versioned hash-join build reuse, deterministic
subplan results, and cached runtime-filter publications.

The `fragment_cache`-marked tests are the fast smoke target (`make
cache-smoke`): warm (second-execution) results must be identical to
`FRAGMENT_CACHE(OFF)` on TPC-H Q3/Q5/Q9 and SSB Q2.1, locally and on the
8-device mesh, and every invalidation edge (DML/DDL version bumps, txn-local
writes, flashback reads, cross-coordinator SyncBus) must never serve a stale
read with the cache enabled by default.
"""

import numpy as np
import pytest

from galaxysql_tpu.exec import fragment_cache as fcmod
from galaxysql_tpu.exec.fragment_cache import (CachedSubplanOp, FragmentCache,
                                               fingerprint)
from galaxysql_tpu.server.instance import Instance
from galaxysql_tpu.server.session import Session


def _rows_equal(a, b):
    keyed = lambda rows: sorted(rows, key=lambda r: tuple(str(x) for x in r))
    assert keyed(a) == keyed(b)


# -- unit: cache mechanics ----------------------------------------------------


class TestCacheMechanics:
    def test_lru_byte_budget_and_evictions(self):
        c = FragmentCache(budget_bytes=1000)
        for i in range(5):
            assert c.put(("k", i), i, 300, frozenset({"s.t"}), "subplan")
        assert c.bytes <= 1000
        assert c.evictions > 0
        assert c.get(("k", 4)) == 4          # MRU survived
        assert c.get(("k", 0)) is None       # LRU evicted

    def test_entry_above_cap_rejected(self):
        c = FragmentCache(budget_bytes=1 << 30)
        assert not c.put(("big",), 1, c.entry_max_bytes + 1,
                         frozenset(), "join_build")
        assert c.admission_rejects == 1
        assert len(c) == 0

    def test_memory_pool_gates_admission(self):
        from galaxysql_tpu.exec.memory import MemoryPool
        parent = MemoryPool("test-root", 500)
        c = FragmentCache(budget_bytes=10_000, mem_parent=parent)
        assert c.put(("a",), 1, 400, frozenset(), "subplan")
        # a second 400b entry exceeds the PARENT pool: LRU shed, then admit
        assert c.put(("b",), 2, 400, frozenset(), "subplan")
        assert c.get(("a",)) is None
        assert parent.reserved <= 500

    def test_revoker_sheds_bytes_under_pressure(self):
        from galaxysql_tpu.exec.memory import MemoryPool
        parent = MemoryPool("test-root", 1000)
        c = FragmentCache(budget_bytes=1000, mem_parent=parent)
        c.put(("a",), 1, 600, frozenset(), "subplan")
        # memory pressure at the shared parent walks into the cache's pool
        # revoker: cached fragments are shed before queries start spilling
        released = parent.revoke(500)
        assert released >= 500
        assert len(c) == 0
        assert parent.reserved == 0

    def test_invalidate_table_frees_bytes(self):
        c = FragmentCache()
        c.put(("a",), 1, 100, frozenset({"d.x"}), "subplan")
        c.put(("b",), 2, 100, frozenset({"d.y"}), "subplan")
        assert c.invalidate_table("d.x") == 1
        assert c.get(("b",)) == 2
        assert c.bytes == 100
        assert c.pool.reserved == 100

    def test_epoch_bump_invalidates(self):
        c = FragmentCache()
        e0 = c.epoch("w.dim")
        c.put(("r", e0), 1, 10, frozenset({"w.dim"}), "subplan")
        c.bump_epoch("w.dim")
        assert c.epoch("w.dim") == e0 + 1
        assert c.get(("r", e0)) is None

    def test_concurrent_put_keeps_first_and_exact_bytes(self):
        c = FragmentCache()
        assert c.put(("k",), "first", 50, frozenset(), "subplan")
        assert c.put(("k",), "second", 50, frozenset(), "subplan")
        assert c.get(("k",)) == "first"
        assert c.bytes == 50
        assert c.pool.reserved == 50

    def test_cached_subplan_op_streams_and_caches(self):
        from galaxysql_tpu.chunk.batch import batch_from_pydict
        from galaxysql_tpu.exec.operators import SourceOp
        from galaxysql_tpu.types import datatype as dt
        b = batch_from_pydict({"k": [1, 2, 3]}, {"k": dt.BIGINT})
        c = FragmentCache()
        fkey = fcmod.FragKey(("frag", "x"), frozenset({"d.t"}))
        pulls = []

        class Counting(SourceOp):
            def batches(self):
                pulls.append(1)
                yield from super().batches()

        op = CachedSubplanOp(Counting([b]), c, fkey)
        assert len(list(op.batches())) == 1
        assert len(list(op.batches())) == 1
        assert len(pulls) == 1  # second pull served from cache
        assert c.hits >= 1


# -- fingerprints -------------------------------------------------------------


@pytest.fixture()
def joined_session():
    inst = Instance()
    s = Session(inst)
    s.execute("CREATE DATABASE f; USE f")
    s.execute("CREATE TABLE dim (id BIGINT PRIMARY KEY, name VARCHAR(16))")
    s.execute("CREATE TABLE fact (id BIGINT, v BIGINT)")
    s.execute("INSERT INTO dim VALUES (1,'a'),(2,'b'),(3,'c')")
    s.execute("INSERT INTO fact VALUES " +
              ",".join(f"({i % 3 + 1},{i})" for i in range(400)))
    yield s
    s.close()


JOIN_Q = ("SELECT d.name, sum(f.v) FROM fact f JOIN dim d ON f.id = d.id "
          "GROUP BY d.name ORDER BY d.name")


def _plan_ctx(s, sql):
    from galaxysql_tpu.plan.physical import ExecContext
    inst = s.instance
    plan = inst.planner.plan_select(sql, s.schema)
    ctx = ExecContext(inst.stores, inst.tso.next_timestamp(), [],
                      archive=inst.archive, archive_instance=inst,
                      hints=getattr(plan, "hints", None))
    return plan, ctx


class TestFingerprint:
    def test_version_bump_changes_key(self, joined_session):
        s = joined_session
        plan, ctx = _plan_ctx(s, "SELECT id, name FROM dim")
        f1 = fingerprint(plan.rel, ctx)
        assert f1 is not None and f1.tables == frozenset({"f.dim"})
        s.execute("INSERT INTO dim VALUES (4,'d')")
        plan2, ctx2 = _plan_ctx(s, "SELECT id, name FROM dim")
        f2 = fingerprint(plan2.rel, ctx2)
        assert f2 is not None and f2.key != f1.key

    def test_literals_are_value_sensitive(self, joined_session):
        s = joined_session
        p1, c1 = _plan_ctx(s, "SELECT id FROM dim WHERE id > 1")
        p2, c2 = _plan_ctx(s, "SELECT id FROM dim WHERE id > 2")
        assert fingerprint(p1.rel, c1).key != fingerprint(p2.rel, c2).key

    def test_flashback_scan_uncacheable(self, joined_session):
        s = joined_session
        ts = s.instance.tso.next_timestamp()
        plan, ctx = _plan_ctx(s, f"SELECT id FROM dim AS OF TSO {ts}")
        assert fingerprint(plan.rel, ctx) is None

    def test_txn_write_set_bypasses(self, joined_session):
        s = joined_session
        plan, ctx = _plan_ctx(s, "SELECT id, name FROM dim")
        store = s.instance.store("f", "dim")
        ctx.txn_id = 77
        ctx.txn_write_uids = frozenset({store.uid})
        assert fingerprint(plan.rel, ctx) is None
        ctx.txn_write_uids = frozenset()   # writes elsewhere: cacheable
        assert fingerprint(plan.rel, ctx) is not None
        ctx.txn_write_uids = None          # unknown write set: bypass
        assert fingerprint(plan.rel, ctx) is None

    def test_old_snapshot_bypasses(self, joined_session):
        s = joined_session
        old_snap = s.instance.tso.next_timestamp()
        s.execute("INSERT INTO dim VALUES (9,'i')")
        plan, ctx = _plan_ctx(s, "SELECT id, name FROM dim")
        assert fingerprint(plan.rel, ctx) is not None
        ctx.snapshot_ts = old_snap  # predates the settled stamp: bypass
        assert fingerprint(plan.rel, ctx) is None

    def test_outside_runtime_filter_target_bypasses(self, joined_session):
        s = joined_session
        plan, ctx = _plan_ctx(s, JOIN_Q)
        from galaxysql_tpu.plan import logical as L
        scans = [n for n in L.walk(plan.rel) if isinstance(n, L.Scan)]
        target = next((n for n in scans if n.rf_targets), None)
        if target is None:
            pytest.skip("planner planted no filter on this shape")
        # the scan ALONE is masked by a filter produced outside it: bypass
        assert fingerprint(target, ctx) is None
        # the whole tree contains the producing join: self-contained
        assert fingerprint(plan.rel, ctx) is not None

    def test_information_schema_uncacheable(self, joined_session):
        s = joined_session
        s.execute("SELECT table_name FROM information_schema.tables")
        plan, ctx = _plan_ctx(
            s, "SELECT table_name FROM information_schema.tables")
        assert fingerprint(plan.rel, ctx) is None


# -- end-to-end: equivalence + invalidation -----------------------------------


@pytest.mark.fragment_cache
class TestEndToEnd:
    def test_warm_join_hits_and_matches(self, joined_session):
        s = joined_session
        fc = s.instance.frag_cache
        fc.clear()
        cold = s.execute(JOIN_Q)
        assert len(fc) > 0
        h0 = fc.hits
        warm = s.execute(JOIN_Q)
        assert fc.hits > h0
        # the aggregate-replay lane serves the whole warm query
        assert any("frag-subplan hit" in t for t in s.last_trace)
        _rows_equal(cold.rows, warm.rows)
        off = s.execute("/*+TDDL:FRAGMENT_CACHE(OFF)*/ " + JOIN_Q)
        _rows_equal(warm.rows, off.rows)
        # with the replay entries dropped, the join-build artifact lane
        # engages: the probe pipeline runs against the cached build
        fc.drop_kind("subplan")
        again = s.execute(JOIN_Q)
        assert any("frag-cache build hit" in t for t in s.last_trace)
        _rows_equal(again.rows, off.rows)

    def test_dml_invalidates(self, joined_session):
        s = joined_session
        s.execute(JOIN_Q)
        s.execute(JOIN_Q)  # warm
        s.execute("INSERT INTO dim VALUES (7,'g')")
        s.execute("INSERT INTO fact VALUES (7, 1000)")
        got = s.execute(JOIN_Q)
        assert ("g", 1000) in [tuple(r) for r in got.rows]
        off = s.execute("/*+TDDL:FRAGMENT_CACHE(OFF)*/ " + JOIN_Q)
        _rows_equal(got.rows, off.rows)

    def test_update_and_delete_invalidate(self, joined_session):
        s = joined_session
        s.execute(JOIN_Q)
        s.execute(JOIN_Q)
        s.execute("UPDATE dim SET name = 'zz' WHERE id = 1")
        got = s.execute(JOIN_Q)
        assert any(r[0] == "zz" for r in got.rows)
        s.execute("DELETE FROM dim WHERE id = 2")
        got2 = s.execute(JOIN_Q)
        assert not any(r[0] == "b" for r in got2.rows)
        _rows_equal(got2.rows,
                    s.execute("/*+TDDL:FRAGMENT_CACHE(OFF)*/ " + JOIN_Q).rows)

    def test_ddl_invalidates(self, joined_session):
        s = joined_session
        s.execute(JOIN_Q)
        s.execute(JOIN_Q)
        s.execute("ALTER TABLE dim ADD COLUMN extra BIGINT")
        got = s.execute("SELECT d.name, sum(f.v) FROM fact f JOIN dim d "
                        "ON f.id = d.id GROUP BY d.name ORDER BY d.name")
        _rows_equal(got.rows,
                    s.execute("/*+TDDL:FRAGMENT_CACHE(OFF)*/ " + JOIN_Q).rows)

    def test_txn_local_writes_bypass(self, joined_session):
        s = joined_session
        s.execute(JOIN_Q)
        s.execute(JOIN_Q)  # warm
        s.execute("BEGIN")
        s.execute("INSERT INTO dim VALUES (8,'h')")
        s.execute("INSERT INTO fact VALUES (8, 500)")
        # the txn must see its OWN uncommitted rows despite the warm cache
        got = s.execute(JOIN_Q)
        assert ("h", 500) in [tuple(r) for r in got.rows]
        s.execute("ROLLBACK")
        got2 = s.execute(JOIN_Q)
        assert not any(r[0] == "h" for r in got2.rows)
        # another session is never served the txn-local view
        s2 = Session(s.instance, schema="f")
        _rows_equal(s2.execute(JOIN_Q).rows, got2.rows)
        s2.close()

    def test_flashback_bypasses(self, joined_session):
        s = joined_session
        ts1 = s.instance.tso.next_timestamp()
        s.execute("INSERT INTO dim VALUES (6,'f')")
        s.execute("INSERT INTO fact VALUES (6, 99)")
        s.execute(JOIN_Q)
        s.execute(JOIN_Q)  # warm at current snapshot
        old = s.execute(
            "SELECT d.name, sum(f2.v) FROM fact AS OF TSO %d f2 "
            "JOIN dim AS OF TSO %d d ON f2.id = d.id "
            "GROUP BY d.name ORDER BY d.name" % (ts1, ts1))
        assert not any(r[0] == "f" for r in old.rows)

    def test_env_and_config_escape_hatches(self, joined_session, monkeypatch):
        s = joined_session
        fc = s.instance.frag_cache
        monkeypatch.setattr(fcmod, "ENABLED", False)
        fc.clear()
        s.execute(JOIN_Q)
        assert len(fc) == 0
        monkeypatch.setattr(fcmod, "ENABLED", True)
        s.execute("SET GLOBAL ENABLE_FRAGMENT_CACHE = 0")
        s.execute(JOIN_Q)
        assert len(fc) == 0
        s.execute("SET GLOBAL ENABLE_FRAGMENT_CACHE = 1")
        s.execute(JOIN_Q)
        assert len(fc) > 0

    def test_observability_surfaces(self, joined_session):
        s = joined_session
        s.execute(JOIN_Q)
        s.execute(JOIN_Q)
        rows = s.execute("SHOW FRAGMENT CACHE").rows
        assert rows and any("f.dim" in r[1] for r in rows)
        names = {r[0] for r in s.execute("SHOW METRICS").rows}
        assert {"frag_cache_hits", "frag_cache_misses", "frag_cache_bytes",
                "frag_cache_evictions"} <= names
        isr = s.execute("SELECT entry_kind, tables FROM "
                        "information_schema.fragment_cache").rows
        assert any("f.dim" in r[1] for r in isr)

    def test_explain_analyze_cached_build_tag(self, joined_session):
        s = joined_session
        s.execute(JOIN_Q)  # warm the artifact
        lines = s.execute("EXPLAIN ANALYZE " + JOIN_Q).rows
        text = "\n".join(r[0] for r in lines)
        assert "[cached build]" in text


# -- TPC-H / SSB equivalence (the acceptance bar) -----------------------------


@pytest.fixture(scope="module")
def tpch_session():
    from galaxysql_tpu.storage import tpch
    data = tpch.generate(0.01)
    inst = Instance()
    s = Session(inst)
    s.execute("CREATE DATABASE tpch")
    s.execute("USE tpch")
    for t in tpch.TABLE_ORDER:
        s.execute(tpch.TPCH_DDL[t])
        inst.store("tpch", t).insert_arrays(data[t], inst.tso.next_timestamp())
    s.execute("ANALYZE TABLE " + ", ".join(tpch.TABLE_ORDER))
    yield s
    s.close()


@pytest.mark.fragment_cache
class TestTpchEquivalence:
    """Warm (cache-hitting) executions must be BIT-identical to
    FRAGMENT_CACHE(OFF): the cached artifacts replay the same arrays through
    the same kernels, so even float aggregation order is unchanged."""

    @pytest.mark.parametrize("qid", [3, 5, 9])
    def test_cache_on_equals_off(self, tpch_session, qid):
        from galaxysql_tpu.storage.tpch_queries import QUERIES
        s = tpch_session
        s.instance.frag_cache.clear()
        cold = s.execute(QUERIES[qid])
        warm = s.execute(QUERIES[qid])
        off = s.execute("/*+TDDL:FRAGMENT_CACHE(OFF)*/ " + QUERIES[qid])
        assert cold.rows == warm.rows == off.rows

    def test_q5_actually_hits(self, tpch_session):
        from galaxysql_tpu.storage.tpch_queries import QUERIES
        s = tpch_session
        s.instance.frag_cache.clear()
        s.execute(QUERIES[5])
        h0 = s.instance.frag_cache.hits
        s.execute(QUERIES[5])
        assert s.instance.frag_cache.hits > h0


@pytest.mark.fragment_cache
class TestSsbEquivalence:
    def test_ssb_q21(self):
        from galaxysql_tpu.storage import ssb
        data = ssb.generate(0.005)
        inst = Instance()
        s = Session(inst)
        s.execute("CREATE DATABASE ssb; USE ssb")
        for t in ssb.TABLE_ORDER:
            s.execute(ssb.SSB_DDL[t])
            inst.store("ssb", t).insert_arrays(data[t],
                                               inst.tso.next_timestamp())
        s.execute("ANALYZE TABLE " + ", ".join(ssb.TABLE_ORDER))
        cold = s.execute(ssb.QUERIES["2.1"])
        warm = s.execute(ssb.QUERIES["2.1"])
        off = s.execute("/*+TDDL:FRAGMENT_CACHE(OFF)*/ " + ssb.QUERIES["2.1"])
        assert cold.rows == warm.rows == off.rows
        s.close()


@pytest.mark.fragment_cache
@pytest.mark.slow  # compiles MPP shard programs; covered by `make cache-smoke`
class TestMeshEquivalence:
    @pytest.mark.parametrize("qid", [3, 5, 9])
    def test_mpp_cache_on_equals_off(self, tpch_session, qid):
        import jax
        from galaxysql_tpu.parallel.mpp import MppExecutor
        from galaxysql_tpu.storage.tpch_queries import QUERIES
        inst = tpch_session.instance
        mesh = inst.mesh()
        if mesh is None or len(jax.devices()) < 8:
            pytest.skip("no 8-device mesh")
        inst.frag_cache.clear()

        def run(sql):
            plan, ctx = _plan_ctx(tpch_session, sql)
            return MppExecutor(ctx, mesh).execute(plan.rel), ctx
        cold, _ = run(QUERIES[qid])
        warm, wctx = run(QUERIES[qid])
        off, _ = run("/*+TDDL:FRAGMENT_CACHE(OFF)*/ " + QUERIES[qid])
        assert cold.to_pylist() == warm.to_pylist() == off.to_pylist()
        assert any("frag-cache mpp" in t for t in wctx.trace)
        # the per-shard build-reuse lane under the aggregate replay
        inst.frag_cache.drop_kind("mpp_agg")
        again, actx = run(QUERIES[qid])
        assert again.to_pylist() == off.to_pylist()
        assert any("frag-cache mpp build hit" in t for t in actx.trace)

    def test_mesh_ssb_q21(self):
        import jax
        from galaxysql_tpu.parallel.mpp import MppExecutor
        from galaxysql_tpu.storage import ssb
        data = ssb.generate(0.005)
        inst = Instance()
        s = Session(inst)
        s.execute("CREATE DATABASE ssb; USE ssb")
        for t in ssb.TABLE_ORDER:
            s.execute(ssb.SSB_DDL[t])
            inst.store("ssb", t).insert_arrays(data[t],
                                               inst.tso.next_timestamp())
        s.execute("ANALYZE TABLE " + ", ".join(ssb.TABLE_ORDER))
        mesh = inst.mesh()
        if mesh is None or len(jax.devices()) < 8:
            s.close()
            pytest.skip("no 8-device mesh")

        def run(sql):
            plan, ctx = _plan_ctx(s, sql)
            return MppExecutor(ctx, mesh).execute(plan.rel)
        cold = run(ssb.QUERIES["2.1"])
        warm = run(ssb.QUERIES["2.1"])
        off = run("/*+TDDL:FRAGMENT_CACHE(OFF)*/ " + ssb.QUERIES["2.1"])
        assert cold.to_pylist() == warm.to_pylist() == off.to_pylist()
        s.close()
