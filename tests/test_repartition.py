"""Online repartition (`ALTER TABLE ... PARTITION BY ... PARTITIONS n`) + MDL.

Reference analog: the scale-out job family (`executor/balancer/Balancer.java`,
`ddl/job/task/gsi/RepartitionCutOverTask`) and the per-CN metadata lock manager
(`executor/mdl/MdlManager.java:35`): shadow backfill -> catchup -> verify ->
cutover under the table's exclusive MDL, resumable after a crash, correct under
concurrent DML.
"""

import threading
import time

import pytest

from galaxysql_tpu.server.instance import Instance
from galaxysql_tpu.server.session import Session
from galaxysql_tpu.utils import errors
from galaxysql_tpu.utils.failpoint import FAIL_POINTS, FailPointError


@pytest.fixture()
def session():
    inst = Instance()
    s = Session(inst)
    s.execute("CREATE DATABASE rp")
    s.execute("USE rp")
    yield s
    FAIL_POINTS.clear()
    s.close()


def load(session, n=1000, parts=2):
    session.execute(
        "CREATE TABLE t (id BIGINT PRIMARY KEY, grp BIGINT, val VARCHAR(16)) "
        f"PARTITION BY HASH(id) PARTITIONS {parts}")
    store = session.instance.store("rp", "t")
    store.insert_pylists(
        {"id": list(range(n)), "grp": [i % 37 for i in range(n)],
         "val": [f"v{i % 11}" for i in range(n)]},
        session.instance.tso.next_timestamp())
    return store


def snapshot(session):
    return session.execute("SELECT id, grp, val FROM t ORDER BY id").rows


class TestRepartition:
    def test_end_to_end_row_identity(self, session):
        load(session, n=1000, parts=2)
        before = snapshot(session)
        session.execute("ALTER TABLE t PARTITION BY HASH(grp) PARTITIONS 8")
        tm = session.instance.catalog.table("rp", "t")
        assert tm.partition.num_partitions == 8
        assert tm.partition.columns == ["grp"]
        store = session.instance.store("rp", "t")
        assert len(store.partitions) == 8
        assert snapshot(session) == before
        # the shadow table is gone
        with pytest.raises(errors.UnknownTableError):
            session.instance.catalog.table("rp", "t$repart")
        # new DML routes by the NEW partitioning
        session.execute("INSERT INTO t VALUES (5000, 3, 'nv')")
        assert session.execute(
            "SELECT grp FROM t WHERE id = 5000").rows == [(3,)]
        from galaxysql_tpu.meta.catalog import hash_partition_of
        import numpy as np
        for pid, p in enumerate(store.partitions):
            if p.num_rows:
                assert (hash_partition_of(p.lanes["grp"], 8) == pid).all()

    def test_crash_mid_backfill_resumes(self, session):
        from galaxysql_tpu.ddl import repartition as rp
        load(session, n=2000, parts=2)
        before = snapshot(session)
        old_chunk = rp.RepartitionBackfillTask.CHUNK
        rp.RepartitionBackfillTask.CHUNK = 128
        try:
            FAIL_POINTS.arm(rp.FP_REPART_PAUSE, 5)
            with pytest.raises(FailPointError):
                session.execute(
                    "ALTER TABLE t PARTITION BY HASH(id) PARTITIONS 6")
            FAIL_POINTS.clear()
            resumed = session.instance.ddl_engine.recover()
            assert resumed
            tm = session.instance.catalog.table("rp", "t")
            assert tm.partition.num_partitions == 6
            assert snapshot(session) == before  # complete, no duplicates
        finally:
            rp.RepartitionBackfillTask.CHUNK = old_chunk

    def test_dml_between_crash_and_resume_is_caught_up(self, session):
        """Writes landing after the backfill snapshot must reach the new
        partitions via the catchup delta (insert + delete decomposition)."""
        from galaxysql_tpu.ddl import repartition as rp
        load(session, n=1500, parts=2)
        old_chunk = rp.RepartitionBackfillTask.CHUNK
        rp.RepartitionBackfillTask.CHUNK = 128
        try:
            FAIL_POINTS.arm(rp.FP_REPART_PAUSE, 4)
            with pytest.raises(FailPointError):
                session.execute(
                    "ALTER TABLE t PARTITION BY HASH(grp) PARTITIONS 5")
            FAIL_POINTS.clear()
            # concurrent DML while the job is interrupted mid-copy
            session.execute("INSERT INTO t VALUES (9001, 1, 'late')")
            session.execute("DELETE FROM t WHERE id = 7")
            session.execute("UPDATE t SET val = 'upd' WHERE id = 11")
            assert session.instance.ddl_engine.recover()
            rows = dict((r[0], (r[1], r[2])) for r in snapshot(session))
            assert rows[9001] == (1, "late")
            assert 7 not in rows
            assert rows[11][1] == "upd"
            assert len(rows) == 1500  # 1500 - deleted + inserted
        finally:
            rp.RepartitionBackfillTask.CHUNK = old_chunk

    def test_cutover_waits_for_open_reader(self, session):
        load(session, n=300, parts=2)
        mdl = session.instance.mdl
        done = threading.Event()
        acquired = threading.Event()

        def reader():
            with mdl.shared(["rp.t"]):
                acquired.set()
                time.sleep(0.8)
            done.set()

        thr = threading.Thread(target=reader)
        thr.start()
        acquired.wait(5)
        t0 = time.time()
        session.execute("ALTER TABLE t PARTITION BY HASH(id) PARTITIONS 4")
        elapsed = time.time() - t0
        thr.join()
        assert done.is_set()  # cutover waited for the reader to drain
        assert elapsed >= 0.3
        assert session.instance.catalog.table(
            "rp", "t").partition.num_partitions == 4

    def test_queries_blocked_while_exclusive_held(self, session):
        load(session, n=100, parts=2)
        mdl = session.instance.mdl
        assert mdl.acquire_exclusive("rp.t", 1)
        try:
            with pytest.raises(errors.TddlError, match="MDL"):
                s2 = Session(session.instance, schema="rp")
                mdl_timeout = 0.2
                with mdl.shared(["rp.t"], timeout=mdl_timeout):
                    pass
        finally:
            mdl.release_exclusive("rp.t")
        # after release, queries flow again
        assert session.execute("SELECT count(*) FROM t").rows == [(100,)]

    def test_parse_rejects_mixed_actions(self, session):
        load(session, n=10, parts=2)
        with pytest.raises(errors.NotSupportedError):
            session.execute(
                "ALTER TABLE t ADD COLUMN x BIGINT, PARTITION BY HASH(id) "
                "PARTITIONS 4")

    def test_repartition_to_fewer_partitions(self, session):
        load(session, n=400, parts=4)
        before = snapshot(session)
        session.execute("ALTER TABLE t PARTITION BY HASH(id) PARTITIONS 2")
        assert snapshot(session) == before
        assert len(session.instance.store("rp", "t").partitions) == 2
