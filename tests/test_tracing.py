"""Distributed span tracing: TraceContext span trees (operators, fused
segments, MPP shard subtrees, worker-process graft), node-prefixed trace ids,
the Histogram metric type, Chrome-trace export, error spans, and the
tracing-off hot-path guard.

The `tracing`-marked tests are the fast smoke target (`make trace-smoke`).
"""

import json
import os
import subprocess
import sys
import threading
import urllib.request

import numpy as np
import pytest

from galaxysql_tpu.exec import operators as ops
from galaxysql_tpu.server.instance import Instance
from galaxysql_tpu.server.session import Session
from galaxysql_tpu.utils import tracing
from galaxysql_tpu.utils.metrics import Histogram, MetricsRegistry


def _spans_of(inst, trace_id):
    p = inst.profiles.get(trace_id)
    assert p is not None
    return p.spans


def _last_tid(s):
    return int(s.last_trace[0].split()[-1])  # "trace-id N"


def _assert_tree_closed(spans):
    """Every non-root parent resolves INSIDE this query's own span set — a
    span grafted from (or leaked to) another query would break closure."""
    ids = {sp.span_id for sp in spans}
    assert len(ids) == len(spans), "duplicate span ids"
    roots = [sp for sp in spans if sp.parent_id == 0]
    assert len(roots) == 1 and roots[0].kind == "query"
    for sp in spans:
        if sp.parent_id:
            assert sp.parent_id in ids, (sp.name, sp.parent_id)


# -- trace ids ----------------------------------------------------------------


@pytest.mark.tracing
class TestTraceIds:
    def test_two_instances_never_collide(self):
        a = tracing.TraceIdAllocator("cn-aaaa0001")
        b = tracing.TraceIdAllocator("cn-bbbb0002")
        ida = [a.next() for _ in range(100)]
        idb = [b.next() for _ in range(100)]
        assert not set(ida) & set(idb)
        assert ida == sorted(ida) and idb == sorted(idb)  # monotonic per node
        assert all(i > 0 for i in ida + idb)  # BIGINT-safe, truthy
        assert tracing.trace_node_hash(ida[0]) == \
            tracing.trace_node_hash(ida[-1])
        assert tracing.trace_node_hash(ida[0]) != \
            tracing.trace_node_hash(idb[0])

    def test_profile_ring_lookup_by_string(self):
        from galaxysql_tpu.utils.tracing import ProfileRing, QueryProfile
        ring = ProfileRing()
        ring.record(QueryProfile(trace_id=12345, sql="x", schema="s",
                                 conn_id=1))
        assert ring.get("12345").trace_id == 12345
        assert ring.get("nonsense") is None
        assert ring.get(999) is None


# -- histogram metric ---------------------------------------------------------


class TestHistogram:
    def test_quantiles_and_reservoir(self):
        h = Histogram("lat_ms", "latency", reservoir=256)
        for v in range(1, 101):
            h.observe(float(v))
        assert h.count == 100 and h.sum == 5050.0
        qs = h.quantiles()
        assert 45 <= qs[0.5] <= 55
        assert 90 <= qs[0.95] <= 100
        assert 94 <= qs[0.99] <= 100
        # reservoir stays bounded under heavy load
        for v in range(10_000):
            h.observe(float(v % 7))
        assert len(h._buf) <= 256 and h.count == 10_100

    def test_registry_rows_and_prometheus_summary(self):
        reg = MetricsRegistry(namespace="t")
        h = reg.histogram("query_latency_ms", "query latency")
        for v in (1.0, 2.0, 3.0, 4.0):
            h.observe(v)
        names = {n for n, _k, _v, _h in reg.rows()}
        assert {"query_latency_ms_p50", "query_latency_ms_p95",
                "query_latency_ms_p99", "query_latency_ms_count",
                "query_latency_ms_sum"} <= names
        text = reg.prometheus_text()
        assert "# TYPE t_query_latency_ms summary" in text
        assert 't_query_latency_ms{quantile="0.5"}' in text
        assert "t_query_latency_ms_count 4" in text
        with pytest.raises(TypeError):
            reg.counter("query_latency_ms")

    def test_instance_exports_latency_quantiles(self):
        inst = Instance()
        s = Session(inst)
        s.execute("CREATE DATABASE hq; USE hq; CREATE TABLE t (a BIGINT)")
        s.execute("SELECT count(*) FROM t")
        rows = {r[0] for r in s.execute("SHOW METRICS").rows}
        assert "query_latency_ms_p95" in rows
        assert "segment_wall_ms_p95" in rows
        assert "rpc_rtt_ms_p95" in rows
        s.close()


# -- local span trees ---------------------------------------------------------


@pytest.mark.tracing
class TestLocalSpanTree:
    @pytest.fixture(scope="class")
    def session(self):
        inst = Instance()
        s = Session(inst)
        s.execute("CREATE DATABASE tr")
        s.execute("USE tr")
        s.execute("CREATE TABLE t (a BIGINT PRIMARY KEY, b BIGINT)")
        inst.store("tr", "t").insert_pylists(
            {"a": list(range(5000)), "b": [i % 13 for i in range(5000)]},
            inst.tso.next_timestamp())
        yield s
        s.close()

    def test_traced_query_builds_nested_tree(self, session):
        s = session
        s.vars["ENABLE_QUERY_TRACING"] = True
        try:
            r = s.execute("SELECT a, b * 2 FROM t WHERE a < 500")
        finally:
            s.vars.pop("ENABLE_QUERY_TRACING", None)
        assert len(r.rows) == 500
        spans = _spans_of(s.instance, _last_tid(s))
        _assert_tree_closed(spans)
        by_kind = {}
        for sp in spans:
            by_kind.setdefault(sp.kind, []).append(sp)
        root = by_kind["query"][0]
        assert root.dur_us > 0 and root.attrs["schema"] == "tr"
        # operator spans nest under the root (plan tree = span tree)
        assert by_kind.get("operator"), [s.kind for s in spans]
        ids = {sp.span_id: sp for sp in spans}
        for op in by_kind["operator"]:
            cur = op
            while cur.parent_id:
                cur = ids[cur.parent_id]
            assert cur is root
        # the fused filter>project dispatch is a CHILD span, not a flat list
        segs = by_kind.get("segment", [])
        assert any("filter" in sp.name for sp in segs)
        assert all(sp.parent_id for sp in segs)
        assert all(sp.node == s.instance.node_id for sp in spans)

    def test_compile_events_attributed(self, session):
        s = session
        s.vars["ENABLE_QUERY_TRACING"] = True
        try:
            # a brand-new expression shape forces at least one fresh program
            s.execute("SELECT a * 7 + 1, b - 2 FROM t WHERE a < 321")
        finally:
            s.vars.pop("ENABLE_QUERY_TRACING", None)
        spans = _spans_of(s.instance, _last_tid(s))
        compiles = [sp for sp in spans if sp.kind == "compile"]
        assert compiles, [sp.kind for sp in spans]
        assert all(sp.attrs.get("wall_ms", 0) >= 0 for sp in compiles)

    def test_show_trace_renders_tree_then_clears(self, session):
        s = session
        s.vars["ENABLE_QUERY_TRACING"] = True
        try:
            s.execute("SELECT count(*) FROM t")
        finally:
            s.vars.pop("ENABLE_QUERY_TRACING", None)
        lines = [r[0] for r in s.execute("SHOW TRACE").rows]
        assert any(l.startswith("query [query]") for l in lines), lines
        # tracing off again: the next query's SHOW TRACE has no stale tree
        s.execute("SELECT count(*) FROM t")
        lines = [r[0] for r in s.execute("SHOW TRACE").rows]
        assert not any("[query]" in l for l in lines)

    def test_query_spans_virtual_table(self, session):
        s = session
        s.vars["ENABLE_QUERY_TRACING"] = True
        try:
            s.execute("SELECT a FROM t WHERE a < 9")
        finally:
            s.vars.pop("ENABLE_QUERY_TRACING", None)
        tid = _last_tid(s)
        r = s.execute(
            "SELECT span_name, kind, parent_id FROM "
            f"information_schema.query_spans WHERE trace_id = {tid}")
        kinds = {row[1] for row in r.rows}
        assert "query" in kinds and "operator" in kinds
        assert any(row[2] == 0 for row in r.rows)  # exactly the root

    def test_chrome_trace_export_endpoint(self, session):
        from galaxysql_tpu.server.web import WebConsole
        s = session
        web = WebConsole(s.instance)
        port = web.start()
        try:
            s.vars["ENABLE_QUERY_TRACING"] = True
            try:
                s.execute("SELECT a, b FROM t WHERE b = 3")
            finally:
                s.vars.pop("ENABLE_QUERY_TRACING", None)
            tid = _last_tid(s)
            with urllib.request.urlopen(
                    f"http://127.0.0.1:{port}/trace/{tid}", timeout=10) as r:
                d = json.loads(r.read())
            assert d["otherData"]["trace_id"] == str(tid)
            evs = [e for e in d["traceEvents"] if e["ph"] == "X"]
            assert evs
            for e in evs:
                assert {"name", "cat", "ts", "dur", "pid", "tid"} <= set(e)
            assert any(e["cat"] == "query" for e in evs)
            # an untraced query's id 404s instead of returning an empty tree
            s.execute("SELECT count(*) FROM t")
            with pytest.raises(urllib.error.HTTPError):
                urllib.request.urlopen(
                    f"http://127.0.0.1:{port}/trace/{_last_tid(s)}",
                    timeout=10)
        finally:
            web.stop()

    def test_error_spans_and_slow_log(self, session):
        from galaxysql_tpu.utils.tracing import SLOW_LOG
        s = session
        SLOW_LOG.clear()
        s.execute("SET SLOW_SQL_MS = 0")
        s.vars["ENABLE_QUERY_TRACING"] = True
        try:
            with pytest.raises(Exception):
                s.execute("SELECT no_such_column FROM t")
        finally:
            s.vars.pop("ENABLE_QUERY_TRACING", None)
            s.execute("SET SLOW_SQL_MS = -1")
        p = s.instance.profiles.entries()[-1]
        assert p.error.startswith("UnknownColumnError")
        assert p.elapsed_ms >= 0
        _assert_tree_closed(p.spans)  # the error span must NOT be a 2nd root
        err_spans = [sp for sp in p.spans if sp.kind == "error"]
        assert err_spans and err_spans[0].attrs["errno"] == 1054
        assert err_spans[0].parent_id == p.spans[0].span_id
        # SHOW SLOW explains the failure: elapsed recorded + error column
        rows = s.execute("SHOW SLOW").rows
        assert any(row[3] == p.trace_id and row[5] == "UnknownColumnError"
                   for row in rows), rows
        # SHOW TRACE shows the failed query's tree with the error span
        lines = [r[0] for r in s.execute("SHOW TRACE").rows]
        assert any("error" in l for l in lines)


# -- concurrent sessions: no span cross-talk ----------------------------------


@pytest.mark.tracing
class TestConcurrentTracing:
    def test_two_sessions_isolated_trees(self):
        inst = Instance()
        s0 = Session(inst)
        s0.execute("CREATE DATABASE ctr; USE ctr")
        s0.execute("CREATE TABLE big (a BIGINT, b BIGINT)")
        s0.execute("CREATE TABLE small (a BIGINT, b BIGINT)")
        inst.store("ctr", "big").insert_pylists(
            {"a": list(range(3000)), "b": list(range(3000))},
            inst.tso.next_timestamp())
        inst.store("ctr", "small").insert_pylists(
            {"a": list(range(700)), "b": list(range(700))},
            inst.tso.next_timestamp())
        results = {}
        barrier = threading.Barrier(2)

        def run(name, table, rounds=6):
            s = Session(inst, "ctr")
            s.vars["ENABLE_QUERY_TRACING"] = True
            barrier.wait()
            tids = []
            for _ in range(rounds):
                s.execute(f"SELECT a, b + 1 FROM {table} WHERE a >= 0")
                tids.append(_last_tid(s))
            results[name] = tids
            s.close()

        t1 = threading.Thread(target=run, args=("big", "big"))
        t2 = threading.Thread(target=run, args=("small", "small"))
        t1.start(); t2.start()
        t1.join(); t2.join()
        for name in ("big", "small"):
            for tid in results[name]:
                spans = _spans_of(inst, tid)
                _assert_tree_closed(spans)
                root = spans[0]
                assert root.kind == "query"
                assert name in root.attrs["sql"], (name, root.attrs)
        s0.close()


# -- MPP: one span subtree per shard ------------------------------------------


@pytest.mark.tracing
class TestMppShardSpans:
    def test_stage_tree_with_shard_children(self):
        inst = Instance()
        if inst.mesh() is None:
            pytest.skip("single device: no MPP mesh")
        S = inst.mesh().shape["shard"]
        s = Session(inst)
        s.execute("CREATE DATABASE mtr; USE mtr")
        s.execute("CREATE TABLE big (k VARCHAR(4), v BIGINT)")
        rng = np.random.default_rng(0)
        inst.store("mtr", "big").insert_arrays(
            {"k": np.array(["x", "y", "z"])[rng.integers(0, 3, 60_000)],
             "v": rng.integers(0, 1000, 60_000)}, inst.tso.next_timestamp())
        s.execute("ANALYZE TABLE big")
        s.vars["MPP_MIN_AP_ROWS"] = 1000
        s.vars["ENABLE_QUERY_TRACING"] = True
        r = s.execute("SELECT k, sum(v) FROM big GROUP BY k ORDER BY k")
        assert len(r.rows) == 3
        p = inst.profiles.entries()[-1]
        assert p.engine == "mpp"
        _assert_tree_closed(p.spans)
        stages = [sp for sp in p.spans if sp.kind == "stage"]
        assert any(sp.name == "mpp:Scan" for sp in stages), \
            [sp.name for sp in stages]
        scan = next(sp for sp in stages if sp.name == "mpp:Scan")
        shards = [sp for sp in p.spans
                  if sp.kind == "shard" and sp.parent_id == scan.span_id]
        assert len(shards) == S
        assert sum(sp.attrs["rows"] for sp in shards) == 60_000
        # chrome export: one tid row per shard
        ct = tracing.chrome_trace(p.trace_id, p.spans)
        tids = {e["tid"] for e in ct["traceEvents"]
                if e.get("cat") == "shard"}
        assert len(tids) == S
        s.close()


# -- worker process: grafted spans --------------------------------------------


INIT_SQL = (
    "CREATE DATABASE w; USE w; "
    "CREATE TABLE dim (k BIGINT PRIMARY KEY, label VARCHAR(16)); "
    "INSERT INTO dim VALUES (1,'alpha'), (2,'beta'), (3,'gamma'), (4,'delta')"
)


@pytest.mark.tracing
class TestWorkerSpanGraft:
    @pytest.fixture(scope="class")
    def worker_session(self):
        env = dict(os.environ, JAX_PLATFORMS="cpu")
        p = subprocess.Popen(
            [sys.executable, "-m", "galaxysql_tpu.net.worker", "--port", "0",
             "--platform", "cpu", "--init-sql", INIT_SQL],
            cwd=os.path.dirname(os.path.dirname(os.path.abspath(__file__))),
            stdout=subprocess.PIPE, stderr=subprocess.PIPE, env=env,
            text=True)
        line = p.stdout.readline()
        if not line.startswith("WORKER_READY"):
            err = p.stderr.read()[-3000:] if p.stderr else ""
            raise AssertionError(f"worker failed to start: {line!r}\n{err}")
        port = int(line.split()[1])
        inst = Instance()
        s = Session(inst)
        s.execute("CREATE DATABASE w")
        s.execute("USE w")
        inst.attach_remote_table("w", "dim", "127.0.0.1", port)
        yield s
        s.close()
        if p.poll() is None:
            p.kill()
            p.wait()

    def test_worker_spans_graft_under_rpc_span(self, worker_session):
        s = worker_session
        s.vars["ENABLE_QUERY_TRACING"] = True
        try:
            r = s.execute("SELECT k, label FROM dim ORDER BY k")
        finally:
            s.vars.pop("ENABLE_QUERY_TRACING", None)
        assert len(r.rows) == 4
        spans = _spans_of(s.instance, _last_tid(s))
        _assert_tree_closed(spans)  # ONE tree: graft remints ids + parents
        coord = s.instance.node_id
        worker_spans = [sp for sp in spans if sp.node and sp.node != coord]
        assert worker_spans, "no grafted worker-side spans"
        rpc = [sp for sp in spans if sp.kind == "rpc"]
        assert rpc and rpc[0].attrs.get("worker_spans", 0) >= 1
        assert "clock_offset_us" in rpc[0].attrs
        # the worker's subtree nests under the coordinator's rpc span
        ids = {sp.span_id: sp for sp in spans}
        rpc_ids = {sp.span_id for sp in rpc}
        for sp in worker_spans:
            cur = sp
            seen_rpc = False
            while cur.parent_id:
                cur = ids[cur.parent_id]
                if cur.span_id in rpc_ids:
                    seen_rpc = True
            assert seen_rpc, (sp.name, sp.node)
        # the fragment executed worker-side: scan + serialize child spans
        names = {sp.name for sp in worker_spans}
        assert any(n.startswith("worker:") for n in names), names
        assert "scan" in names and "serialize" in names, names
        # clock correction keeps worker spans inside the query's envelope
        root = spans[0]
        for sp in worker_spans:
            assert sp.start_us >= root.start_us - 1_000_000
            assert sp.start_us <= root.start_us + root.dur_us + 1_000_000


# -- tracing off: bit-identical results, unchanged dispatch count -------------


@pytest.mark.tracing
@pytest.mark.slow
class TestTracingEquivalenceTpchQ5:
    def test_q5_traced_vs_untraced_bit_identical(self):
        from galaxysql_tpu.storage import tpch
        from galaxysql_tpu.storage.tpch_queries import QUERIES
        data = tpch.generate(0.01)
        inst = Instance()
        s = Session(inst)
        s.execute("CREATE DATABASE tpch")
        s.execute("USE tpch")
        for t in tpch.TABLE_ORDER:
            s.execute(tpch.TPCH_DDL[t])
            inst.store("tpch", t).insert_pylists(
                data[t], inst.tso.next_timestamp())
        s.execute("ANALYZE TABLE " + ", ".join(tpch.TABLE_ORDER))
        plain = s.execute(QUERIES[5])
        # drop the compiled-program cache so the traced run pays (and records)
        # fresh trace+compile events — the compile-attribution acceptance shape
        with ops._JIT_CACHE_LOCK:
            ops._JIT_CACHE.clear()
        s.vars["ENABLE_QUERY_TRACING"] = True
        traced = s.execute(QUERIES[5])
        tid = _last_tid(s)
        s.vars.pop("ENABLE_QUERY_TRACING", None)
        assert traced.rows == plain.rows  # bit-identical, not approximate
        spans = _spans_of(inst, tid)
        _assert_tree_closed(spans)
        assert any(sp.kind == "operator" for sp in spans)
        assert any(sp.kind == "compile" for sp in spans), \
            sorted({sp.kind for sp in spans})
        json.dumps(tracing.chrome_trace(tid, spans))  # well-formed export
        # hot-path guard: a traced run must not perturb the untraced steady
        # state (same programs, same dispatch count, no stats variants)
        s.execute(QUERIES[5])  # settle
        ops.reset_dispatch_stats()
        s.execute(QUERIES[5])
        baseline = ops.DISPATCH_STATS["dispatches"]
        s.vars["ENABLE_QUERY_TRACING"] = True
        s.execute(QUERIES[5])
        s.vars.pop("ENABLE_QUERY_TRACING", None)
        ops.reset_dispatch_stats()
        s.execute(QUERIES[5])
        assert ops.DISPATCH_STATS["dispatches"] == baseline
        s.close()


@pytest.mark.tracing
class TestTracingOffFastPath:
    def test_no_trace_context_when_disabled(self):
        inst = Instance()
        inst.config.set_instance("ENABLE_QUERY_TRACING", 0)
        s = Session(inst)
        s.execute("CREATE DATABASE off; USE off; CREATE TABLE t (a BIGINT)")
        inst.store("off", "t").insert_pylists(
            {"a": list(range(100))}, inst.tso.next_timestamp())
        s.execute("SELECT count(*) FROM t")
        p = inst.profiles.entries()[-1]
        assert p.spans == [] and not p.error
        assert tracing.current() is None
        s.close()
