"""Columnar HTAP replica: CDC-fed delta+base tier, stats-driven routing.

The contract under test (storage/columnar.py): a replica read routed at
watermark W is BIT-identical to a row-store read at W — through sustained
DML, compaction racing in-flight views, DDL mid-tail (reseed), and
crash/restart resume from the persisted watermark.  Plus the routing gates
(size signal, read-your-writes fence, freshness SLA, txn/point bypass), the
hatch trio, and the SHOW / information_schema / EXPLAIN surfaces.

Tests run the tailer synchronously (COLUMNAR_POLL_MS=0 disables the thread;
`tail_once()` is driven explicitly) with a 1ms watermark margin, so every
`sleep(MARGIN); tail_once()` deterministically advances the watermark past
all prior commits.
"""

import time

import pytest

from galaxysql_tpu.server.instance import Instance
from galaxysql_tpu.server.session import Session
from galaxysql_tpu.storage import columnar as col

# sleep long enough that now - margin exceeds every prior commit's TSO
MARGIN_S = 0.005

DDL = ("CREATE TABLE t (id BIGINT PRIMARY KEY, grp BIGINT, val VARCHAR(16)) "
       "PARTITION BY HASH(id) PARTITIONS 4")
Q_AGG = "SELECT grp, count(*), sum(id) FROM t GROUP BY grp ORDER BY grp"
Q_ALL = "SELECT id, grp, val FROM t ORDER BY id"
HINT = "/*+TDDL:COLUMNAR(ON)*/ "


def make_instance(data_dir=None, **params):
    inst = Instance(data_dir=data_dir)
    inst.config.set_instance("COLUMNAR_POLL_MS", 0)  # synchronous tailer
    inst.config.set_instance("COLUMNAR_WATERMARK_LAG_MS", 1)
    for k, v in params.items():
        inst.config.set_instance(k, v)
    return inst


def advance(inst):
    """Let the margin elapse, then run one tail cycle: afterwards the
    watermark covers every commit made before this call."""
    time.sleep(MARGIN_S)
    return inst.columnar.tail_once()


@pytest.fixture()
def session():
    inst = make_instance()
    s = Session(inst)
    s.execute("CREATE DATABASE c; USE c")
    s.execute(DDL)
    s.execute("INSERT INTO t VALUES " +
              ",".join(f"({i},{i % 7},'v{i % 5}')" for i in range(200)))
    yield s
    s.close()


def both(s, q):
    """(columnar rows, row-store rows, routed?) for one query."""
    r0 = s.instance.columnar.routed.value
    on = s.execute(HINT + q).rows
    off = s.execute("/*+TDDL:COLUMNAR(OFF)*/ " + q).rows
    return on, off, s.instance.columnar.routed.value > r0


@pytest.mark.columnar
class TestBitIdentity:
    def test_seeded_scan_identical_and_routed(self, session):
        s = session
        time.sleep(MARGIN_S)
        rep = s.instance.columnar.ensure_ready("c", "t")
        assert rep.state == col.READY and rep.watermark > 0
        for q in (Q_ALL, Q_AGG, "SELECT count(*) FROM t WHERE grp = 3"):
            on, off, routed = both(s, q)
            assert routed
            assert on == off

    def test_identity_through_dml_stream(self, session):
        s = session
        time.sleep(MARGIN_S)
        rep = s.instance.columnar.ensure_ready("c", "t")
        for rnd in range(3):
            base = 1000 * (rnd + 1)
            s.execute("INSERT INTO t VALUES " + ",".join(
                f"({base + i},{i % 7},'n{rnd}')" for i in range(40)))
            s.execute(f"DELETE FROM t WHERE id < {20 * (rnd + 1)}")
            s.execute(f"UPDATE t SET grp = grp + 1 WHERE id >= {base + 30}")
            advance(s.instance)
            assert rep.state == col.READY  # no reseed: deltas applied cleanly
            on, off, routed = both(s, Q_ALL)
            assert routed and on == off
            on, off, _ = both(s, Q_AGG)
            assert on == off
        assert rep.applied_events > 0 and rep.applied_rows > 0

    def test_old_view_matches_flashback_at_its_watermark(self, session):
        """A view snapshot taken before later DML + compaction still reads
        exactly the rows the row store shows AS OF that watermark."""
        s = session
        time.sleep(MARGIN_S)
        rep = s.instance.columnar.ensure_ready("c", "t")
        v1 = rep.view()
        s.execute("DELETE FROM t WHERE id < 100")
        s.execute("INSERT INTO t VALUES (5000, 1, 'late')")
        advance(s.instance)
        tm = s.instance.catalog.table("c", "t")
        live = sum(int(b.num_live()) for b in col.scan_view(v1, tm, ["id"]))
        flashback = s.execute(
            f"SELECT count(*) FROM t AS OF TSO {v1.watermark}").rows
        assert [(live,)] == flashback

    @pytest.mark.parametrize("qid", [1, 3, 5])
    def test_tpch_on_vs_off(self, tpch_columnar, qid):
        from galaxysql_tpu.storage.tpch_queries import QUERIES
        s = tpch_columnar
        r0 = s.instance.columnar.routed.value
        on = s.execute(HINT + QUERIES[qid]).rows
        assert s.instance.columnar.routed.value > r0
        off = s.execute("/*+TDDL:COLUMNAR(OFF)*/ " + QUERIES[qid]).rows
        assert on == off


@pytest.fixture(scope="module")
def tpch_columnar():
    from galaxysql_tpu.storage import tpch
    data = tpch.generate(0.01)
    inst = make_instance()
    s = Session(inst)
    s.execute("CREATE DATABASE tpch; USE tpch")
    for t in tpch.TABLE_ORDER:
        s.execute(tpch.TPCH_DDL[t])
        inst.store("tpch", t).insert_arrays(data[t],
                                            inst.tso.next_timestamp())
    s.execute("ANALYZE TABLE " + ", ".join(tpch.TABLE_ORDER))
    time.sleep(MARGIN_S)
    for t in tpch.TABLE_ORDER:
        inst.columnar.ensure_ready("tpch", t)
    yield s
    s.close()


@pytest.mark.columnar
class TestTailer:
    def test_crash_restart_resumes_from_persisted_watermark(self, tmp_path):
        d = str(tmp_path / "data")
        inst = make_instance(data_dir=d)
        s = Session(inst)
        s.execute("CREATE DATABASE c; USE c")
        s.execute(DDL)
        s.execute("INSERT INTO t VALUES " +
                  ",".join(f"({i},{i % 3},'a')" for i in range(100)))
        time.sleep(MARGIN_S)
        rep = inst.columnar.ensure_ready("c", "t")
        s.execute("DELETE FROM t WHERE id < 10")
        advance(inst)
        saved_seq, saved_wm = rep.seq, rep.watermark
        inst.save()
        s.close()

        inst2 = make_instance(data_dir=d)
        s2 = Session(inst2, "c")
        rep2 = inst2.columnar.replica("c", "t")
        assert rep2 is not None and rep2.state == col.READY
        assert rep2.seq == saved_seq and rep2.watermark == saved_wm
        assert rep2.reseeds == 0  # resumed, not rebuilt
        s2.execute("INSERT INTO t VALUES (900, 1, 'post'), (901, 2, 'post')")
        advance(inst2)
        on, off, routed = both(s2, Q_ALL)
        assert routed and on == off
        s2.close()

    def test_compaction_races_writes_and_inflight_views(self, session):
        s = session
        s.instance.config.set_instance("COLUMNAR_COMPACT_ROWS", 32)
        time.sleep(MARGIN_S)
        rep = s.instance.columnar.ensure_ready("c", "t")
        views = []
        for rnd in range(4):
            base = 2000 + 100 * rnd
            s.execute("INSERT INTO t VALUES " + ",".join(
                f"({base + i},{i % 5},'c{rnd}')" for i in range(40)))
            s.execute(f"DELETE FROM t WHERE id >= {base} "
                      f"AND id < {base + 10}")
            advance(s.instance)
            views.append(rep.view())
            on, off, _ = both(s, Q_AGG)
            assert on == off
        assert rep.compactions >= 1
        # every in-flight view still reads its own watermark exactly —
        # compaction swapped the tier wholesale and only dropped rows dead
        # below the minimum watermark
        tm = s.instance.catalog.table("c", "t")
        for v in views:
            live = sum(int(b.num_live())
                       for b in col.scan_view(v, tm, ["id"]))
            assert [(live,)] == s.execute(
                f"SELECT count(*) FROM t AS OF TSO {v.watermark}").rows

    @pytest.mark.parametrize("ddl", ["ALTER TABLE t ADD COLUMN extra BIGINT",
                                     "ALTER TABLE t DROP COLUMN val"])
    def test_ddl_mid_tail_reseeds(self, session, ddl):
        s = session
        time.sleep(MARGIN_S)
        rep = s.instance.columnar.ensure_ready("c", "t")
        s.execute("INSERT INTO t VALUES (3000, 1, 'pre')")
        s.execute(ddl)
        s.execute("DELETE FROM t WHERE id = 3000")
        advance(s.instance)   # detects the signature change -> RESEED
        advance(s.instance)   # reseeds against the new schema
        assert rep.state == col.READY
        assert rep.reseeds >= 1
        assert rep.sig == tuple(
            s.instance.catalog.table("c", "t").column_names())
        q = "SELECT * FROM t ORDER BY id"
        on, off, routed = both(s, q)
        assert routed and on == off

    def test_unmatched_delete_image_self_heals(self, session):
        s = session
        time.sleep(MARGIN_S)
        rep = s.instance.columnar.ensure_ready("c", "t")
        rep.tier = ((), ())  # simulate divergence: the replica lost its rows
        rep.pk = None
        s.execute("DELETE FROM t WHERE id = 7")
        advance(s.instance)
        assert rep.state == col.RESEED  # delete image had no live match
        advance(s.instance)
        assert rep.state == col.READY and rep.reseeds >= 1
        on, off, _ = both(s, Q_ALL)
        assert on == off

    def test_tailer_failure_publishes_event(self, session):
        from galaxysql_tpu.utils import events
        inst = session.instance
        inst.config.set_instance("COLUMNAR_POLL_MS", 5)
        mgr = inst.columnar
        orig = mgr.tail_once
        mgr.tail_once = lambda: (_ for _ in ()).throw(RuntimeError("boom"))
        try:
            mgr._start_thread()
            deadline = time.time() + 5
            while time.time() < deadline and not events.EVENTS.entries(
                    kind="columnar_tail_failed"):
                time.sleep(0.01)
            assert events.EVENTS.entries(kind="columnar_tail_failed")
        finally:
            mgr.tail_once = orig
            mgr.shutdown()
            inst.config.set_instance("COLUMNAR_POLL_MS", 0)


@pytest.mark.columnar
class TestRouting:
    def test_hatch_trio_structurally_off_path(self, session, monkeypatch):
        s = session
        mgr = s.instance.columnar
        time.sleep(MARGIN_S)
        mgr.ensure_ready("c", "t")
        # leg 1: hint OFF wins over the session/global param
        s.instance.config.set_instance("ENABLE_COLUMNAR_REPLICA", True)
        s.instance.config.set_instance("COLUMNAR_MIN_SCAN_ROWS", 1)
        r0 = mgr.routed.value
        off = s.execute("/*+TDDL:COLUMNAR(OFF)*/ " + Q_AGG).rows
        assert mgr.routed.value == r0
        # param on + signal above threshold: routes without any hint
        s.execute(Q_AGG)  # warms the digest's rows-examined signal
        assert s.execute(Q_AGG).rows == off
        assert mgr.routed.value > r0
        # leg 2: param off (the default) never routes without the hint
        s.instance.config.set_instance("ENABLE_COLUMNAR_REPLICA", False)
        r1 = mgr.routed.value
        s.execute(Q_AGG)
        assert mgr.routed.value == r1
        # leg 3: env kill switch beats even COLUMNAR(ON)
        monkeypatch.setattr(col, "ENABLED", False)
        r2 = mgr.routed.value
        assert s.execute(HINT + Q_AGG).rows == off
        assert mgr.routed.value == r2
        assert mgr.tail_once() == 0  # the tailer is dead too

    def test_size_signal_enrolls_async_then_routes(self, session):
        s = session
        mgr = s.instance.columnar
        s.instance.config.set_instance("ENABLE_COLUMNAR_REPLICA", True)
        s.instance.config.set_instance("COLUMNAR_MIN_SCAN_ROWS", 1)
        s.execute("ANALYZE TABLE t")
        assert mgr.replica("c", "t") is None
        r0 = mgr.routed.value
        rows = s.execute(Q_AGG).rows  # signal fires: enroll, stay on row store
        assert mgr.routed.value == r0
        rep = mgr.replica("c", "t")
        assert rep is not None and rep.state == col.SEEDING
        time.sleep(MARGIN_S)
        advance(s.instance)
        assert rep.state == col.READY
        assert s.execute(Q_AGG).rows == rows
        assert mgr.routed.value > r0

    def test_point_and_txn_reads_stay_on_row_store(self, session):
        s = session
        mgr = s.instance.columnar
        time.sleep(MARGIN_S)
        mgr.ensure_ready("c", "t")
        s.instance.config.set_instance("ENABLE_COLUMNAR_REPLICA", True)
        s.instance.config.set_instance("COLUMNAR_MIN_SCAN_ROWS", 1)
        r0 = mgr.routed.value
        s.execute("SELECT val FROM t WHERE id = 7")  # TP key-Get path
        assert mgr.routed.value == r0
        s.execute("BEGIN")
        s.execute(HINT + Q_AGG)  # txn reads see provisional rows: no route
        s.execute("ROLLBACK")
        assert mgr.routed.value == r0

    def test_read_your_writes_fence(self, session):
        s = session
        mgr = s.instance.columnar
        time.sleep(MARGIN_S)
        mgr.ensure_ready("c", "t")
        s.execute("INSERT INTO t VALUES (4000, 1, 'mine')")
        # no tail cycle ran: the watermark predates this session's write
        r0 = mgr.routed.value
        rows = s.execute(HINT + Q_ALL).rows
        assert mgr.routed.value == r0  # fence held: row store served it
        assert (4000, 1, "mine") in rows
        advance(s.instance)  # watermark passes the write: fence opens
        assert s.execute(HINT + Q_ALL).rows == rows
        assert mgr.routed.value > r0

    def test_freshness_slo_blocks_stale_replica(self, session):
        s = session
        mgr = s.instance.columnar
        time.sleep(MARGIN_S)
        mgr.ensure_ready("c", "t")
        advance(s.instance)
        s.instance.config.set_instance("ENABLE_COLUMNAR_REPLICA", True)
        s.instance.config.set_instance("COLUMNAR_MIN_SCAN_ROWS", 1)
        s.execute(Q_AGG)  # warm the digest signal
        s.instance.config.set_instance("COLUMNAR_MAX_LAG_MS", 1)
        time.sleep(0.05)  # let the replica go stale past the 1ms SLA
        r0 = mgr.routed.value
        s.execute(Q_AGG)
        assert mgr.routed.value == r0  # SLA blown: row store
        assert s.execute(HINT + Q_AGG)  # explicit hint overrides the SLA
        assert mgr.routed.value > r0

    def test_zone_maps_prune_stripes(self, session):
        s = session
        s.instance.config.set_instance("COLUMNAR_COMPACT_ROWS", 10)
        s.execute("CREATE TABLE zp (id BIGINT PRIMARY KEY, v BIGINT)")
        s.execute("INSERT INTO zp VALUES " +
                  ",".join(f"({i},{i})" for i in range(64)))
        time.sleep(MARGIN_S)
        rep = s.instance.columnar.ensure_ready("c", "zp")
        s.execute("INSERT INTO zp VALUES " +
                  ",".join(f"({i},{i})" for i in range(100000, 100064)))
        advance(s.instance)  # compacts the high-id delta into its own stripe
        assert len(rep.tier[0]) >= 2
        p0 = rep.pruned_stripes
        q = "SELECT count(*), sum(v) FROM zp WHERE id < 50"
        on, off, routed = both(s, q)
        assert routed and on == off
        assert rep.pruned_stripes > p0  # the 100000+ stripe never scanned


@pytest.mark.columnar
class TestSurfaces:
    def test_show_and_information_schema_parity(self, session):
        s = session
        time.sleep(MARGIN_S)
        s.instance.columnar.ensure_ready("c", "t")
        show = s.execute("SHOW COLUMNAR REPLICA").rows
        assert len(show) == 1 and show[0][0] == "c.t"
        assert show[0][1] == "READY" and show[0][5] > 0  # base stripes
        info = s.execute(
            "SELECT table_name, state, base_stripes "
            "FROM information_schema.columnar_replica").rows
        assert info == [(r[0], r[1], r[5]) for r in show]
        metrics = s.execute(
            "SELECT metric_name FROM information_schema.metrics "
            "WHERE metric_name LIKE 'columnar%'").rows
        assert {"columnar_events_applied", "columnar_routed_queries",
                "columnar_lag_ms"} <= {r[0] for r in metrics}

    def test_explain_shows_freshness_and_route(self, session):
        s = session
        time.sleep(MARGIN_S)
        s.instance.columnar.ensure_ready("c", "t")
        plain = [r[0] for r in s.execute(
            "EXPLAIN " + HINT + Q_AGG).rows]
        line = [l for l in plain if l.startswith("-- columnar: c.t")]
        assert line and "freshness_lag_ms=" in line[0] \
            and "watermark=" in line[0]
        analyzed = [r[0] for r in s.execute(
            "EXPLAIN ANALYZE " + HINT + Q_AGG).rows]
        assert any("scan-columnar t" in l for l in analyzed)
        # OFF leaves no columnar trace at all
        off = [r[0] for r in s.execute(
            "EXPLAIN ANALYZE /*+TDDL:COLUMNAR(OFF)*/ " + Q_AGG).rows]
        assert not any("columnar" in l for l in off)


@pytest.mark.columnar
class TestGuards:
    def test_steady_state_retraces_zero(self, session):
        from galaxysql_tpu.exec.operators import (COMPILE_STATS,
                                                  reset_compile_stats)
        s = session
        time.sleep(MARGIN_S)
        s.instance.columnar.ensure_ready("c", "t")
        for _ in range(2):  # warm every kernel shape on the replica path
            s.execute(HINT + Q_AGG)
        reset_compile_stats()
        for _ in range(3):
            s.execute(HINT + Q_AGG)
        assert COMPILE_STATS["retraces"] == 0

    def test_default_instance_has_no_columnar_footprint(self):
        """ENABLE_COLUMNAR_REPLICA defaults off: a plain instance never
        enrolls, routes, or tails — the row-store path is unperturbed."""
        inst = make_instance()
        s = Session(inst)
        s.execute("CREATE DATABASE c; USE c")
        s.execute(DDL)
        s.execute("INSERT INTO t VALUES (1, 1, 'a')")
        s.execute(Q_AGG)
        assert inst.columnar.replicas == {}
        assert inst.columnar.routed.value == 0
        assert inst.columnar._thread is None
        s.close()


@pytest.mark.columnar
class TestClusteringAndCacheKeys:
    def test_clustered_seed_prunes_and_stays_identical(self):
        """COLUMNAR_CLUSTER_BY re-sorts the seed on the cluster column and
        slices it into threshold stripes with disjoint zone-map ranges: a
        range SARG then prunes whole stripes, and every result still matches
        the row store (decimal/int aggregation is order-independent)."""
        inst = make_instance(COLUMNAR_CLUSTER_BY="t:grp",
                             COLUMNAR_COMPACT_ROWS=64)
        s = Session(inst)
        s.execute("CREATE DATABASE c; USE c")
        s.execute(DDL)
        s.execute("INSERT INTO t VALUES " +
                  ",".join(f"({i},{i % 7},'v{i % 5}')" for i in range(200)))
        time.sleep(MARGIN_S)
        rep = inst.columnar.ensure_ready("c", "t")
        stripes = rep.tier[0]
        assert len(stripes) == 4  # 200 rows / 64-row threshold
        ranges = [st.zmap["grp"] for st in stripes]
        assert ranges == sorted(ranges)
        # consecutive stripes overlap at most at the slice boundary value
        for (_, hi), (lo, _) in zip(ranges, ranges[1:]):
            assert lo >= hi - 1
        p0 = inst.columnar.pruned.value
        on, off, routed = both(s, "SELECT count(*), sum(id) FROM t "
                                  "WHERE grp >= 5")
        assert routed and on == off
        assert inst.columnar.pruned.value > p0
        # full-range queries cannot prune but still agree bit-for-bit
        for q in (Q_ALL, Q_AGG):
            on, off, _ = both(s, q)
            assert on == off
        s.close()

    def test_cluster_spec_unknown_column_is_ignored(self):
        inst = make_instance(COLUMNAR_CLUSTER_BY="t:nope,other:grp")
        s = Session(inst)
        s.execute("CREATE DATABASE c; USE c")
        s.execute(DDL)
        s.execute("INSERT INTO t VALUES (1, 1, 'a'), (2, 2, 'b')")
        time.sleep(MARGIN_S)
        rep = inst.columnar.ensure_ready("c", "t")
        on, off, _ = both(s, Q_ALL)
        assert rep.state == col.READY and on == off
        s.close()

    def test_generation_key_caches_idle_and_recomputes_on_dml(self):
        """Replica scans fingerprint by (seed_ts, applied_events), not the
        watermark: idle watermark advances keep fragments warm; applied DML
        moves the generation so results are recomputed, and the
        max_applied_ts guard blocks caching while the routed watermark is
        still below the newest applied stamp."""
        inst = make_instance()
        s = Session(inst)
        s.execute("CREATE DATABASE c; USE c")
        s.execute(DDL)
        s.execute("INSERT INTO t VALUES " +
                  ",".join(f"({i},{i % 7},'v{i % 5}')" for i in range(200)))
        time.sleep(MARGIN_S)
        rep = inst.columnar.ensure_ready("c", "t")
        sr = Session(inst, schema="c")
        r1 = sr.execute(HINT + Q_AGG).rows
        w1 = rep.watermark
        advance(inst)  # idle cycle: watermark moves, generation does not
        assert rep.watermark > w1
        h0, m0 = inst.frag_cache.hits, inst.frag_cache.misses
        assert sr.execute(HINT + Q_AGG).rows == r1
        assert inst.frag_cache.hits > h0
        assert inst.frag_cache.misses == m0
        ev = rep.applied_events
        s.execute("UPDATE t SET grp = 99 WHERE id < 10")
        advance(inst)
        assert rep.applied_events > ev  # generation moved with the DML
        assert rep.max_applied_ts > w1
        r2 = sr.execute(HINT + Q_AGG).rows
        off = sr.execute("/*+TDDL:COLUMNAR(OFF)*/ " + Q_AGG).rows
        assert r2 == off and r2 != r1
        sr.close()
        s.close()

    def test_view_snapshot_is_consistent_tuple(self):
        """view() must come from one published tuple: the watermark a view
        carries never outruns the tier it pairs with (publish() swaps them
        together), and compaction republishes without moving the
        generation."""
        inst = make_instance()
        s = Session(inst)
        s.execute("CREATE DATABASE c; USE c")
        s.execute(DDL)
        s.execute("INSERT INTO t VALUES " +
                  ",".join(f"({i},{i % 7},'v{i % 5}')" for i in range(100)))
        time.sleep(MARGIN_S)
        rep = inst.columnar.ensure_ready("c", "t")
        v = rep.view()
        assert (v.stripes, v.delta) == rep.tier
        assert v.events == rep.applied_events
        assert v.max_applied_ts == rep.max_applied_ts
        inst.config.set_instance("COLUMNAR_COMPACT_ROWS", 1)
        ev = rep.applied_events
        s.execute("INSERT INTO t VALUES (1000, 1, 'x')")
        advance(inst)
        assert rep.compactions >= 1
        v2 = rep.view()
        assert v2.events == rep.applied_events > ev
        assert v2.delta == ()  # compacted tier republished
        s.close()
