"""Planned runtime-filter pushdown: build-side bloom/min-max filters driven
down to scans, fused segments, MPP shards, remote workers, and archive files.

The `runtime_filter`-marked tests are the fast smoke target (`make rf-smoke`):
result equivalence with `RUNTIME_FILTER(OFF)` on TPC-H Q3/Q5/Q9/Q18 and SSB
Q2.1, on both the local engine and the 8-device mesh — the correctness guard
for the filter planner and every pushdown surface.
"""

import numpy as np
import pytest

import jax.numpy as jnp

from galaxysql_tpu.chunk.batch import Column, ColumnBatch, batch_from_pydict
from galaxysql_tpu.exec import runtime_filter as rfmod
from galaxysql_tpu.exec.fusion import FusedSegment
from galaxysql_tpu.exec.runtime_filter import (RuntimeFilter,
                                               RuntimeFilterManager,
                                               RuntimeFilterTarget)
from galaxysql_tpu.expr import ir
from galaxysql_tpu.plan import logical as L
from galaxysql_tpu.server.instance import Instance
from galaxysql_tpu.server.session import Session
from galaxysql_tpu.sql.hints import parse_hints
from galaxysql_tpu.types import datatype as dt


def _stage_mask(f: RuntimeFilter, data, valid=None, xp=np):
    """Apply a published filter to a key lane through the real rf stage."""
    mgr = RuntimeFilterManager()
    mgr.publish(1, f)
    t = RuntimeFilterTarget(1, "k", "k", frozenset({"bloom", "minmax"}))
    ref = rfmod.RfStageRef(mgr, t)
    n = len(data)
    env = {"k": (xp.asarray(data), None if valid is None else xp.asarray(valid))}
    live = xp.ones(n, dtype=bool)
    out = ref.make_fn(xp)(env, live, ref.runtime_args())
    return np.asarray(out)


class TestRuntimeFilterValue:
    def test_no_false_negatives(self):
        keys = np.arange(0, 5000, 7, dtype=np.int64)
        f = RuntimeFilter.build(keys, {"bloom", "minmax"})
        for xp in (np, jnp):
            mask = _stage_mask(f, keys.tolist(), xp=xp)
            assert mask.all()  # every build key MUST pass (no false negatives)

    def test_minmax_refutes_out_of_range(self):
        f = RuntimeFilter.build(np.asarray([100, 200, 300], np.int64),
                                {"minmax"})
        mask = _stage_mask(f, [50, 100, 250, 300, 999])
        np.testing.assert_array_equal(mask, [False, True, True, True, False])

    def test_bloom_prunes_most_absent_keys(self):
        f = RuntimeFilter.build(np.arange(100, dtype=np.int64), {"bloom"})
        absent = np.arange(10_000, 20_000, dtype=np.int64)
        mask = _stage_mask(f, absent.tolist())
        # ~16 bits/key: false-positive rate far below 5%
        assert mask.sum() < 0.05 * absent.size

    def test_empty_build_passes_nothing(self):
        f = RuntimeFilter.build(np.zeros(0, dtype=np.int64),
                                {"bloom", "minmax"})
        assert f.pass_nothing()
        mask = _stage_mask(f, [0, 1, 2, 3])
        assert not mask.any()  # pass NOTHING, never everything

    def test_null_keys_masked_out(self):
        f = RuntimeFilter.build(np.arange(10, dtype=np.int64),
                                {"bloom", "minmax"})
        mask = _stage_mask(f, [1, 2, 3, 4],
                           valid=np.asarray([True, False, True, False]))
        np.testing.assert_array_equal(mask, [True, False, True, False])

    def test_in_list_for_small_builds(self):
        f = RuntimeFilter.build(np.asarray([5, 5, 9, 9, 11], np.int64),
                                {"bloom", "minmax"})
        np.testing.assert_array_equal(f.in_values, [5, 9, 11])
        big = RuntimeFilter.build(np.arange(100_000, dtype=np.int64),
                                  {"bloom"})
        assert big.in_values is None

    def test_absent_filter_is_identity(self):
        mgr = RuntimeFilterManager()
        t = RuntimeFilterTarget(7, "k", "k", frozenset({"bloom"}))
        ref = rfmod.RfStageRef(mgr, t)
        env = {"k": (np.asarray([1, 2, 3]), None)}
        live = np.asarray([True, False, True])
        out = ref.make_fn(np)(env, live, ref.runtime_args())
        np.testing.assert_array_equal(out, live)

    def test_unpublished_rf_segment_is_inert_passthrough(self):
        # grace-spilled / oversized / deactivated edge: the rf-only segment
        # must pass batches through without ANY program dispatch
        from galaxysql_tpu.exec import operators as ops
        from galaxysql_tpu.exec.fusion import FusedPipelineOp
        from galaxysql_tpu.exec.operators import SourceOp
        mgr = RuntimeFilterManager()
        t = RuntimeFilterTarget(3, "k", "k", frozenset({"bloom", "minmax"}))
        seg = FusedSegment([("rf", rfmod.RfStageRef(mgr, t))])
        b = batch_from_pydict({"k": [1, 2, 3]}, {"k": dt.BIGINT})
        ops.reset_dispatch_stats()
        out = list(FusedPipelineOp(SourceOp([b]), seg).batches())
        assert out[0] is b  # the very same object: zero copies
        assert ops.DISPATCH_STATS["dispatches"] == 0
        assert seg.inert()

    def test_published_rf_segment_is_not_inert(self):
        mgr = RuntimeFilterManager()
        mgr.publish(3, RuntimeFilter.build(np.asarray([1], np.int64),
                                           {"minmax"}))
        t = RuntimeFilterTarget(3, "k", "k", frozenset({"minmax"}))
        seg = FusedSegment([("rf", rfmod.RfStageRef(mgr, t))])
        assert not seg.inert()

    def test_in_list_gated_by_bloom_kind(self):
        # RUNTIME_FILTER(MINMAX) must suppress membership pushdown too
        f = RuntimeFilter.build(np.asarray([5, 9], np.int64), {"minmax"})
        assert f.in_values is None and f.lo == 5


class TestBloomCapUnified:
    """Satellite: `_build_bloom` gates on live rows, `_build_bloom_device`
    used to gate on padded CAPACITY — a small build padded to a large bucket
    silently skipped the device bloom.  Both now gate (and size) on the live
    count."""

    def _join(self, cap_rows, live_rows):
        from galaxysql_tpu.exec.operators import HashJoinOp, SourceOp
        data = np.zeros(cap_rows, dtype=np.int64)
        data[:live_rows] = np.arange(live_rows)
        live = np.arange(cap_rows) < live_rows
        build = ColumnBatch({"k": Column(jnp.asarray(data), None,
                                         dt.BIGINT, None)}, jnp.asarray(live))
        return HashJoinOp(SourceOp([build]), SourceOp([build]),
                          [ir.ColRef("k", dt.BIGINT, None)],
                          [ir.ColRef("k", dt.BIGINT, None)]), build

    def test_padded_small_build_gets_device_bloom(self, monkeypatch):
        from galaxysql_tpu.exec.operators import HashJoinOp
        from galaxysql_tpu.kernels import relational as K
        if not K.prefer_scatter():
            pytest.skip("device-bloom path is the scatter backend's")
        monkeypatch.setattr(HashJoinOp, "BLOOM_MAX_BUILD", 256)
        op, build = self._join(cap_rows=1024, live_rows=100)
        _, pf = op._key_compilers()
        apply = op._build_bloom_device(build, pf[0])
        assert apply is not None  # capacity 1024 > cap, live 100 <= cap
        probe = ColumnBatch({"k": Column(jnp.asarray(
            np.asarray([5, 99, 5000], np.int64)), None, dt.BIGINT, None)},
            None)
        out = apply(probe)
        got = np.asarray(out.live_mask())
        assert got[0] and got[1] and not got[2]

    def test_oversized_live_build_still_skips(self, monkeypatch):
        from galaxysql_tpu.exec.operators import HashJoinOp
        from galaxysql_tpu.kernels import relational as K
        if not K.prefer_scatter():
            pytest.skip("device-bloom path is the scatter backend's")
        monkeypatch.setattr(HashJoinOp, "BLOOM_MAX_BUILD", 64)
        op, build = self._join(cap_rows=1024, live_rows=100)
        _, pf = op._key_compilers()
        assert op._build_bloom_device(build, pf[0]) is None


class TestRuntimeFilterHints:
    def test_runtime_filter_directive_paren_and_eq(self):
        assert parse_hints("/*+TDDL: RUNTIME_FILTER(OFF)*/") == \
            {"runtime_filter": "off"}
        assert parse_hints("/*+TDDL: RUNTIME_FILTER=BLOOM*/") == \
            {"runtime_filter": "bloom"}
        assert parse_hints("/*+TDDL: RUNTIME_FILTER(MINMAX) NO_FUSE*/") == \
            {"runtime_filter": "minmax", "no_fuse": True}

    def test_unknown_mode_ignored(self):
        assert parse_hints("/*+TDDL: RUNTIME_FILTER(WAT)*/") == {}

    def test_no_bloom_disables_planned_filters(self):
        h = parse_hints("/*+TDDL: NO_BLOOM*/")
        assert RuntimeFilterManager(hints=h).mode == "off"


@pytest.fixture(scope="module")
def rf_session():
    inst = Instance()
    s = Session(inst)
    s.execute("CREATE DATABASE rf")
    s.execute("USE rf")
    s.execute("CREATE TABLE big (id BIGINT, k BIGINT, v DOUBLE)")
    s.execute("CREATE TABLE small (k BIGINT, grp VARCHAR(4))")
    n = 20000
    inst.store("rf", "big").insert_pylists(
        {"id": list(range(n)),
         "k": [i % 1000 if i % 17 else None for i in range(n)],
         "v": [float(i) for i in range(n)]},
        inst.tso.next_timestamp())
    inst.store("rf", "small").insert_pylists(
        {"k": list(range(100)), "grp": ["a" if i % 2 else "b"
                                        for i in range(100)]},
        inst.tso.next_timestamp())
    s.execute("ANALYZE TABLE big, small")
    yield s
    s.close()


def _plan(s, sql):
    return s.instance.planner.plan_select(sql, "rf", [], s)


def _rf_scans(plan):
    return [n for n in L.walk(plan.rel)
            if isinstance(n, L.Scan) and n.rf_targets]


class TestPlanning:
    Q = "select count(*) from big, small where big.k = small.k"

    def test_probe_scan_annotated(self, rf_session):
        scans = _rf_scans(_plan(rf_session, self.Q))
        assert len(scans) == 1 and scans[0].table.name == "big"
        t = scans[0].rf_targets[0]
        assert t.column == "k" and t.kinds == {"bloom", "minmax"}
        joins = [n for n in L.walk(_plan(rf_session, self.Q).rel)
                 if isinstance(n, L.Join) and n.rf_plans]
        assert joins and joins[0].rf_plans[0].filter_id == t.filter_id

    def test_off_hint_and_no_bloom_disable(self, rf_session):
        for h in ("RUNTIME_FILTER(OFF)", "RUNTIME_FILTER=OFF", "NO_BLOOM"):
            plan = _plan(rf_session, f"/*+TDDL:{h}*/ " + self.Q)
            assert not _rf_scans(plan), h

    def test_kind_restriction_hints(self, rf_session):
        p = _plan(rf_session, "/*+TDDL:RUNTIME_FILTER(MINMAX)*/ " + self.Q)
        assert _rf_scans(p)[0].rf_targets[0].kinds == {"minmax"}
        p = _plan(rf_session, "/*+TDDL:RUNTIME_FILTER(BLOOM)*/ " + self.Q)
        assert _rf_scans(p)[0].rf_targets[0].kinds == {"bloom"}

    def test_small_probe_not_filtered(self, rf_session):
        # probe below RF_MIN_PROBE_ROWS: broadcast-small shape, no filter
        q = "select count(*) from small a, small b where a.k = b.k"
        assert not _rf_scans(_plan(rf_session, q))

    def test_semi_join_probe_annotated(self, rf_session):
        q = ("select count(*) from big where big.k in "
             "(select k from small)")
        scans = _rf_scans(_plan(rf_session, q))
        assert scans and scans[0].table.name == "big"

    def test_both_probe_directions_planted_when_selective(self):
        # engines pick build sides differently (MPP flips only below a 4x
        # ratio): every direction passing the gates gets its own edge, and
        # only the one matching the actual probe side ever publishes
        inst = Instance()
        s = Session(inst)
        s.execute("CREATE DATABASE dd; USE dd")
        s.execute("CREATE TABLE t1 (k BIGINT, v BIGINT)")
        s.execute("CREATE TABLE t2 (k BIGINT, v BIGINT)")
        n = 50000
        for t in ("t1", "t2"):
            inst.store("dd", t).insert_arrays(
                {"k": np.arange(n) % 40000, "v": np.arange(n) % 100},
                inst.tso.next_timestamp())
        s.execute("ANALYZE TABLE t1, t2")
        q = ("select count(*) from t1, t2 where t1.k = t2.k "
             "and t1.v < 20 and t2.v < 20")
        plan = inst.planner.plan_select(q, "dd", [], s)
        scans = _rf_scans(plan)
        assert sorted(sc.table.name for sc in scans) == ["t1", "t2"]
        # and execution stays correct: only one direction publishes
        on = s.execute(q)
        off = s.execute("/*+TDDL:RUNTIME_FILTER(OFF)*/ " + q)
        assert on.rows == off.rows
        s.close()


class TestExecutionEquivalence:
    Q = ("select small.grp, count(*), sum(big.v) from big, small "
         "where big.k = small.k group by small.grp order by small.grp")

    def _both(self, s, q):
        on = s.execute(q)
        off = s.execute("/*+TDDL:RUNTIME_FILTER(OFF)*/ " + q)
        assert len(on.rows) == len(off.rows)
        for a, b in zip(on.rows, off.rows):
            for x, y in zip(a, b):
                if isinstance(x, float):
                    assert abs(x - y) <= max(abs(y) * 1e-9, 1e-9)
                else:
                    assert x == y
        return on

    def test_join_with_null_keys_matches(self, rf_session):
        # big.k has NULLs (every 17th row): the filter must mask them, the
        # join must not match them — same answer with filters off
        rfmod.reset_rf_stats(enabled=True)
        self._both(rf_session, self.Q)
        assert rfmod.RF_STATS["filters_built"] > 0
        rfmod.reset_rf_stats()

    def test_probe_rows_pruned(self, rf_session):
        q = "select count(*) from big, small where big.k = small.k"
        # cleared per run: a fragment-cached aggregate replay skips the probe
        # stages whose row counts this test measures
        rf_session.instance.frag_cache.clear()
        rfmod.reset_rf_stats(enabled=True)
        rf_session.execute(q)
        on_rows = rfmod.RF_STATS["probe_rows"]
        rf_session.instance.frag_cache.clear()
        rfmod.reset_rf_stats(enabled=True)
        rf_session.execute("/*+TDDL:RUNTIME_FILTER(OFF)*/ " + q)
        off_rows = rfmod.RF_STATS["probe_rows"]
        rfmod.reset_rf_stats()
        assert on_rows < off_rows / 2  # 100 of 1000 keys: >=2x fewer rows

    def test_empty_build_yields_empty_not_everything(self, rf_session):
        q = ("select count(*) from big, small "
             "where big.k = small.k and small.k < 0")
        r = self._both(rf_session, q)
        assert r.rows == [(0,)]

    def test_unfused_paths_match(self, rf_session):
        # NO_FUSE forces the scan-level rf wrapper (no segment to ride in)
        on = rf_session.execute("/*+TDDL:NO_FUSE*/ " + self.Q)
        off = rf_session.execute(
            "/*+TDDL:NO_FUSE RUNTIME_FILTER(OFF)*/ " + self.Q)
        assert on.rows == off.rows


class TestObservability:
    Q = "select count(*) from big, small where big.k = small.k"

    def test_explain_analyze_runtime_filter_lines(self, rf_session):
        r = rf_session.execute("EXPLAIN ANALYZE " + self.Q)
        text = "\n".join(l for (l,) in r.rows)
        assert "RuntimeFilter(k, bloom+minmax, pruned=" in text

    def test_show_metrics_round_trip(self, rf_session):
        rf_session.execute("EXPLAIN ANALYZE " + self.Q)
        rows = {r[0]: r for r in rf_session.execute("SHOW METRICS").rows}
        assert "rf_build_ms" in rows and rows["rf_build_ms"][2] >= 0
        assert "rf_rows_pruned" in rows
        assert "rf_files_pruned" in rows
        pruned = rows["rf_rows_pruned"][2]
        rf_session.execute("EXPLAIN ANALYZE " + self.Q)
        rows2 = {r[0]: r for r in rf_session.execute("SHOW METRICS").rows}
        assert rows2["rf_rows_pruned"][2] >= pruned

    def test_trace_marks_publish(self, rf_session):
        rf_session.execute("EXPLAIN ANALYZE " + self.Q)


class TestWorkerPushdown:
    """DN-side pruning: min/max sargs + IN-lists inside the shipped fragment
    exclude rows before they cross the process seam (in-process Worker)."""

    @pytest.fixture(scope="class")
    def worker(self, tmp_path_factory):
        from galaxysql_tpu.net.worker import Worker
        w = Worker(data_dir=str(tmp_path_factory.mktemp("rfworker")))
        s = Session(w.instance)
        s.execute("CREATE DATABASE d; USE d")
        s.execute("CREATE TABLE t (id BIGINT, k BIGINT)")
        w.instance.store("d", "t").insert_pylists(
            {"id": list(range(1000)), "k": [i % 50 for i in range(1000)]},
            w.instance.tso.next_timestamp())
        s.close()
        return w

    def test_minmax_sargs_prune(self, worker):
        frag = {"schema": "d", "table": "t", "columns": ["id", "k"],
                "sargs": [["k", "ge", 10], ["k", "le", 12]]}
        hdr, arrays = worker._exec_plan({"fragment": frag})
        assert hdr["rows"] == 60  # k in {10,11,12}: 20 rows each

    def test_rf_in_list_prunes(self, worker):
        frag = {"schema": "d", "table": "t", "columns": ["id"],
                "sargs": [], "rf_in": [["k", [3, 7]]]}
        hdr, arrays = worker._exec_plan({"fragment": frag})
        assert hdr["rows"] == 40

    def test_empty_in_list_passes_nothing(self, worker):
        frag = {"schema": "d", "table": "t", "columns": ["id"],
                "sargs": [], "rf_in": [["k", []]]}
        hdr, arrays = worker._exec_plan({"fragment": frag})
        assert hdr["rows"] == 0

    def test_scan_pushdown_extraction(self):
        # the CN-side extraction that feeds the fragment: lane-domain numbers
        class _Col:
            def __init__(self):
                self.dtype = dt.BIGINT
        class _TM:
            def column(self, n):
                return _Col()
        scan = L.Scan.__new__(L.Scan)
        scan.table = _TM()
        scan.rf_targets = [RuntimeFilterTarget(1, "t.k", "k",
                                               frozenset({"bloom", "minmax"}))]
        mgr = RuntimeFilterManager()
        mgr.publish(1, RuntimeFilter.build(
            np.asarray([5, 9], np.int64), {"bloom", "minmax"}))
        sargs, inlists = mgr.scan_pushdown(scan)
        assert ("k", "ge", 5) in sargs and ("k", "le", 9) in sargs
        assert inlists == [("k", [5, 9])]


class TestArchiveFilePrune:
    def test_rf_minmax_skips_refuted_files(self, tmp_path):
        pq = pytest.importorskip("pyarrow.parquet")
        from galaxysql_tpu.types import temporal
        inst = Instance()
        inst.archive.directory = str(tmp_path / "arch")
        s = Session(inst)
        s.execute("CREATE DATABASE a; USE a")
        s.execute("CREATE TABLE fact (k BIGINT, d DATE, v BIGINT)")
        s.execute("CREATE TABLE dim (k BIGINT)")
        today = temporal.days_from_civil(2026, 7, 29)
        store = inst.store("a", "fact")
        # two archive epochs with DISJOINT key ranges: ks 0..99, 1000..1099
        for base, age in ((0, 400), (1000, 800)):
            store.insert_pylists(
                {"k": list(range(base, base + 100)),
                 "d": [temporal.format_date(today - age)] * 100,
                 "v": [1] * 100},
                inst.tso.next_timestamp())
            n = inst.archive.archive_older_than(inst, "a", "fact", "d",
                                                today - age + 1)
            assert n == 100
        # hot rows so the probe is big enough for the planning gate
        store.insert_pylists(
            {"k": [i % 100 for i in range(10000)],
             "d": [temporal.format_date(today)] * 10000,
             "v": [1] * 10000},
            inst.tso.next_timestamp())
        inst.store("a", "dim").insert_pylists(
            {"k": list(range(90, 100))}, inst.tso.next_timestamp())
        s.execute("ANALYZE TABLE fact, dim")
        am = inst.archive
        before = am.rf_pruned_files
        r = s.execute("select count(*) from fact, dim "
                      "where fact.k = dim.k")
        # dim keys 90..99: the second file (ks 1000..1099) is min/max-refuted
        assert am.rf_pruned_files > before
        off = s.execute("/*+TDDL:RUNTIME_FILTER(OFF)*/ "
                        "select count(*) from fact, dim "
                        "where fact.k = dim.k")
        assert r.rows == off.rows
        s.close()


# -- SQL-level equivalence smoke (the `runtime_filter` marker target) ---------


def _rows_close(a, b):
    assert len(a) == len(b)
    for ra, rb in zip(sorted(a, key=lambda r: tuple(str(x) for x in r)),
                      sorted(b, key=lambda r: tuple(str(x) for x in r))):
        assert len(ra) == len(rb)
        for va, vb in zip(ra, rb):
            if isinstance(va, float) or isinstance(vb, float):
                assert abs(float(va) - float(vb)) <= \
                    max(abs(float(vb)) * 1e-6, 1e-6)
            else:
                assert va == vb


@pytest.fixture(scope="module")
def tpch_session():
    from galaxysql_tpu.storage import tpch
    data = tpch.generate(0.01)
    inst = Instance()
    s = Session(inst)
    s.execute("CREATE DATABASE tpch")
    s.execute("USE tpch")
    for t in tpch.TABLE_ORDER:
        s.execute(tpch.TPCH_DDL[t])
        inst.store("tpch", t).insert_arrays(data[t], inst.tso.next_timestamp())
    s.execute("ANALYZE TABLE " + ", ".join(tpch.TABLE_ORDER))
    yield s
    s.close()


@pytest.mark.runtime_filter
class TestTpchEquivalence:
    """Bloom false positives are tolerable (the join re-verifies), false
    NEGATIVES are not: filters-on results must equal RUNTIME_FILTER(OFF)."""

    @pytest.mark.parametrize("qid", [3, 5, 9, 18])
    def test_filters_on_equals_off(self, tpch_session, qid):
        from galaxysql_tpu.storage.tpch_queries import QUERIES
        s = tpch_session
        on = s.execute(QUERIES[qid])
        off = s.execute("/*+TDDL:RUNTIME_FILTER(OFF)*/ " + QUERIES[qid])
        _rows_close(on.rows, off.rows)

    def test_filters_actually_engage_on_q5(self, tpch_session):
        from galaxysql_tpu.storage.tpch_queries import QUERIES
        # cold: the fragment cache may hold this query from an earlier test —
        # clear it so the filters are genuinely BUILT here
        fcache = tpch_session.instance.frag_cache
        fcache.clear()
        rfmod.reset_rf_stats(enabled=True)
        tpch_session.execute(QUERIES[5])
        assert rfmod.RF_STATS["filters_built"] > 0
        # warm at the JOIN level: drop the aggregate-replay entries so the
        # probe pipeline runs again — the cached build artifacts must hand
        # the filters back without rebuilding them
        fcache.drop_kind("subplan")
        rfmod.reset_rf_stats(enabled=True)
        tpch_session.execute(QUERIES[5])
        assert rfmod.RF_STATS["filters_cached"] > 0
        assert rfmod.RF_STATS["filters_built"] == 0
        rfmod.reset_rf_stats()


@pytest.mark.runtime_filter
class TestSsbEquivalence:
    def test_ssb_q21(self):
        from galaxysql_tpu.storage import ssb
        data = ssb.generate(0.005)
        inst = Instance()
        s = Session(inst)
        s.execute("CREATE DATABASE ssb; USE ssb")
        for t in ssb.TABLE_ORDER:
            s.execute(ssb.SSB_DDL[t])
            inst.store("ssb", t).insert_arrays(data[t],
                                               inst.tso.next_timestamp())
        s.execute("ANALYZE TABLE " + ", ".join(ssb.TABLE_ORDER))
        on = s.execute(ssb.QUERIES["2.1"])
        off = s.execute("/*+TDDL:RUNTIME_FILTER(OFF)*/ " + ssb.QUERIES["2.1"])
        _rows_close(on.rows, off.rows)
        s.close()


@pytest.mark.runtime_filter
@pytest.mark.slow  # compiles MPP shard programs; covered by `make rf-smoke`
class TestMeshEquivalence:
    @pytest.mark.parametrize("qid", [3, 5, 9, 18])
    def test_mpp_filters_on_equals_off(self, tpch_session, qid):
        import jax
        from galaxysql_tpu.parallel.mpp import MppExecutor
        from galaxysql_tpu.plan.physical import ExecContext
        from galaxysql_tpu.storage.tpch_queries import QUERIES
        inst = tpch_session.instance
        mesh = inst.mesh()
        if mesh is None or len(jax.devices()) < 8:
            pytest.skip("no 8-device mesh")

        def run(sql):
            plan = inst.planner.plan_select(sql, "tpch")
            ctx = ExecContext(inst.stores, inst.tso.next_timestamp(), [],
                              archive=inst.archive, archive_instance=inst)
            return MppExecutor(ctx, mesh).execute(plan.rel), ctx
        on, ctx_on = run(QUERIES[qid])
        off, _ = run("/*+TDDL:RUNTIME_FILTER(OFF)*/ " + QUERIES[qid])
        _rows_close(on.to_pylist(), off.to_pylist())
        if qid == 5:
            assert any("mpp-rf" in t for t in ctx_on.trace)

    def test_mesh_ssb_q21(self):
        import jax
        from galaxysql_tpu.parallel.mpp import MppExecutor
        from galaxysql_tpu.plan.physical import ExecContext
        from galaxysql_tpu.storage import ssb
        data = ssb.generate(0.005)
        inst = Instance()
        s = Session(inst)
        s.execute("CREATE DATABASE ssb; USE ssb")
        for t in ssb.TABLE_ORDER:
            s.execute(ssb.SSB_DDL[t])
            inst.store("ssb", t).insert_arrays(data[t],
                                               inst.tso.next_timestamp())
        s.execute("ANALYZE TABLE " + ", ".join(ssb.TABLE_ORDER))
        mesh = inst.mesh()
        if mesh is None or len(jax.devices()) < 8:
            s.close()
            pytest.skip("no 8-device mesh")

        def run(sql):
            plan = inst.planner.plan_select(sql, "ssb")
            ctx = ExecContext(inst.stores, inst.tso.next_timestamp(), [],
                              archive=inst.archive, archive_instance=inst)
            return MppExecutor(ctx, mesh).execute(plan.rel)
        on = run(ssb.QUERIES["2.1"])
        off = run("/*+TDDL:RUNTIME_FILTER(OFF)*/ " + ssb.QUERIES["2.1"])
        _rows_close(on.to_pylist(), off.to_pylist())
        s.close()
