"""Two coordinator Instances sharing one GMS metadb file: the DCN-plane story.

Reference analog: multiple CNs over one shared GMS (SURVEY.md §5.8): catalog
loads on the second node, leadership is exclusive, background jobs fire once
across the fleet, and config changes propagate through the metadb listener.
"""

import pytest

from galaxysql_tpu.server.instance import Instance
from galaxysql_tpu.server.session import Session


@pytest.fixture()
def gms_dir(tmp_path):
    return str(tmp_path / "shared")


class TestTwoCoordinators:
    def test_second_node_loads_shared_catalog(self, gms_dir):
        a = Instance(data_dir=gms_dir)
        sa = Session(a)
        sa.execute("CREATE DATABASE m")
        sa.execute("USE m")
        sa.execute("CREATE TABLE t (id BIGINT PRIMARY KEY, v BIGINT)")
        sa.execute("INSERT INTO t VALUES (1, 10), (2, 20)")
        a.save()
        sa.close()

        b = Instance(data_dir=gms_dir)
        sb = Session(b, schema="m")
        assert sb.execute("SELECT id, v FROM t ORDER BY id").rows == \
            [(1, 10), (2, 20)]
        sb.close()

    def test_leadership_is_exclusive_and_scheduler_fires_once(self, gms_dir):
        a = Instance(data_dir=gms_dir)
        b = Instance(data_dir=gms_dir)
        # both heartbeat into the SAME node_info table
        a.ha.heartbeat()
        b.ha.heartbeat()
        a.ha.check()
        b.ha.check()
        leaders = [i for i in (a, b) if i.ha.is_leader()]
        assert len(leaders) == 1
        leader = leaders[0]
        follower = a if leader is b else b
        # a due job fires on the leader only
        leader.scheduler.register("job", "analyze", "x", "y", {},
                                  interval_s=3600)
        assert follower.scheduler.run_due() == []
        assert leader.scheduler.run_due() == ["job"]
        # at-most-once per interval: the slot is consumed fleet-wide (the
        # conditional last_fire UPDATE lives in the shared metadb row)
        assert leader.scheduler.run_due() == []
        assert follower.scheduler.run_due() == []

    def test_peer_grant_revoke_invalidates_decision_cache(self, gms_dir):
        # privilege decision caches (meta/privileges.py) are per-Instance;
        # peers share only the metadb, so mutations must broadcast the
        # invalidate_privilege_cache sync action or a peer serves stale auth
        a = Instance(data_dir=gms_dir)
        b = Instance(data_dir=gms_dir)
        a.sync_bus.attach(b.sync_peer())
        b.sync_bus.attach(a.sync_peer())
        sa = Session(a)
        sa.execute("CREATE DATABASE p")
        sa.execute("CREATE USER 'u' IDENTIFIED BY 'pw'")
        # warm B's cache with the DENIED decision, then grant on A
        assert not b.privileges.has_privilege("u", "SELECT", "p", "t")
        sa.execute("USE p")
        sa.execute("GRANT SELECT ON p.t TO 'u'")
        assert b.privileges.has_privilege("u", "SELECT", "p", "t")
        # warm the ALLOWED decision, revoke on A: B must deny again
        sa.execute("REVOKE SELECT ON p.t FROM 'u'")
        assert not b.privileges.has_privilege("u", "SELECT", "p", "t")
        sa.close()

    def test_config_listener_propagates(self, gms_dir):
        a = Instance(data_dir=gms_dir)
        b = Instance(data_dir=gms_dir)
        sa = Session(a)
        sa.execute("SET GLOBAL SLOW_SQL_MS = 4321")
        # node B observes the change through the shared config listener
        fired = b.config_listener.poll()
        assert "config.params" in fired
        assert b.config.get("SLOW_SQL_MS", {}) == 4321
        # and a freshly booted node C sees it immediately (persisted)
        c = Instance(data_dir=gms_dir)
        assert c.config.get("SLOW_SQL_MS", {}) == 4321
        sa.close()


@pytest.mark.fragment_cache
class TestFragmentCacheAcrossCoordinators:
    """Two coordinators over ONE worker-resident table: remote-table fragment
    reuse on each CN, with DML on either side invalidating the other through
    the `invalidate_fragment_cache` SyncBus action (exec/fragment_cache.py).

    Remote tables have no CN-side version, so their fingerprints ride a
    per-table epoch — the broadcast is the ONLY thing standing between a
    peer's write and a stale cached build."""

    @pytest.fixture()
    def two_cns_one_worker(self):
        import os
        import subprocess
        import sys
        init = ("CREATE DATABASE w; USE w; "
                "CREATE TABLE dim (k BIGINT PRIMARY KEY, label VARCHAR(16)); "
                "INSERT INTO dim VALUES (1,'alpha'), (2,'beta'), (3,'gamma')")
        env = dict(os.environ, JAX_PLATFORMS="cpu")
        p = subprocess.Popen(
            [sys.executable, "-m", "galaxysql_tpu.net.worker", "--port", "0",
             "--platform", "cpu", "--init-sql", init],
            cwd=os.path.dirname(os.path.dirname(os.path.abspath(__file__))),
            stdout=subprocess.PIPE, stderr=subprocess.PIPE, env=env,
            text=True)
        line = p.stdout.readline()
        assert line.startswith("WORKER_READY"), line
        port = int(line.split()[1])
        nodes = []
        for _ in range(2):
            inst = Instance()
            s = Session(inst)
            s.execute("CREATE DATABASE w")
            s.execute("USE w")
            s.execute("CREATE TABLE fact (k BIGINT, v BIGINT)")
            s.execute("INSERT INTO fact VALUES (1,10),(2,20),(3,30),(1,40)")
            inst.attach_remote_table("w", "dim", "127.0.0.1", port)
            nodes.append((inst, s))
        (a, sa), (b, sb) = nodes
        # the cross-coordinator invalidation plane: each CN's broadcasts also
        # reach its peer (Instance.sync_peer rides the same SyncBus protocol)
        a.sync_bus.attach(b.sync_peer())
        b.sync_bus.attach(a.sync_peer())
        yield sa, sb
        sa.close()
        sb.close()
        if p.poll() is None:
            p.kill()
            p.wait()

    JOIN = ("SELECT d.label, sum(f.v) FROM fact f JOIN dim d ON f.k = d.k "
            "GROUP BY d.label ORDER BY d.label")

    def test_peer_dml_invalidates_remote_fragment(self, two_cns_one_worker):
        sa, sb = two_cns_one_worker
        a = sa.instance
        a.frag_cache.clear()
        cold = sa.execute(self.JOIN)
        h0 = a.frag_cache.hits
        warm = sa.execute(self.JOIN)
        assert warm.rows == cold.rows
        assert a.frag_cache.hits > h0  # the remote build artifact was reused
        # coordinator B writes through the shared worker; its broadcast must
        # bump A's epoch so A's next read misses and re-reads the worker
        sb.execute("INSERT INTO dim VALUES (9, 'omega')")
        sb.execute("INSERT INTO fact VALUES (9, 900)")
        sa.execute("INSERT INTO fact VALUES (9, 1)")
        got = sa.execute(self.JOIN)
        assert ("omega", 1) in [tuple(r) for r in got.rows]

    def test_txn_commit_rebumps_epoch(self, two_cns_one_worker):
        """The stale-window regression: B writes INSIDE a txn (statement-time
        bump fires pre-commit), A re-caches the still-uncommitted worker
        state under the new epoch, then B COMMITs — the commit-time bump must
        invalidate A's pre-commit fragment or A serves old rows forever."""
        sa, sb = two_cns_one_worker
        sa.execute(self.JOIN)
        sb.execute("BEGIN")
        sb.execute("INSERT INTO dim VALUES (8, 'theta')")
        sa.execute("INSERT INTO fact VALUES (8, 5)")
        # A caches the PRE-commit view under the post-statement epoch
        pre = sa.execute(self.JOIN)
        assert not any(r[0] == "theta" for r in pre.rows)
        sa.execute(self.JOIN)  # warm on the pre-commit view
        sb.execute("COMMIT")
        got = sa.execute(self.JOIN)
        assert ("theta", 5) in [tuple(r) for r in got.rows]

    def test_sync_action_bumps_epoch_directly(self, two_cns_one_worker):
        sa, sb = two_cns_one_worker
        a, b = sa.instance, sb.instance
        e0 = a.frag_cache.epoch("w.dim")
        acks = b.sync_bus.broadcast("invalidate_fragment_cache",
                                    {"schema": "w", "table": "dim"})
        assert any(ack.get("ok") for ack in acks)
        assert a.frag_cache.epoch("w.dim") == e0 + 1
