"""Two coordinator Instances sharing one GMS metadb file: the DCN-plane story.

Reference analog: multiple CNs over one shared GMS (SURVEY.md §5.8): catalog
loads on the second node, leadership is exclusive, background jobs fire once
across the fleet, and config changes propagate through the metadb listener.
"""

import pytest

from galaxysql_tpu.server.instance import Instance
from galaxysql_tpu.server.session import Session


@pytest.fixture()
def gms_dir(tmp_path):
    return str(tmp_path / "shared")


class TestTwoCoordinators:
    def test_second_node_loads_shared_catalog(self, gms_dir):
        a = Instance(data_dir=gms_dir)
        sa = Session(a)
        sa.execute("CREATE DATABASE m")
        sa.execute("USE m")
        sa.execute("CREATE TABLE t (id BIGINT PRIMARY KEY, v BIGINT)")
        sa.execute("INSERT INTO t VALUES (1, 10), (2, 20)")
        a.save()
        sa.close()

        b = Instance(data_dir=gms_dir)
        sb = Session(b, schema="m")
        assert sb.execute("SELECT id, v FROM t ORDER BY id").rows == \
            [(1, 10), (2, 20)]
        sb.close()

    def test_leadership_is_exclusive_and_scheduler_fires_once(self, gms_dir):
        a = Instance(data_dir=gms_dir)
        b = Instance(data_dir=gms_dir)
        # both heartbeat into the SAME node_info table
        a.ha.heartbeat()
        b.ha.heartbeat()
        a.ha.check()
        b.ha.check()
        leaders = [i for i in (a, b) if i.ha.is_leader()]
        assert len(leaders) == 1
        leader = leaders[0]
        follower = a if leader is b else b
        # a due job fires on the leader only
        leader.scheduler.register("job", "analyze", "x", "y", {},
                                  interval_s=3600)
        assert follower.scheduler.run_due() == []
        assert leader.scheduler.run_due() == ["job"]
        # at-most-once per interval: the slot is consumed fleet-wide (the
        # conditional last_fire UPDATE lives in the shared metadb row)
        assert leader.scheduler.run_due() == []
        assert follower.scheduler.run_due() == []

    def test_config_listener_propagates(self, gms_dir):
        a = Instance(data_dir=gms_dir)
        b = Instance(data_dir=gms_dir)
        sa = Session(a)
        sa.execute("SET GLOBAL SLOW_SQL_MS = 4321")
        # node B observes the change through the shared config listener
        fired = b.config_listener.poll()
        assert "config.params" in fired
        assert b.config.get("SLOW_SQL_MS", {}) == 4321
        # and a freshly booted node C sees it immediately (persisted)
        c = Instance(data_dir=gms_dir)
        assert c.config.get("SLOW_SQL_MS", {}) == 4321
        sa.close()
