"""Histograms + HLL NDV sketches feeding the optimizer.

Reference analog: `config/table/statistic/Histogram.java` (equi-depth range
selectivity) and `executor/statistic/ndv` (mergeable HLL).  The done bar:
skewed data flips the join order vs uniform data.
"""

import numpy as np
import pytest

from galaxysql_tpu.meta.statistics import Histogram, NdvSketch
from galaxysql_tpu.server.instance import Instance
from galaxysql_tpu.server.session import Session


class TestNdvSketch:
    def test_estimate_accuracy(self):
        rng = np.random.default_rng(1)
        for true_ndv in (100, 5000, 200_000):
            sk = NdvSketch()
            vals = rng.integers(0, true_ndv, true_ndv * 3)
            # add in chunks: per-partition sketches merge via register max
            a, b = NdvSketch(), NdvSketch()
            a.add_array(vals[: len(vals) // 2])
            b.add_array(vals[len(vals) // 2:])
            sk = a.merge(b)
            est = sk.estimate()
            # the 3x oversample hits ~95% of the domain
            expect = len(np.unique(vals))
            assert abs(est - expect) / expect < 0.08, (true_ndv, est, expect)

    def test_roundtrip(self):
        sk = NdvSketch()
        sk.add_array(np.arange(1000))
        sk2 = NdvSketch.from_json(sk.to_json())
        assert sk2.estimate() == sk.estimate()


class TestHistogram:
    def test_uniform_range_fracs(self):
        h = Histogram.build(np.arange(10_000, dtype=np.int64), 10_000)
        assert abs(h.frac_le(2500) - 0.25) < 0.02
        assert abs(h.frac_le(7500) - 0.75) < 0.02
        assert h.frac_le(-5) == 0.0 and h.frac_le(10**6) == 1.0

    def test_skewed_range_fracs(self):
        # 90% of mass below 10, long tail to 10_000
        vals = np.concatenate([np.random.default_rng(2).integers(0, 10, 9000),
                               np.random.default_rng(3).integers(10, 10_000, 1000)])
        h = Histogram.build(vals.astype(np.int64), 5000)
        assert h.frac_le(10) > 0.85       # the head holds most of the mass
        assert 1.0 - h.frac_le(100) < 0.15


@pytest.fixture()
def session():
    inst = Instance()
    s = Session(inst)
    s.execute("CREATE DATABASE st")
    s.execute("USE st")
    yield s
    s.close()


def _orders(session, sql):
    plan = session.instance.planner.bind_statement(
        __import__("galaxysql_tpu.sql.parser", fromlist=["parse"]).parse(sql),
        "st", [], session)
    return plan.join_orders


class TestOptimizerFeedback:
    def test_analyze_builds_histograms(self, session):
        session.execute("CREATE TABLE t (id BIGINT, v BIGINT)")
        session.instance.store("st", "t").insert_pylists(
            {"id": list(range(5000)), "v": [i % 100 for i in range(5000)]},
            session.instance.tso.next_timestamp())
        session.execute("ANALYZE TABLE t")
        tm = session.instance.catalog.table("st", "t")
        assert tm.stats.row_count == 5000
        assert "id" in tm.stats.histograms and "v" in tm.stats.histograms
        assert abs(tm.stats.ndv["v"] - 100) <= 2
        assert abs(tm.stats.ndv["id"] - 5000) / 5000 < 0.05

    def test_skew_flips_join_order(self, session):
        """Same tables/rows, same query: a selective range filter on the big
        table flips which side leads once the histogram knows the skew."""
        session.execute("CREATE TABLE fact (id BIGINT, k BIGINT, ts BIGINT)")
        session.execute("CREATE TABLE dim (k BIGINT, name BIGINT)")
        inst = session.instance
        n_fact, n_dim = 20_000, 2_000
        # ts is heavily skewed: 99% of rows have ts < 100, 1% reach 1e6
        rng = np.random.default_rng(5)
        ts_vals = np.where(rng.random(n_fact) < 0.99,
                           rng.integers(0, 100, n_fact),
                           rng.integers(100, 10**6, n_fact))
        inst.store("st", "fact").insert_pylists(
            {"id": list(range(n_fact)), "k": [i % n_dim for i in range(n_fact)],
             "ts": ts_vals.tolist()}, inst.tso.next_timestamp())
        inst.store("st", "dim").insert_pylists(
            {"k": list(range(n_dim)), "name": list(range(n_dim))},
            inst.tso.next_timestamp())
        session.execute("ANALYZE TABLE fact, dim")

        # unselective predicate: fact stays big, dim (2k) leads
        q_loose = ("select count(*) from fact, dim "
                   "where fact.k = dim.k and fact.ts >= 0")
        loose = _orders(session, q_loose)
        assert loose and loose[0][0] == "st.dim"

        # selective predicate (ts > 100 keeps ~1%): the filtered fact (~200
        # rows) is now smaller than dim, so fact leads — the histogram is the
        # only thing that can know this (the guess-based 0.3 would say 6000)
        q_tight = ("select count(*) from fact, dim "
                   "where fact.k = dim.k and fact.ts > 100")
        tight = _orders(session, q_tight)
        assert tight and tight[0][0] == "st.fact"
