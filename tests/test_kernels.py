"""Kernel tier: Pallas join/agg kernels + persistent AOT compile cache.

Coverage: Pallas-vs-reference bit-identity on the direct kernel matrix (NULL
keys, empty build, duplicate keys, overflow-ladder doubling, both hybrid
orientations) and on TPC-H Q5/Q9 end-to-end via the KERNEL hint; the
escape-hatch trio proven structurally off-path with trace-time selection
counters (`KERNEL_STATS`) and dispatch-count guards (the SHOW PROFILES
unchanged-dispatch idiom extended to the kernel selector); persistent
AOT-cache restart round trip (save -> boot -> same query with zero steady
retraces and cache hits > 0), corrupted-entry recompile tolerance, and the
compile_cache_* observability surfaces.  Fast target: make kernel-smoke.
"""

import numpy as np
import pytest

import jax
import jax.numpy as jnp

from galaxysql_tpu.exec import operators as ops
from galaxysql_tpu.exec.compile_cache import GLOBAL_COMPILE_CACHE
from galaxysql_tpu.kernels import relational as R
from galaxysql_tpu.server.instance import Instance
from galaxysql_tpu.server.session import Session

pytestmark = pytest.mark.kernel


def _lanes(pairs):
    return [(jnp.asarray(d), None if v is None else jnp.asarray(v))
            for d, v in pairs]


def _leaves(result):
    return [np.asarray(x) for x in jax.tree_util.tree_leaves(result)]


def _assert_bit_identical(a, b):
    la, lb = _leaves(a), _leaves(b)
    assert len(la) == len(lb)
    for x, y in zip(la, lb):
        assert x.dtype == y.dtype and x.shape == y.shape
        assert np.array_equal(x, y)


def _groupby(mode, keys, inputs, specs, live, max_groups, max_rounds=64):
    with R.kernel_scope(mode):
        return R.hash_groupby(_lanes(keys), _lanes(inputs), specs,
                              jnp.asarray(live), max_groups, max_rounds)


def _join(mode, bk, pk, b_live, p_live, cap):
    with R.kernel_scope(mode):
        return R.hash_join_pairs(_lanes(bk), _lanes(pk), jnp.asarray(b_live),
                                 jnp.asarray(p_live), cap)


def _hybrid(mode, bk, pk, b_live, p_live, cap):
    with R.kernel_scope(mode):
        return R.hash_join_probe_hybrid(_lanes(bk), _lanes(pk),
                                        jnp.asarray(b_live),
                                        jnp.asarray(p_live), cap)


# -- Pallas vs reference: direct kernel bit-identity matrix -------------------


class TestPallasBitIdentity:
    """`kernel_scope('pallas')` forces the Pallas formulation (interpret mode
    on CPU); `'off'` forces the reference formulation, which is the
    correctness oracle.  Everything — group placement order, pair slot
    layout, overflow flags — must be BIT-identical, because the Pallas
    kernels reimplement the same deterministic algorithm, not merely the
    same relation."""

    def test_groupby_duplicate_keys(self):
        rng = np.random.default_rng(7)
        n = 1536
        k = rng.integers(0, 53, n).astype(np.int64)  # heavy duplication
        v = rng.integers(-1000, 1000, n).astype(np.int64)
        keys = [(k, None)]
        inputs = [(v, None), (k, None)]
        specs = [R.AggSpec("sum", 0), R.AggSpec("count_star", -1),
                 R.AggSpec("min", 1)]
        live = np.ones(n, bool)
        ref = _groupby("off", keys, inputs, specs, live, 256)
        pal = _groupby("pallas", keys, inputs, specs, live, 256)
        assert not bool(ref.overflow)
        _assert_bit_identical(ref, pal)

    def test_groupby_null_keys(self):
        rng = np.random.default_rng(8)
        n = 1024
        k1 = rng.integers(0, 31, n).astype(np.int64)
        k2 = rng.integers(0, 5, n).astype(np.int64)
        valid1 = rng.random(n) > 0.2  # NULLs form their own groups
        v = rng.integers(0, 100, n).astype(np.int64)
        keys = [(k1, valid1), (k2, None)]
        inputs = [(v, None)]
        specs = [R.AggSpec("sum", 0), R.AggSpec("count_star", -1)]
        live = rng.random(n) > 0.1
        ref = _groupby("off", keys, inputs, specs, live, 512)
        pal = _groupby("pallas", keys, inputs, specs, live, 512)
        _assert_bit_identical(ref, pal)

    def test_groupby_empty_input(self):
        # zero LIVE rows at positive static capacity — the engine's "empty"
        n = 256
        keys = [(np.zeros(n, np.int64), None)]
        inputs = [(np.zeros(n, np.int64), None)]
        specs = [R.AggSpec("sum", 0)]
        live = np.zeros(n, bool)
        ref = _groupby("off", keys, inputs, specs, live, 64)
        pal = _groupby("pallas", keys, inputs, specs, live, 64)
        assert int(ref.num_groups) == 0
        _assert_bit_identical(ref, pal)

    def test_groupby_overflow_ladder_doubling(self):
        """Overflow semantics ARE the ladder contract: both formulations must
        overflow at the same undersized capacity and both must succeed —
        bit-identically — after one doubling."""
        rng = np.random.default_rng(9)
        n = 512
        k = rng.permutation(n).astype(np.int64)  # n distinct groups
        keys = [(k, None)]
        inputs = [(k, None)]
        specs = [R.AggSpec("count_star", -1)]
        live = np.ones(n, bool)
        ref_s = _groupby("off", keys, inputs, specs, live, 16, max_rounds=8)
        pal_s = _groupby("pallas", keys, inputs, specs, live, 16, max_rounds=8)
        assert bool(ref_s.overflow) and bool(pal_s.overflow)
        ref_b = _groupby("off", keys, inputs, specs, live, 1024)
        pal_b = _groupby("pallas", keys, inputs, specs, live, 1024)
        assert not bool(ref_b.overflow) and not bool(pal_b.overflow)
        _assert_bit_identical(ref_b, pal_b)

    def test_join_pairs_duplicates_and_nulls(self):
        rng = np.random.default_rng(10)
        nb, npr = 512, 1024
        bk = rng.integers(0, 37, nb).astype(np.int64)
        pk = rng.integers(0, 50, npr).astype(np.int64)
        bv = rng.random(nb) > 0.15  # NULL build keys never match
        pv = rng.random(npr) > 0.15
        cap = 16 * npr
        ref = _join("off", [(bk, bv)], [(pk, pv)], np.ones(nb, bool),
                    np.ones(npr, bool), cap)
        pal = _join("pallas", [(bk, bv)], [(pk, pv)], np.ones(nb, bool),
                    np.ones(npr, bool), cap)
        assert not bool(ref.overflow)
        _assert_bit_identical(ref, pal)

    def test_join_empty_build(self):
        nb, npr = 128, 256
        bk = np.zeros(nb, np.int64)
        pk = np.zeros(npr, np.int64)
        ref = _join("off", [(bk, None)], [(pk, None)], np.zeros(nb, bool),
                    np.ones(npr, bool), npr)
        pal = _join("pallas", [(bk, None)], [(pk, None)], np.zeros(nb, bool),
                    np.ones(npr, bool), npr)
        assert not np.asarray(ref.live).any()
        _assert_bit_identical(ref, pal)

    @pytest.mark.parametrize("orientation", ["skewed_probe", "skewed_build"])
    def test_hybrid_orientations(self, orientation):
        """The hybrid entry now rides the CSR probe on every backend
        (previously a bare `hash_join_pairs` delegation), so the Pallas
        kernels must reproduce its layout for BOTH skew orientations."""
        rng = np.random.default_rng(11)
        if orientation == "skewed_probe":
            nb, npr, hot_side = 256, 2048, "p"
        else:
            nb, npr, hot_side = 2048, 256, "b"
        bk = rng.integers(0, 40, nb).astype(np.int64)
        pk = rng.integers(0, 40, npr).astype(np.int64)
        hot = bk if hot_side == "b" else pk
        hot[: len(hot) // 2] = 7  # one dominant key
        cap = 8 * max(nb, npr)
        ref = _hybrid("off", [(bk, None)], [(pk, None)], np.ones(nb, bool),
                      np.ones(npr, bool), cap)
        pal = _hybrid("pallas", [(bk, None)], [(pk, None)], np.ones(nb, bool),
                      np.ones(npr, bool), cap)
        assert not bool(ref.overflow)
        _assert_bit_identical(ref, pal)


# -- escape hatches + dispatch guards -----------------------------------------


def _clear_jit_cache():
    with ops._JIT_CACHE_LOCK:
        ops._JIT_CACHE.clear()


def _reset_kernel_stats():
    R.KERNEL_STATS["pallas"] = 0
    R.KERNEL_STATS["reference"] = 0


class TestKernelSelector:
    """The hatch trio must be STRUCTURALLY off-path: with a hatch engaged,
    tracing a program never even consults the Pallas formulation
    (`KERNEL_STATS['pallas']` stays zero) — not merely that results agree."""

    def test_env_hatch_beats_forced_pallas(self, monkeypatch):
        monkeypatch.setattr(R, "_PALLAS_ENV_OFF", True)
        _clear_jit_cache()
        _reset_kernel_stats()
        n = 300
        keys = [(np.arange(n, dtype=np.int64) % 11, None)]
        specs = [R.AggSpec("count_star", -1)]
        _groupby("pallas", keys, [], specs, np.ones(n, bool), 64)
        assert R.KERNEL_STATS["pallas"] == 0
        assert R.KERNEL_STATS["reference"] > 0

    def test_mode_resolution_precedence(self):
        inst = Instance()
        assert R.exec_kernel_mode({"kernel": "off"}, inst) == "off"
        assert R.exec_kernel_mode({"kernel": "pallas"}, inst) == "pallas"
        assert R.exec_kernel_mode({}, inst) == "auto"
        inst.config.set_instance("ENABLE_PALLAS_KERNELS", False)
        assert R.exec_kernel_mode({}, inst) == "off"
        # KERNEL(ON) restores auto selection under a disabling param
        assert R.exec_kernel_mode({"kernel": "on"}, inst) == "auto"

    def test_auto_mode_on_cpu_keeps_reference(self):
        # CPU backend: auto never picks Pallas regardless of row count
        _clear_jit_cache()
        _reset_kernel_stats()
        n = 400
        keys = [(np.arange(n, dtype=np.int64) % 13, None)]
        _groupby("auto", keys, [], [R.AggSpec("count_star", -1)],
                 np.ones(n, bool), 64)
        assert R.KERNEL_STATS["pallas"] == 0

    def test_session_hatches_off_path_and_hint_engages(self):
        # AP-scale rows (> AP_ROW_THRESHOLD): the query must reach the DEVICE
        # aggregation kernels — a host-TP-path query never consults the
        # selector and would prove nothing
        inst = Instance()
        s = Session(inst)
        s.execute("CREATE DATABASE kt; USE kt")
        s.execute("CREATE TABLE t (g BIGINT, v BIGINT) "
                  "PARTITION BY HASH(g) PARTITIONS 4")
        rng = np.random.default_rng(12)
        n = 70_000
        inst.store("kt", "t").insert_arrays(
            {"g": rng.integers(0, 40, n).astype(np.int64),
             "v": rng.integers(0, 1000, n).astype(np.int64)},
            inst.tso.next_timestamp())
        inst.config.set_instance("MPP_MIN_AP_ROWS", 1)  # force mesh execution
        q = "SELECT g, SUM(v), COUNT(*) FROM t GROUP BY g ORDER BY g"

        def fresh():
            # every run must actually TRACE: drop compiled programs AND the
            # fragment cache (a replayed fragment is bit-identical across
            # formulations, so serving it is sound — but it would hide the
            # selector from this structural guard)
            _clear_jit_cache()
            inst.frag_cache.clear()
            _reset_kernel_stats()

        fresh()
        base = s.execute(q)  # default auto on CPU
        assert R.KERNEL_STATS["pallas"] == 0

        fresh()
        off = s.execute("/*+TDDL:KERNEL(OFF)*/ " + q)
        assert R.KERNEL_STATS["pallas"] == 0

        inst.config.set_instance("ENABLE_PALLAS_KERNELS", False)
        fresh()
        param_off = s.execute(q)
        assert R.KERNEL_STATS["pallas"] == 0
        inst.config.set_instance("ENABLE_PALLAS_KERNELS", True)

        fresh()
        pal = s.execute("/*+TDDL:KERNEL(PALLAS)*/ " + q)
        assert R.KERNEL_STATS["pallas"] > 0  # the hint reached the selector
        assert base.rows == off.rows == param_off.rows == pal.rows
        s.close()

    def test_dispatch_count_kernel_off_equals_default(self):
        """SKEW(OFF)-style guard: on CPU the default path IS the reference
        formulation, so a KERNEL(OFF) hint compiles a twin program with the
        exact same dispatch count."""
        inst = Instance()
        s = Session(inst)
        s.execute("CREATE DATABASE kd; USE kd")
        s.execute("CREATE TABLE t (g BIGINT, v BIGINT) "
                  "PARTITION BY HASH(g) PARTITIONS 4")
        rng = np.random.default_rng(13)
        n = 70_000
        inst.store("kd", "t").insert_arrays(
            {"g": rng.integers(0, 20, n).astype(np.int64),
             "v": rng.integers(0, 100, n).astype(np.int64)},
            inst.tso.next_timestamp())
        inst.config.set_instance("MPP_MIN_AP_ROWS", 1)  # force mesh execution
        q = "SELECT g, SUM(v) FROM t GROUP BY g"

        def dispatches(sql):
            s.execute(sql)  # warmup/compile
            ops.reset_dispatch_stats()
            s.execute(sql)
            return ops.DISPATCH_STATS["dispatches"]

        assert dispatches(q) == dispatches("/*+TDDL:KERNEL(OFF)*/ " + q)
        s.close()

    def test_steady_dispatches_unchanged_after_pallas_run(self):
        """The SHOW PROFILES unchanged-dispatch guard, extended to the kernel
        selector: a KERNEL(PALLAS)-hinted run compiles a DIFFERENT program
        (the mode rides the global_jit key) and must not perturb subsequent
        default executions — same dispatch count, zero retraces."""
        inst = Instance()
        s = Session(inst)
        s.execute("CREATE DATABASE kg; USE kg")
        s.execute("CREATE TABLE t (g BIGINT, v BIGINT) "
                  "PARTITION BY HASH(g) PARTITIONS 4")
        rng = np.random.default_rng(14)
        n = 70_000
        inst.store("kg", "t").insert_arrays(
            {"g": rng.integers(0, 16, n).astype(np.int64),
             "v": rng.integers(0, 100, n).astype(np.int64)},
            inst.tso.next_timestamp())
        inst.config.set_instance("MPP_MIN_AP_ROWS", 1)  # force mesh execution
        q = "SELECT g, COUNT(*) FROM t GROUP BY g"
        s.execute(q)  # warmup
        ops.reset_dispatch_stats()
        s.execute(q)
        baseline = ops.DISPATCH_STATS["dispatches"]
        s.execute("/*+TDDL:KERNEL(PALLAS)*/ " + q)  # may dispatch differently
        ops.reset_dispatch_stats()
        ops.reset_compile_stats()
        s.execute(q)
        assert ops.DISPATCH_STATS["dispatches"] == baseline
        assert ops.COMPILE_STATS["retraces"] == 0
        s.close()


# -- TPC-H end-to-end equivalence ---------------------------------------------


@pytest.fixture(scope="module")
def tpch_session():
    from galaxysql_tpu.storage import tpch
    data = tpch.generate(0.005)
    inst = Instance()
    s = Session(inst)
    s.execute("CREATE DATABASE tpch")
    s.execute("USE tpch")
    for t in tpch.TABLE_ORDER:
        s.execute(tpch.TPCH_DDL[t])
        inst.store("tpch", t).insert_arrays(data[t], inst.tso.next_timestamp())
    s.execute("ANALYZE TABLE " + ", ".join(tpch.TABLE_ORDER))
    yield s
    s.close()


class TestTpchKernelEquivalence:
    @pytest.mark.parametrize("qid", [5, 9])
    def test_kernel_on_equals_off(self, tpch_session, qid):
        from galaxysql_tpu.storage.tpch_queries import QUERIES
        s = tpch_session
        off = s.execute("/*+TDDL:KERNEL(OFF)*/ " + QUERIES[qid])
        default = s.execute(QUERIES[qid])
        on = s.execute("/*+TDDL:KERNEL(PALLAS)*/ " + QUERIES[qid])
        assert off.rows == default.rows == on.rows


# -- persistent AOT compile cache ---------------------------------------------


def _restart(data_dir):
    """The validated restart recipe: drop every in-process compiled program
    (ours + jax's), zero the counters, boot a fresh Instance on the same
    data_dir.  Any steady-state program the new process compiles from
    scratch shows up as a retrace."""
    _clear_jit_cache()
    jax.clear_caches()
    ops.reset_compile_stats()
    return Instance(data_dir=str(data_dir))


def _seed_instance(data_dir):
    # fresh-process semantics: in production every program compiled after
    # boot is observed by the attached cache; here, earlier tests may have
    # compiled shared programs BEFORE attach (in-memory hits are never
    # observed), so start the seed process with an empty program set
    _clear_jit_cache()
    jax.clear_caches()
    inst = Instance(data_dir=str(data_dir))
    s = Session(inst)
    s.execute("CREATE DATABASE cc; USE cc")
    s.execute("CREATE TABLE t (g BIGINT, v BIGINT) "
              "PARTITION BY HASH(g) PARTITIONS 4")
    rng = np.random.default_rng(15)
    inst.store("cc", "t").insert_arrays(
        {"g": rng.integers(0, 25, 1500).astype(np.int64),
         "v": rng.integers(0, 500, 1500).astype(np.int64)},
        inst.tso.next_timestamp())
    return inst, s


QUERY = "SELECT g, SUM(v), COUNT(*) FROM t GROUP BY g ORDER BY g"


class TestCompileCachePersistence:
    def test_memory_only_instance_detaches(self):
        Instance()
        assert not GLOBAL_COMPILE_CACHE.attached

    def test_restart_round_trip_zero_steady_retraces(self, tmp_path):
        inst, s = _seed_instance(tmp_path / "db")
        rows = s.execute(QUERY).rows
        s.execute(QUERY)  # steady
        inst.save()
        s.close()

        inst2 = _restart(tmp_path / "db")
        assert GLOBAL_COMPILE_CACHE.attached
        s2 = Session(inst2)
        s2.execute("USE cc")
        rows2 = s2.execute(QUERY).rows
        assert rows2 == rows
        assert ops.COMPILE_STATS["cache_hits"] > 0
        assert ops.COMPILE_STATS["retraces"] == 0
        # and the replayed programs stay steady
        ops.reset_compile_stats()
        s2.execute(QUERY)
        assert ops.COMPILE_STATS["retraces"] == 0
        s2.close()

    def test_corrupted_entries_recompile_never_error(self, tmp_path):
        inst, s = _seed_instance(tmp_path / "db")
        rows = s.execute(QUERY).rows
        inst.save()
        s.close()

        cache_dir = tmp_path / "db" / "compile_cache"
        entries = sorted(cache_dir.glob("*.aot"))
        assert entries
        for p in entries:
            p.write_bytes(b"\x00garbage not a pickle\xff" * 7)

        inst2 = _restart(tmp_path / "db")
        s2 = Session(inst2)
        s2.execute("USE cc")
        assert s2.execute(QUERY).rows == rows  # recompiles, never errors
        assert ops.COMPILE_STATS["cache_hits"] == 0
        assert ops.COMPILE_STATS["retraces"] > 0
        # the bad entries were dropped so the next save can rewrite them
        assert not any(p.exists() for p in entries)
        s2.close()

    def test_compile_cache_metrics_surface(self, tmp_path):
        inst, s = _seed_instance(tmp_path / "db")
        s.execute(QUERY)
        inst.save()
        names = {r[0] for r in s.execute("SHOW METRICS").rows}
        assert {"compile_cache_hits", "compile_cache_misses",
                "compile_cache_bytes", "compile_cache_entries"} <= names
        s.close()

    def test_explain_analyze_reports_cached(self, tmp_path):
        inst, s = _seed_instance(tmp_path / "db")
        s.execute(QUERY)
        inst.save()
        s.close()
        inst2 = _restart(tmp_path / "db")
        s2 = Session(inst2)
        s2.execute("USE cc")
        text = "\n".join(str(r[0]) for r in
                         s2.execute("EXPLAIN ANALYZE " + QUERY).rows)
        assert "cached=" in text
        s2.close()

    def test_mesh_sharded_inputs_replay_from_disk(self, tmp_path):
        """A program whose steady-state args are mesh-sharded (MPP scan
        segments) must AOT-lower for that NamedSharding: without it the
        restored executable rejects every call and the disk hit degrades
        into a silent retrace."""
        from jax.sharding import Mesh, NamedSharding, PartitionSpec

        devs = jax.devices()
        if len(devs) < 8:
            pytest.skip("needs the 8-virtual-device mesh")
        mesh = Mesh(np.array(devs[:8]), ("shard",))
        sharded = jax.device_put(
            jnp.arange(8 * 1024, dtype=jnp.int64),
            NamedSharding(mesh, PartitionSpec("shard")))
        key = ("test", "sharded-replay")

        GLOBAL_COMPILE_CACHE.attach(str(tmp_path / "cc"))
        try:
            _clear_jit_cache()
            ops.reset_compile_stats()
            f = ops.global_jit(key, lambda: jax.jit(lambda a: a * 2 + 1))
            r1 = np.asarray(f(sharded))
            GLOBAL_COMPILE_CACHE.flush()

            _clear_jit_cache()
            jax.clear_caches()
            ops.reset_compile_stats()
            f2 = ops.global_jit(key, lambda: jax.jit(lambda a: a * 2 + 1))
            r2 = np.asarray(f2(sharded))
            np.testing.assert_array_equal(r1, r2)
            assert ops.COMPILE_STATS["cache_hits"] == 1
            # the loaded executable must ACCEPT the sharded call — a
            # call-time fallback would count a retrace here
            assert ops.COMPILE_STATS["retraces"] == 0
        finally:
            GLOBAL_COMPILE_CACHE.detach()
