"""Expression engine: JAX lowering vs numpy golden backend, MySQL null semantics."""

import numpy as np
import pytest

from galaxysql_tpu.chunk.batch import (ColumnBatch, Dictionary, batch_from_pydict,
                                       column_from_pylist)
from galaxysql_tpu.expr import ir
from galaxysql_tpu.expr.compiler import ExprCompiler, batch_env
from galaxysql_tpu.types import datatype as dt
from galaxysql_tpu.types import temporal


def _env(batch):
    return {n: (c.np_data(), c.valid if c.valid is None else c.np_valid())
            for n, c in batch.columns.items()}


def both_backends(expr, batch):
    import jax.numpy as jnp
    jf = ExprCompiler(jnp).compile(expr)
    nf = ExprCompiler(np).compile(expr)
    jd, jv = jf(batch_env(batch))
    nd, nv = nf(_env(batch))
    jd = np.asarray(jd)
    nd = np.asarray(nd)
    jvm = np.ones(jd.shape, bool) if jv is None else np.asarray(jv)
    nvm = np.ones(nd.shape, bool) if nv is None else np.asarray(nv)
    np.testing.assert_array_equal(jvm, nvm)
    if jd.dtype.kind == "f":
        np.testing.assert_allclose(jd[jvm], nd[nvm], rtol=1e-5)
    else:
        np.testing.assert_array_equal(jd[jvm], nd[nvm])
    return jd, jvm


def make_batch():
    schema = {
        "a": dt.BIGINT, "b": dt.INT, "p": dt.decimal(15, 2), "q": dt.decimal(15, 2),
        "f": dt.DOUBLE, "s": dt.VARCHAR, "d": dt.DATE,
    }
    return batch_from_pydict({
        "a": [1, 2, None, 4, 5],
        "b": [10, None, 30, 40, 50],
        "p": [1.50, 2.25, 3.00, None, 10.10],
        "q": [2.00, 0.50, None, 4.00, 0.00],
        "f": [0.5, 1.5, 2.5, None, 4.5],
        "s": ["apple", "banana", None, "cherry", "apple"],
        "d": ["1994-01-01", "1994-06-15", "1995-12-31", None, "1996-02-29"],
    }, schema)


def col(batch, name):
    c = batch.columns[name]
    return ir.ColRef(name, c.dtype, c.dictionary)


class TestArithmetic:
    def test_int_add_nulls(self):
        b = make_batch()
        e = ir.call("add", col(b, "a"), col(b, "b"))
        d, v = both_backends(e, b)
        assert v.tolist() == [True, False, False, True, True]
        assert d[0] == 11 and d[3] == 44

    def test_decimal_mul(self):
        b = make_batch()
        e = ir.call("mul", col(b, "p"), col(b, "q"))
        assert e.dtype.clazz == dt.TypeClass.DECIMAL
        d, v = both_backends(e, b)
        # 1.50*2.00=3.00 at scale 4 -> 30000
        assert d[0] == 30000
        assert v.tolist() == [True, True, False, False, True]

    def test_decimal_add_rescale(self):
        b = make_batch()
        e = ir.call("add", col(b, "p"), ir.lit(1))
        d, v = both_backends(e, b)
        assert d[0] == 250  # 2.50 at scale 2

    def test_division_by_zero_is_null(self):
        b = make_batch()
        e = ir.call("div", col(b, "p"), col(b, "q"))
        d, v = both_backends(e, b)
        assert not v[4]  # q=0.00
        # 1.50/2.00 = 0.75 at scale 6 (2+4)
        assert e.dtype.scale == 6
        assert d[0] == 750000

    def test_int_div_is_float(self):
        b = make_batch()
        e = ir.call("div", col(b, "a"), col(b, "b"))
        assert e.dtype.clazz == dt.TypeClass.FLOAT
        d, v = both_backends(e, b)
        np.testing.assert_allclose(d[0], 0.1, rtol=1e-6)

    def test_q1_style_expression(self):
        # l_extendedprice * (1 - l_discount) * (1 + l_tax)
        b = make_batch()
        one = ir.lit(1)
        e = ir.call("mul", ir.call("mul", col(b, "p"),
                                   ir.call("sub", one, col(b, "q"))),
                    ir.call("add", one, col(b, "q")))
        both_backends(e, b)


class TestComparisonsAndLogic:
    def test_cmp_null_propagates(self):
        b = make_batch()
        e = ir.call("gt", col(b, "a"), ir.lit(2))
        d, v = both_backends(e, b)
        assert d[3] and d[4] and not d[0]
        assert not v[2]

    def test_kleene_and_or(self):
        b = make_batch()
        t = ir.call("gt", col(b, "a"), ir.lit(0))   # T T N T T
        f = ir.call("lt", col(b, "b"), ir.lit(0))   # F N F F F
        e = ir.call("and", t, f)
        d, v = both_backends(e, b)
        # T&F=F, T&N=N, N&F=F, T&F=F, T&F=F
        assert v.tolist() == [True, False, True, True, True]
        assert not d[0]
        e2 = ir.call("or", t, f)
        d2, v2 = both_backends(e2, b)
        # T|F=T, T|N=T, N|F=N, ...
        assert v2.tolist() == [True, True, False, True, True]

    def test_between_dates(self):
        b = make_batch()
        e = ir.call("between", col(b, "d"), ir.lit("1994-01-01"), ir.lit("1994-12-31"))
        d, v = both_backends(e, b)
        assert d[0] and d[1] and not d[2]
        assert not v[3]

    def test_is_null(self):
        b = make_batch()
        e = ir.call("is_null", col(b, "a"))
        d, v = both_backends(e, b)
        assert d.tolist() == [False, False, True, False, False]
        assert v.all()


class TestStrings:
    def test_eq_literal(self):
        b = make_batch()
        e = ir.call("eq", col(b, "s"), ir.lit("apple"))
        d, v = both_backends(e, b)
        assert d.tolist()[0] and d.tolist()[4] and not d.tolist()[1]
        assert not v[2]

    def test_in_list(self):
        b = make_batch()
        e = ir.InList(col(b, "s"), ("apple", "cherry", "missing"), False)
        d, v = both_backends(e, b)
        assert d[0] and not d[1] and d[3] and d[4]
        assert not v[2]

    def test_like(self):
        b = make_batch()
        e = ir.call("like", col(b, "s"), ir.lit("%an%"))
        d, v = both_backends(e, b)
        assert d.tolist() == [False, True, False, False, False]

    def test_ordering_via_ranks(self):
        b = make_batch()
        e = ir.call("lt", col(b, "s"), ir.lit("banana"))
        d, v = both_backends(e, b)
        assert d.tolist()[0] and not d.tolist()[1] and not d.tolist()[3]


class TestTemporal:
    def test_year_extract(self):
        b = make_batch()
        e = ir.call("year", col(b, "d"))
        d, v = both_backends(e, b)
        assert d.tolist()[:3] == [1994, 1994, 1995]

    def test_civil_roundtrip(self):
        for s in ["1970-01-01", "1992-02-29", "1999-12-31", "2024-03-01", "1900-01-01"]:
            days = temporal.parse_date(s)
            assert temporal.format_date(days) == s

    def test_date_add_months_clamps(self):
        d = temporal.parse_date("1994-01-31")
        assert temporal.format_date(temporal.add_interval_months(d, 1)) == "1994-02-28"

    def test_date_plus_days(self):
        b = make_batch()
        e = ir.call("date_add_days", col(b, "d"), ir.lit(90))
        d, v = both_backends(e, b)
        assert temporal.format_date(d[0]) == "1994-04-01"


class TestCase:
    def test_case_when(self):
        b = make_batch()
        c1 = ir.call("gt", col(b, "a"), ir.lit(3))
        e = ir.Case([(c1, ir.lit(100))], ir.lit(0), dt.BIGINT)
        d, v = both_backends(e, b)
        assert d.tolist()[0] == 0 and d.tolist()[3] == 100

    def test_coalesce(self):
        b = make_batch()
        e = ir.call("coalesce", col(b, "a"), col(b, "b"))
        d, v = both_backends(e, b)
        assert d[2] == 30
        assert v.all()
