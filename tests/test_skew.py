"""Skew-aware distributed execution: heavy-hitter hybrid joins + salted agg.

Coverage: hybrid-on vs SKEW(OFF) bit-identical results on Q9-like joins and
salted aggregation across a Zipf theta sweep {0, 0.8, 1.2}, both hybrid
orientations (skewed probe / skewed build), NULL-key and empty-build edges,
stats-drift deactivation, fragment-cache invalidation when the hot-key set
changes, the escape-hatch trio, shard-skew observability surfaces, and a
dispatch-count guard proving the uniform-data path is unchanged.
"""

import numpy as np
import pytest

from galaxysql_tpu.exec import operators as ops
from galaxysql_tpu.exec import skew as sk
from galaxysql_tpu.meta.statistics import HeavyHitterSketch
from galaxysql_tpu.parallel import mpp as M
from galaxysql_tpu.parallel.mesh import make_mesh
from galaxysql_tpu.plan import logical as L
from galaxysql_tpu.plan.physical import ExecContext
from galaxysql_tpu.server.instance import Instance
from galaxysql_tpu.server.session import Session

pytestmark = pytest.mark.skew

N = 57344           # rows per fact table (>= exec/skew.MIN_SKEW_ROWS, and
                    # > AP_ROW_THRESHOLD so session-level runs classify AP)
K = 800             # key domain
MID = 16384         # mid-size dim: big enough that the build does NOT flip


def zipf_keys(rng, theta: float, n: int = N, k: int = K) -> np.ndarray:
    if theta <= 0:
        return rng.integers(0, k, size=n)
    p = np.arange(1, k + 1, dtype=np.float64) ** -theta
    p /= p.sum()
    return rng.choice(k, size=n, p=p)


@pytest.fixture(scope="module")
def env():
    import jax
    assert len(jax.devices()) >= 8
    rng = np.random.default_rng(13)
    inst = Instance()
    s = Session(inst)
    s.execute("CREATE DATABASE sk; USE sk")
    tables = []
    for name, theta in (("fact_t0", 0.0), ("fact_t08", 0.8),
                        ("fact_t12", 1.2)):
        s.execute(f"CREATE TABLE {name} (id BIGINT PRIMARY KEY, k BIGINT, "
                  "v BIGINT) PARTITION BY HASH(id) PARTITIONS 8")
        keys = zipf_keys(rng, theta)
        inst.store("sk", name).insert_arrays(
            {"id": np.arange(N, dtype=np.int64),
             "k": keys.astype(np.int64),
             "v": rng.integers(0, 1000, size=N).astype(np.int64)},
            inst.tso.next_timestamp())
        tables.append(name)
    # "hot" fact: one dominant key (35%) — production hot-key incident shape
    s.execute("CREATE TABLE fact_hot (id BIGINT PRIMARY KEY, k BIGINT, "
              "v BIGINT) PARTITION BY HASH(id) PARTITIONS 8")
    p = np.full(K, 0.65 / (K - 1))
    p[5] = 0.35
    inst.store("sk", "fact_hot").insert_arrays(
        {"id": np.arange(N, dtype=np.int64),
         "k": rng.choice(K, size=N, p=p).astype(np.int64),
         "v": rng.integers(0, 1000, size=N).astype(np.int64)},
        inst.tso.next_timestamp())
    tables.append("fact_hot")
    # dim: one row per key; partitioned by an unrelated column so storage
    # placement does not accidentally align with the exchange hash
    s.execute("CREATE TABLE dim (did BIGINT PRIMARY KEY, k BIGINT, "
              "attr BIGINT) PARTITION BY HASH(did) PARTITIONS 8")
    inst.store("sk", "dim").insert_arrays(
        {"did": (np.arange(K, dtype=np.int64) * 7919) % (1 << 30),
         "k": np.arange(K, dtype=np.int64),
         "attr": np.arange(K, dtype=np.int64) % 7},
        inst.tso.next_timestamp())
    # mid: many rows per key, sized so the engine keeps fact as the BUILD
    # side (no 4x flip) — exercises the skewed-build orientation
    s.execute("CREATE TABLE mid (mid BIGINT PRIMARY KEY, k BIGINT, "
              "w BIGINT) PARTITION BY HASH(mid) PARTITIONS 8")
    inst.store("sk", "mid").insert_arrays(
        {"mid": np.arange(MID, dtype=np.int64),
         "k": (np.arange(MID, dtype=np.int64) * 31) % K,
         "w": np.arange(MID, dtype=np.int64) % 13},
        inst.tso.next_timestamp())
    s.execute("ANALYZE TABLE " + ", ".join(tables + ["dim", "mid"]))
    mesh = make_mesh(8)
    old = M.BROADCAST_BUILD_LIMIT
    M.BROADCAST_BUILD_LIMIT = 0  # force the shuffle shape for every join
    yield inst, s, mesh
    M.BROADCAST_BUILD_LIMIT = old
    s.close()


def run_mpp(inst, mesh, sql, collect=False):
    plan = inst.planner.plan_select(sql, "sk")
    ctx = ExecContext(inst.stores, inst.tso.next_timestamp(), [],
                      archive=inst.archive, archive_instance=inst,
                      hints=plan.hints)
    ctx.collect_stats = collect
    out = M.MppExecutor(ctx, mesh).execute(plan.rel)
    return sorted(out.to_pylist()), ctx


def on_vs_off(inst, mesh, sql):
    rows_on, ctx_on = run_mpp(inst, mesh, sql)
    rows_off, ctx_off = run_mpp(inst, mesh, "/*+TDDL: SKEW(OFF)*/ " + sql)
    assert rows_on == rows_off
    return ctx_on, ctx_off


def hybrid_engaged(ctx):
    return any("mpp-hybrid-join" in t for t in ctx.trace)


def salted(ctx):
    return any("mpp-salted-agg" in t for t in ctx.trace)


class TestSketch:
    def test_heavy_hitters_and_merge(self):
        rng = np.random.default_rng(1)
        a = np.concatenate([np.full(5000, 7), rng.integers(100, 4000, 15000)])
        hh = HeavyHitterSketch()
        hh.add_array(a)
        cands = dict(hh.candidates(1 / 64))
        assert 7 in cands and abs(cands[7] - 0.25) < 0.03
        other = HeavyHitterSketch()
        other.add_array(np.full(20000, 9))
        m = hh.merge(other)
        top = m.candidates(1 / 64)
        assert top[0][0] == 9 and abs(top[0][1] - 0.5) < 0.05
        rt = HeavyHitterSketch.from_json(m.to_json())
        assert rt.total == m.total and rt.counts == m.counts

    def test_mg_bound_many_distinct(self):
        hh = HeavyHitterSketch()
        hh.add_array(np.arange(100000))  # all unique: nothing is frequent
        assert hh.candidates(1 / 64) == []
        assert len(hh.counts) <= HeavyHitterSketch.K

    def test_host_device_hash_twin(self):
        import jax.numpy as jnp
        from galaxysql_tpu.kernels import relational as KK
        vals = np.array([0, 5, -3, 1 << 40, 123456789], dtype=np.int64)
        host = sk.hot_hash_lane(vals.tolist())
        dev64 = np.asarray(KK.hash_columns([(jnp.asarray(vals), None)]))
        assert (host == dev64).all()
        v32 = np.array([0, 5, -3, 77], dtype=np.int32)
        dev32 = np.asarray(KK.hash_columns([(jnp.asarray(v32), None)]))
        assert (sk.hot_hash_lane(v32.tolist()) == dev32).all()


class TestHybridJoin:
    @pytest.mark.parametrize("fact,want_hybrid", [
        ("fact_t0", False), ("fact_t08", None), ("fact_t12", True),
        ("fact_hot", True)])
    def test_theta_sweep_bit_identical(self, env, fact, want_hybrid):
        inst, _s, mesh = env
        sql = (f"SELECT d.attr, COUNT(*), SUM(f.v) FROM {fact} f, dim d "
               "WHERE f.k = d.k GROUP BY d.attr")
        ctx_on, ctx_off = on_vs_off(inst, mesh, sql)
        if want_hybrid is not None:  # theta=0.8 sits on the hot threshold
            assert hybrid_engaged(ctx_on) == want_hybrid
        assert not hybrid_engaged(ctx_off)

    def test_build_orientation(self, env):
        inst, _s, mesh = env
        # mid is big enough that the engine keeps the skewed fact as BUILD
        sql = ("SELECT COUNT(*), SUM(m.w) FROM mid m, fact_hot f "
               "WHERE m.k = f.k")
        ctx_on, _ = on_vs_off(inst, mesh, sql)
        assert any("skew=build" in t for t in ctx_on.trace), ctx_on.trace

    def test_left_and_semi(self, env):
        inst, _s, mesh = env
        # left join keeps unmatched probe rows (restrict dim: half the keys)
        left = ("SELECT COUNT(*), SUM(f.v), COUNT(d.attr) FROM fact_hot f "
                "LEFT JOIN dim d ON f.k = d.k AND d.k < 400")
        ctx_on, _ = on_vs_off(inst, mesh, left)
        assert hybrid_engaged(ctx_on)
        semi = ("SELECT COUNT(*), SUM(v) FROM fact_hot WHERE k IN "
                "(SELECT k FROM dim WHERE attr < 3)")
        ctx_on, _ = on_vs_off(inst, mesh, semi)

    def test_null_keys_and_empty_build(self, env):
        inst, s, mesh = env
        s.execute("CREATE TABLE fnull (id BIGINT PRIMARY KEY, k BIGINT, "
                  "v BIGINT) PARTITION BY HASH(id) PARTITIONS 8")
        rng = np.random.default_rng(3)
        keys = zipf_keys(rng, 1.2).astype(object)
        keys[::17] = None  # ~6% NULL join keys
        inst.store("sk", "fnull").insert_pylists(
            {"id": list(range(N)), "k": list(keys),
             "v": [int(x) for x in rng.integers(0, 100, N)]},
            inst.tso.next_timestamp())
        s.execute("ANALYZE TABLE fnull")
        on_vs_off(inst, mesh,
                  "SELECT COUNT(*), SUM(f.v) FROM fnull f, dim d "
                  "WHERE f.k = d.k")
        on_vs_off(inst, mesh,
                  "SELECT COUNT(*), SUM(f.v), COUNT(d.attr) FROM fnull f "
                  "LEFT JOIN dim d ON f.k = d.k")
        # empty build side: no dim rows survive the filter
        on_vs_off(inst, mesh,
                  "SELECT COUNT(*), SUM(f.v) FROM fnull f, dim d "
                  "WHERE f.k = d.k AND d.k < 0")

    def test_steady_state_retraces_zero(self, env):
        inst, _s, mesh = env
        sql = ("SELECT COUNT(*), SUM(f.v) FROM fact_hot f, dim d "
               "WHERE f.k = d.k")
        run_mpp(inst, mesh, sql)
        inst.frag_cache.clear()
        ops.reset_compile_stats()
        ctx, _ = run_mpp(inst, mesh, sql)[1], None
        assert ops.COMPILE_STATS["retraces"] == 0

    def test_dispatch_guard_uniform_path_unchanged(self, env):
        inst, _s, mesh = env
        sql = ("SELECT d.attr, COUNT(*) FROM fact_t0 f, dim d "
               "WHERE f.k = d.k GROUP BY d.attr")
        run_mpp(inst, mesh, sql)  # warm compiles
        run_mpp(inst, mesh, "/*+TDDL: SKEW(OFF)*/ " + sql)

        def dispatches(q):
            inst.frag_cache.clear()
            ops.reset_dispatch_stats()
            run_mpp(inst, mesh, q)
            return ops.DISPATCH_STATS["dispatches"]
        assert dispatches(sql) == dispatches("/*+TDDL: SKEW(OFF)*/ " + sql)


class TestSaltedAgg:
    @pytest.mark.parametrize("fact,want_salt", [
        ("fact_t0", False), ("fact_t08", False), ("fact_t12", True),
        ("fact_hot", True)])
    def test_theta_sweep_bit_identical(self, env, fact, want_salt):
        inst, _s, mesh = env
        sql = (f"SELECT k, COUNT(*), SUM(v), MIN(v), MAX(v) FROM {fact} "
               "GROUP BY k")
        ctx_on, ctx_off = on_vs_off(inst, mesh, sql)
        assert salted(ctx_on) == want_salt
        assert not salted(ctx_off)

    def test_salted_with_filter_prelude(self, env):
        inst, _s, mesh = env
        on_vs_off(inst, mesh,
                  "SELECT k, COUNT(*), SUM(v) FROM fact_hot "
                  "WHERE v < 500 GROUP BY k")


class TestDeactivation:
    def test_stats_drift_deactivates(self, env):
        inst, s, mesh = env
        s.execute("CREATE TABLE fdrift (id BIGINT PRIMARY KEY, k BIGINT, "
                  "v BIGINT) PARTITION BY HASH(id) PARTITIONS 8")
        rng = np.random.default_rng(5)
        p = np.full(K, 0.6 / (K - 1))
        p[0] = 0.4
        inst.store("sk", "fdrift").insert_arrays(
            {"id": np.arange(N, dtype=np.int64),
             "k": rng.choice(K, size=N, p=p).astype(np.int64),
             "v": np.ones(N, dtype=np.int64)},
            inst.tso.next_timestamp())
        s.execute("ANALYZE TABLE fdrift")
        sql = ("SELECT COUNT(*), SUM(f.v) FROM fdrift f, dim d "
               "WHERE f.k = d.k")
        ctx, _ = run_mpp(inst, mesh, sql)[1], None
        assert hybrid_engaged(ctx)
        # bulk load doubles the table WITHOUT re-ANALYZE: the runtime
        # re-check must deactivate the stale plan, not execute it
        inst.store("sk", "fdrift").insert_arrays(
            {"id": np.arange(N, 3 * N, dtype=np.int64),
             "k": rng.integers(0, K, size=2 * N).astype(np.int64),
             "v": np.ones(2 * N, dtype=np.int64)},
            inst.tso.next_timestamp())
        inst.catalog.table("sk", "fdrift").bump_version()
        ctx2, _ = run_mpp(inst, mesh, sql)[1], None
        assert not hybrid_engaged(ctx2)
        assert any("skew-deactivated" in t for t in ctx2.trace)

    def test_runtime_refresh_from_build_side(self, env):
        inst, s, _mesh = env
        tm = inst.catalog.table("sk", "mid")
        tm.stats.heavy_rt.pop("k", None)
        # local-engine join: mid (>= 4096 live rows) is the build side, so
        # its key lane refreshes the runtime sketch as it materializes
        s.execute("SELECT COUNT(*) FROM fact_t0 f, mid m WHERE f.k = m.k")
        hh = tm.stats.heavy_rt.get("k")
        assert hh is not None and hh.total >= 4096


class TestFragmentCacheInvalidation:
    def test_hot_key_set_change_rekeys_fingerprint(self, env):
        inst, _s, mesh = env
        from galaxysql_tpu.exec import fragment_cache as fc
        plan = inst.planner.plan_select(
            "SELECT k, COUNT(*) FROM fact_hot GROUP BY k", "sk")
        agg = next(n for n in L.walk(plan.rel) if isinstance(n, L.Aggregate))
        ctx = ExecContext(inst.stores, inst.tso.next_timestamp(), [],
                          archive=inst.archive, archive_instance=inst)
        key1 = fc.fingerprint(agg, ctx).key
        # the hot-key candidate set changed (a re-ANALYZE after data shifted)
        tm = inst.catalog.table("sk", "fact_hot")
        old = tm.stats.heavy["k"]
        try:
            tm.stats.heavy["k"] = HeavyHitterSketch({11: 30000}, old.total)
            inst.planner.cache.invalidate_all()
            plan2 = inst.planner.plan_select(
                "SELECT k, COUNT(*) FROM fact_hot GROUP BY k", "sk")
            agg2 = next(n for n in L.walk(plan2.rel)
                        if isinstance(n, L.Aggregate))
            ctx2 = ExecContext(inst.stores, inst.tso.next_timestamp(), [],
                               archive=inst.archive, archive_instance=inst)
            key2 = fc.fingerprint(agg2, ctx2).key
            assert key1 != key2
            # disabled skew execution separates the cached shapes too
            ctx3 = ExecContext(inst.stores, inst.tso.next_timestamp(), [],
                               archive=inst.archive, archive_instance=inst,
                               hints={"skew": "off"})
            # same plan (hints only gate execution here): signature goes inert
            key3 = fc.fingerprint(agg2, ctx3).key
            assert key3 != key2
        finally:
            tm.stats.heavy["k"] = old


class TestHatches:
    def test_hint_structurally_unplants(self, env):
        inst, _s, _mesh = env
        sql = "SELECT COUNT(*) FROM fact_hot f, dim d WHERE f.k = d.k"
        plan = inst.planner.plan_select("/*+TDDL: SKEW(OFF)*/ " + sql, "sk")
        assert all(not getattr(n, "skew_plans", None)
                   for n in L.walk(plan.rel))
        plan2 = inst.planner.plan_select(sql, "sk")
        assert any(getattr(n, "skew_plans", None)
                   for n in L.walk(plan2.rel))

    def test_hint_join_agg_split(self, env):
        inst, _s, mesh = env
        sql = ("SELECT f.k, COUNT(*) FROM fact_hot f, dim d "
               "WHERE f.k = d.k GROUP BY f.k")
        _, ctx_j = run_mpp(inst, mesh, "/*+TDDL: SKEW(JOIN)*/ " + sql)
        assert hybrid_engaged(ctx_j) and not salted(ctx_j)
        _, ctx_a = run_mpp(inst, mesh, "/*+TDDL: SKEW(AGG)*/ " + sql)
        assert not hybrid_engaged(ctx_a) and salted(ctx_a)

    def test_param_gates_execution(self, env):
        inst, _s, mesh = env
        sql = "SELECT COUNT(*) FROM fact_hot f, dim d WHERE f.k = d.k"
        inst.config.set_instance("ENABLE_SKEW_EXECUTION", False)
        try:
            _, ctx = run_mpp(inst, mesh, sql)
            assert not hybrid_engaged(ctx)
        finally:
            inst.config.set_instance("ENABLE_SKEW_EXECUTION", True)
        _, ctx2 = run_mpp(inst, mesh, sql)
        assert hybrid_engaged(ctx2)

    def test_session_set_gates_execution(self, env):
        inst, _s, _mesh = env
        s2 = Session(inst)
        s2.execute("USE sk")
        inst.config.set_instance("MPP_MIN_AP_ROWS", 1)
        sql = "SELECT COUNT(*) FROM fact_hot f, dim d WHERE f.k = d.k"
        try:
            s2.execute("SET ENABLE_SKEW_EXECUTION = 0")
            inst.frag_cache.clear()  # a warm mpp agg would skip the join
            s2.execute(sql)
            trace = "\n".join(t[0] for t in s2.execute("SHOW TRACE").rows)
            assert "mpp-hybrid-join" not in trace, trace
            s2.execute("SET ENABLE_SKEW_EXECUTION = 1")
            inst.frag_cache.clear()
            s2.execute(sql)
            trace = "\n".join(t[0] for t in s2.execute("SHOW TRACE").rows)
            assert "mpp-hybrid-join" in trace, trace
        finally:
            inst.config.set_instance("MPP_MIN_AP_ROWS", 1 << 22)
            s2.close()

    def test_env_kill_switch(self, env, monkeypatch):
        inst, _s, _mesh = env
        monkeypatch.setattr(sk, "ENABLED", False)
        inst.planner.cache.invalidate_all()
        sql = "SELECT COUNT(*) FROM fact_hot f, dim d WHERE f.k = d.k"
        try:
            plan = inst.planner.plan_select(sql, "sk")
            assert all(not getattr(n, "skew_plans", None)
                       for n in L.walk(plan.rel))
        finally:
            # drop the unplanted plan so later tests re-plan with skew on
            inst.planner.cache.invalidate_all()


class TestObservability:
    def test_shard_skew_stats_and_gauge(self, env):
        inst, _s, mesh = env
        _, ctx = run_mpp(
            inst, mesh,
            "SELECT COUNT(*) FROM fact_hot f, dim d WHERE f.k = d.k",
            collect=True)
        skews = [st.get("shard_skew") for st in ctx.op_stats
                 if st.get("shard_skew")]
        assert skews, ctx.op_stats
        assert all(x >= 1.0 for x in skews)
        vals = {n: v for n, k_, v, _h in inst.metrics.rows()}
        assert vals.get("mpp_shard_skew", 0) >= 1.0
        info = ctx.skew_stats
        assert any(i.get("kind") == "join" for i in info.values())

    def test_explain_analyze_annotations(self, env):
        inst, _s, _mesh = env
        s2 = Session(inst)
        s2.execute("USE sk")
        s2.execute("SET ENABLE_MPP = 1")
        inst.config.set_instance("MPP_MIN_AP_ROWS", 1)
        old = M.BROADCAST_BUILD_LIMIT
        M.BROADCAST_BUILD_LIMIT = 0
        try:
            r = s2.execute(
                "EXPLAIN ANALYZE SELECT f.k, COUNT(*), SUM(f.v) "
                "FROM fact_hot f, dim d WHERE f.k = d.k GROUP BY f.k")
            text = "\n".join(row[0] for row in r.rows)
            assert "HotKeys(" in text, text
            assert "Salted(" in text, text
        finally:
            M.BROADCAST_BUILD_LIMIT = old
            inst.config.set_instance("MPP_MIN_AP_ROWS", 1 << 22)
            s2.close()

    def test_show_profiles_max_shard_rows(self, env):
        inst, _s, _mesh = env
        s2 = Session(inst)
        s2.execute("USE sk")
        s2.execute("SET ENABLE_QUERY_PROFILING = 1")
        inst.config.set_instance("MPP_MIN_AP_ROWS", 1)
        old = M.BROADCAST_BUILD_LIMIT
        M.BROADCAST_BUILD_LIMIT = 0
        try:
            s2.execute("SELECT COUNT(*) FROM fact_hot f, dim d "
                       "WHERE f.k = d.k")
            r = s2.execute("SHOW PROFILES")
            ix = r.names.index("Max_shard_rows")
            assert any(row[ix] > 0 for row in r.rows)
        finally:
            M.BROADCAST_BUILD_LIMIT = old
            inst.config.set_instance("MPP_MIN_AP_ROWS", 1 << 22)
            s2.close()
