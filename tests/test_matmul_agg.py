"""MXU one-hot matmul aggregation vs the sort-groupby reference kernel."""

import jax.numpy as jnp
import numpy as np
import pytest

from galaxysql_tpu.kernels import relational as K
from galaxysql_tpu.server.instance import Instance
from galaxysql_tpu.server.session import Session


def _to_dict(r: K.GroupByResult):
    """{key tuple: (agg values)} over live slots, NULL key encoded as None."""
    live = np.asarray(r.live)
    out = {}
    for i in np.nonzero(live)[0]:
        key = tuple(
            None if (v is not None and not bool(np.asarray(v)[i]))
            else int(np.asarray(d)[i]) for d, v in r.keys)
        aggs = tuple(
            None if (v is not None and not bool(np.asarray(v)[i]))
            else int(np.asarray(d)[i]) for d, v in r.aggs)
        out[key] = aggs
    return out


class TestMatmulGroupby:
    def _compare(self, keys, inputs, specs, live, domains, max_groups=64):
        a = K.matmul_groupby(keys, inputs, specs, live, domains)
        b = K.sort_groupby(keys, inputs, specs, live, max_groups)
        assert not bool(b.overflow)
        assert _to_dict(a) == _to_dict(b)
        assert int(a.num_groups) == int(b.num_groups)

    def test_matches_sort_groupby_with_nulls_and_negatives(self):
        rng = np.random.default_rng(7)
        n = 5000
        k1 = jnp.asarray(rng.integers(0, 3, n).astype(np.int32))
        k1v = jnp.asarray(rng.random(n) > 0.1)
        k2 = jnp.asarray(rng.integers(0, 2, n).astype(np.int32))
        x = jnp.asarray(rng.integers(-10**12, 10**12, n).astype(np.int64))
        xv = jnp.asarray(rng.random(n) > 0.2)
        live = jnp.asarray(rng.random(n) > 0.15)
        self._compare(
            keys=[(k1, k1v), (k2, None)],
            inputs=[(x, xv)],
            specs=[K.AggSpec("sum", 0), K.AggSpec("count", 0),
                   K.AggSpec("count_star", -1), K.AggSpec("min", 0),
                   K.AggSpec("max", 0)],
            live=live, domains=[3, 2])

    def test_int64_wraparound_is_exact(self):
        # sums that exceed 2^53 (f64 mantissa) still come out exact
        big = (1 << 60)
        x = jnp.asarray(np.array([big, big, big, -5], dtype=np.int64))
        k = jnp.asarray(np.zeros(4, dtype=np.int32))
        live = jnp.ones(4, dtype=jnp.bool_)
        r = K.matmul_groupby([(k, None)], [(x, None)],
                             [K.AggSpec("sum", 0)], live, [1])
        want = np.int64(big) * 3 - 5  # wraps mod 2^64 exactly like int64 does
        assert int(np.asarray(r.aggs[0][0])[0]) == int(want)

    def test_global_agg_domain_one(self):
        x = jnp.asarray(np.arange(100, dtype=np.int64))
        live = jnp.asarray(np.arange(100) % 2 == 0)
        r = K.matmul_groupby([], [(x, None)],
                             [K.AggSpec("sum", 0), K.AggSpec("count_star", -1)],
                             live, [])
        assert int(np.asarray(r.aggs[0][0])[0]) == int(np.arange(0, 100, 2).sum())
        assert int(np.asarray(r.aggs[1][0])[0]) == 50

    def test_empty_input_no_live_groups(self):
        x = jnp.zeros(16, dtype=jnp.int64)
        k = jnp.zeros(16, dtype=jnp.int32)
        live = jnp.zeros(16, dtype=jnp.bool_)
        r = K.matmul_groupby([(k, None)], [(x, None)],
                             [K.AggSpec("sum", 0)], live, [4])
        assert int(r.num_groups) == 0 and not np.asarray(r.live).any()


class TestEngineUsesMatmulAgg:
    def test_q1_style_query_correct(self):
        inst = Instance()
        s = Session(inst)
        s.execute("CREATE DATABASE m; USE m")
        s.execute("CREATE TABLE t (flag VARCHAR(1), status VARCHAR(1), qty BIGINT,"
                  " price BIGINT)")
        rng = np.random.default_rng(3)
        n = 4000
        flags = np.array(["A", "N", "R"])[rng.integers(0, 3, n)]
        stats = np.array(["F", "O"])[rng.integers(0, 2, n)]
        qty = rng.integers(1, 100, n)
        price = rng.integers(-1000, 100000, n)
        store = inst.store("m", "t")
        store.insert_arrays({"flag": flags, "status": stats, "qty": qty,
                             "price": price}, inst.tso.next_timestamp())
        # the group keys are dictionary strings: eligible for the matmul path
        from galaxysql_tpu.exec.operators import HashAggOp
        rows = s.execute(
            "SELECT flag, status, sum(qty), count(*), min(price), max(price), "
            "avg(qty) FROM t GROUP BY flag, status ORDER BY flag, status").rows
        import pandas as pd
        df = pd.DataFrame({"flag": flags, "status": stats, "qty": qty,
                           "price": price})
        g = df.groupby(["flag", "status"], sort=True).agg(
            s=("qty", "sum"), c=("qty", "size"), mn=("price", "min"),
            mx=("price", "max"))
        for row, (key, want) in zip(rows, g.iterrows()):
            assert (row[0], row[1]) == key
            assert row[2] == want.s and row[3] == want.c
            assert row[4] == want.mn and row[5] == want.mx
        s.close()
