"""galaxylint + lockdep witness suite (marker: lint; fast target: make lint-smoke).

Covers every lint rule with positive/negative fixture snippets, the pragma and
baseline suppression round-trips, the whole-tree self-run (zero unsuppressed
findings — the same gate `make lint` enforces in CI), and the runtime lockdep
witness: unit cycle-detection plus the failpoint-driven seeded
append_lock/partition-lock inversion caught on a real engine insert ramp.
"""

import threading

import pytest

from galaxysql_tpu.devtools import lint as L
from galaxysql_tpu.devtools.checkers import ALL_CHECKERS
from galaxysql_tpu.devtools.checkers.hygiene import HygieneChecker
from galaxysql_tpu.devtools.checkers.lock_order import LockOrderChecker
from galaxysql_tpu.utils import lockdep
from galaxysql_tpu.utils.failpoint import FAIL_POINTS, FP_LOCK_INVERT

pytestmark = pytest.mark.lint


def rules_of(findings, suppressed=False):
    return sorted({f.rule for f in findings
                   if bool(f.suppressed) == suppressed})


# -- lock-order / lock-blocking ------------------------------------------------

class TestLockOrderRule:
    def test_inversion_flagged(self):
        fs = L.lint_source(
            "def f(store, p):\n"
            "    with p.lock:\n"
            "        with store.append_lock:\n"
            "            pass\n",
            "galaxysql_tpu/storage/x.py")
        assert rules_of(fs) == ["lock-order"]

    def test_canonical_order_clean(self):
        fs = L.lint_source(
            "def f(store, p, metadb):\n"
            "    with store.append_lock, p.lock:\n"
            "        metadb.kv_put('k', 'v')\n"
            "    with p.lock:\n"
            "        pass\n",
            "galaxysql_tpu/storage/x.py")
        # the metadb IO under the partition lock is a lock-blocking warn,
        # but the ORDER is canonical: no lock-order finding
        assert "lock-order" not in rules_of(fs)

    def test_multi_item_with_orders_left_to_right(self):
        fs = L.lint_source(
            "def f(store, p):\n"
            "    with p.lock, store.append_lock:\n"
            "        pass\n",
            "galaxysql_tpu/txn/x.py")
        assert rules_of(fs) == ["lock-order"]

    def test_one_level_call_propagation(self):
        fs = L.lint_source(
            "def helper(self):\n"
            "    with self.append_lock:\n"
            "        pass\n"
            "class MetaDb:\n"
            "    def g(self):\n"
            "        with self._lock:\n"
            "            self.helper()\n",
            "galaxysql_tpu/meta/x.py")
        assert any(f.rule == "lock-order" and "via call to helper" in f.message
                   for f in fs)

    def test_two_same_class_locks_flagged(self):
        fs = L.lint_source(
            "def f(p, part):\n"
            "    with p.lock:\n"
            "        with part.lock:\n"
            "            pass\n",
            "galaxysql_tpu/storage/x.py")
        assert any(f.rule == "lock-order" and "intra-class" in f.message
                   for f in fs)

    def test_reentrant_same_expr_clean(self):
        fs = L.lint_source(
            "class Partition:\n"
            "    def f(self):\n"
            "        with self.lock:\n"
            "            with self.lock:\n"
            "                pass\n",
            "galaxysql_tpu/storage/x.py")
        assert rules_of(fs) == []

    def test_blocking_ops_under_hot_lock(self):
        fs = L.lint_source(
            "import time\n"
            "def f(store, client, metadb):\n"
            "    with store.append_lock:\n"
            "        time.sleep(0.1)\n"
            "        client.request({})\n"
            "        metadb.execute('x')\n"
            "    time.sleep(0.1)\n",  # outside: clean
            "galaxysql_tpu/server/x.py")
        blocking = [f for f in fs if f.rule == "lock-blocking"]
        assert len(blocking) == 3
        assert all(f.line in (4, 5, 6) for f in blocking)

    def test_out_of_scope_dir_ignored(self):
        fs = L.lint_source(
            "def f(store, p):\n"
            "    with p.lock:\n"
            "        with store.append_lock:\n"
            "            pass\n",
            "galaxysql_tpu/plan/x.py")
        assert [f for f in fs if f.rule.startswith("lock-")] == []


# -- jit-raw / jit-device-sync -------------------------------------------------

class TestJitRules:
    def test_raw_jit_flagged(self):
        fs = L.lint_source(
            "import jax\n"
            "def f():\n"
            "    return jax.jit(lambda x: x)\n",
            "galaxysql_tpu/exec/x.py")
        assert rules_of(fs) == ["jit-raw"]

    def test_builder_closure_clean(self):
        fs = L.lint_source(
            "import jax\n"
            "def op(key):\n"
            "    def build():\n"
            "        def run(x):\n"
            "            return x\n"
            "        return jax.jit(run)\n"
            "    return global_jit(key, build)\n"
            "def op2(key):\n"
            "    return global_jit(key, lambda: jax.jit(lambda x: x))\n",
            "galaxysql_tpu/exec/x.py")
        assert rules_of(fs) == []

    def test_raw_pallas_call_flagged(self):
        fs = L.lint_source(
            "from jax.experimental import pallas as pl\n"
            "def f(shape):\n"
            "    return pl.pallas_call(lambda r, o: None, out_shape=shape)\n",
            "galaxysql_tpu/kernels/x.py")
        assert rules_of(fs) == ["pallas-raw"]

    def test_pallas_call_in_builder_clean(self):
        fs = L.lint_source(
            "from jax.experimental import pallas as pl\n"
            "def wrap(key, shape):\n"
            "    def build():\n"
            "        def kernel(r, o):\n"
            "            pass\n"
            "        return pl.pallas_call(kernel, out_shape=shape)\n"
            "    return global_jit(key, build)\n",
            "galaxysql_tpu/kernels/x.py")
        assert rules_of(fs) == []

    def test_device_sync_in_hot_dir_flagged(self):
        fs = L.lint_source(
            "def drain(v):\n"
            "    return v.item()\n"
            "def wait(v):\n"
            "    v.block_until_ready()\n",
            "galaxysql_tpu/exec/x.py")
        assert len([f for f in fs if f.rule == "jit-device-sync"]) == 2

    def test_profiling_scope_allowlisted(self):
        fs = L.lint_source(
            "def profile_drain(v):\n"
            "    return v.item()\n"
            "class Bench:\n"
            "    def run(self, v):\n"
            "        return v.item()\n",  # Bench.run matches 'bench'
            "galaxysql_tpu/exec/x.py")
        assert rules_of(fs) == []

    def test_cold_dir_ignored(self):
        fs = L.lint_source(
            "def f(v):\n"
            "    return v.item()\n",
            "galaxysql_tpu/meta/x.py")
        assert rules_of(fs) == []


# -- swallow / untyped-raise ---------------------------------------------------

class TestTypedErrorRules:
    def test_silent_swallow_flagged(self):
        fs = L.lint_source(
            "def f():\n"
            "    try:\n"
            "        g()\n"
            "    except Exception:\n"
            "        pass\n"
            "def h():\n"
            "    for i in x:\n"
            "        try:\n"
            "            g()\n"
            "        except Exception:\n"
            "            continue\n",
            "galaxysql_tpu/net/x.py")
        assert len([f for f in fs if f.rule == "swallow"]) == 2

    def test_handled_swallows_clean(self):
        fs = L.lint_source(
            "def a():\n"
            "    try:\n"
            "        g()\n"
            "    except Exception:\n"
            "        raise errors.TddlError('x')\n"
            "def b():\n"
            "    try:\n"
            "        g()\n"
            "    except Exception as e:\n"
            "        events.publish('boom', str(e))\n"
            "def c(out):\n"
            "    try:\n"
            "        g()\n"
            "    except Exception as e:\n"
            "        out['err'] = e\n",  # records the exception: handled
            "galaxysql_tpu/net/x.py",
            test_text="boom")  # the published kind is test-covered here
        assert rules_of(fs) == []

    def test_untyped_raise_flagged_on_ramp_only(self):
        src = ("def f():\n"
               "    raise ValueError('boom')\n")
        assert rules_of(L.lint_source(src, "galaxysql_tpu/server/x.py")) == \
            ["untyped-raise"]
        assert rules_of(L.lint_source(src, "galaxysql_tpu/expr/x.py")) == []

    def test_typed_raise_clean(self):
        fs = L.lint_source(
            "def f():\n"
            "    raise errors.QueryTimeoutError('deadline')\n",
            "galaxysql_tpu/server/x.py")
        assert rules_of(fs) == []


# -- hygiene (cross-file) ------------------------------------------------------

class TestHygieneRules:
    def _project(self, srcs, test_text=""):
        mods = [L.Module(p, s) for p, s in srcs]
        return L.Project("", mods, test_text)

    def test_dead_failpoint_flagged(self):
        proj = self._project(
            [("galaxysql_tpu/utils/fp.py", 'FP_NEVER = "FP_NEVER"\n')])
        fs = list(HygieneChecker().finalize(proj))
        assert [f.rule for f in fs] == ["dead-failpoint"]

    def test_armed_failpoint_clean(self):
        proj = self._project(
            [("galaxysql_tpu/utils/fp.py", 'FP_USED = "FP_USED"\n')],
            test_text='FAIL_POINTS.arm(FP_USED)\n')
        assert list(HygieneChecker().finalize(proj)) == []

    def test_failpoint_prefix_of_covered_key_still_dead(self):
        """FP_RPC_DELAY must not count as covered just because tests arm
        FP_RPC_DELAY_MS (word-boundary, not substring, matching)."""
        proj = self._project(
            [("galaxysql_tpu/utils/fp.py",
              'FP_RPC_DELAY = "FP_RPC_DELAY"\n')],
            test_text='FAIL_POINTS.arm(FP_RPC_DELAY_MS, 5)\n')
        fs = list(HygieneChecker().finalize(proj))
        assert [f.rule for f in fs] == ["dead-failpoint"]

    def test_metric_orphans(self):
        metrics = ("DEAD = Counter('dead', 'never updated')\n"
                   "HIDDEN = Counter('hidden', 'never adopted')\n"
                   "GOOD = Counter('good', 'updated and adopted')\n"
                   "HIDDEN.inc()\n"
                   "GOOD.inc()\n")
        inst = ("def boot(reg):\n"
                "    reg.adopt(DEAD)\n"
                "    reg.adopt(GOOD)\n")
        proj = self._project(
            [("galaxysql_tpu/utils/m.py", metrics),
             ("galaxysql_tpu/server/i.py", inst)])
        fs = list(HygieneChecker().finalize(proj))
        assert len(fs) == 2
        assert any("DEAD" in f.message and "never updated" in f.message
                   for f in fs)
        assert any("HIDDEN" in f.message and "never adopted" in f.message
                   for f in fs)
        assert all(f.rule == "metric-orphan" for f in fs)

    # -- event-uncorrelated: trigger-kind publishes must carry digest/trace_id

    def test_uncorrelated_trigger_event_flagged(self):
        fs = L.lint_source(
            "def trip(events, worker):\n"
            "    events.publish('breaker_open', 'worker tripped',\n"
            "                   worker=worker)\n",
            "galaxysql_tpu/server/x.py",
            test_text="breaker_open")  # kind is test-covered; only the
        assert rules_of(fs) == ["event-uncorrelated"]  # correlation is missing

    def test_correlated_trigger_event_clean(self):
        fs = L.lint_source(
            "def regress(events, d, tid):\n"
            "    events.publish('plan_regression', 'plan got slower',\n"
            "                   digest=d)\n"
            "    events.publish('slo_burn', 'window burning',\n"
            "                   trace_id=tid)\n",
            "galaxysql_tpu/server/x.py",
            test_text="plan_regression slo_burn")
        assert "event-uncorrelated" not in rules_of(fs)

    def test_trigger_event_splat_unchecked(self):
        # **kwargs may carry the keys — statically unverifiable, so clean
        fs = L.lint_source(
            "def fwd(events, kw):\n"
            "    events.publish('columnar_tail_failed', 'tail', **kw)\n",
            "galaxysql_tpu/server/x.py",
            test_text="columnar_tail_failed")
        assert "event-uncorrelated" not in rules_of(fs)

    def test_nontrigger_kind_not_checked(self):
        fs = L.lint_source(
            "def note(events):\n"
            "    events.publish('gc_pause', 'background sweep')\n",
            "galaxysql_tpu/server/x.py",
            test_text="gc_pause")
        assert "event-uncorrelated" not in rules_of(fs)

    def test_uncorrelated_pragma_suppresses(self):
        fs = L.lint_source(
            "def trip(events):\n"
            "    events.publish('breaker_open', 'no query context')"
            "  # galaxylint: disable=event-uncorrelated"
            " -- background health loop, no statement to implicate\n",
            "galaxysql_tpu/server/x.py",
            test_text="breaker_open")
        assert "event-uncorrelated" not in rules_of(fs)
        assert "event-uncorrelated" in rules_of(fs, suppressed=True)


# -- pragmas -------------------------------------------------------------------

class TestPragmas:
    SRC = ("def f(store, p):\n"
           "    with p.lock:\n"
           "        with store.append_lock:{pragma}\n"
           "            pass\n")

    def test_justified_pragma_suppresses(self):
        fs = L.lint_source(self.SRC.format(
            pragma="  # galaxylint: disable=lock-order -- seeded inversion"),
            "galaxysql_tpu/storage/x.py")
        assert rules_of(fs) == []                       # nothing unsuppressed
        assert rules_of(fs, suppressed=True) == ["lock-order"]

    def test_unjustified_pragma_suppresses_nothing(self):
        fs = L.lint_source(self.SRC.format(
            pragma="  # galaxylint: disable=lock-order"),
            "galaxysql_tpu/storage/x.py")
        open_rules = rules_of(fs)
        assert "pragma-justify" in open_rules
        assert "lock-order" in open_rules  # NOT suppressed without a why

    def test_wrong_rule_pragma_does_not_suppress(self):
        fs = L.lint_source(self.SRC.format(
            pragma="  # galaxylint: disable=swallow -- wrong rule"),
            "galaxysql_tpu/storage/x.py")
        open_rules = rules_of(fs)
        assert "lock-order" in open_rules
        # and the useless pragma is itself flagged
        assert "pragma-unknown" in open_rules

    def test_stale_pragma_flagged(self):
        """A pragma on a line where nothing fires (typo'd rule name or the
        finding was fixed) must not look like safety."""
        fs = L.lint_source(
            "def f():\n"
            "    x = 1  # galaxylint: disable=lock-ordr -- typo'd rule\n",
            "galaxysql_tpu/storage/x.py")
        assert rules_of(fs) == ["pragma-unknown"]

    def test_file_level_pragma(self):
        fs = L.lint_source(
            "# galaxylint: disable-file=lock-order -- fixture file\n" +
            self.SRC.format(pragma=""),
            "galaxysql_tpu/storage/x.py")
        assert rules_of(fs) == []

    def test_file_level_pragma_hygiene(self):
        # unjustified file pragma: flagged even with no finding in the file
        fs = L.lint_source(
            "# galaxylint: disable-file=swallow\n"
            "X = 1\n",
            "galaxysql_tpu/storage/x.py")
        assert "pragma-justify" in rules_of(fs)
        # justified but nothing fires: stale, delete it
        fs = L.lint_source(
            "# galaxylint: disable-file=swallow -- nothing here\n"
            "X = 1\n",
            "galaxysql_tpu/storage/x.py")
        assert rules_of(fs) == ["pragma-unknown"]


# -- baseline ------------------------------------------------------------------

class TestBaseline:
    def _findings(self):
        return L.lint_source(
            "def f():\n"
            "    try:\n"
            "        g()\n"
            "    except Exception:\n"
            "        pass\n",
            "galaxysql_tpu/net/x.py")

    def test_round_trip_suppresses(self):
        fs = self._findings()
        entries = [{"rule": f.rule, "path": f.path, "qualname": f.qualname,
                    "line_text": f.line_text, "why": "grandfathered"}
                   for f in fs]
        out = L.apply_baseline(self._findings(), entries)
        assert rules_of(out) == []
        assert rules_of(out, suppressed=True) == ["swallow"]

    def test_stale_entry_flagged(self):
        entries = [{"rule": "swallow", "path": "galaxysql_tpu/net/x.py",
                    "qualname": "gone", "line_text": "except Exception:",
                    "why": "was fixed"}]
        out = L.apply_baseline(self._findings(), entries)
        assert "baseline-stale" in rules_of(out)

    def test_unjustified_entry_suppresses_nothing(self):
        fs = self._findings()
        entries = [{"rule": f.rule, "path": f.path, "qualname": f.qualname,
                    "line_text": f.line_text, "why": ""} for f in fs]
        out = L.apply_baseline(self._findings(), entries)
        assert "swallow" in rules_of(out)           # NOT suppressed
        assert "baseline-justify" in rules_of(out)

    def test_save_load_round_trip(self, tmp_path):
        path = str(tmp_path / "baseline.json")
        entries = [{"rule": "swallow", "path": "a.py", "qualname": "f",
                    "line_text": "except Exception:", "why": "because"}]
        L.save_baseline(path, entries)
        assert L.load_baseline(path) == entries


# -- whole-tree self-run -------------------------------------------------------

class TestTreeClean:
    def test_zero_unsuppressed_findings(self):
        """The same gate `make lint` enforces: the committed tree + baseline
        + pragmas lint clean."""
        findings = L.collect()
        open_fs = [f for f in findings if not f.suppressed]
        assert open_fs == [], "\n".join(f.render() for f in open_fs)

    def test_every_suppression_is_justified(self):
        for e in L.load_baseline(L.BASELINE_PATH):
            assert e.get("why"), f"unjustified baseline entry: {e}"

    def test_rules_registered(self):
        rules = {r for ck in ALL_CHECKERS for r in ck.rules}
        assert rules == {"lock-order", "lock-blocking", "jit-raw",
                         "pallas-raw", "jit-device-sync", "swallow",
                         "untyped-raise", "dead-failpoint", "metric-orphan",
                         "event-untested", "histogram-unsampled",
                         "event-uncorrelated"}

    def test_cli_exits_zero(self, capsys):
        assert L.main([]) == 0
        assert "0 finding(s)" in capsys.readouterr().out


# -- lockdep witness (runtime) -------------------------------------------------

@pytest.fixture()
def armed_lockdep():
    lockdep.enable()
    lockdep.WITNESS.reset()
    yield lockdep.WITNESS
    lockdep.disable()
    lockdep.WITNESS.reset()
    FAIL_POINTS.clear()


class TestLockdepUnit:
    def test_disarmed_returns_plain_lock(self):
        assert not lockdep.enabled() or True  # env may arm the whole run
        if not lockdep.enabled():
            lk = lockdep.named_lock("x")
            assert not hasattr(lk, "dep_name")

    def test_consistent_order_clean(self, armed_lockdep):
        a, b, c = (lockdep.named_lock(n) for n in ("la", "lb", "lc"))
        for _ in range(3):
            with a:
                with b:
                    with c:
                        pass
        armed_lockdep.assert_clean()
        assert ("la", "lb") in armed_lockdep.edges()

    def test_inversion_raises(self, armed_lockdep):
        a, b = lockdep.named_lock("ia"), lockdep.named_lock("ib")
        with a:
            with b:
                pass
        with pytest.raises(lockdep.LockOrderViolation, match="inverts"):
            with b:
                with a:
                    pass
        assert armed_lockdep.violations

    def test_three_lock_cycle(self, armed_lockdep):
        a, b, c = (lockdep.named_lock(n) for n in ("ca", "cb", "cc"))
        with a:
            with b:
                pass
        with b:
            with c:
                pass
        with pytest.raises(lockdep.LockOrderViolation):
            with c:
                with a:
                    pass

    def test_reentrant_instance_ok(self, armed_lockdep):
        a = lockdep.named_lock("ra")
        with a:
            with a:
                pass
        armed_lockdep.assert_clean()

    def test_same_class_two_instances_raises(self, armed_lockdep):
        a1, a2 = lockdep.named_lock("pp"), lockdep.named_lock("pp")
        with pytest.raises(lockdep.LockOrderViolation, match="intra-class"):
            with a1:
                with a2:
                    pass

    def test_violation_does_not_wedge(self, armed_lockdep):
        """The inverted lock is never acquired — the thread holds nothing
        extra afterwards and other threads proceed."""
        a, b = lockdep.named_lock("wa"), lockdep.named_lock("wb")
        with a:
            with b:
                pass
        with pytest.raises(lockdep.LockOrderViolation):
            with b:
                with a:
                    pass
        done = []
        t = threading.Thread(target=lambda: (a.acquire(), a.release(),
                                             done.append(1)))
        t.start()
        t.join(5)
        assert done == [1]


class TestLockdepSeeded:
    def test_seeded_inversion_caught_on_insert_ramp(self, armed_lockdep):
        """FP_LOCK_INVERT drives a deliberate partition->append_lock
        acquisition on the real insert ramp; the witness must trip — and a
        disarmed re-run of the identical statement must pass clean."""
        from galaxysql_tpu.server.instance import Instance
        from galaxysql_tpu.server.session import Session
        inst = Instance()
        s = Session(inst)
        try:
            s.execute("CREATE DATABASE ld")
            s.execute("USE ld")
            s.execute("CREATE TABLE t (a BIGINT, b BIGINT) "
                      "PARTITION BY HASH(a) PARTITIONS 2")
            # normal insert: establishes the canonical append->partition edge
            s.execute("INSERT INTO t VALUES (1, 10)")
            armed_lockdep.assert_clean()
            assert any(a == "append_lock" and b.startswith("partition")
                       for a, b in armed_lockdep.edges())
            FAIL_POINTS.arm(FP_LOCK_INVERT, True)
            with pytest.raises(lockdep.LockOrderViolation):
                s.execute("INSERT INTO t VALUES (2, 20)")
            assert armed_lockdep.violations
            # disarmed: the same statement goes through clean
            FAIL_POINTS.clear()
            armed_lockdep.violations.clear()
            s.execute("INSERT INTO t VALUES (3, 30)")
            assert s.execute("SELECT count(*) FROM t").rows[0][0] >= 2
            armed_lockdep.assert_clean()
        finally:
            s.close()

    def test_canonical_write_path_clean(self, armed_lockdep):
        """A write-heavy mixed workload (insert/update/delete + GSI) records
        only DAG edges — every concurrency test doubles as this proof when
        GALAXYSQL_LOCKDEP=1 (the dml/chaos/batch smoke wiring)."""
        from galaxysql_tpu.server.instance import Instance
        from galaxysql_tpu.server.session import Session
        inst = Instance()
        s = Session(inst)
        try:
            s.execute("CREATE DATABASE lw")
            s.execute("USE lw")
            s.execute("CREATE TABLE w (a BIGINT, b BIGINT) "
                      "PARTITION BY HASH(a) PARTITIONS 4")
            s.execute("CREATE GLOBAL INDEX gw ON w (b)")
            for i in range(8):
                s.execute(f"INSERT INTO w VALUES ({i}, {i * 10})")
            s.execute("UPDATE w SET b = 99 WHERE a = 3")
            s.execute("DELETE FROM w WHERE a = 5")
            assert s.execute("SELECT count(*) FROM w").rows == [(7,)]
            armed_lockdep.assert_clean()
        finally:
            s.close()
