"""Pipeline segment fusion: fused-vs-unfused equivalence, the global_jit LRU,
bucket_capacity ladder boundaries, and segment tracing spans.

The `fusion`-marked tests are the fast smoke target (`make fusion-smoke`):
TPC-H Q1/Q3 (+ Q5, SSB Q1.1, TPC-DS Q7) at tiny SF through BOTH execution
paths, asserting identical results — the tier-1 correctness guard for the
fuser."""

import math

import numpy as np
import pytest

import jax.numpy as jnp

from galaxysql_tpu.chunk.batch import Column, ColumnBatch, batch_from_pydict
from galaxysql_tpu.exec import fusion
from galaxysql_tpu.exec import operators as ops
from galaxysql_tpu.exec.fusion import (FusedPipelineOp, FusedSegment,
                                       collapse_streaming_chain)
from galaxysql_tpu.exec.operators import (AggCall, FilterOp, HashAggOp,
                                          ProjectOp, SourceOp, bucket_capacity,
                                          run_to_batch)
from galaxysql_tpu.expr import ir
from galaxysql_tpu.types import datatype as dt


def col(batch, name):
    c = batch.columns[name]
    return ir.ColRef(name, c.dtype, c.dictionary)


def sample_batch(n=200, device=False):
    schema = {"a": dt.BIGINT, "b": dt.DOUBLE, "s": dt.VARCHAR}
    data = {"a": list(range(n)),
            "b": [round(i * 0.25, 2) for i in range(n)],
            "s": ["x" if i % 2 else "y" for i in range(n)]}
    b = batch_from_pydict(data, schema)
    if device:
        cols = {k: Column(jnp.asarray(c.np_data()),
                          None if c.valid is None else jnp.asarray(c.np_valid()),
                          c.dtype, c.dictionary) for k, c in b.columns.items()}
        b = ColumnBatch(cols, None)
    return b


def seg_filter_project(b, lim=100):
    pred = ir.call("lt", col(b, "a"), ir.lit(lim))
    projs = [("c", ir.call("mul", col(b, "b"), ir.lit(2.0))),
             ("a", col(b, "a")), ("s", col(b, "s"))]
    return pred, projs


class TestGlobalJitLru:
    def test_lru_eviction_no_full_clear(self, monkeypatch):
        monkeypatch.setattr(ops, "_JIT_CACHE_LIMIT", 4)
        with ops._JIT_CACHE_LOCK:
            saved = dict(ops._JIT_CACHE)
            ops._JIT_CACHE.clear()
        try:
            for i in range(4):
                ops.global_jit(("lru", i), lambda i=i: f"f{i}")
            # hit entry 0: it becomes most-recent
            assert ops.global_jit(("lru", 0), lambda: "REBUILT") == "f0"
            # overflow: evicts the OLDEST (entry 1), not the whole cache
            ops.global_jit(("lru", 4), lambda: "f4")
            assert len(ops._JIT_CACHE) == 4  # no thundering full clear
            assert ("lru", 1) not in ops._JIT_CACHE
            for k in (("lru", 0), ("lru", 2), ("lru", 3), ("lru", 4)):
                assert k in ops._JIT_CACHE
            # the hit entry survives and does NOT rebuild
            assert ops.global_jit(("lru", 0), lambda: "REBUILT") == "f0"
        finally:
            with ops._JIT_CACHE_LOCK:
                ops._JIT_CACHE.clear()
                ops._JIT_CACHE.update(saved)

    def test_built_flag_fires_only_on_build(self):
        calls = []
        key = ("lru-flag", object())  # unique key
        ops.global_jit(key, lambda: 1, built_flag=lambda: calls.append(1))
        ops.global_jit(key, lambda: 2, built_flag=lambda: calls.append(1))
        assert calls == [1]


class TestBucketCapacityLadder:
    def test_quarter_step_boundaries_above_64k(self):
        K64, K80, K96, K112, K128 = (1 << 16, 80 << 10, 96 << 10,
                                     112 << 10, 1 << 17)
        assert bucket_capacity(K64) == K64
        assert bucket_capacity(K64 + 1) == K80
        assert bucket_capacity(K80) == K80
        assert bucket_capacity(K80 + 1) == K96
        assert bucket_capacity(K96) == K96
        assert bucket_capacity(K96 + 1) == K112
        assert bucket_capacity(K112) == K112
        assert bucket_capacity(K112 + 1) == K128
        assert bucket_capacity(K128) == K128

    def test_exact_powers_of_two(self):
        for p in (10, 14, 16, 17, 18, 20):
            assert bucket_capacity(1 << p) == 1 << p

    def test_below_64k_powers_of_two(self):
        assert bucket_capacity(1) == 1024
        assert bucket_capacity(1025) == 2048
        assert bucket_capacity(40000) == 1 << 16

    def test_quarter_ladder_bounds_padding_waste(self):
        for n in (70000, 100000, 150000, 1_200_000):
            cap = bucket_capacity(n)
            assert cap >= n
            assert cap / n <= 1.26  # ladder caps padding waste at ~25%

    def test_fused_and_unfused_pick_identical_buckets(self):
        # a bucket-padded scan batch flows through both paths shape-preserving:
        # fused and unfused executions see identical capacities end to end
        raw = sample_batch(300, device=True)
        b = raw.pad_to(bucket_capacity(raw.capacity))
        assert b.capacity == bucket_capacity(300) == 1024
        pred, projs = seg_filter_project(b)
        u_out = list(ProjectOp(FilterOp(SourceOp([b]), pred), projs).batches())
        f_out = list(FusedPipelineOp(SourceOp([b]),
                                     FusedSegment([("filter", pred),
                                                   ("project", projs)])).batches())
        assert [o.capacity for o in u_out] == [o.capacity for o in f_out] \
            == [1024]
        u = run_to_batch(ProjectOp(FilterOp(SourceOp([b]), pred), projs))
        f = run_to_batch(FusedPipelineOp(SourceOp([b]),
                                         FusedSegment([("filter", pred),
                                                       ("project", projs)])))
        assert u.capacity == f.capacity


class TestFusedSegment:
    def test_fused_matches_unfused_chain(self):
        for device in (False, True):
            b = sample_batch(200, device=device)
            pred, projs = seg_filter_project(b)
            u = run_to_batch(ProjectOp(FilterOp(SourceOp([b]), pred), projs))
            f = run_to_batch(FusedPipelineOp(
                SourceOp([b]),
                FusedSegment([("filter", pred), ("project", projs)])))
            assert sorted(u.to_pylist()) == sorted(f.to_pylist())

    def test_passthrough_columns_zero_copy(self):
        b = sample_batch(200, device=True)
        pred, projs = seg_filter_project(b)
        seg = FusedSegment([("filter", pred), ("project", projs)])
        out = seg.run_batch(b)
        # untouched lanes are the ORIGINAL buffers, not XLA output copies
        assert out.columns["a"].data is b.columns["a"].data
        assert out.columns["s"].data is b.columns["s"].data
        assert "c" in seg.computed and "a" not in seg.computed

    def test_filter_only_segment_returns_mask_only(self):
        b = sample_batch(200, device=True)
        pred = ir.call("lt", col(b, "a"), ir.lit(42))
        seg = FusedSegment([("filter", pred)])
        out = seg.run_batch(b)
        assert out.num_live() == 42
        for name in b.columns:
            assert out.columns[name].data is b.columns[name].data

    def test_lifted_literals_share_one_program(self):
        b = sample_batch(200, device=True)
        with ops._JIT_CACHE_LOCK:
            before = set(ops._JIT_CACHE)
        keys = set()
        for lim in (10, 50, 120):
            pred, projs = seg_filter_project(b, lim=lim)
            seg = FusedSegment([("filter", pred), ("project", projs)])
            keys.add(seg.key())
            run_to_batch(FusedPipelineOp(SourceOp([b]), seg))
        assert len(keys) == 1  # value-independent: one cache entry, no retrace
        with ops._JIT_CACHE_LOCK:
            added = set(ops._JIT_CACHE) - before
        assert len(added) <= 1

    def test_rename_chain_stays_passthrough(self):
        b = sample_batch(100)
        st1 = ("project", [("x", col(b, "a")), ("b", col(b, "b"))])
        st2 = ("project", [("y", ir.ColRef("x", dt.BIGINT, None)),
                           ("z", ir.call("add", ir.ColRef("x", dt.BIGINT, None),
                                         ir.lit(1)))])
        seg = FusedSegment([st1, st2])
        assert seg.alias["y"] == "a"   # rename-of-rename resolves to the input
        assert seg.alias["z"] is None  # computed
        out = seg.run_batch(b)
        assert out.columns["y"].data is b.columns["a"].data
        np.testing.assert_array_equal(np.asarray(out.columns["z"].data),
                                      np.arange(100) + 1)

    def test_agg_prelude_matches_stacked_operators(self):
        b = sample_batch(400, device=True)
        pred, projs = seg_filter_project(b, lim=300)
        groups = [("s", ir.ColRef("s", dt.VARCHAR, b.columns["s"].dictionary))]
        aggs = [AggCall("sum", ir.ColRef("c", dt.DOUBLE, None), "sc"),
                AggCall("count_star", None, "n")]
        u = run_to_batch(HashAggOp(
            ProjectOp(FilterOp(SourceOp([b]), pred), projs), groups, aggs))
        seg = FusedSegment([("filter", pred), ("project", projs)])
        f = run_to_batch(HashAggOp(SourceOp([b]), groups, aggs, prelude=seg))
        ur = sorted(u.compact().to_pylist())
        fr = sorted(f.compact().to_pylist())
        assert len(ur) == len(fr)
        for ru, rf in zip(ur, fr):
            assert ru[0] == rf[0] and ru[2] == rf[2]
            assert math.isclose(ru[1], rf[1], rel_tol=1e-9)

    def test_collapse_streaming_chain(self):
        from galaxysql_tpu.plan import logical as L
        scan = L.Values([], [])
        pred = ir.call("lt", ir.ColRef("a", dt.BIGINT, None), ir.lit(5))
        node = L.Project(L.Filter(scan, pred),
                         [("a", ir.ColRef("a", dt.BIGINT, None))])
        stages, base = collapse_streaming_chain(node)
        assert [k for k, _ in stages] == ["filter", "project"]
        assert base is scan

    def test_dispatch_counter_counts_fusion_win(self):
        b = sample_batch(200, device=True)
        pred, projs = seg_filter_project(b)
        ops.reset_dispatch_stats()
        run_to_batch(ProjectOp(FilterOp(SourceOp([b]), pred), projs))
        unfused = ops.DISPATCH_STATS["dispatches"]
        ops.reset_dispatch_stats()
        run_to_batch(FusedPipelineOp(
            SourceOp([b]), FusedSegment([("filter", pred), ("project", projs)])))
        fused = ops.DISPATCH_STATS["dispatches"]
        assert (unfused, fused) == (2, 1)


class TestJoinProbePrelude:
    def _sides(self, device=True):
        n = 500
        probe = sample_batch(n, device=device)
        bschema = {"k": dt.BIGINT, "v": dt.DOUBLE}
        bdata = {"k": [i * 3 for i in range(60)],
                 "v": [float(i) for i in range(60)]}
        build = batch_from_pydict(bdata, bschema)
        if device:
            cols = {k: Column(jnp.asarray(c.np_data()), None, c.dtype, None)
                    for k, c in build.columns.items()}
            build = ColumnBatch(cols, None)
        bk = [ir.ColRef("k", dt.BIGINT, None)]
        pk = [ir.ColRef("a", dt.BIGINT, None)]
        pred = ir.call("lt", ir.ColRef("a", dt.BIGINT, None), ir.lit(200))
        return build, probe, bk, pk, pred

    def _check(self, monkeypatch=None, native=True, spill=1 << 62):
        from galaxysql_tpu.exec.operators import HashJoinOp
        build, probe, bk, pk, pred = self._sides()
        if not native:
            from galaxysql_tpu import native as native_mod
            monkeypatch.setattr(native_mod, "AVAILABLE", False)
        u = run_to_batch(HashJoinOp(
            SourceOp([build]), FilterOp(SourceOp([probe]), pred), bk, pk,
            "inner", spill_threshold=spill)).compact()
        seg = FusedSegment([("filter", pred)])
        f = run_to_batch(HashJoinOp(
            SourceOp([build]), SourceOp([probe]), bk, pk, "inner",
            spill_threshold=spill, probe_prelude=seg)).compact()
        assert sorted(u.to_pylist()) == sorted(f.to_pylist())
        assert u.num_live() > 0  # the join actually matched rows

    def test_native_path_matches(self):
        self._check()

    def test_device_path_matches(self, monkeypatch):
        self._check(monkeypatch, native=False)

    def test_grace_spill_path_matches(self, monkeypatch):
        self._check(monkeypatch, native=False, spill=1)

    def test_probe_prelude_saves_the_filter_dispatch(self, monkeypatch):
        from galaxysql_tpu import native as native_mod
        from galaxysql_tpu.exec.operators import HashJoinOp
        monkeypatch.setattr(native_mod, "AVAILABLE", False)
        build, probe, bk, pk, pred = self._sides()
        ops.reset_dispatch_stats()
        run_to_batch(HashJoinOp(SourceOp([build]),
                                FilterOp(SourceOp([probe]), pred), bk, pk,
                                "inner"))
        unfused = ops.DISPATCH_STATS["dispatches"]
        ops.reset_dispatch_stats()
        run_to_batch(HashJoinOp(SourceOp([build]), SourceOp([probe]), bk, pk,
                                "inner",
                                probe_prelude=FusedSegment([("filter", pred)])))
        fused = ops.DISPATCH_STATS["dispatches"]
        assert (unfused, fused) == (1, 0)  # the probe-side filter fused away

    def test_non_inner_joins_reject_prelude(self):
        from galaxysql_tpu.exec.operators import HashJoinOp
        build, probe, bk, pk, pred = self._sides(device=False)
        with pytest.raises(AssertionError):
            HashJoinOp(SourceOp([build]), SourceOp([probe]), bk, pk, "left",
                       probe_prelude=FusedSegment([("filter", pred)]))


class TestSegmentTracing:
    def test_spans_record_chain_rows_and_compile_state(self):
        from galaxysql_tpu.utils.tracing import SEGMENT_TRACER
        b = sample_batch(200, device=True)
        pred, projs = seg_filter_project(b, lim=77)
        seg = FusedSegment([("filter", pred), ("project", projs)])
        SEGMENT_TRACER.clear()
        SEGMENT_TRACER.enabled = True
        try:
            seg.run_batch(b)
            seg.run_batch(b)
        finally:
            SEGMENT_TRACER.enabled = False
        spans = SEGMENT_TRACER.spans()
        assert len(spans) == 2
        s0, s1 = spans
        assert s0.chain == "filter>project"
        assert s0.segment_id == seg.segment_id == s1.segment_id
        assert s0.rows_in == 200 and s0.rows_out == 77
        assert not s1.compiled  # second dispatch is a cache hit
        assert s1.wall_ms >= 0


# -- SQL-level fused-vs-unfused smoke (the `fusion` marker target) ------------


def _rows_close(a, b):
    assert len(a) == len(b)
    for ra, rb in zip(sorted(a), sorted(b)):
        assert len(ra) == len(rb)
        for va, vb in zip(ra, rb):
            if isinstance(va, float) or isinstance(vb, float):
                assert math.isclose(float(va), float(vb),
                                    rel_tol=1e-9, abs_tol=1e-9)
            else:
                assert va == vb


def _run_both(s, sql, monkeypatch):
    r_f = s.execute(sql)
    monkeypatch.setattr(fusion, "ENABLED", False)
    try:
        r_u = s.execute(sql)
    finally:
        monkeypatch.setattr(fusion, "ENABLED", True)
    _rows_close(r_f.rows, r_u.rows)
    return r_f


@pytest.fixture(scope="module")
def tpch_session():
    from galaxysql_tpu.server.instance import Instance
    from galaxysql_tpu.server.session import Session
    from galaxysql_tpu.storage import tpch
    data = tpch.generate(0.01)
    inst = Instance()
    s = Session(inst)
    s.execute("CREATE DATABASE tpch")
    s.execute("USE tpch")
    for t in tpch.TABLE_ORDER:
        s.execute(tpch.TPCH_DDL[t])
        inst.store("tpch", t).insert_pylists(data[t], inst.tso.next_timestamp())
    s.execute("ANALYZE TABLE " + ", ".join(tpch.TABLE_ORDER))
    yield s
    s.close()


@pytest.mark.fusion
class TestTpchFusedVsUnfused:
    def test_q1(self, tpch_session, monkeypatch):
        from galaxysql_tpu.storage.tpch_queries import QUERIES
        r = _run_both(tpch_session, QUERIES[1], monkeypatch)
        assert len(r.rows) == 4

    def test_q3(self, tpch_session, monkeypatch):
        from galaxysql_tpu.storage.tpch_queries import QUERIES
        _run_both(tpch_session, QUERIES[3], monkeypatch)

    def test_q5(self, tpch_session, monkeypatch):
        from galaxysql_tpu.storage.tpch_queries import QUERIES
        _run_both(tpch_session, QUERIES[5], monkeypatch)

    def test_fusion_engages_and_no_fuse_hint_disables(self, tpch_session):
        s = tpch_session
        q = ("select l_returnflag, sum(l_quantity) from lineitem "
             "where l_shipdate <= date '1998-09-02' group by l_returnflag")
        s.execute(q)
        assert any("fuse" in t for t in s.last_trace)
        s.execute("/*+TDDL: NO_FUSE*/ " + q)
        assert not any("fuse" in t for t in s.last_trace)


@pytest.mark.fusion
@pytest.mark.slow  # two extra engine instances + datasets; covered by `make fusion-smoke`
class TestSsbTpcdsFusedVsUnfused:
    @pytest.fixture(scope="class")
    def ssb_session(self):
        from galaxysql_tpu.server.instance import Instance
        from galaxysql_tpu.server.session import Session
        from galaxysql_tpu.storage import ssb
        data = ssb.generate(0.01)
        inst = Instance()
        s = Session(inst)
        s.execute("CREATE DATABASE ssb")
        s.execute("USE ssb")
        for t in ssb.TABLE_ORDER:
            s.execute(ssb.SSB_DDL[t])
            inst.store("ssb", t).insert_arrays(data[t],
                                               inst.tso.next_timestamp())
        s.execute("ANALYZE TABLE " + ", ".join(ssb.TABLE_ORDER))
        yield s
        s.close()

    @pytest.fixture(scope="class")
    def tpcds_session(self):
        from galaxysql_tpu.server.instance import Instance
        from galaxysql_tpu.server.session import Session
        from galaxysql_tpu.storage import tpcds
        data = tpcds.generate(0.005)
        inst = Instance()
        s = Session(inst)
        s.execute("CREATE DATABASE tpcds")
        s.execute("USE tpcds")
        for t in tpcds.TABLE_ORDER:
            s.execute(tpcds.TPCDS_DDL[t])
            inst.store("tpcds", t).insert_pylists(data[t],
                                                  inst.tso.next_timestamp())
        s.execute("ANALYZE TABLE " + ", ".join(tpcds.TABLE_ORDER))
        yield s
        s.close()

    def test_ssb_q1_1(self, ssb_session, monkeypatch):
        from galaxysql_tpu.storage import ssb
        _run_both(ssb_session, ssb.QUERIES["1.1"], monkeypatch)

    def test_tpcds_q7(self, tpcds_session, monkeypatch):
        from galaxysql_tpu.storage import tpcds
        _run_both(tpcds_session, tpcds.QUERIES["q7"], monkeypatch)


@pytest.mark.fusion
@pytest.mark.slow  # compiles MPP shard programs; covered by `make fusion-smoke`
class TestMppFusedVsUnfused:
    def test_mpp_chain_and_agg_prelude(self, tpch_session):
        import jax
        from galaxysql_tpu.parallel.mpp import MppExecutor
        from galaxysql_tpu.plan.physical import ExecContext
        from galaxysql_tpu.storage.tpch_queries import QUERIES
        inst = tpch_session.instance
        mesh = inst.mesh()
        if mesh is None:
            pytest.skip("no multi-device mesh")
        for q in (QUERIES[6], QUERIES[1]):
            plan = inst.planner.plan_select(q, "tpch")
            ctx_f = ExecContext(inst.stores)
            out_f = MppExecutor(ctx_f, mesh).execute(plan.rel)
            ctx_u = ExecContext(inst.stores)
            ctx_u.enable_fusion = False
            out_u = MppExecutor(ctx_u, mesh).execute(plan.rel)
            _rows_close(out_f.to_pylist(), out_u.to_pylist())
            assert any("fuse" in t for t in ctx_f.trace)
            assert not any("fuse" in t for t in ctx_u.trace)
