"""MPP engine tests on the 8-virtual-device CPU mesh: distributed results must equal
the single-device engine's (the LocalServer-style in-proc cluster test, SURVEY.md §4)."""

import numpy as np
import pytest

from galaxysql_tpu.parallel.mesh import make_mesh
from galaxysql_tpu.parallel.mpp import MppExecutor
from galaxysql_tpu.plan.physical import ExecContext
from galaxysql_tpu.server.instance import Instance
from galaxysql_tpu.server.session import Session
from galaxysql_tpu.storage import tpch
from galaxysql_tpu.storage.tpch_queries import QUERIES
from galaxysql_tpu.utils import errors


@pytest.fixture(scope="module")
def env():
    import jax
    assert len(jax.devices()) >= 8, "conftest must provide 8 virtual devices"
    data = tpch.generate(0.01)
    inst = Instance()
    s = Session(inst)
    s.execute("CREATE DATABASE tpch")
    s.execute("USE tpch")
    for t in tpch.TABLE_ORDER:
        s.execute(tpch.TPCH_DDL[t])
        inst.store("tpch", t).insert_arrays(data[t], inst.tso.next_timestamp())
    s.execute("ANALYZE TABLE " + ", ".join(tpch.TABLE_ORDER))
    mesh = make_mesh(8)
    yield inst, s, mesh
    s.close()


def run_mpp(inst, s, mesh, sql):
    plan = inst.planner.plan_select(sql, "tpch")
    ctx = ExecContext(inst.stores, inst.tso.next_timestamp(), [])
    ex = MppExecutor(ctx, mesh)
    return ex.execute(plan.rel)


def rows_of(batch):
    return batch.to_pylist()


def assert_same(mpp_rows, local_rows, ordered):
    if not ordered:
        keyf = lambda r: tuple(str(x) for x in r)
        mpp_rows = sorted(mpp_rows, key=keyf)
        local_rows = sorted(local_rows, key=keyf)
    assert len(mpp_rows) == len(local_rows)
    for a, b in zip(mpp_rows, local_rows):
        assert len(a) == len(b)
        for x, y in zip(a, b):
            if isinstance(x, float) and isinstance(y, float):
                assert abs(x - y) <= max(abs(y) * 1e-6, 1e-6)
            else:
                assert x == y


MPP_QUERIES = {
    # qid: ordered?
    1: True,    # scan + big multi-agg + sort
    3: True,    # 3-way join + agg + topn
    5: True,    # 6-way join incl. broadcast dims
    6: False,   # scan + global agg
    10: True,   # 4-way join + agg + topn
    12: True,   # join + conditional agg
    14: False,  # join + case agg ratio
    19: False,  # factored OR join
}


@pytest.mark.parametrize("qid", sorted(MPP_QUERIES))
def test_tpch_mpp_matches_local(env, qid):
    inst, s, mesh = env
    sql = QUERIES[qid]
    local = s.execute(sql)
    mpp = run_mpp(inst, s, mesh, sql)
    assert_same(rows_of(mpp), local.rows, MPP_QUERIES[qid])


def test_shuffle_join_path(env):
    """Force the hash-shuffle path by dropping the broadcast threshold."""
    import galaxysql_tpu.parallel.mpp as M
    inst, s, mesh = env
    old = M.BROADCAST_BUILD_LIMIT
    M.BROADCAST_BUILD_LIMIT = 0
    try:
        sql = ("SELECT o_orderpriority, count(*) AS n FROM orders, lineitem "
               "WHERE o_orderkey = l_orderkey AND l_quantity < 10 "
               "GROUP BY o_orderpriority ORDER BY o_orderpriority")
        local = s.execute(sql)
        mpp = run_mpp(inst, s, mesh, sql)
        assert_same(rows_of(mpp), local.rows, True)
    finally:
        M.BROADCAST_BUILD_LIMIT = old


def test_semi_anti_join_mpp(env):
    inst, s, mesh = env
    sql = ("SELECT c_custkey FROM customer WHERE c_custkey IN "
           "(SELECT o_custkey FROM orders WHERE o_totalprice > 100) "
           "ORDER BY c_custkey LIMIT 20")
    local = s.execute(sql)
    mpp = run_mpp(inst, s, mesh, sql)
    assert_same(rows_of(mpp), local.rows, True)
    sql2 = ("SELECT count(*) FROM customer WHERE c_custkey NOT IN "
            "(SELECT o_custkey FROM orders)")
    local2 = s.execute(sql2)
    mpp2 = run_mpp(inst, s, mesh, sql2)
    assert_same(rows_of(mpp2), local2.rows, False)
