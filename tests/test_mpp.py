"""MPP engine tests on the 8-virtual-device CPU mesh: distributed results must equal
the single-device engine's (the LocalServer-style in-proc cluster test, SURVEY.md §4).

Coverage: ALL 22 TPC-H queries, all 13 SSB queries, window/union/distinct shapes,
archive-table scans, the shuffle path, and the session-level dispatch (MPP actually
runs, and fallback is counted + traced, never silent)."""

import numpy as np
import pytest

from galaxysql_tpu.parallel.mesh import make_mesh
from galaxysql_tpu.parallel.mpp import MppExecutor
from galaxysql_tpu.plan.physical import ExecContext
from galaxysql_tpu.server.instance import Instance
from galaxysql_tpu.server.session import Session
from galaxysql_tpu.storage import ssb, tpch
from galaxysql_tpu.storage.tpch_queries import QUERIES
from galaxysql_tpu.utils import errors


@pytest.fixture(scope="module")
def env():
    import jax
    assert len(jax.devices()) >= 8, "conftest must provide 8 virtual devices"
    data = tpch.generate(0.01)
    inst = Instance()
    s = Session(inst)
    s.execute("CREATE DATABASE tpch")
    s.execute("USE tpch")
    for t in tpch.TABLE_ORDER:
        s.execute(tpch.TPCH_DDL[t])
        inst.store("tpch", t).insert_arrays(data[t], inst.tso.next_timestamp())
    s.execute("ANALYZE TABLE " + ", ".join(tpch.TABLE_ORDER))
    mesh = make_mesh(8)
    yield inst, s, mesh
    s.close()


@pytest.fixture(scope="module")
def ssb_env():
    data = ssb.generate(0.005)
    inst = Instance()
    s = Session(inst)
    s.execute("CREATE DATABASE ssb; USE ssb")
    for t in ssb.TABLE_ORDER:
        s.execute(ssb.SSB_DDL[t])
        inst.store("ssb", t).insert_arrays(data[t], inst.tso.next_timestamp())
    s.execute("ANALYZE TABLE " + ", ".join(ssb.TABLE_ORDER))
    mesh = make_mesh(8)
    yield inst, s, mesh
    s.close()


def run_mpp(inst, s, mesh, sql, schema="tpch"):
    plan = inst.planner.plan_select(sql, schema)
    ctx = ExecContext(inst.stores, inst.tso.next_timestamp(), [],
                      archive=inst.archive, archive_instance=inst)
    ex = MppExecutor(ctx, mesh)
    return ex.execute(plan.rel)


def rows_of(batch):
    return batch.to_pylist()


def assert_same(mpp_rows, local_rows, ordered):
    if not ordered:
        keyf = lambda r: tuple(str(x) for x in r)
        mpp_rows = sorted(mpp_rows, key=keyf)
        local_rows = sorted(local_rows, key=keyf)
    assert len(mpp_rows) == len(local_rows)
    for a, b in zip(mpp_rows, local_rows):
        assert len(a) == len(b)
        for x, y in zip(a, b):
            if isinstance(x, float) and isinstance(y, float):
                assert abs(x - y) <= max(abs(y) * 1e-6, 1e-6)
            else:
                assert x == y


# every TPC-H query distributes; True = result is ordered (compare in order)
TPCH_ORDERED = {1: True, 2: True, 3: True, 4: True, 5: True, 6: False, 7: True,
                8: True, 9: True, 10: True, 11: True, 12: True, 13: True,
                14: False, 15: True, 16: True, 17: False, 18: True, 19: False,
                20: True, 21: True, 22: True}


@pytest.mark.parametrize("qid", sorted(TPCH_ORDERED))
def test_tpch_mpp_matches_local(env, qid):
    inst, s, mesh = env
    sql = QUERIES[qid]
    local = s.execute(sql)
    mpp = run_mpp(inst, s, mesh, sql)
    assert_same(rows_of(mpp), local.rows, TPCH_ORDERED[qid])


@pytest.mark.parametrize("qid", sorted(ssb.QUERIES))
def test_ssb_mpp_matches_local(ssb_env, qid):
    inst, s, mesh = ssb_env
    sql = ssb.QUERIES[qid]
    local = s.execute(sql)
    mpp = run_mpp(inst, s, mesh, sql, "ssb")
    assert_same(rows_of(mpp), local.rows, True)


def test_shuffle_join_path(env):
    """Force the hash-shuffle path by dropping the broadcast threshold."""
    import galaxysql_tpu.parallel.mpp as M
    inst, s, mesh = env
    old = M.BROADCAST_BUILD_LIMIT
    M.BROADCAST_BUILD_LIMIT = 0
    try:
        sql = ("SELECT o_orderpriority, count(*) AS n FROM orders, lineitem "
               "WHERE o_orderkey = l_orderkey AND l_quantity < 10 "
               "GROUP BY o_orderpriority ORDER BY o_orderpriority")
        local = s.execute(sql)
        mpp = run_mpp(inst, s, mesh, sql)
        assert_same(rows_of(mpp), local.rows, True)
    finally:
        M.BROADCAST_BUILD_LIMIT = old


def test_semi_anti_join_mpp(env):
    inst, s, mesh = env
    sql = ("SELECT c_custkey FROM customer WHERE c_custkey IN "
           "(SELECT o_custkey FROM orders WHERE o_totalprice > 100) "
           "ORDER BY c_custkey LIMIT 20")
    local = s.execute(sql)
    mpp = run_mpp(inst, s, mesh, sql)
    assert_same(rows_of(mpp), local.rows, True)
    sql2 = ("SELECT count(*) FROM customer WHERE c_custkey NOT IN "
            "(SELECT o_custkey FROM orders)")
    local2 = s.execute(sql2)
    mpp2 = run_mpp(inst, s, mesh, sql2)
    assert_same(rows_of(mpp2), local2.rows, False)


class TestMppOperators:
    """Window / union / distinct / multi-distinct / topn distribute."""

    @pytest.fixture(scope="class")
    def wenv(self):
        inst = Instance()
        s = Session(inst)
        s.execute("CREATE DATABASE d; USE d")
        s.execute("CREATE TABLE w (k VARCHAR(4), v BIGINT, y BIGINT)")
        s.execute("CREATE TABLE w2 (k VARCHAR(4), v BIGINT)")
        rng = np.random.default_rng(5)
        inst.store("d", "w").insert_arrays(
            {"k": np.array(["a", "b", "c"])[rng.integers(0, 3, 3000)],
             "v": rng.integers(0, 50, 3000), "y": rng.integers(0, 100, 3000)},
            inst.tso.next_timestamp())
        inst.store("d", "w2").insert_arrays(
            {"k": np.array(["c", "d", "e"])[rng.integers(0, 3, 500)],
             "v": rng.integers(0, 50, 500)}, inst.tso.next_timestamp())
        s.execute("ANALYZE TABLE w, w2")
        yield inst, s, make_mesh(8)
        s.close()

    CASES = {
        "window_frames": ("SELECT k, v, sum(v) OVER (PARTITION BY k ORDER BY v),"
                          " row_number() OVER (PARTITION BY k ORDER BY v DESC),"
                          " rank() OVER (PARTITION BY k ORDER BY v) FROM w"),
        "window_avg": "SELECT k, avg(y) OVER (PARTITION BY k) FROM w",
        "window_global": "SELECT k, rank() OVER (ORDER BY v) FROM w WHERE v < 5",
        "union_all": ("SELECT k, v FROM w WHERE v < 10 "
                      "UNION ALL SELECT k, v FROM w2 WHERE v > 40"),
        "union_distinct": "SELECT k FROM w UNION SELECT k FROM w2",
        "distinct": "SELECT DISTINCT k FROM w",
        "multi_distinct": ("SELECT k, count(DISTINCT v), sum(y), min(y) FROM w "
                           "GROUP BY k"),
        "topn": "SELECT k, v, y FROM w ORDER BY y DESC, v, k LIMIT 17",
    }

    @pytest.mark.parametrize("case", sorted(CASES))
    def test_operator_case(self, wenv, case):
        inst, s, mesh = wenv
        sql = self.CASES[case]
        local = s.execute(sql)
        mpp = run_mpp(inst, s, mesh, sql, "d")
        ordered = "ORDER BY" in sql and "OVER" not in sql
        assert_same(rows_of(mpp), local.rows, ordered)


class TestMppArchive:
    def test_archive_scan_distributes(self):
        pytest.importorskip("pyarrow")
        from galaxysql_tpu.types import temporal
        inst = Instance()
        s = Session(inst)
        s.execute("CREATE DATABASE a; USE a")
        s.execute("CREATE TABLE ev (id BIGINT, d DATE, v BIGINT)")
        base = temporal.parse_date("2020-01-01")
        inst.store("a", "ev").insert_arrays(
            {"id": np.arange(2000), "d": base + np.arange(2000) % 100,
             "v": np.arange(2000) * 3}, inst.tso.next_timestamp())
        s.execute("ANALYZE TABLE ev")
        n = inst.archive.archive_older_than(inst, "a", "ev", "d", base + 50)
        assert n > 0
        mesh = make_mesh(8)
        for sql in ("SELECT count(*), sum(v) FROM ev",
                    "SELECT d, count(*) FROM ev GROUP BY d ORDER BY d LIMIT 10"):
            local = s.execute(sql)
            mpp = run_mpp(inst, s, mesh, sql, "a")
            assert_same(rows_of(mpp), local.rows, True)
            # both hot and archive sides contributed
        plan = inst.planner.plan_select("SELECT count(*) FROM ev", "a")
        ctx = ExecContext(inst.stores, inst.tso.next_timestamp(), [],
                          archive=inst.archive, archive_instance=inst)
        MppExecutor(ctx, mesh).execute(plan.rel)
        assert any("mpp-scan-archive" in t for t in ctx.trace)
        s.close()


class TestSessionDispatch:
    """The session-level MPP path: MPP actually runs above the row threshold,
    and a non-distributable shape falls back LOUDLY (counter + trace tag)."""

    def test_session_runs_mpp_and_counts(self):
        inst = Instance()
        s = Session(inst)
        s.execute("CREATE DATABASE sd; USE sd")
        s.execute("CREATE TABLE big (k VARCHAR(4), v BIGINT)")
        rng = np.random.default_rng(0)
        inst.store("sd", "big").insert_arrays(
            {"k": np.array(["x", "y", "z"])[rng.integers(0, 3, 50_000)],
             "v": rng.integers(0, 1000, 50_000)}, inst.tso.next_timestamp())
        s.execute("ANALYZE TABLE big")
        s.vars["MPP_MIN_AP_ROWS"] = 1000
        before = inst.counters["mpp_queries"]
        r = s.execute("SELECT k, sum(v), count(*) FROM big GROUP BY k ORDER BY k")
        assert len(r.rows) == 3
        if inst.mesh() is not None:  # 8 virtual devices in tests
            assert inst.counters["mpp_queries"] == before + 1
            assert any(t.startswith("mpp-") for t in s.last_trace)
        s.close()

    def test_session_fallback_is_loud(self, monkeypatch):
        inst = Instance()
        s = Session(inst)
        s.execute("CREATE DATABASE sd2; USE sd2")
        s.execute("CREATE TABLE t (k VARCHAR(4), v BIGINT)")
        rng = np.random.default_rng(1)
        inst.store("sd2", "t").insert_arrays(
            {"k": np.array(["x", "y"])[rng.integers(0, 2, 60_000)],
             "v": np.arange(60_000)}, inst.tso.next_timestamp())
        s.execute("ANALYZE TABLE t")
        s.vars["MPP_MIN_AP_ROWS"] = 1000
        if inst.mesh() is None:
            pytest.skip("no multi-device mesh")
        from galaxysql_tpu.parallel.mpp import MppExecutor as ME

        def boom(self, node):
            raise errors.NotSupportedError("test shape")
        monkeypatch.setattr(ME, "run", boom)
        before = inst.counters["mpp_fallback_local"]
        r = s.execute("SELECT k, sum(v) FROM t GROUP BY k")
        assert sum(x[1] for x in r.rows) == int(np.arange(60_000).sum())
        assert inst.counters["mpp_fallback_local"] == before + 1
        assert any(t.startswith("mpp-fallback") for t in s.last_trace)
        # the counter is visible through information_schema
        rows = s.execute("SELECT value FROM information_schema.engine_counters "
                         "WHERE counter_name = 'mpp_fallback_local'").rows
        assert rows and rows[0][0] >= 1
        s.close()
