"""COLLATE expression semantics over dictionary-encoded strings.

Reference analog: `polardbx-common/.../common/collation/*` (~30 handlers) —
here a collation is a host fold function lowered to one code-translation
gather, so CI/AI comparisons stay integer compares on device.
"""

import pytest

from galaxysql_tpu.server.instance import Instance
from galaxysql_tpu.server.session import Session
from galaxysql_tpu.types import collation as coll
from galaxysql_tpu.utils import errors


class TestFoldFns:
    def test_handlers(self):
        assert coll.fold_fn("utf8mb4_bin")("Ab") == "Ab"
        assert coll.fold_fn("utf8mb4_general_ci")("AbC") == "abc"
        assert coll.fold_fn("utf8mb4_0900_ai_ci")("Café") == "cafe"
        assert coll.fold_fn("utf8mb4_unicode_ci")("ÀÉî") == "aei"
        # any *_ci name gets the generic case-fold handler (permissive, like
        # the reference's charset fallback); truly unknown suffixes refuse
        assert coll.fold_fn("klingon_ci")("AB") == "ab"
        with pytest.raises(errors.NotSupportedError):
            coll.fold_fn("klingon_sorting")


@pytest.fixture()
def session():
    inst = Instance()
    s = Session(inst)
    s.execute("CREATE DATABASE co")
    s.execute("USE co")
    s.execute("CREATE TABLE t (id BIGINT, name VARCHAR(32))")
    s.execute("INSERT INTO t VALUES (1,'Apple'), (2,'apple'), (3,'APPLE'), "
              "(4,'Banana'), (5,'café'), (6,'CAFE')")
    yield s
    s.close()


class TestCollateQueries:
    def test_binary_default(self, session):
        r = session.execute("SELECT id FROM t WHERE name = 'apple'")
        assert [x[0] for x in r.rows] == [2]

    def test_ci_equality(self, session):
        r = session.execute(
            "SELECT id FROM t WHERE name = 'apple' COLLATE utf8mb4_general_ci "
            "ORDER BY id")
        assert [x[0] for x in r.rows] == [1, 2, 3]
        # the collation can sit on either side
        r = session.execute(
            "SELECT id FROM t WHERE name COLLATE utf8mb4_general_ci = 'APPLE' "
            "ORDER BY id")
        assert [x[0] for x in r.rows] == [1, 2, 3]

    def test_accent_insensitive(self, session):
        r = session.execute(
            "SELECT id FROM t WHERE name = 'cafe' COLLATE utf8mb4_0900_ai_ci "
            "ORDER BY id")
        assert [x[0] for x in r.rows] == [5, 6]

    def test_ci_group_by(self, session):
        r = session.execute(
            "SELECT count(*) AS c FROM t "
            "GROUP BY name COLLATE utf8mb4_general_ci ORDER BY c DESC")
        assert [x[0] for x in r.rows][0] == 3  # the apple class collapses

    def test_ci_literal_absent_from_table(self, session):
        r = session.execute(
            "SELECT id FROM t WHERE name = 'durian' COLLATE utf8mb4_general_ci")
        assert r.rows == []

    def test_unknown_collation_refused(self, session):
        with pytest.raises(errors.NotSupportedError):
            session.execute(
                "SELECT id FROM t WHERE name = 'x' COLLATE klingon_sorting")


class TestCollationOrdering:
    """Differential ORDER BY under collations vs known MySQL orderings.

    Reference analog: sort keys of `common/collation/*CollationHandler` —
    ordering under *_ci groups case variants ('a' < 'B' although binary code
    order says 'B' < 'a'), *_unicode/_0900_ai_ci also merge accents."""

    @pytest.fixture()
    def osess(self):
        inst = Instance()
        s = Session(inst)
        s.execute("CREATE DATABASE oc")
        s.execute("USE oc")
        s.execute("CREATE TABLE w (id INT, s VARCHAR(20))")
        rows = [(1, "banana"), (2, "Apple"), (3, "cherry"), (4, "apple"),
                (5, "Banana"), (6, "CHERRY")]
        vals = ", ".join(f"({i}, '{v}')" for i, v in rows)
        s.execute(f"INSERT INTO w VALUES {vals}")
        return s

    def test_order_by_ci_matches_mysql(self, osess):
        # MySQL utf8mb4_general_ci: apple-class < banana-class < cherry-class
        r = osess.execute(
            "SELECT s FROM w ORDER BY s COLLATE utf8mb4_general_ci, id")
        got = [x[0].lower() for x in r.rows]
        assert got == ["apple", "apple", "banana", "banana",
                       "cherry", "cherry"]
        # binary ordering differs: uppercase sorts first
        rb = osess.execute("SELECT s FROM w ORDER BY s COLLATE utf8mb4_bin")
        assert [x[0] for x in rb.rows] == sorted(
            ["banana", "Apple", "cherry", "apple", "Banana", "CHERRY"])

    def test_order_by_unicode_ci_accents(self, osess):
        osess.execute("CREATE TABLE acc (id INT, s VARCHAR(20))")
        osess.execute("INSERT INTO acc VALUES (1,'zebra'), (2,'école'), "
                      "(3,'edge'), (4,'Énorme'), (5,'apple')")
        # MySQL utf8mb4_unicode_ci: apple, école/edge/Énorme (e-class,
        # accent-insensitive), zebra
        r = osess.execute(
            "SELECT s FROM acc ORDER BY s COLLATE utf8mb4_unicode_ci")
        got = [x[0] for x in r.rows]
        assert got[0] == "apple" and got[-1] == "zebra"
        assert {g for g in got[1:4]} == {"école", "edge", "Énorme"}
        # 'école' < 'edge'? MySQL ai_ci folds é->e: 'ecole' < 'edge' (c < d)
        assert got[1] == "école"

    def test_range_compare_under_ci(self, osess):
        # s < 'BANANA' under ci: the whole apple class qualifies, banana
        # class does not (equal under the collation), cherry neither
        r = osess.execute(
            "SELECT id FROM w WHERE s COLLATE utf8mb4_general_ci < 'BANANA' "
            "ORDER BY id")
        assert [x[0] for x in r.rows] == [2, 4]
        r = osess.execute(
            "SELECT id FROM w WHERE s COLLATE utf8mb4_general_ci <= 'BANANA' "
            "ORDER BY id")
        assert [x[0] for x in r.rows] == [1, 2, 4, 5]

    def test_min_max_under_ci(self, osess):
        r = osess.execute(
            "SELECT min(s COLLATE utf8mb4_general_ci), "
            "max(s COLLATE utf8mb4_general_ci) FROM w")
        lo, hi = r.rows[0]
        assert lo.lower() == "apple" and hi.lower() == "cherry"

    def test_collation_name_surface(self):
        from galaxysql_tpu.types import collation as coll
        # the enumerated MySQL name set resolves to handler families
        assert len(coll.COLLATIONS) >= 30
        for name in ("utf8mb4_general_ci", "latin1_swedish_ci", "utf8_bin",
                     "utf8mb4_0900_ai_ci", "gbk_chinese_ci",
                     "utf8mb4_0900_as_cs"):
            assert coll.family_of(name)
