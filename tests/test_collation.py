"""COLLATE expression semantics over dictionary-encoded strings.

Reference analog: `polardbx-common/.../common/collation/*` (~30 handlers) —
here a collation is a host fold function lowered to one code-translation
gather, so CI/AI comparisons stay integer compares on device.
"""

import pytest

from galaxysql_tpu.server.instance import Instance
from galaxysql_tpu.server.session import Session
from galaxysql_tpu.types import collation as coll
from galaxysql_tpu.utils import errors


class TestFoldFns:
    def test_handlers(self):
        assert coll.fold_fn("utf8mb4_bin")("Ab") == "Ab"
        assert coll.fold_fn("utf8mb4_general_ci")("AbC") == "abc"
        assert coll.fold_fn("utf8mb4_0900_ai_ci")("Café") == "cafe"
        assert coll.fold_fn("utf8mb4_unicode_ci")("ÀÉî") == "aei"
        # any *_ci name gets the generic case-fold handler (permissive, like
        # the reference's charset fallback); truly unknown suffixes refuse
        assert coll.fold_fn("klingon_ci")("AB") == "ab"
        with pytest.raises(errors.NotSupportedError):
            coll.fold_fn("klingon_sorting")


@pytest.fixture()
def session():
    inst = Instance()
    s = Session(inst)
    s.execute("CREATE DATABASE co")
    s.execute("USE co")
    s.execute("CREATE TABLE t (id BIGINT, name VARCHAR(32))")
    s.execute("INSERT INTO t VALUES (1,'Apple'), (2,'apple'), (3,'APPLE'), "
              "(4,'Banana'), (5,'café'), (6,'CAFE')")
    yield s
    s.close()


class TestCollateQueries:
    def test_binary_default(self, session):
        r = session.execute("SELECT id FROM t WHERE name = 'apple'")
        assert [x[0] for x in r.rows] == [2]

    def test_ci_equality(self, session):
        r = session.execute(
            "SELECT id FROM t WHERE name = 'apple' COLLATE utf8mb4_general_ci "
            "ORDER BY id")
        assert [x[0] for x in r.rows] == [1, 2, 3]
        # the collation can sit on either side
        r = session.execute(
            "SELECT id FROM t WHERE name COLLATE utf8mb4_general_ci = 'APPLE' "
            "ORDER BY id")
        assert [x[0] for x in r.rows] == [1, 2, 3]

    def test_accent_insensitive(self, session):
        r = session.execute(
            "SELECT id FROM t WHERE name = 'cafe' COLLATE utf8mb4_0900_ai_ci "
            "ORDER BY id")
        assert [x[0] for x in r.rows] == [5, 6]

    def test_ci_group_by(self, session):
        r = session.execute(
            "SELECT count(*) AS c FROM t "
            "GROUP BY name COLLATE utf8mb4_general_ci ORDER BY c DESC")
        assert [x[0] for x in r.rows][0] == 3  # the apple class collapses

    def test_ci_literal_absent_from_table(self, session):
        r = session.execute(
            "SELECT id FROM t WHERE name = 'durian' COLLATE utf8mb4_general_ci")
        assert r.rows == []

    def test_unknown_collation_refused(self, session):
        with pytest.raises(errors.NotSupportedError):
            session.execute(
                "SELECT id FROM t WHERE name = 'x' COLLATE klingon_sorting")
