"""Chaos-hardened elastic rebalancing: crashes at every state transition.

The fault matrix drives SPLIT / MERGE / MOVE jobs into a crash at each
failpoint (per-task boundaries via FP_BEFORE_DDL_TASK plus the in-task
checkpoints: mid-backfill chunk, mid-catchup page, inside the cutover
critical section before and after the swap) while DML races the move and
readers watch — asserting, for every schedule:

- queries observe bit-identical-or-typed-error results (never a torn map),
- zero lost and zero duplicated writes among acknowledged DML,
- crash-resume completes the job from its last checkpoint — or, for the
  verify-mismatch schedule, reverse-order undo restores the source exactly
  (FastChecker-proven) and the table keeps serving.

`make chaos-rebalance` runs this file with GALAXYSQL_LOCKDEP=1.
"""

import threading

import pytest

from galaxysql_tpu.ddl import rebalance as rb
from galaxysql_tpu.server.instance import Instance
from galaxysql_tpu.server.session import Session
from galaxysql_tpu.utils import errors
from galaxysql_tpu.utils.failpoint import (FAIL_POINTS, FP_BEFORE_DDL_TASK,
                                           FP_REBALANCE_AFTER_SWAP,
                                           FP_REBALANCE_BEFORE_SWAP,
                                           FP_REBALANCE_CATCHUP,
                                           FP_REBALANCE_CHUNK,
                                           FP_REBALANCE_VERIFY_MISMATCH,
                                           FailPointError)
from galaxysql_tpu.utils.fastchecker import partitions_checksum

pytestmark = pytest.mark.rebalance_chaos

N_SEED = 3000

# (failpoint key, arm value) — one crash site per schedule.  The
# FP_BEFORE_DDL_TASK arms fire on the N-th task boundary, covering the
# transitions the in-task failpoints don't.
SCHEDULES = [
    (FP_BEFORE_DDL_TASK, 3),        # before backfill starts
    (FP_REBALANCE_CHUNK, 3),        # mid-copy, after a persisted checkpoint
    (FP_BEFORE_DDL_TASK, 4),        # before catchup
    (FP_REBALANCE_CATCHUP, 1),      # mid-catchup, after a persisted page
    (FP_BEFORE_DDL_TASK, 5),        # before verify
    (FP_BEFORE_DDL_TASK, 6),        # before cutover
    (FP_REBALANCE_BEFORE_SWAP, 1),  # inside cutover, swap not yet applied
    (FP_REBALANCE_AFTER_SWAP, 1),   # swap durable, cleanup not yet run
    (FP_BEFORE_DDL_TASK, 7),        # before cleanup
]

OPS = [
    ("ALTER TABLE t SPLIT PARTITION p1 INTO 2", 5),
    ("ALTER TABLE t MERGE PARTITIONS p0, p2", 3),
    ("ALTER TABLE t MOVE PARTITION p0 TO 'g1'", 4),
]


@pytest.fixture()
def harness():
    inst = Instance()
    s = Session(inst)
    s.execute("CREATE DATABASE cz")
    s.execute("USE cz")
    s.execute("CREATE TABLE t (id BIGINT PRIMARY KEY, grp BIGINT, "
              "val VARCHAR(16)) PARTITION BY HASH(id) PARTITIONS 4")
    store = inst.store("cz", "t")
    store.insert_pylists(
        {"id": list(range(N_SEED)), "grp": [i % 37 for i in range(N_SEED)],
         "val": [f"v{i % 11}" for i in range(N_SEED)]},
        inst.tso.next_timestamp())
    old_chunk = rb.RebalanceBackfillTask.CHUNK
    rb.RebalanceBackfillTask.CHUNK = 256
    yield inst, s, store
    rb.RebalanceBackfillTask.CHUNK = old_chunk
    FAIL_POINTS.clear()
    s.close()


class _Traffic:
    """Concurrent writers (acked-op ledger) + readers (typed-or-correct)."""

    def __init__(self, inst, n_writers=2):
        self.inst = inst
        self.stop = threading.Event()
        self.acked_ins = []
        self.acked_del = []
        self.reader_violations = []
        self.threads = [
            threading.Thread(target=self._writer, args=(1_000_000 * (k + 1),))
            for k in range(n_writers)
        ] + [threading.Thread(target=self._reader)]

    def _writer(self, base):
        s = Session(self.inst, "cz")
        try:
            i = 0
            while not self.stop.is_set() and i < 500:
                wid = base + i
                try:
                    s.execute(f"INSERT INTO t VALUES ({wid}, {wid % 37}, 'w')")
                    self.acked_ins.append(wid)
                    if i % 5 == 2:
                        s.execute(f"DELETE FROM t WHERE id = {wid}")
                        self.acked_del.append(wid)
                except errors.TddlError:
                    pass  # typed refusal (MDL wait etc.) is in-contract
                i += 1
        finally:
            s.close()

    def _reader(self):
        s = Session(self.inst, "cz")
        try:
            while not self.stop.is_set():
                try:
                    rows = s.execute(
                        "SELECT count(*) FROM t WHERE id < 1000000").rows
                    if rows != [(N_SEED,)]:
                        self.reader_violations.append(rows)
                    s.execute("SELECT grp, val FROM t WHERE id = 17")
                except errors.TddlError:
                    pass  # typed error is the contract under faults
                except Exception as e:  # noqa: BLE001 - the assertion target
                    self.reader_violations.append(repr(e))
        finally:
            s.close()

    def __enter__(self):
        for t in self.threads:
            t.start()
        return self

    def __exit__(self, *exc):
        self.stop.set()
        for t in self.threads:
            t.join()


def _assert_final_state(inst, s, store):
    """Zero lost/duplicated writes + structural integrity after the storm."""
    rows = s.execute("SELECT id FROM t ORDER BY id").rows
    ids = [r[0] for r in rows]
    assert len(ids) == len(set(ids)), "duplicated rows after rebalance"
    assert [i for i in ids if i < 1_000_000] == list(range(N_SEED))
    # routing invariant: every row is where the live router puts it
    tm = inst.catalog.table("cz", "t")
    cols = [tm.column(c).name for c in tm.partition.columns]
    for pid, p in enumerate(store.partitions):
        if p.num_rows:
            got = store.router.route_rows(
                [p.lanes[c][:p.num_rows] for c in cols])
            assert (got == pid).all()
    check = s.execute("CHECK TABLE t").rows
    assert check[-1][-1] == "OK", check


@pytest.mark.parametrize("fp_key,arm", SCHEDULES,
                         ids=[f"{k}@{v}" for k, v in SCHEDULES])
def test_crash_schedule_resumes_exactly_once(harness, fp_key, arm):
    inst, s, store = harness
    acked = None
    with _Traffic(inst) as traffic:
        FAIL_POINTS.arm(fp_key, arm)
        with pytest.raises(FailPointError):
            s.execute("ALTER TABLE t SPLIT PARTITION p1 INTO 2")
        FAIL_POINTS.clear()
        # serving continues while the job is parked RUNNING
        assert s.execute("SELECT count(*) FROM t WHERE id < 1000000"
                         ).rows == [(N_SEED,)]
        resumed = inst.ddl_engine.recover()
        assert resumed, "crashed job did not resume"
    acked = (set(traffic.acked_ins), set(traffic.acked_del))
    assert traffic.reader_violations == []
    tm = inst.catalog.table("cz", "t")
    assert tm.partition.num_partitions == 5
    got = {r[0] for r in s.execute(
        "SELECT id FROM t WHERE id >= 1000000").rows}
    assert got == acked[0] - acked[1], "lost or duplicated racing writes"
    _assert_final_state(inst, s, store)


@pytest.mark.parametrize("sql,expect_parts", OPS,
                         ids=["split", "merge", "move"])
def test_each_op_under_traffic_no_faults(harness, sql, expect_parts):
    inst, s, store = harness
    with _Traffic(inst) as traffic:
        s.execute(sql)
    assert traffic.reader_violations == []
    tm = inst.catalog.table("cz", "t")
    assert tm.partition.num_partitions == expect_parts
    got = {r[0] for r in s.execute(
        "SELECT id FROM t WHERE id >= 1000000").rows}
    assert got == set(traffic.acked_ins) - set(traffic.acked_del)
    _assert_final_state(inst, s, store)


def test_verify_mismatch_under_traffic_rolls_back_clean(harness):
    inst, s, store = harness
    tm = inst.catalog.table("cz", "t")
    with _Traffic(inst) as traffic:
        FAIL_POINTS.arm(FP_REBALANCE_VERIFY_MISMATCH, True)
        with pytest.raises(errors.TddlError, match="verify failed"):
            s.execute("ALTER TABLE t SPLIT PARTITION p1 INTO 2")
        FAIL_POINTS.clear()
    assert traffic.reader_violations == []
    # undo restored the source exactly: still the old map, no shadow state,
    # every acked write present, and FastChecker agrees with a fresh scan
    assert tm.partition.num_partitions == 4
    assert not inst.rebalance_shadows
    got = {r[0] for r in s.execute(
        "SELECT id FROM t WHERE id >= 1000000").rows}
    assert got == set(traffic.acked_ins) - set(traffic.acked_del)
    ts = inst.tso.next_timestamp()
    n, _ = partitions_checksum(store.partitions, tm.column_names(), ts)
    assert n == N_SEED + len(got)
    _assert_final_state(inst, s, store)


def test_double_crash_same_job(harness):
    """Two consecutive crashes (backfill, then cutover) on one job: each
    resume continues from the newest checkpoint."""
    inst, s, store = harness
    FAIL_POINTS.arm(FP_REBALANCE_CHUNK, 2)
    with pytest.raises(FailPointError):
        s.execute("ALTER TABLE t MOVE PARTITION p0 TO 'g1'")
    FAIL_POINTS.clear()
    s.execute("INSERT INTO t VALUES (7777777, 1, 'between')")
    FAIL_POINTS.arm(FP_REBALANCE_BEFORE_SWAP, 1)
    with pytest.raises(FailPointError):
        inst.ddl_engine.recover()
    FAIL_POINTS.clear()
    assert inst.ddl_engine.recover()
    tm = inst.catalog.table("cz", "t")
    assert tm.partition.group_of(0) == "g1"
    assert s.execute("SELECT count(*) FROM t").rows == [(N_SEED + 1,)]
    assert s.execute("SELECT grp FROM t WHERE id = 7777777").rows == [(1,)]
    _assert_final_state(inst, s, store)
