"""End-to-end session tests: SQL in, rows out, through the full stack."""

import numpy as np
import pytest

from galaxysql_tpu.server.instance import Instance
from galaxysql_tpu.server.session import Session
from galaxysql_tpu.utils import errors


@pytest.fixture()
def session():
    inst = Instance()
    s = Session(inst)
    s.execute("CREATE DATABASE test")
    s.execute("USE test")
    yield s
    s.close()


class TestBasics:
    def test_create_insert_select(self, session):
        session.execute("""
            CREATE TABLE t (
                id BIGINT NOT NULL AUTO_INCREMENT PRIMARY KEY,
                name VARCHAR(20),
                amount DECIMAL(10,2),
                d DATE
            ) PARTITION BY HASH(id) PARTITIONS 4
        """)
        r = session.execute(
            "INSERT INTO t (id, name, amount, d) VALUES "
            "(1, 'alice', 10.50, '2024-01-01'), (2, 'bob', 20.25, '2024-06-15'), "
            "(3, NULL, NULL, NULL)")
        assert r.affected == 3
        r = session.execute("SELECT id, name, amount, d FROM t ORDER BY id")
        assert r.rows == [(1, "alice", 10.5, "2024-01-01"),
                          (2, "bob", 20.25, "2024-06-15"),
                          (3, None, None, None)]

    def test_where_and_expressions(self, session):
        session.execute("CREATE TABLE t (a BIGINT, b BIGINT)")
        session.execute("INSERT INTO t VALUES (1, 10), (2, 20), (3, 30), (4, NULL)")
        r = session.execute("SELECT a + b AS s FROM t WHERE b > 10 ORDER BY a")
        assert r.rows == [(22,), (33,)]
        r = session.execute("SELECT count(*), sum(b), avg(b) FROM t")
        assert r.rows[0][0] == 4 and r.rows[0][1] == 60

    def test_group_by_having(self, session):
        session.execute("CREATE TABLE s (g VARCHAR(5), v BIGINT)")
        session.execute(
            "INSERT INTO s VALUES ('a', 1), ('a', 2), ('b', 5), ('b', 7), ('c', 1)")
        r = session.execute(
            "SELECT g, sum(v) AS total FROM s GROUP BY g HAVING sum(v) > 2 "
            "ORDER BY total DESC")
        assert r.rows == [("b", 12), ("a", 3)]

    def test_join(self, session):
        session.execute("CREATE TABLE c (id BIGINT, name VARCHAR(10))")
        session.execute("CREATE TABLE o (cid BIGINT, total BIGINT)")
        session.execute("INSERT INTO c VALUES (1, 'x'), (2, 'y'), (3, 'z')")
        session.execute("INSERT INTO o VALUES (1, 100), (1, 200), (2, 50)")
        r = session.execute(
            "SELECT c.name, sum(o.total) AS t FROM c, o WHERE c.id = o.cid "
            "GROUP BY c.name ORDER BY t DESC")
        assert r.rows == [("x", 300), ("y", 50)]
        r = session.execute(
            "SELECT c.name, o.total FROM c LEFT JOIN o ON c.id = o.cid "
            "ORDER BY c.name, o.total")
        assert r.rows == [("x", 100), ("x", 200), ("y", 50), ("z", None)]

    def test_update_delete(self, session):
        session.execute("CREATE TABLE t (id BIGINT, v BIGINT)")
        session.execute("INSERT INTO t VALUES (1, 10), (2, 20), (3, 30)")
        r = session.execute("UPDATE t SET v = v + 1 WHERE id >= 2")
        assert r.affected == 2
        r = session.execute("SELECT v FROM t ORDER BY id")
        assert r.rows == [(10,), (21,), (31,)]
        r = session.execute("DELETE FROM t WHERE id = 2")
        assert r.affected == 1
        assert session.execute("SELECT count(*) FROM t").rows == [(2,)]

    def test_transaction_rollback(self, session):
        session.execute("CREATE TABLE t (id BIGINT)")
        session.execute("INSERT INTO t VALUES (1)")
        session.execute("BEGIN")
        session.execute("INSERT INTO t VALUES (2)")
        session.execute("DELETE FROM t WHERE id = 1")
        assert session.execute("SELECT count(*) FROM t").rows == [(1,)]
        session.execute("ROLLBACK")
        r = session.execute("SELECT id FROM t")
        assert r.rows == [(1,)]

    def test_show_and_describe(self, session):
        session.execute("CREATE TABLE t1 (a INT PRIMARY KEY, b VARCHAR(10))")
        assert ("test",) in session.execute("SHOW DATABASES").rows
        assert session.execute("SHOW TABLES").rows == [("t1",)]
        r = session.execute("DESC t1")
        assert r.rows[0][0] == "a" and r.rows[0][3] == "PRI"
        r = session.execute("SHOW CREATE TABLE t1")
        assert "CREATE TABLE" in r.rows[0][1]

    def test_explain(self, session):
        session.execute("CREATE TABLE t (a BIGINT) PARTITION BY HASH(a) PARTITIONS 8")
        session.execute("INSERT INTO t VALUES (1), (2), (3)")
        r = session.execute("EXPLAIN SELECT * FROM t WHERE a = 1")
        text = "\n".join(r0[0] for r0 in r.rows)
        assert "Scan" in text and "partitions=[" in text  # partition pruning visible

    def test_errors(self, session):
        with pytest.raises(errors.UnknownTableError):
            session.execute("SELECT * FROM missing")
        with pytest.raises(errors.UnknownColumnError):
            session.execute("CREATE TABLE e (a INT)") and None
            session.execute("SELECT nope FROM e")
        with pytest.raises(errors.TddlError):
            session.execute("CREATE TABLE e2 (a INT)")
            session.execute("CREATE TABLE e2 (a INT)")

    def test_insert_select_and_autoinc(self, session):
        session.execute("CREATE TABLE src (v BIGINT)")
        session.execute("INSERT INTO src VALUES (5), (6)")
        session.execute(
            "CREATE TABLE dst (id BIGINT AUTO_INCREMENT PRIMARY KEY, v BIGINT)")
        session.execute("INSERT INTO dst (v) SELECT v FROM src")
        r = session.execute("SELECT id, v FROM dst ORDER BY id")
        assert r.rows == [(1, 5), (2, 6)]

    def test_distinct_union_limit(self, session):
        session.execute("CREATE TABLE t (a BIGINT)")
        session.execute("INSERT INTO t VALUES (1), (1), (2), (3), (3)")
        assert session.execute("SELECT DISTINCT a FROM t ORDER BY a").rows == \
            [(1,), (2,), (3,)]
        r = session.execute("SELECT a FROM t UNION SELECT a + 10 FROM t ORDER BY 1")
        assert len(r.rows) == 6
        assert session.execute("SELECT a FROM t ORDER BY a LIMIT 2, 2").rows == \
            [(2,), (3,)]

    def test_plan_cache_hit(self, session):
        session.execute("CREATE TABLE t (a BIGINT)")
        session.execute("INSERT INTO t VALUES (1), (2), (3)")
        p = session.instance.planner
        before = p.cache.misses
        session.execute("SELECT * FROM t WHERE a = 1")
        session.execute("SELECT * FROM t WHERE a = 2")
        assert p.cache.hits >= 1
        # different values reuse the cached AST (no reparse), same key
        assert p.cache.misses == before + 1


class TestScalarSubqueryInSelect:
    def test_select_list_subqueries(self, session):
        session.execute("CREATE TABLE sq1 (a BIGINT)")
        session.execute("CREATE TABLE sq2 (b BIGINT)")
        session.execute("INSERT INTO sq1 VALUES (1), (2); INSERT INTO sq2 VALUES (10)")
        r = session.execute(
            "SELECT (SELECT count(*) FROM sq1) + (SELECT sum(b) FROM sq2) AS n")
        assert r.rows == [(12,)]
        r = session.execute(
            "SELECT a, (SELECT max(b) FROM sq2) AS mx FROM sq1 ORDER BY a")
        assert r.rows == [(1, 10), (2, 10)]

    def test_correlated_select_subquery_left_semantics(self, session):
        session.execute("CREATE TABLE rt1 (a BIGINT)")
        session.execute("CREATE TABLE rt2 (k BIGINT, b BIGINT)")
        session.execute("INSERT INTO rt1 VALUES (1), (2); "
                        "INSERT INTO rt2 VALUES (1, 10)")
        r = session.execute(
            "SELECT a, (SELECT max(b) FROM rt2 WHERE rt2.k = rt1.a) AS mx "
            "FROM rt1 ORDER BY a")
        assert r.rows == [(1, 10), (2, None)]  # unmatched row survives with NULL

    def test_empty_scalar_subquery_null_extends(self, session):
        session.execute("CREATE TABLE e1 (a BIGINT)")
        session.execute("CREATE TABLE e2 (b BIGINT)")
        session.execute("INSERT INTO e1 VALUES (1), (2)")
        r = session.execute("SELECT a, (SELECT max(b) FROM e2 WHERE b > 100) AS m "
                            "FROM e1 ORDER BY a")
        assert r.rows == [(1, None), (2, None)]

    def test_multirow_scalar_subquery_errors(self, session):
        session.execute("CREATE TABLE m1 (a BIGINT)")
        session.execute("CREATE TABLE m2 (b BIGINT)")
        session.execute("INSERT INTO m1 VALUES (1); INSERT INTO m2 VALUES (1), (2)")
        with pytest.raises(errors.TddlError):
            session.execute("SELECT a, (SELECT b FROM m2) FROM m1")


class TestExplainAnalyzeStats:
    def test_per_operator_runtime_stats(self):
        """EXPLAIN ANALYZE reports per-operator rows/batches/wall time
        (RuntimeStatistics analog) — collected only when analyzing."""
        from galaxysql_tpu.server.instance import Instance
        from galaxysql_tpu.server.session import Session
        inst = Instance()
        s = Session(inst)
        s.execute("CREATE DATABASE ea")
        s.execute("USE ea")
        s.execute("CREATE TABLE t (a BIGINT, b BIGINT)")
        inst.store("ea", "t").insert_pylists(
            {"a": list(range(500)), "b": [i % 9 for i in range(500)]},
            inst.tso.next_timestamp())
        lines = [r[0] for r in s.execute(
            "EXPLAIN ANALYZE SELECT b, count(*) FROM t WHERE a >= 100 "
            "GROUP BY b").rows]
        ops = [l for l in lines if l.startswith("-- op ")]
        assert any("Aggregate" in l for l in ops)
        assert any("Filter" in l for l in ops)
        assert any("Scan" in l for l in ops)
        agg = next(l for l in ops if "Aggregate" in l)
        assert "rows=9" in agg and "wall=" in agg
        s.close()
