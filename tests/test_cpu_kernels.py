"""Backend-adaptive kernel parity: CPU scatter/hash formulations vs the
sort/matmul reference kernels, and literal lifting (template compile keys).

The CPU twins exist because XLA:CPU inverts TPU's cost model (scatters are
native loops, comparator sorts are single-threaded): `scatter_groupby` /
`hash_groupby` / `_hash_join_pairs_table` must agree bit-for-bit with the
TPU-oriented formulations on every group/join contract the engine relies on.
"""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from galaxysql_tpu.kernels import relational as K


def _groups(r: K.GroupByResult):
    """{key tuple: agg tuple} over live slots; NULL encoded as None."""
    live = np.asarray(r.live)
    out = {}
    for i in np.nonzero(live)[0]:
        key = tuple(
            None if (v is not None and not bool(np.asarray(v)[i]))
            else np.asarray(d)[i].item() for d, v in r.keys)
        aggs = tuple(
            None if (v is not None and not bool(np.asarray(v)[i]))
            else np.asarray(d)[i].item() for d, v in r.aggs)
        out[key] = aggs
    return out


SPECS = [K.AggSpec("sum", 0), K.AggSpec("count", 0), K.AggSpec("count_star", -1),
         K.AggSpec("min", 0), K.AggSpec("max", 0)]


class TestHashGroupby:
    def _mk(self, n, ndv, seed=7):
        rng = np.random.default_rng(seed)
        k1 = jnp.asarray(rng.integers(-ndv // 2, ndv // 2, n))
        k1v = jnp.asarray(rng.random(n) > 0.1)
        k2 = jnp.asarray(rng.integers(0, 7, n).astype(np.int32))
        x = jnp.asarray(rng.integers(-10**12, 10**12, n))
        xv = jnp.asarray(rng.random(n) > 0.2)
        live = jnp.asarray(rng.random(n) > 0.15)
        return [(k1, k1v), (k2, None)], [(x, xv)], live

    def test_matches_sort_groupby(self):
        keys, inputs, live = self._mk(30_000, 2000)
        a = K.hash_groupby(keys, inputs, SPECS, live, 20_000)
        b = K.sort_groupby(keys, inputs, SPECS, live, 20_000)
        assert not bool(a.overflow) and not bool(b.overflow)
        assert _groups(a) == _groups(b)
        assert int(a.num_groups) == int(b.num_groups)

    def test_overflow_when_capacity_exceeded(self):
        n = 4096
        kk = jnp.asarray(np.arange(n))
        x = jnp.asarray(np.ones(n, np.int64))
        r = K.hash_groupby([(kk, None)], [(x, None)], [K.AggSpec("sum", 0)],
                           jnp.ones(n, bool), 128)
        assert bool(r.overflow)

    def test_float_keys_nan_negzero_one_group(self):
        # SQL GROUP BY: all NaNs one group, -0.0 == 0.0
        f = jnp.asarray(np.array([np.nan, np.nan, -0.0, 0.0, 1.5, 1.5, np.nan]))
        x = jnp.asarray(np.arange(7, dtype=np.int64))
        r = K.hash_groupby([(f, None)], [(x, None)],
                           [K.AggSpec("count_star", -1)], jnp.ones(7, bool), 16)
        assert int(r.num_groups) == 3
        counts = sorted(v[0] for v in _groups(r).values())
        assert counts == [2, 2, 3]

    def test_int64_sums_exact_beyond_f64(self):
        big = 1 << 60
        x = jnp.asarray(np.array([big, big, big, -5], dtype=np.int64))
        k = jnp.asarray(np.zeros(4, np.int32))
        r = K.hash_groupby([(k, None)], [(x, None)], [K.AggSpec("sum", 0)],
                           jnp.ones(4, bool), 16)
        want = (np.int64(big) * 3 - 5).item()
        assert list(_groups(r).values())[0][0] == want

    def test_empty_input(self):
        n = 64
        k = jnp.zeros(n, jnp.int64)
        x = jnp.zeros(n, jnp.int64)
        r = K.hash_groupby([(k, None)], [(x, None)], SPECS,
                           jnp.zeros(n, bool), 16)
        assert int(r.num_groups) == 0 and not bool(r.overflow)


class TestScatterGroupby:
    def test_matches_matmul_groupby(self):
        rng = np.random.default_rng(11)
        n = 8000
        k1 = jnp.asarray(rng.integers(0, 3, n).astype(np.int32))
        k1v = jnp.asarray(rng.random(n) > 0.1)
        k2 = jnp.asarray(rng.integers(0, 2, n).astype(np.int32))
        x = jnp.asarray(rng.integers(-10**11, 10**11, n))
        xv = jnp.asarray(rng.random(n) > 0.2)
        live = jnp.asarray(rng.random(n) > 0.15)
        a = K.scatter_groupby([(k1, k1v), (k2, None)], [(x, xv)], SPECS,
                              live, [3, 2])
        b = K.matmul_groupby([(k1, k1v), (k2, None)], [(x, xv)], SPECS,
                             live, [3, 2])
        assert _groups(a) == _groups(b)
        # identical slot layout (domain cross product), not just same groups
        assert (np.asarray(a.live) == np.asarray(b.live)).all()

    def test_float_sum_supported(self):
        # the matmul byte-limb path rejects float sums; scatter handles them
        n = 1000
        rng = np.random.default_rng(3)
        k = jnp.asarray(rng.integers(0, 2, n).astype(np.int32))
        f = jnp.asarray(rng.standard_normal(n))
        a = K.scatter_groupby([(k, None)], [(f, None)],
                              [K.AggSpec("sum", 0)], jnp.ones(n, bool), [2])
        want0 = np.asarray(f)[np.asarray(k) == 0].sum()
        got0 = np.asarray(a.aggs[0][0])[0]
        assert abs(got0 - want0) < 1e-9


class TestTableJoin:
    def test_matches_sorted_join(self):
        rng = np.random.default_rng(5)
        nb, npr = 2048, 20_000
        bk = jnp.asarray(rng.integers(0, 1500, nb))
        bkv = jnp.asarray(rng.random(nb) > 0.1)
        pk = jnp.asarray(rng.integers(0, 1500, npr))
        pkv = jnp.asarray(rng.random(npr) > 0.1)
        bl = jnp.asarray(rng.random(nb) > 0.2)
        pl = jnp.asarray(rng.random(npr) > 0.2)
        cap = 1 << 18
        a = K._hash_join_pairs_table([(bk, bkv)], [(pk, pkv)], bl, pl, cap)
        b = K._hash_join_pairs_sorted([(bk, bkv)], [(pk, pkv)], bl, pl, cap)
        assert not bool(a.overflow) and not bool(b.overflow)

        def pairs(r):
            live = np.asarray(r.live)
            return set(zip(np.asarray(r.build_idx)[live].tolist(),
                           np.asarray(r.probe_idx)[live].tolist()))
        assert pairs(a) == pairs(b)
        assert (np.asarray(a.probe_matched) == np.asarray(b.probe_matched)).all()

    def test_empty_build(self):
        nb, npr = 64, 256
        r = K._hash_join_pairs_table(
            [(jnp.zeros(nb, jnp.int64), None)], [(jnp.zeros(npr, jnp.int64), None)],
            jnp.zeros(nb, bool), jnp.ones(npr, bool), 1024)
        assert int(np.asarray(r.live).sum()) == 0
        assert not bool(r.overflow)

    def test_overflow_reported(self):
        # every probe row matches every build row: cap too small must flag
        nb, npr = 128, 128
        k = jnp.zeros(nb, jnp.int64)
        r = K._hash_join_pairs_table([(k, None)], [(jnp.zeros(npr, jnp.int64), None)],
                                     jnp.ones(nb, bool), jnp.ones(npr, bool), 256)
        assert bool(r.overflow)


class TestLiteralLifting:
    def test_template_key_value_independent(self):
        from galaxysql_tpu.expr import ir
        from galaxysql_tpu.expr.compiler import LiftedLiterals
        from galaxysql_tpu.types import datatype as dt
        col = ir.ColRef("c", dt.BIGINT)
        e1 = ir.call("eq", col, ir.lit(7))
        e2 = ir.call("eq", col, ir.lit(9))
        l1, l2 = LiftedLiterals([e1]), LiftedLiterals([e2])
        assert l1.template_key(e1) == l2.template_key(e2)
        assert l1.values() != l2.values()

    def test_distinct_literals_share_compiled_kernel(self):
        from galaxysql_tpu.exec.operators import _JIT_CACHE, FilterOp, SourceOp
        from galaxysql_tpu.chunk.batch import Column, ColumnBatch
        from galaxysql_tpu.expr import ir
        from galaxysql_tpu.types import datatype as dt

        col = Column(jnp.arange(64, dtype=jnp.int64), None, dt.BIGINT, None)
        batch = ColumnBatch({"c": col}, jnp.ones(64, bool))
        colref = ir.ColRef("c", dt.BIGINT)

        def run(v):
            op = FilterOp(SourceOp([batch]), ir.call("eq", colref, ir.lit(v)))
            out = list(op.batches())[0]
            return int(np.asarray(out.live_mask()).sum())

        run(3)
        before = len(_JIT_CACHE)
        assert run(5) == 1 and run(41) == 1
        assert len(_JIT_CACHE) == before  # no new kernels for new literals
