"""Aux subsystems: information_schema, sequences, CCL, slow log, write conflicts."""

import threading

import pytest

from galaxysql_tpu.server.instance import Instance
from galaxysql_tpu.server.session import Session
from galaxysql_tpu.utils import errors
from galaxysql_tpu.utils.ccl import GLOBAL_CCL, CclRule


@pytest.fixture()
def session():
    inst = Instance()
    s = Session(inst)
    s.execute("CREATE DATABASE a")
    s.execute("USE a")
    yield s
    GLOBAL_CCL.clear()
    s.close()


class TestInformationSchema:
    def test_tables_and_columns(self, session):
        session.execute("CREATE TABLE t1 (id BIGINT PRIMARY KEY, v VARCHAR(10)) "
                        "PARTITION BY HASH(id) PARTITIONS 4")
        session.execute("INSERT INTO t1 VALUES (1, 'x'), (2, 'y')")
        r = session.execute(
            "SELECT table_name, table_rows FROM information_schema.tables "
            "WHERE table_schema = 'a'")
        assert ("t1", 2) in r.rows
        r = session.execute(
            "SELECT column_name, column_key FROM information_schema.columns "
            "WHERE table_name = 't1' ORDER BY ordinal_position")
        assert r.rows == [("id", "PRI"), ("v", "")]

    def test_partitions_and_statistics(self, session):
        session.execute("CREATE TABLE t2 (id BIGINT) PARTITION BY HASH(id) "
                        "PARTITIONS 4")
        session.execute("CREATE INDEX i2 ON t2 (id)")
        r = session.execute(
            "SELECT count(*) FROM information_schema.partitions "
            "WHERE table_name = 't2'")
        assert r.rows == [(4,)]
        r = session.execute(
            "SELECT index_name, index_status FROM information_schema.statistics "
            "WHERE table_name = 't2'")
        assert ("i2", "PUBLIC") in r.rows

    def test_processlist_and_joinable(self, session):
        # info-schema tables are real tables: joins work over them
        r = session.execute(
            "SELECT s.schema_name FROM information_schema.schemata s "
            "JOIN information_schema.schemata s2 "
            "ON s.schema_name = s2.schema_name WHERE s.schema_name = 'a'")
        assert r.rows == [("a",)]


class TestSequences:
    def test_nextval_monotonic(self, session):
        a = session.execute("SELECT NEXTVAL('s1') AS v").rows[0][0]
        b = session.execute("SELECT NEXTVAL('s1') AS v").rows[0][0]
        c = session.execute("SELECT NEXTVAL('s2') AS v").rows[0][0]
        assert b > a
        assert c == 1  # independent sequence

    def test_range_grab_survives_restart(self, tmp_path):
        d = str(tmp_path / "data")
        inst = Instance(data_dir=d)
        s = Session(inst)
        s.execute("CREATE DATABASE sq")
        s.execute("USE sq")
        v1 = s.execute("SELECT NEXTVAL('k')").rows[0][0]
        s.close()
        inst2 = Instance(data_dir=d)
        s2 = Session(inst2, "sq")
        v2 = s2.execute("SELECT NEXTVAL('k')").rows[0][0]
        assert v2 > v1  # new range, never reused
        s2.close()


class TestCcl:
    def test_reject_on_queue_full(self, session):
        GLOBAL_CCL.add_rule(CclRule("block_t3", max_concurrency=1, keyword="t3",
                                    wait_queue_size=0, wait_timeout_ms=100))
        session.execute("CREATE TABLE t3 (a BIGINT)")
        session.execute("INSERT INTO t3 VALUES (1)")
        # one slot: first query fine
        assert session.execute("SELECT * FROM t3").rows == [(1,)]
        # hold the slot manually, then the next query must be rejected (queue size 0)
        st = GLOBAL_CCL.rules()[0]
        st.sem.acquire()
        try:
            with pytest.raises(errors.CclRejectError):
                session.execute("SELECT * FROM t3")
        finally:
            st.sem.release()
        r = session.execute("SHOW CCL_RULES")
        assert r.rows[0][0] == "block_t3" and r.rows[0][7] >= 1  # rejected count

    def test_non_matching_unaffected(self, session):
        GLOBAL_CCL.add_rule(CclRule("only_bob", max_concurrency=1, user="bob",
                                    wait_queue_size=0))
        session.execute("CREATE TABLE t4 (a BIGINT)")
        assert session.execute("SELECT count(*) FROM t4").rows == [(0,)]


class TestSlowLog:
    def test_slow_query_recorded(self, session):
        from galaxysql_tpu.utils.tracing import SLOW_LOG
        SLOW_LOG.clear()
        session.execute("SET SLOW_SQL_MS = 0")  # everything is slow
        session.execute("CREATE TABLE t5 (a BIGINT)")
        session.execute("SELECT * FROM t5")
        r = session.execute("SHOW SLOW")
        assert any("t5" in row[2] for row in r.rows)


class TestWriteConflict:
    def test_first_writer_wins(self, session):
        inst = session.instance
        session.execute("CREATE TABLE w (id BIGINT, v BIGINT)")
        session.execute("INSERT INTO w VALUES (1, 10)")
        s2 = Session(inst, "a")
        session.execute("BEGIN")
        session.execute("UPDATE w SET v = 20 WHERE id = 1")
        # a second transaction touching the same row must fail fast (no deadlock
        # possible by design)
        s2.execute("BEGIN")
        with pytest.raises(errors.TransactionError):
            s2.execute("DELETE FROM w WHERE id = 1")
        s2.execute("ROLLBACK")
        session.execute("COMMIT")
        assert session.execute("SELECT v FROM w WHERE id = 1").rows == [(20,)]
        s2.close()


class TestGsiTxn:
    def test_gsi_rollback_and_commit(self, session):
        inst = session.instance
        session.execute("CREATE TABLE gt (id BIGINT PRIMARY KEY, k BIGINT) "
                        "PARTITION BY HASH(id) PARTITIONS 2")
        session.execute("INSERT INTO gt VALUES (1, 10), (2, 20)")
        session.execute("CREATE GLOBAL INDEX gk ON gt (k)")
        gstore = inst.store("a", "gt$gk")
        assert gstore.row_count() == 2
        # rollback: inserted GSI rows vanish, deleted ones return
        session.execute("BEGIN")
        session.execute("INSERT INTO gt VALUES (3, 30)")
        session.execute("DELETE FROM gt WHERE id = 1")
        session.execute("ROLLBACK")
        assert gstore.row_count() == 2
        # commit: visible to other sessions
        session.execute("BEGIN")
        session.execute("UPDATE gt SET k = 99 WHERE id = 2")
        session.execute("COMMIT")
        s2 = Session(inst, "a")
        ts = inst.tso.next_timestamp()
        vals = []
        for p in gstore.partitions:
            vis = p.visible_mask(ts)
            vals += p.lanes["k"][vis].tolist()
        assert sorted(vals) == [10, 99]
        s2.close()

    def test_composite_pk_no_cross_product(self, session):
        inst = session.instance
        session.execute("CREATE TABLE cp (a BIGINT, b BIGINT, v BIGINT, "
                        "PRIMARY KEY (a, b)) PARTITION BY HASH(a) PARTITIONS 2")
        session.execute("INSERT INTO cp VALUES (1,2,0), (3,4,0), (1,4,0), (3,2,0)")
        session.execute("CREATE GLOBAL INDEX gv ON cp (v)")
        gstore = inst.store("a", "cp$gv")
        assert gstore.row_count() == 4
        session.execute("DELETE FROM cp WHERE a = 1 AND b = 2")
        session.execute("DELETE FROM cp WHERE a = 3 AND b = 4")
        # (1,4) and (3,2) must SURVIVE in the GSI (cross-product bug regression)
        assert gstore.row_count() == 2
