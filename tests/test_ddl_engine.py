"""DDL job engine: online schema change, GSI backfill, crash-resume, rollback."""

import numpy as np
import pytest

from galaxysql_tpu.server.instance import Instance
from galaxysql_tpu.server.session import Session
from galaxysql_tpu.utils import errors
from galaxysql_tpu.utils.failpoint import FAIL_POINTS, FP_AFTER_DDL_TASK, \
    FailPointError


@pytest.fixture()
def session():
    inst = Instance()
    s = Session(inst)
    s.execute("CREATE DATABASE d")
    s.execute("USE d")
    yield s
    FAIL_POINTS.clear()
    s.close()


class TestAlterTable:
    def test_add_drop_column(self, session):
        session.execute("CREATE TABLE t (a BIGINT, b VARCHAR(10))")
        session.execute("INSERT INTO t VALUES (1, 'x'), (2, 'y')")
        session.execute("ALTER TABLE t ADD COLUMN c BIGINT DEFAULT 7")
        r = session.execute("SELECT a, c FROM t ORDER BY a")
        assert r.rows == [(1, 7), (2, 7)]
        session.execute("INSERT INTO t (a, b, c) VALUES (3, 'z', 9)")
        assert session.execute("SELECT c FROM t ORDER BY a").rows == \
            [(7,), (7,), (9,)]
        session.execute("ALTER TABLE t DROP COLUMN b")
        with pytest.raises(errors.UnknownColumnError):
            session.execute("SELECT b FROM t")

    def test_crash_before_first_task_recovers(self, session):
        """FP_BEFORE_DDL_TASK: a crash BEFORE any task of a DDL job runs
        leaves the job RUNNING with zero tasks done; recovery completes it
        with no partial state (the galaxylint dead-failpoint pass keeps
        this key armed — it was dead chaos coverage before)."""
        session.execute("CREATE TABLE bt (a BIGINT, b BIGINT)")
        session.execute("INSERT INTO bt VALUES (1, 2)")
        FAIL_POINTS.arm("FP_BEFORE_DDL_TASK", 1)
        with pytest.raises(FailPointError):
            session.execute("ALTER TABLE bt ADD COLUMN c BIGINT DEFAULT 5")
        FAIL_POINTS.clear()
        assert session.instance.ddl_engine.recover()
        assert session.execute("SELECT a, c FROM bt").rows == [(1, 5)]

    def test_rename(self, session):
        session.execute("CREATE TABLE r1 (a BIGINT)")
        session.execute("INSERT INTO r1 VALUES (5)")
        session.execute("ALTER TABLE r1 RENAME TO r2")
        assert session.execute("SELECT a FROM r2").rows == [(5,)]
        with pytest.raises(errors.UnknownTableError):
            session.execute("SELECT * FROM r1")

    def test_drop_partition_column_rejected_and_rolled_back(self, session):
        session.execute(
            "CREATE TABLE pt (a BIGINT, b BIGINT) PARTITION BY HASH(a) PARTITIONS 4")
        with pytest.raises(errors.TddlError):
            session.execute("ALTER TABLE pt ADD COLUMN c BIGINT, DROP COLUMN a")
        # rollback removed the added column again
        with pytest.raises(errors.UnknownColumnError):
            session.execute("SELECT c FROM pt")


class TestGsi:
    def load(self, session, n=500):
        session.execute(
            "CREATE TABLE orders2 (id BIGINT PRIMARY KEY, cust BIGINT, "
            "amount BIGINT) PARTITION BY HASH(id) PARTITIONS 4")
        store = session.instance.store("d", "orders2")
        store.insert_pylists(
            {"id": list(range(n)), "cust": [i % 50 for i in range(n)],
             "amount": [i * 10 for i in range(n)]},
            session.instance.tso.next_timestamp())
        return store

    def test_gsi_build_and_content(self, session):
        self.load(session)
        session.execute("CREATE GLOBAL INDEX g_cust ON orders2 (cust) COVERING (amount)")
        r = session.execute("SHOW INDEX FROM orders2")
        gsi_rows = [row for row in r.rows if row[2] == "g_cust"]
        assert gsi_rows and gsi_rows[0][6] == "PUBLIC"
        # the GSI table exists, is partitioned by cust, and holds every row
        gstore = session.instance.store("d", "orders2$g_cust")
        assert gstore.row_count() == 500
        assert gstore.table.partition.columns == ["cust"]
        # co-partitioning: every row in a partition hashes to that partition
        from galaxysql_tpu.meta.catalog import hash_partition_of
        for pid, p in enumerate(gstore.partitions):
            if p.num_rows:
                assert (hash_partition_of(p.lanes["cust"], 4) == pid).all()

    def test_gsi_maintained_by_dml(self, session):
        self.load(session, n=100)
        session.execute("CREATE GLOBAL INDEX g2 ON orders2 (cust)")
        gstore = session.instance.store("d", "orders2$g2")
        assert gstore.row_count() == 100
        session.execute("INSERT INTO orders2 VALUES (1000, 7, 70)")
        assert gstore.row_count() == 101
        session.execute("DELETE FROM orders2 WHERE id = 1000")
        assert gstore.row_count() == 100

    def test_backfill_crash_resume(self, session):
        self.load(session, n=3000)  # ~ multiple backfill chunks? CHUNK=8192 -> shrink
        from galaxysql_tpu.ddl import jobs
        old_chunk = jobs.GsiBackfillTask.CHUNK
        jobs.GsiBackfillTask.CHUNK = 256
        try:
            # crash mid-backfill on the 4th chunk
            FAIL_POINTS.arm("FP_BACKFILL_PAUSE", 4)
            with pytest.raises(FailPointError):
                session.execute("CREATE GLOBAL INDEX g3 ON orders2 (cust)")
            FAIL_POINTS.clear()
            # job left RUNNING; recovery resumes from the checkpointed position
            resumed = session.instance.ddl_engine.recover()
            assert resumed
            gstore = session.instance.store("d", "orders2$g3")
            assert gstore.row_count() == 3000  # complete, no duplicates
            r = session.execute("SHOW INDEX FROM orders2")
            st = [row[6] for row in r.rows if row[2] == "g3"]
            assert st == ["PUBLIC"]
        finally:
            jobs.GsiBackfillTask.CHUNK = old_chunk

    def test_drop_index_removes_gsi_table(self, session):
        self.load(session, n=50)
        session.execute("CREATE GLOBAL INDEX g4 ON orders2 (cust)")
        session.execute("DROP INDEX g4 ON orders2")
        with pytest.raises(KeyError):
            session.instance.store("d", "orders2$g4")


class TestPersistence:
    def test_restart_reloads_catalog_and_data(self, tmp_path):
        d = str(tmp_path / "data")
        inst = Instance(data_dir=d)
        s = Session(inst)
        s.execute("CREATE DATABASE p")
        s.execute("USE p")
        s.execute("CREATE TABLE t (a BIGINT, s VARCHAR(8)) "
                  "PARTITION BY HASH(a) PARTITIONS 2")
        s.execute("INSERT INTO t VALUES (1, 'x'), (2, 'y'), (3, NULL)")
        inst.save()
        s.close()

        inst2 = Instance(data_dir=d)
        s2 = Session(inst2, "p")
        r = s2.execute("SELECT a, s FROM t ORDER BY a")
        assert r.rows == [(1, "x"), (2, "y"), (3, None)]
        # auto-increment and versions survive
        tm = inst2.catalog.table("p", "t")
        assert tm.partition.count == 2
        s2.close()

    def test_config_listener_fires(self):
        inst = Instance()
        fired = []
        inst.config_listener.bind("table.d.t", lambda d, v: fired.append((d, v)))
        inst.metadb.notify("table.d.t")
        assert inst.config_listener.poll() == ["table.d.t"]
        assert fired == [("table.d.t", 1)]
        inst.metadb.notify("table.d.t")
        inst.config_listener.poll()
        assert fired[-1][1] == 2


class TestFastChecker:
    def test_consistent_and_detects_corruption(self, session):
        from galaxysql_tpu.utils.fastchecker import check_gsi
        inst = session.instance
        session.execute("CREATE TABLE fc (id BIGINT PRIMARY KEY, k BIGINT, "
                        "v VARCHAR(8)) PARTITION BY HASH(id) PARTITIONS 4")
        inst.store("d", "fc").insert_pylists(
            {"id": list(range(200)), "k": [i % 9 for i in range(200)],
             "v": [f"s{i % 5}" for i in range(200)]},
            inst.tso.next_timestamp())
        session.execute("CREATE GLOBAL INDEX gk ON fc (k) COVERING (v)")
        rep = check_gsi(inst, "d", "fc", "gk")
        assert rep["consistent"] and rep["base_rows"] == rep["gsi_rows"] == 200
        # DML keeps it consistent
        session.execute("DELETE FROM fc WHERE id < 50")
        session.execute("INSERT INTO fc VALUES (999, 3, 's1')")
        rep = check_gsi(inst, "d", "fc", "gk")
        assert rep["consistent"] and rep["base_rows"] == 151
        # inject corruption into the GSI store: checker must catch it
        g = inst.store("d", "fc$gk")
        for p in g.partitions:
            vis = p.visible_mask(inst.tso.next_timestamp())
            ids = np.nonzero(vis)[0]
            if ids.size:
                p.lanes["k"][ids[0]] += 1  # corrupt a LIVE row
                break
        rep = check_gsi(inst, "d", "fc", "gk")
        assert not rep["consistent"]
