"""Scheduled jobs: TTL archive rotation, auto-analyze, at-most-once firing."""

import numpy as np
import pytest

from galaxysql_tpu.server.instance import Instance
from galaxysql_tpu.server.session import Session
from galaxysql_tpu.types import temporal


@pytest.fixture()
def session(tmp_path):
    inst = Instance()
    inst.archive.directory = str(tmp_path / "arch")
    s = Session(inst)
    s.execute("CREATE DATABASE j; USE j")
    yield s
    s.close()


class TestScheduler:
    def test_ttl_archive_job(self, session):
        inst = session.instance
        session.execute("CREATE TABLE ev (id BIGINT, d DATE)")
        import time
        today = temporal.days_from_civil(*time.gmtime()[:3])
        inst.store("j", "ev").insert_arrays(
            {"id": np.arange(100), "d": today - np.arange(100)},  # 0..99 days old
            inst.tso.next_timestamp())
        inst.scheduler.register("ev_ttl", "ttl_archive", "j", "ev",
                                {"column": "d", "ttl_days": 30}, interval_s=60)
        fired = inst.scheduler.run_due()
        assert fired == ["ev_ttl"]
        # rows older than 30 days archived; all rows still queryable
        assert inst.store("j", "ev").row_count() < 100
        assert session.execute("SELECT count(*) FROM ev").rows == [(100,)]
        hist = inst.scheduler.history("ev_ttl")
        assert hist[-1][2] == "SUCCESS" and "archived" in hist[-1][3]

    def test_at_most_once_per_interval(self, session):
        inst = session.instance
        session.execute("CREATE TABLE t (a BIGINT)")
        inst.scheduler.register("an", "analyze", "j", "t", {}, interval_s=3600)
        assert inst.scheduler.run_due() == ["an"]
        assert inst.scheduler.run_due() == []  # interval not elapsed
        # next interval fires again
        assert inst.scheduler.run_due(now=__import__("time").time() + 7200) == ["an"]

    def test_failed_job_recorded_not_fatal(self, session):
        inst = session.instance
        inst.scheduler.register("bad", "analyze", "j", "missing_table", {},
                                interval_s=1)
        fired = inst.scheduler.run_due()
        assert fired == ["bad"]
        assert inst.scheduler.history("bad")[-1][2] == "FAILED"
        # scheduler keeps working for other jobs afterwards
        session.execute("CREATE TABLE ok (a BIGINT)")
        inst.scheduler.register("good", "analyze", "j", "ok", {}, interval_s=1)
        assert "good" in inst.scheduler.run_due(
            now=__import__("time").time() + 10)
