"""SPM (SQL Plan Management): baseline capture, plan stability, evolution, DAL.

Reference analog: `optimizer/planmanager/PlanManager.java:92` — accepted plans
pin the join order against cost-model drift; unaccepted candidates evolve by
measured execution; DDL invalidates; baselines persist in the metadb.
"""

import pytest

from galaxysql_tpu.server.instance import Instance
from galaxysql_tpu.server.session import Session


@pytest.fixture()
def session():
    inst = Instance()
    s = Session(inst)
    s.execute("CREATE DATABASE sp")
    s.execute("USE sp")
    s.execute("CREATE TABLE big (id BIGINT PRIMARY KEY, k BIGINT)")
    s.execute("CREATE TABLE mid (id BIGINT PRIMARY KEY, k BIGINT)")
    s.execute("CREATE TABLE small (id BIGINT PRIMARY KEY, k BIGINT)")
    for name, n in (("big", 400), ("mid", 80), ("small", 10)):
        store = inst.store("sp", name)
        store.insert_pylists({"id": list(range(n)), "k": [i % 10 for i in range(n)]},
                             inst.tso.next_timestamp())
    s.execute("ANALYZE TABLE big, mid, small")
    yield s
    s.close()


Q = ("select count(*) from big, mid, small "
     "where big.k = mid.k and mid.k = small.k and big.id > 1")


def join_orders(session, sql):
    schema = session.schema
    plan = session.instance.planner.plan_select(sql, schema, [], session)
    return plan.join_orders


class TestSpm:
    def test_capture_on_first_execution(self, session):
        session.execute(Q)
        rows = session.execute("SHOW BASELINE").rows
        assert len(rows) == 1
        (bid, schema, psql, accepted, origin, runs, avg_ms, cand,
         regressions, last_regression, state, rollbacks, last_heal) = rows[0]
        assert schema == "sp"
        assert "big" in psql and "?" in psql  # parameterized text is the key
        assert origin == "cost"
        assert runs >= 1 and avg_ms is not None
        assert cand is None
        assert regressions == 0 and last_regression == ""
        # self-heal quarantine machine starts idle
        assert state == "HEALTHY" and rollbacks == 0 and last_heal == ""

    def test_accepted_plan_overrides_cost_drift(self, session):
        session.execute(Q)
        accepted = join_orders(session, Q)
        assert accepted  # the smallest table leads under the greedy cost choice
        # cost-model drift: corrupt stats so the greedy would now pick another
        # order (small claims to be huge), and force a replan
        inst = session.instance
        inst.catalog.table("sp", "small").stats.row_count = 10**9
        inst.catalog.table("sp", "big").stats.row_count = 1
        inst.planner.cache.invalidate_all()
        followed = join_orders(session, Q)
        assert followed == accepted  # baseline pinned the original order
        # and the cost model's new (different) choice was kept as a candidate
        session.execute(Q)
        rows = session.execute("SHOW BASELINE").rows
        assert rows[0][7] is not None  # candidate recorded, not adopted

    def test_evolve_promotes_faster_candidate(self, session):
        session.execute(Q)
        spm = session.instance.planner.spm
        key = list(spm._baselines)[0]
        b = spm._baselines[key]
        # manufacture: accepted looks slow (fake history), candidate differs
        b.accepted.runs = 5
        b.accepted.total_ms = 5 * 60_000.0
        from galaxysql_tpu.plan.spm import PlanRecord
        cand_orders = [tuple(reversed(b.accepted.orders[0]))]
        b.candidate = PlanRecord(cand_orders, "cost")
        r = session.execute("BASELINE EVOLVE")
        assert len(r.rows) == 1
        bid, promoted, cand_ms, acc_ms = r.rows[0]
        assert promoted  # measured ms << faked 60s average
        rows = session.execute("SHOW BASELINE").rows
        assert rows[0][4] == "evolved"
        # the promoted order now drives planning
        session.instance.planner.cache.invalidate_all()
        assert join_orders(session, Q) == cand_orders

    def test_ddl_invalidates_baseline(self, session):
        session.execute(Q)
        assert session.execute("SHOW BASELINE").rows
        session.execute("ALTER TABLE small ADD COLUMN extra BIGINT")
        session.instance.planner.cache.invalidate_all()
        session.execute(Q)  # replans; stale baseline dropped, fresh one captured
        rows = session.execute("SHOW BASELINE").rows
        assert len(rows) == 1
        assert rows[0][5] >= 1  # the fresh baseline is live

    def test_baseline_delete(self, session):
        session.execute(Q)
        rows = session.execute("SHOW BASELINE").rows
        bid = rows[0][0]
        r = session.execute(f"BASELINE DELETE {bid}")
        assert r.affected == 1
        assert session.execute("SHOW BASELINE").rows == []

    def test_baselines_persist_across_restart(self, tmp_path):
        d = str(tmp_path / "spm")
        inst = Instance(data_dir=d)
        s = Session(inst)
        s.execute("CREATE DATABASE sp")
        s.execute("USE sp")
        s.execute("CREATE TABLE a (id BIGINT, k BIGINT)")
        s.execute("CREATE TABLE b (id BIGINT, k BIGINT)")
        for name in ("a", "b"):
            inst.store("sp", name).insert_pylists(
                {"id": [1, 2], "k": [1, 2]}, inst.tso.next_timestamp())
        s.execute("select count(*) from a, b where a.k = b.k")
        n_baselines = len(s.execute("SHOW BASELINE").rows)
        assert n_baselines == 1
        inst.save()
        s.close()

        inst2 = Instance(data_dir=d)
        s2 = Session(inst2, schema="sp")
        assert len(s2.execute("SHOW BASELINE").rows) == 1
        s2.close()
