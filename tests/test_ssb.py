"""SSB differential suite vs sqlite3 (BASELINE config 4: fact scan + broadcast
dimension joins)."""

import sqlite3

import pytest

from galaxysql_tpu.server.instance import Instance
from galaxysql_tpu.server.session import Session
from galaxysql_tpu.storage import ssb


@pytest.fixture(scope="module")
def env():
    data = ssb.generate(0.002)
    inst = Instance()
    s = Session(inst)
    s.execute("CREATE DATABASE ssb")
    s.execute("USE ssb")
    for t in ssb.TABLE_ORDER:
        s.execute(ssb.SSB_DDL[t])
        inst.store("ssb", t).insert_arrays(data[t], inst.tso.next_timestamp())
    s.execute("ANALYZE TABLE " + ", ".join(ssb.TABLE_ORDER))

    db = sqlite3.connect(":memory:")
    for t in ssb.TABLE_ORDER:
        cols = list(data[t].keys())
        decls = ", ".join(
            f"{c} {'TEXT' if isinstance(data[t][c][0], str) else 'NUMERIC'}"
            for c in cols)
        db.execute(f"CREATE TABLE {t} ({decls})")
        db.executemany(f"INSERT INTO {t} VALUES ({','.join('?' * len(cols))})",
                       list(zip(*[data[t][c] for c in cols])))
    db.commit()
    yield s, db
    s.close()
    db.close()


@pytest.mark.parametrize("qid", sorted(ssb.QUERIES))
def test_ssb_query(env, qid):
    s, db = env
    q = ssb.QUERIES[qid]
    mine = sorted(tuple(str(x) for x in r) for r in s.execute(q).rows)
    theirs = sorted(tuple(str(x) for x in r) for r in db.execute(q).fetchall())
    assert mine == theirs, f"SSB {qid}\nmine:   {mine[:4]}\nsqlite: {theirs[:4]}"
