"""External sort + grace hash join under memory pressure.

Reference analog: `operator/SpilledTopNExec.java` (SpilledTopNHeap) and
`HybridHashJoinExec` — ORDER BY and join builds ~4x over the memory threshold
must complete via disk spill, observable through the operators' spill counters.
"""

import numpy as np
import pytest

from galaxysql_tpu.chunk.batch import Column, ColumnBatch
from galaxysql_tpu.exec.operators import HashJoinOp, SortOp, SourceOp
from galaxysql_tpu.expr import ir
from galaxysql_tpu.types import datatype as dt


def _batch(vals: np.ndarray, extra: np.ndarray, prefix: str = "t") -> ColumnBatch:
    import jax.numpy as jnp
    return ColumnBatch(
        {f"{prefix}.k": Column(jnp.asarray(vals), None, dt.BIGINT, None),
         f"{prefix}.x": Column(jnp.asarray(extra), None, dt.BIGINT, None)},
        jnp.ones(vals.shape[0], dtype=jnp.bool_))


def col(name: str) -> ir.ColRef:
    return ir.ColRef(name, dt.BIGINT)


class TestExternalSort:
    def _run(self, n, limit=None, offset=0, threshold=1 << 16, desc=False):
        rng = np.random.default_rng(0)
        vals = rng.integers(-10**9, 10**9, n)
        batches = [_batch(vals[i:i + 8192], vals[i:i + 8192] * 2)
                   for i in range(0, n, 8192)]
        op = SortOp(SourceOp(batches), [(col("t.k"), desc)], limit=limit,
                    offset=offset, spill_threshold=threshold)
        rows = []
        for b in op.batches():
            live = b.np_live()
            rows += b.columns["t.k"].np_data()[live].tolist()
        return op, rows, vals

    def test_spilled_sort_matches_full_sort(self):
        op, rows, vals = self._run(100_000)
        assert op.spilled_runs >= 4  # ~4x over the 64KB threshold
        assert rows == sorted(vals.tolist())

    def test_spilled_sort_descending(self):
        op, rows, vals = self._run(50_000, desc=True)
        assert op.spilled_runs > 0
        assert rows == sorted(vals.tolist(), reverse=True)

    def test_spilled_sort_limit_offset(self):
        op, rows, vals = self._run(60_000, limit=100, offset=7)
        assert op.spilled_runs > 0
        assert rows == sorted(vals.tolist())[7:107]

    def test_in_memory_path_unchanged(self):
        op, rows, vals = self._run(20_000, threshold=1 << 30)
        assert op.spilled_runs == 0
        assert rows == sorted(vals.tolist())

    def test_spilled_sort_with_nulls(self):
        import jax.numpy as jnp
        rng = np.random.default_rng(1)
        n = 40_000
        vals = rng.integers(0, 1000, n)
        valid = rng.random(n) > 0.1
        batches = []
        for i in range(0, n, 8192):
            batches.append(ColumnBatch(
                {"t.k": Column(jnp.asarray(vals[i:i + 8192]),
                               jnp.asarray(valid[i:i + 8192]), dt.BIGINT, None)},
                jnp.ones(min(8192, n - i), dtype=jnp.bool_)))
        op = SortOp(SourceOp(batches), [(col("t.k"), False)],
                    spill_threshold=1 << 15)
        got = []
        for b in op.batches():
            live = b.np_live()
            d = b.columns["t.k"].np_data()[live]
            v = b.columns["t.k"].np_valid()[live]
            got += [None if not vi else di for di, vi in zip(d.tolist(), v.tolist())]
        assert op.spilled_runs > 0
        want = [None] * int((~valid).sum()) + sorted(vals[valid].tolist())
        assert got == want  # NULLs first ascending (MySQL)


class TestGraceJoin:
    def _sides(self, nb, npr, dups=4):
        rng = np.random.default_rng(2)
        bkeys = np.repeat(np.arange(nb // dups), dups)
        rng.shuffle(bkeys)
        pkeys = rng.integers(0, nb // dups * 2, npr)  # ~half match
        build = [_batch(bkeys[i:i + 8192], bkeys[i:i + 8192] + 1, "b")
                 for i in range(0, nb, 8192)]
        probe = [_batch(pkeys[i:i + 8192], pkeys[i:i + 8192] + 2, "p")
                 for i in range(0, npr, 8192)]
        return build, probe, bkeys, pkeys

    def _pairs(self, op):
        out = []
        for b in op.batches():
            live = b.np_live()
            bk = b.columns["b.k"].np_data()[live]
            pk = b.columns["p.k"].np_data()[live]
            out += list(zip(bk.tolist(), pk.tolist()))
        return sorted(out)

    def test_grace_inner_matches_in_memory(self):
        build, probe, bkeys, pkeys = self._sides(60_000, 60_000)
        grace = HashJoinOp(SourceOp(build), SourceOp(probe), [col("b.k")],
                           [col("p.k")], "inner", spill_threshold=1 << 17)
        mem = HashJoinOp(SourceOp(build), SourceOp(probe), [col("b.k")],
                         [col("p.k")], "inner")
        got = self._pairs(grace)
        assert grace.grace_partitions >= 4  # build ~4x over the 128KB threshold
        assert mem.grace_partitions == 0
        assert got == self._pairs(mem)

    def test_grace_left_and_anti(self):
        import jax.numpy as jnp
        build, probe, bkeys, pkeys = self._sides(40_000, 30_000)
        bschema = {"b.k": (dt.BIGINT, None), "b.x": (dt.BIGINT, None)}
        for kind in ("left", "anti", "semi"):
            grace = HashJoinOp(SourceOp(build), SourceOp(probe), [col("b.k")],
                               [col("p.k")], kind, build_schema=bschema,
                               spill_threshold=1 << 17)
            mem = HashJoinOp(SourceOp(build), SourceOp(probe), [col("b.k")],
                             [col("p.k")], kind, build_schema=bschema)

            def probe_rows(op):
                out = []
                for b in op.batches():
                    live = b.np_live()
                    out += b.columns["p.k"].np_data()[live].tolist()
                return sorted(out)
            assert probe_rows(grace) == probe_rows(mem), kind
            assert grace.grace_partitions > 0
