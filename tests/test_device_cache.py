"""DeviceCache: single-flight builds, byte accounting, LRU, typed metrics.

The double-build race this guards: two threads missing the same key must not
BOTH run the (possibly O(table)) builder and insert — one builds, the rest
wait on the per-key event and adopt its entry, keeping `_bytes` exact.
"""

import threading
import time

import numpy as np
import pytest

from galaxysql_tpu.exec.device_cache import DeviceCache


class _Store:
    def __init__(self, uid=1):
        self.uid = uid


class TestSingleFlight:
    def test_concurrent_misses_build_once(self):
        cache = DeviceCache()
        store = _Store()
        builds = []
        barrier = threading.Barrier(8)

        def builder():
            builds.append(1)
            time.sleep(0.02)  # widen the race window
            return np.arange(1024, dtype=np.int64)

        out = [None] * 8

        def worker(i):
            barrier.wait()
            out[i] = cache.get_lane_built(store, 0, "c", 1, 1024, builder)

        threads = [threading.Thread(target=worker, args=(i,)) for i in range(8)]
        for t in threads:
            t.start()
        for t in threads:
            t.join()
        assert len(builds) == 1          # the builder ran exactly once
        assert cache.misses == 1
        assert cache.hits == 7
        first = out[0]
        assert all(o is first for o in out)  # everyone adopted ONE entry
        assert cache._bytes == int(first.nbytes)  # no double count

    def test_stress_many_keys_exact_bytes(self):
        cache = DeviceCache()
        store = _Store()
        n_threads, n_keys = 8, 16
        lane = np.arange(256, dtype=np.int64)

        def worker():
            for k in range(n_keys):
                cache.get_lane(store, k, "c", 1, lane)

        threads = [threading.Thread(target=worker) for _ in range(n_threads)]
        for t in threads:
            t.start()
        for t in threads:
            t.join()
        assert cache.misses == n_keys
        assert cache.hits == n_threads * n_keys - n_keys
        assert len(cache._map) == n_keys
        assert cache._bytes == sum(v.nbytes for v in cache._map.values())

    def test_failed_build_releases_waiters(self):
        cache = DeviceCache()
        store = _Store()
        calls = []

        def failing():
            calls.append(1)
            raise RuntimeError("boom")

        with pytest.raises(RuntimeError):
            cache.get_lane_built(store, 0, "c", 1, 8, failing)
        # the key is not poisoned: the next caller becomes the builder
        got = cache.get_lane_built(store, 0, "c", 1, 8,
                                   lambda: np.arange(8, dtype=np.int64))
        assert int(np.asarray(got).sum()) == 28
        assert len(calls) == 1


class TestEvictionAndVersioning:
    def test_lru_eviction_keeps_bytes_under_budget(self):
        lane = np.arange(1024, dtype=np.int64)
        cache = DeviceCache(budget_bytes=3 * lane.nbytes)
        store = _Store()
        for k in range(6):
            cache.get_lane(store, k, "c", 1, lane)
        assert cache._bytes <= cache.budget
        assert len(cache._map) <= 3
        # the most recent key survived
        assert (store.uid, 5, "c", 1, 1024) in cache._map

    def test_version_bump_is_a_miss(self):
        cache = DeviceCache()
        store = _Store()
        lane = np.arange(16, dtype=np.int64)
        cache.get_lane(store, 0, "c", 1, lane)
        cache.get_lane(store, 0, "c", 2, lane)
        assert cache.misses == 2 and cache.hits == 0


class TestMetrics:
    def test_typed_registry_gauges(self):
        from galaxysql_tpu.utils.metrics import MetricsRegistry
        reg = MetricsRegistry()
        cache = DeviceCache()
        cache.bind_metrics(reg)
        store = _Store()
        lane = np.arange(32, dtype=np.int64)
        cache.get_lane(store, 0, "c", 1, lane)
        cache.get_lane(store, 0, "c", 1, lane)
        rows = {n: v for n, _k, v, _h in reg.rows()}
        assert rows["device_cache_hits"] == 1
        assert rows["device_cache_misses"] == 1
        assert rows["device_cache_bytes"] == cache._bytes
        assert rows["device_cache_entries"] == 1

    def test_instance_binds_global_cache(self):
        from galaxysql_tpu.server.instance import Instance
        inst = Instance()
        names = {n for n, *_ in inst.metrics.rows()}
        assert {"device_cache_hits", "device_cache_misses",
                "device_cache_bytes"} <= names
