"""Operator engine tests — the BaseExecTest analog (mock sources, asserted results)."""

import numpy as np
import pytest

from galaxysql_tpu.chunk.batch import ColumnBatch, batch_from_pydict
from galaxysql_tpu.exec.operators import (AggCall, DistinctOp, FilterOp, HashAggOp,
                                          HashJoinOp, LimitOp, ProjectOp, SortOp,
                                          SourceOp, run_to_batch)
from galaxysql_tpu.expr import ir
from galaxysql_tpu.types import datatype as dt


def col(batch, name):
    c = batch.columns[name]
    return ir.ColRef(name, c.dtype, c.dictionary)


def lineitem_like(n=100, seed=0):
    rng = np.random.default_rng(seed)
    schema = {
        "flag": dt.VARCHAR, "status": dt.VARCHAR,
        "qty": dt.decimal(15, 2), "price": dt.decimal(15, 2),
        "disc": dt.decimal(15, 2), "key": dt.BIGINT,
    }
    flags = ["A", "N", "R"]
    stats = ["F", "O"]
    data = {
        "flag": [flags[i % 3] for i in range(n)],
        "status": [stats[i % 2] for i in range(n)],
        "qty": [float(rng.integers(1, 50)) for _ in range(n)],
        "price": [round(float(rng.uniform(1, 1000)), 2) for _ in range(n)],
        "disc": [round(float(rng.uniform(0, 0.1)), 2) for _ in range(n)],
        "key": list(range(n)),
    }
    return batch_from_pydict(data, schema), data


class TestFilterProject:
    def test_filter_live_mask(self):
        b, data = lineitem_like(50)
        op = FilterOp(SourceOp([b]), ir.call("lt", col(b, "key"), ir.lit(10)))
        out = run_to_batch(op)
        assert out.num_live() == 10
        assert sorted(r[-1] for r in out.to_pylist()) == list(range(10))

    def test_project(self):
        b, data = lineitem_like(20)
        e = ir.call("mul", col(b, "price"), ir.call("sub", ir.lit(1), col(b, "disc")))
        op = ProjectOp(SourceOp([b]), [("disc_price", e), ("key", col(b, "key"))])
        out = run_to_batch(op)
        rows = out.to_pydict()
        expected = [round(round(p * (1 - d), 4), 4)
                    for p, d in zip(data["price"], data["disc"])]
        np.testing.assert_allclose(rows["disc_price"], expected, atol=1e-9)


class TestHashAgg:
    def test_groupby_sums_match_pandas(self):
        import pandas as pd
        b, data = lineitem_like(200)
        aggs = [
            AggCall("sum", col(b, "qty"), "sum_qty"),
            AggCall("count_star", None, "cnt"),
            AggCall("avg", col(b, "qty"), "avg_qty"),
            AggCall("min", col(b, "price"), "min_price"),
            AggCall("max", col(b, "price"), "max_price"),
        ]
        op = HashAggOp(SourceOp([b]), [("flag", col(b, "flag")),
                                       ("status", col(b, "status"))], aggs)
        out = run_to_batch(op).to_pydict()
        df = pd.DataFrame(data)
        g = df.groupby(["flag", "status"]).agg(
            sum_qty=("qty", "sum"), cnt=("qty", "size"),
            avg_qty=("qty", "mean"), min_price=("price", "min"),
            max_price=("price", "max")).reset_index()
        got = {(f, s): (sq, c, aq, mn, mx) for f, s, sq, c, aq, mn, mx in zip(
            out["flag"], out["status"], out["sum_qty"], out["cnt"], out["avg_qty"],
            out["min_price"], out["max_price"])}
        assert len(got) == len(g)
        for _, r in g.iterrows():
            sq, c, aq, mn, mx = got[(r["flag"], r["status"])]
            assert abs(sq - r["sum_qty"]) < 1e-6
            assert c == r["cnt"]
            assert abs(aq - r["avg_qty"]) < 1e-3  # avg scale+4 rounding
            assert abs(mn - r["min_price"]) < 1e-9
            assert abs(mx - r["max_price"]) < 1e-9

    def test_global_agg(self):
        b, data = lineitem_like(64)
        op = HashAggOp(SourceOp([b]), [],
                       [AggCall("sum", col(b, "qty"), "s"),
                        AggCall("count_star", None, "c")])
        out = run_to_batch(op).to_pydict()
        assert out["c"] == [64]
        assert abs(out["s"][0] - sum(data["qty"])) < 1e-6

    def test_multiple_batches_merge(self):
        b1, d1 = lineitem_like(60, seed=1)
        b2, d2 = lineitem_like(60, seed=2)
        # share dictionaries across batches (same table would)
        op = HashAggOp(SourceOp([b1, ColumnBatch(b2.columns, b2.live)]),
                       [("flag", col(b1, "flag"))],
                       [AggCall("count_star", None, "c")])
        out = run_to_batch(op).to_pydict()
        assert sum(out["c"]) == 120

    def test_groupby_with_null_keys(self):
        schema = {"k": dt.BIGINT, "v": dt.BIGINT}
        b = batch_from_pydict({"k": [1, None, 1, None, 2], "v": [1, 2, 3, 4, 5]}, schema)
        op = HashAggOp(SourceOp([b]), [("k", col(b, "k"))],
                       [AggCall("sum", col(b, "v"), "s")])
        out = run_to_batch(op).to_pydict()
        m = dict(zip(out["k"], out["s"]))
        assert m[1] == 4 and m[2] == 5 and m[None] == 6

    def test_distinct(self):
        schema = {"k": dt.BIGINT}
        b = batch_from_pydict({"k": [3, 1, 2, 3, 1, 1]}, schema)
        out = run_to_batch(DistinctOp(SourceOp([b]), [("k", col(b, "k"))])).to_pydict()
        assert sorted(out["k"]) == [1, 2, 3]


class TestHashJoin:
    def make_sides(self):
        orders = batch_from_pydict(
            {"o_key": [1, 2, 3, 4], "o_cust": [10, 20, 10, 30]},
            {"o_key": dt.BIGINT, "o_cust": dt.BIGINT})
        items = batch_from_pydict(
            {"l_okey": [1, 1, 2, 5, None], "l_qty": [5, 6, 7, 8, 9]},
            {"l_okey": dt.BIGINT, "l_qty": dt.BIGINT})
        return orders, items

    def test_inner(self):
        orders, items = self.make_sides()
        op = HashJoinOp(SourceOp([orders]), SourceOp([items]),
                        [col(orders, "o_key")], [col(items, "l_okey")], "inner")
        out = run_to_batch(op).to_pydict()
        pairs = sorted(zip(out["l_okey"], out["l_qty"], out["o_cust"]))
        assert pairs == [(1, 5, 10), (1, 6, 10), (2, 7, 20)]

    def test_left(self):
        orders, items = self.make_sides()
        op = HashJoinOp(SourceOp([orders]), SourceOp([items]),
                        [col(orders, "o_key")], [col(items, "l_okey")], "left")
        out = run_to_batch(op).to_pydict()
        rows = sorted(zip(out["l_qty"], out["o_cust"]), key=lambda r: r[0])
        assert rows == [(5, 10), (6, 10), (7, 20), (8, None), (9, None)]

    def test_semi_anti(self):
        orders, items = self.make_sides()
        semi = HashJoinOp(SourceOp([orders]), SourceOp([items]),
                          [col(orders, "o_key")], [col(items, "l_okey")], "semi")
        out = run_to_batch(semi).to_pydict()
        assert sorted(out["l_qty"]) == [5, 6, 7]
        anti = HashJoinOp(SourceOp([orders]), SourceOp([items]),
                          [col(orders, "o_key")], [col(items, "l_okey")], "anti")
        out = run_to_batch(anti).to_pydict()
        assert sorted(out["l_qty"]) == [8, 9]  # NULL key row never matches; NULL in anti?

    def test_duplicate_heavy_overflow_retry(self):
        n = 3000
        build = batch_from_pydict({"k": [i % 3 for i in range(30)]}, {"k": dt.BIGINT})
        probe = batch_from_pydict({"k": [i % 3 for i in range(n)],
                                   "v": list(range(n))}, {"k": dt.BIGINT, "v": dt.BIGINT})
        bk = ir.ColRef("k", dt.BIGINT)
        op = HashJoinOp(SourceOp([build]), SourceOp([probe]), [bk], [bk], "inner")
        out = run_to_batch(op)
        assert out.num_live() == n * 10  # each probe row matches 10 build rows

    def test_string_key_join(self):
        left = batch_from_pydict({"name": ["asia", "europe", "africa"], "id": [1, 2, 3]},
                                 {"name": dt.VARCHAR, "id": dt.BIGINT})
        right = batch_from_pydict({"rname": ["europe", "asia", "asia"], "x": [7, 8, 9]},
                                  {"rname": dt.VARCHAR, "x": dt.BIGINT})
        # different dictionaries: comparison resolved via translation at compile time
        lk = col(left, "name")
        rk = col(right, "rname")
        op = HashJoinOp(SourceOp([left]), SourceOp([right]), [lk], [rk], "inner")
        out = run_to_batch(op).to_pydict()
        assert sorted(zip(out["x"], out["id"])) == [(7, 2), (8, 1), (9, 1)]


class TestSortLimit:
    def test_sort_multi_key(self):
        b = batch_from_pydict(
            {"a": [2, 1, 2, 1, None], "b": [5, 6, 7, 8, 9]},
            {"a": dt.BIGINT, "b": dt.BIGINT})
        op = SortOp(SourceOp([b]), [(col(b, "a"), False), (col(b, "b"), True)])
        out = run_to_batch(op).to_pydict()
        assert out["a"] == [None, 1, 1, 2, 2]  # MySQL: NULLs first ascending
        assert out["b"] == [9, 8, 6, 7, 5]

    def test_topn(self):
        b = batch_from_pydict({"v": list(range(100))}, {"v": dt.BIGINT})
        op = SortOp(SourceOp([b]), [(col(b, "v"), True)], limit=5)
        out = run_to_batch(op).to_pydict()
        assert out["v"] == [99, 98, 97, 96, 95]

    def test_limit_offset_across_batches(self):
        b1 = batch_from_pydict({"v": list(range(10))}, {"v": dt.BIGINT})
        b2 = batch_from_pydict({"v": list(range(10, 20))}, {"v": dt.BIGINT})
        op = LimitOp(SourceOp([b1, b2]), limit=8, offset=7)
        out = run_to_batch(op).to_pydict()
        assert out["v"] == list(range(7, 15))
