"""REST observability endpoints (mpp/web analog): read-only JSON resources."""

import json
import urllib.request

import pytest

from galaxysql_tpu.server.instance import Instance
from galaxysql_tpu.server.session import Session
from galaxysql_tpu.server.web import WebConsole


@pytest.fixture(scope="module")
def console():
    inst = Instance()
    s = Session(inst)
    s.execute("CREATE DATABASE wc")
    s.execute("USE wc")
    s.execute("CREATE TABLE t (a BIGINT, b BIGINT)")
    inst.store("wc", "t").insert_pylists(
        {"a": list(range(100)), "b": list(range(100))},
        inst.tso.next_timestamp())
    s.execute("SET GLOBAL SLOW_SQL_MS = 0")  # log every query
    s.execute("SELECT count(*) FROM t")
    s.execute("SELECT t.a, count(*) FROM t, t t2 WHERE t.a = t2.b GROUP BY t.a")
    web = WebConsole(inst)
    port = web.start()
    yield inst, s, port
    web.stop()
    s.close()


def fetch(port, path):
    with urllib.request.urlopen(f"http://127.0.0.1:{port}{path}", timeout=10) as r:
        return json.loads(r.read())


class TestWebConsole:
    def test_status(self, console):
        inst, s, port = console
        d = fetch(port, "/status")
        assert d["node_id"] == inst.node_id
        assert d["sessions"] >= 1

    def test_queries_and_slow_log(self, console):
        _, s, port = console
        d = fetch(port, "/queries")
        assert any(q["conn_id"] == s.conn_id for q in d["sessions"])
        assert d["slow_queries"]  # SLOW_SQL_MS=0 logs everything
        assert any("count" in q["sql"] for q in d["slow_queries"])

    def test_cluster(self, console):
        inst, _, port = console
        d = fetch(port, "/cluster")
        assert d["nodes"].get(inst.node_id) == "ALIVE"
        assert d["leader"] is not None

    def test_plan_cache_and_baselines(self, console):
        _, _, port = console
        pc = fetch(port, "/plan-cache")
        assert pc["size"] >= 1
        bl = fetch(port, "/baselines")
        assert isinstance(bl["baselines"], list)
        assert bl["baselines"], "the join query should have captured a baseline"

    def test_scheduler_and_404(self, console):
        _, _, port = console
        d = fetch(port, "/scheduler")
        assert "jobs" in d and "history" in d
        with pytest.raises(urllib.error.HTTPError):
            fetch(port, "/nope")
