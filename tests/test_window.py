"""Window functions: differential against sqlite3 + targeted semantics."""

import sqlite3

import pytest

from galaxysql_tpu.server.instance import Instance
from galaxysql_tpu.server.session import Session


@pytest.fixture()
def env():
    inst = Instance()
    s = Session(inst)
    s.execute("CREATE DATABASE w; USE w")
    s.execute("CREATE TABLE sales (region VARCHAR(10), emp BIGINT, amount BIGINT)")
    rows = [("east", 1, 100), ("east", 2, 200), ("east", 3, 200), ("east", 1, 50),
            ("west", 4, 300), ("west", 5, 100), ("west", 4, 100), ("north", 6, 10)]
    s.execute("INSERT INTO sales VALUES " +
              ", ".join(f"('{r}', {e}, {a})" for r, e, a in rows))
    db = sqlite3.connect(":memory:")
    db.execute("CREATE TABLE sales (region TEXT, emp INTEGER, amount INTEGER)")
    db.executemany("INSERT INTO sales VALUES (?,?,?)", rows)
    yield s, db
    s.close()
    db.close()


def same(mine, theirs):
    a = sorted(tuple(str(x) for x in r) for r in mine)
    b = sorted(tuple(str(x) for x in r) for r in theirs)
    assert a == b, f"\nmine:   {a}\nsqlite: {b}"


QUERIES = [
    "SELECT region, amount, row_number() OVER (PARTITION BY region ORDER BY amount) "
    "AS rn FROM sales",
    "SELECT region, amount, rank() OVER (PARTITION BY region ORDER BY amount DESC) "
    "AS r FROM sales",
    "SELECT region, amount, dense_rank() OVER (PARTITION BY region ORDER BY amount) "
    "AS dr FROM sales",
    "SELECT region, amount, sum(amount) OVER (PARTITION BY region ORDER BY amount) "
    "AS running FROM sales",
    "SELECT region, amount, sum(amount) OVER (PARTITION BY region) AS total "
    "FROM sales",
    "SELECT region, amount, count(*) OVER (PARTITION BY region) AS c FROM sales",
    "SELECT region, amount, min(amount) OVER (PARTITION BY region) AS mn, "
    "max(amount) OVER (PARTITION BY region) AS mx FROM sales",
    "SELECT region, emp, amount, lag(amount) OVER (PARTITION BY region ORDER BY "
    "amount, emp) AS prev FROM sales",
    "SELECT region, emp, amount, lead(amount, 2) OVER (PARTITION BY region ORDER BY "
    "amount, emp) AS nxt FROM sales",
    "SELECT region, amount, first_value(amount) OVER (PARTITION BY region ORDER BY "
    "amount) AS fv FROM sales",
    "SELECT region, amount, sum(amount) OVER (PARTITION BY region ORDER BY amount "
    "ROWS BETWEEN UNBOUNDED PRECEDING AND CURRENT ROW) AS srows FROM sales",
    "SELECT amount, row_number() OVER (ORDER BY amount DESC) AS rn FROM sales",
]


@pytest.mark.parametrize("q", QUERIES)
def test_differential(env, q):
    s, db = env
    same(s.execute(q).rows, db.execute(q).fetchall())


def test_range_default_frame_ties(env):
    """SQL default RANGE frame: tied order keys share the running value."""
    s, db = env
    q = ("SELECT region, amount, sum(amount) OVER (PARTITION BY region "
         "ORDER BY amount) AS r FROM sales WHERE region = 'east'")
    same(s.execute(q).rows, db.execute(q).fetchall())
    # east amounts: 50, 100, 200, 200 -> the two 200s BOTH see 550
    rows = {tuple(r[:2]): r[2] for r in s.execute(q).rows}
    assert rows[("east", 200)] == 550


class TestReviewRegressions:
    def test_last_value_whole_partition_with_padding(self, env):
        s, db = env
        q = ("SELECT region, amount, last_value(amount) OVER (PARTITION BY region "
             "ORDER BY amount ROWS BETWEEN UNBOUNDED PRECEDING AND UNBOUNDED "
             "FOLLOWING) AS lv FROM sales")
        same(s.execute(q).rows, db.execute(q).fetchall())

    def test_null_partition_keys_form_one_partition(self, env):
        s, _ = env
        s.execute("CREATE TABLE np (g BIGINT, v BIGINT)")
        s.execute("INSERT INTO np VALUES (NULL, 7), (NULL, 9), (1, 1)")
        r = s.execute("SELECT g, count(v) OVER (PARTITION BY g) c FROM np")
        by_g = sorted(r.rows, key=lambda t: (t[0] is not None, t[0] or 0))
        assert by_g == [(None, 2), (None, 2), (1, 1)]

    def test_current_row_frame_rejected(self, env):
        s, _ = env
        from galaxysql_tpu.utils.errors import NotSupportedError
        with pytest.raises(NotSupportedError):
            s.execute("SELECT sum(amount) OVER (ORDER BY amount ROWS BETWEEN "
                      "CURRENT ROW AND UNBOUNDED FOLLOWING) FROM sales")

    def test_distinct_window_rejected(self, env):
        s, _ = env
        from galaxysql_tpu.utils.errors import NotSupportedError
        with pytest.raises(NotSupportedError):
            s.execute("SELECT sum(DISTINCT amount) OVER (PARTITION BY region) "
                      "FROM sales")
