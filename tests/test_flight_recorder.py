"""Incident flight recorder: always-on tail-sampled tracing + trigger-driven
evidence capture (round 19).

Covers, deterministically:

- tail retention at TRACE_SAMPLE_RATE=0: fast queries drop, slow / errored /
  shed queries keep their trace with a phase breakdown — including the
  PARTIAL breakdown (admission/queue stamped before the raise) on shed and
  failed statements that previously recorded nothing
- full phase attribution on the root span of a successful sampled query
  (admission wait, queue, plan, execute, serialize)
- byte-budgeted ring: the store evicts oldest-first and never exceeds its
  budget
- hot-path guards: with sampling ON, dispatch counts and host<->device
  transfers are identical to tracing OFF, and steady-state retraces stay 0
- the acceptance e2e: an FP_SLO_LATENCY_MS-injected burn fires `slo_burn`
  and the recorder captures EXACTLY ONE bundle whose implicated digest's
  tail-retained trace carries a non-empty phase breakdown, plus the
  metric-history window and admission/memory state — retrievable via
  SHOW INCIDENTS [id], information_schema.incidents and web /incidents
- the admission_reject STORM detector (counter-delta per tick, not one
  bundle per routine shed)
- episode cooldown dedupe: same episode inside the cooldown is suppressed,
  a different correlation key opens a new episode
- persistence: bundles land in data_dir/incidents/ and reload from disk
  after the in-memory ring is gone
- the ENABLE_FLIGHT_RECORDER hatch
- cluster propagation: one trace id spans router -> coordinator (grafted
  peer span tree under the route span) over an in-process peer AND over a
  REAL subprocess peer on the MySQL + sync wires; SHOW TRACE on the router
  session renders the whole path

Covered event kinds: slo_burn, plan_regression, admission_reject (journal
round-trips keep galaxylint's event-untested rule green).

The `incident`-marked tests are the fast smoke target (`make
incident-smoke`).
"""

import json
import os
import subprocess
import sys
import time

import numpy as np
import pytest

from galaxysql_tpu.exec import operators as ops
from galaxysql_tpu.server.instance import Instance
from galaxysql_tpu.server.router import FrontRouter, InprocPeer, RouterSession
from galaxysql_tpu.server.session import Session
from galaxysql_tpu.server.web import WebConsole
from galaxysql_tpu.utils import errors
from galaxysql_tpu.utils.events import EVENTS, publish
from galaxysql_tpu.utils.failpoint import FAIL_POINTS, FP_SLO_LATENCY_MS

pytestmark = pytest.mark.incident


@pytest.fixture(autouse=True)
def _clean_failpoints():
    FAIL_POINTS.clear()
    yield
    FAIL_POINTS.clear()


def _mk(schema="fr", rows=200, data_dir=None):
    inst = Instance(data_dir=data_dir)
    s = Session(inst)
    s.execute(f"CREATE DATABASE IF NOT EXISTS {schema}")
    s.execute(f"USE {schema}")
    if rows:
        s.execute("CREATE TABLE t (a BIGINT PRIMARY KEY, b BIGINT)")
        inst.store(schema, "t").insert_arrays(
            {"a": np.arange(rows), "b": np.arange(rows) % 17},
            inst.tso.next_timestamp())
        s.execute("ANALYZE TABLE t")
    return inst, s


class _Ticker:
    """Synthetic 5s-spaced maintenance ticks (same idiom as test_slo)."""

    def __init__(self, inst):
        self.inst = inst
        self.t0 = time.time()
        self.n = 0

    def __call__(self, k=1):
        for _ in range(k):
            self.n += 1
            assert self.inst.slo_tick(now=self.t0 + 5.0 * self.n, force=True)

    @property
    def now(self):
        return self.t0 + 5.0 * self.n


# -- tail-sampled retention ---------------------------------------------------


class TestTailRetention:
    def test_fast_query_drops_at_rate_zero(self):
        inst, s = _mk("tr1")
        inst.trace_store.configure(rate=0.0)
        inst.trace_store.clear()
        s.execute("SELECT b FROM t WHERE a = 5")
        assert inst.trace_store.stats()["count"] == 0
        s.close()

    def test_slow_error_shed_retained_at_rate_zero(self):
        """The tail is always kept: slow, errored and shed statements
        retain their trace even with head sampling fully off — and the
        failed ones carry the PARTIAL phase breakdown stamped before the
        raise (previously they recorded nothing)."""
        inst, s = _mk("tr2")
        inst.trace_store.configure(rate=0.0)
        inst.trace_store.clear()
        # slow: every statement is over a 0ms threshold
        inst.config.set_instance("SLOW_SQL_MS", 0)
        s.execute("SELECT b FROM t WHERE a = 6")
        ents = inst.trace_store.entries()
        assert [e.reason for e in ents] == ["slow"]
        assert ents[0].phases and "execute" in ents[0].phases
        inst.config.set_instance("SLOW_SQL_MS", 10 ** 9)
        # error: binder failure after admission — partial phases
        with pytest.raises(errors.TddlError):
            s.execute("SELECT nope FROM t")
        err = [e for e in inst.trace_store.entries() if e.reason == "error"]
        assert len(err) == 1
        assert "UnknownColumnError" in err[0].error
        assert err[0].phases and "admission" in err[0].phases
        # shed: queue full -> typed refusal, trace retained with the
        # admission wait it spent before being refused
        inst.config.set_instance("ADMISSION_AP_LIMIT", 1)
        inst.config.set_instance("ADMISSION_QUEUE_SIZE", 0)
        inst.admission._limit.clear()
        inst.admission._tokens["AP"].append(None)
        try:
            with pytest.raises(errors.ServerOverloadError):
                s.execute("SELECT b, count(*) FROM t GROUP BY b")
        finally:
            inst.admission._tokens["AP"].pop()
        shed = [e for e in inst.trace_store.entries() if e.reason == "shed"]
        assert len(shed) == 1
        assert shed[0].phases and "admission" in shed[0].phases
        s.close()

    def test_full_phase_breakdown_on_sampled_query(self):
        inst, s = _mk("tr3")
        inst.trace_store.configure(rate=1.0)
        inst.trace_store.clear()
        s.execute("SELECT b FROM t WHERE a = 7")
        ents = inst.trace_store.entries()
        assert ents and ents[-1].reason == "sampled"
        ph = ents[-1].phases
        for want in ("admission", "queue", "plan", "execute", "serialize"):
            assert want in ph, f"missing phase {want}: {ph}"
        # the root span carries the breakdown for SHOW TRACE / Perfetto
        root = ents[-1].spans[0]
        assert root["attrs"].get("phases") == ph
        s.close()

    def test_budget_bounded_evicts_oldest_first(self):
        inst, s = _mk("tr4")
        inst.trace_store.configure(rate=1.0, budget_bytes=4096)
        inst.trace_store.clear()
        for i in range(40):
            s.execute(f"SELECT b FROM t WHERE a = {i}")
        st = inst.trace_store.stats()
        assert st["bytes"] <= 4096
        assert st["evicted"] > 0
        assert st["count"] >= 1
        # survivors are the newest traces (entries() is newest-first)
        ids = [e.trace_id for e in inst.trace_store.entries()]
        assert ids == sorted(ids, reverse=True)
        s.close()

    def test_tracing_hatch_off_retains_nothing(self):
        inst, s = _mk("tr5")
        inst.config.set_instance("ENABLE_QUERY_TRACING", False)
        inst.trace_store.configure(rate=1.0)
        inst.trace_store.clear()
        inst.config.set_instance("SLOW_SQL_MS", 0)
        s.execute("SELECT b FROM t WHERE a = 8")
        assert inst.trace_store.stats()["count"] == 0
        s.close()


# -- hot-path guards ----------------------------------------------------------


class TestHotPathGuards:
    def test_sampling_on_same_dispatches_zero_retraces(self):
        """Always-on collection must be invisible to the device plane:
        identical dispatch + transfer counts vs tracing OFF, and a warm
        workload stays at 0 retraces with sampling fully on."""
        from galaxysql_tpu.exec.device_cache import TRANSFER_STATS
        inst, s = _mk("hp1", rows=1000)
        q = "SELECT a, b * 3 FROM t WHERE a < 500"
        inst.trace_store.configure(rate=1.0)
        s.execute(q)  # warm: compile once
        r0 = ops.COMPILE_STATS["retraces"]
        ops.reset_dispatch_stats()
        x0 = TRANSFER_STATS["transfers"]
        on = s.execute(q)
        d_on = ops.DISPATCH_STATS["dispatches"]
        x_on = TRANSFER_STATS["transfers"] - x0
        assert ops.COMPILE_STATS["retraces"] == r0  # steady state: 0 new
        inst.config.set_instance("ENABLE_QUERY_TRACING", False)
        s.execute(q)  # re-warm under the new config
        ops.reset_dispatch_stats()
        x0 = TRANSFER_STATS["transfers"]
        off = s.execute(q)
        assert ops.DISPATCH_STATS["dispatches"] == d_on
        assert TRANSFER_STATS["transfers"] - x0 == x_on
        assert on.rows == off.rows
        s.close()


# -- the acceptance e2e: burn -> bundle ---------------------------------------


class TestBurnToBundle:
    def test_injected_burn_yields_one_complete_bundle(self):
        EVENTS.clear()
        inst, s = _mk("burn")
        inst.config.set_instance("SLO_FAST_WINDOW_SAMPLES", 2)
        inst.config.set_instance("SLO_SLOW_WINDOW_SAMPLES", 4)
        T = _Ticker(inst)
        for i in range(10):
            s.execute(f"SELECT b FROM t WHERE a = {i}")
        T(4)
        assert inst.recorder.bundles() == []
        FAIL_POINTS.arm(FP_SLO_LATENCY_MS, {"ms": 10000, "workload": "TP"})
        for i in range(20):
            s.execute(f"SELECT b FROM t WHERE a = {i % 200}")
        T(3)
        bundles = [b for b in inst.recorder.bundles() if b.kind == "slo_burn"]
        assert len(bundles) == 1, [b.episode for b in bundles]
        b = bundles[0]
        assert b.severity == "critical"
        assert b.episode == "slo_burn:tp_latency_p99"
        # the implicated digest is the burning statement's, and its
        # tail-retained trace is IN the bundle with a phase breakdown
        assert b.digests, "burn bundle implicated no digest"
        assert b.traces, "burn bundle carries no traces"
        tr = b.traces[0]
        assert tr["digest"] == b.digests[0]
        assert tr["reason"] in ("slow", "error", "shed")
        assert tr["phases"] and "execute" in tr["phases"]
        assert tr["spans"], "retained trace lost its span tree"
        # frozen evidence: metric window + admission/memory state + events
        assert b.metric_window, "no metric-history window frozen"
        assert any("latency" in k or "admission" in k
                   for k in b.metric_window)
        assert b.admission, "no admission state frozen"
        assert "mem_tier" in b.state and "burning" in b.state
        assert "tp_latency_p99" in b.state["burning"]
        assert b.events and any(e["kind"] == "slo_burn" for e in b.events)
        # summary rows for the implicated digest ride along
        assert any(str(r[0]) == b.digests[0] for r in b.summary_rows)
        # continuing burn inside the cooldown: still exactly one bundle
        for i in range(10):
            s.execute(f"SELECT b FROM t WHERE a = {i % 200}")
        T(2)
        assert len([x for x in inst.recorder.bundles()
                    if x.kind == "slo_burn"]) == 1

        # -- surfaces over the SAME live incident --------------------------
        rs = s.execute("SHOW INCIDENTS")
        assert rs.names[0] == "Incident"
        row = next(r for r in rs.rows if r[0] == b.incident_id)
        assert row[2] == "slo_burn" and b.digests[0] in row[6]
        seq = b.incident_id.split("-")[1]
        det = s.execute(f"SHOW INCIDENTS {seq}")
        fields = {r[0]: r[1] for r in det.rows}
        assert fields["kind"] == "slo_burn"
        assert fields["digests"] == ",".join(b.digests)
        assert any(k.startswith("metric:") for k in fields)
        assert any(k.startswith("trace:") for k in fields)
        with pytest.raises(errors.TddlError):
            s.execute("SHOW INCIDENTS 9999")
        rs = s.execute("SELECT incident_id, kind, digests FROM "
                       "information_schema.incidents")
        assert (b.incident_id, "slo_burn", ",".join(b.digests)) in [
            tuple(r) for r in rs.rows]
        w = WebConsole(inst)
        idx = w.resource("/incidents")
        assert idx["captured"] >= 1
        assert any(e["incident_id"] == b.incident_id
                   for e in idx["incidents"])
        detail = w.resource(f"/incidents/{b.incident_id}")
        assert detail["kind"] == "slo_burn" and detail["traces"]
        # the retained trace stays Perfetto-linkable through the store
        ct = w.resource(f"/trace/{tr['trace_id']}")
        assert ct and ct["traceEvents"]
        FAIL_POINTS.clear()
        s.close()

    def test_reject_storm_captures_one_bundle(self):
        """Routine single sheds do NOT open incidents; a storm (counter
        delta >= INCIDENT_REJECT_STORM in one tick) opens exactly one."""
        EVENTS.clear()
        inst, s = _mk("storm")
        T = _Ticker(inst)
        T(1)  # baseline the reject counter
        inst.config.set_instance("INCIDENT_REJECT_STORM", 5)
        inst.config.set_instance("ADMISSION_AP_LIMIT", 1)
        inst.config.set_instance("ADMISSION_QUEUE_SIZE", 0)
        inst.admission._limit.clear()
        inst.admission._tokens["AP"].append(None)
        try:
            # 2 rejects: routine backpressure, below the storm bar
            for _ in range(2):
                with pytest.raises(errors.ServerOverloadError):
                    s.execute("SELECT b, count(*) FROM t GROUP BY b")
            T(1)
            assert [b for b in inst.recorder.bundles()
                    if b.kind == "admission_reject"] == []
            # 6 more: storm
            for _ in range(6):
                with pytest.raises(errors.ServerOverloadError):
                    s.execute("SELECT b, count(*) FROM t GROUP BY b")
            T(1)
        finally:
            inst.admission._tokens["AP"].pop()
        storms = [b for b in inst.recorder.bundles()
                  if b.kind == "admission_reject"]
        assert len(storms) == 1
        assert "storm" in storms[0].detail
        # the shed statements' tail-retained traces are the evidence
        assert any(t["reason"] == "shed" for t in storms[0].traces)
        s.close()

    def test_cooldown_dedupes_per_episode(self):
        EVENTS.clear()
        inst, s = _mk("cool", rows=0)
        T = _Ticker(inst)
        rec = inst.recorder
        publish("plan_regression", "digest d1 regressed", severity="warn",
                digest="d1")
        T(1)
        assert len(rec.bundles()) == 1
        # same episode, inside the cooldown: suppressed
        publish("plan_regression", "digest d1 regressed again",
                severity="warn", digest="d1")
        T(1)
        assert len(rec.bundles()) == 1
        assert rec.suppressed >= 1
        # different correlation key: a NEW episode
        publish("plan_regression", "digest d2 regressed", severity="warn",
                digest="d2")
        T(1)
        eps = {b.episode for b in rec.bundles()}
        assert eps == {"plan_regression:d1", "plan_regression:d2"}
        # past the cooldown the same episode may fire again
        inst.config.set_instance("INCIDENT_COOLDOWN_S", 1.0)
        publish("plan_regression", "digest d1 regressed later",
                severity="warn", digest="d1")
        T(1)  # synthetic clock advanced 5s > 1s cooldown
        assert len([b for b in rec.bundles()
                    if b.episode == "plan_regression:d1"]) == 2
        s.close()

    def test_bundles_persist_and_reload_from_disk(self, tmp_path):
        EVENTS.clear()
        inst, s = _mk("disk", rows=0, data_dir=str(tmp_path / "n1"))
        T = _Ticker(inst)
        publish("plan_regression", "digest px regressed", severity="warn",
                digest="px")
        T(1)
        b = inst.recorder.bundles()[0]
        path = os.path.join(str(tmp_path / "n1"), "incidents",
                            f"{b.incident_id}.json")
        assert os.path.exists(path)
        with open(path) as f:
            raw = json.load(f)
        assert raw["episode"] == "plan_regression:px"
        # in-memory ring gone (restart stand-in): get() falls through to
        # disk, bare sequence number accepted
        inst.recorder.clear()
        got = inst.recorder.get(b.incident_id.split("-")[1])
        assert got is not None and got.episode == "plan_regression:px"
        s.close()

    def test_recorder_hatch_off_captures_nothing(self):
        EVENTS.clear()
        inst, s = _mk("hatch", rows=0)
        inst.config.set_instance("ENABLE_FLIGHT_RECORDER", False)
        T = _Ticker(inst)
        publish("plan_regression", "digest hx regressed", severity="warn",
                digest="hx")
        T(1)
        assert inst.recorder.bundles() == []
        s.close()


# -- cluster propagation: router -> coordinator graft -------------------------


def _seed_router_schema(inst):
    s = Session(inst)
    s.execute("CREATE DATABASE d")
    s.execute("USE d")
    s.execute("CREATE TABLE t (k BIGINT PRIMARY KEY, v BIGINT)")
    s.execute("INSERT INTO t VALUES (1, 10), (2, 20), (3, 30)")
    return s


class TestRouterTraceGraft:
    def test_inproc_peer_hop_grafts_one_trace(self):
        """One trace id spans router -> peer: the peer session adopts the
        hinted id, force-retains, and the router pulls + grafts its span
        tree under the route span."""
        a = Instance()
        sa = _seed_router_schema(a)
        router = FrontRouter(a)
        router.local.down_until = float("inf")  # hub routes, never serves
        b = Instance()
        _seed_router_schema(b).close()
        peer = InprocPeer(b)
        router.add_peer(peer)
        try:
            a.trace_store.configure(rate=1.0)
            rsess = RouterSession(router, schema="d")
            rs = rsess.execute("select v from t where k = 2")
            assert [tuple(map(int, r)) for r in rs.rows] == [(20,)]
            spans = rsess.last_spans
            assert spans[0].name == "route" and spans[0].node == a.node_id
            # grafted peer subtree hangs under the route span
            peer_spans = [sp for sp in spans if sp.node == b.node_id]
            assert peer_spans, "no peer spans grafted"
            root_children = [sp for sp in peer_spans
                             if sp.parent_id == spans[0].span_id]
            assert root_children and root_children[0].name == "query"
            # assembled cluster path retained on the ROUTER under one id
            rt = a.trace_store.get(rsess.last_trace_id)
            assert rt is not None
            assert rt.phases and "execute" in rt.phases  # peer's breakdown
            assert {s2["node"] for s2 in rt.spans} == {a.node_id, b.node_id}
            # the peer kept the same id too (forced by the sampled flag)
            prt = b.trace_store.get(rsess.last_trace_id)
            assert prt is not None and prt.reason == "remote"
            # SHOW TRACE on the router session renders the whole path
            out = [r[0] for r in rsess.execute("SHOW TRACE").rows]
            assert f"trace-id {rsess.last_trace_id}" in out[0]
            assert any("route" in line and a.node_id in line for line in out)
            assert any(b.node_id in line for line in out)
            rsess.close()
        finally:
            router.close()
            sa.close()

    def test_peer_error_still_retains_routed_trace(self):
        """An app-level failure on a live peer is evidence, not a
        transport fault: the router keeps the assembled trace with
        reason=error."""
        a = Instance()
        sa = _seed_router_schema(a)
        router = FrontRouter(a)
        router.local.down_until = float("inf")
        b = Instance()
        _seed_router_schema(b).close()
        router.add_peer(InprocPeer(b))
        try:
            a.trace_store.configure(rate=0.0)  # tail-only
            rsess = RouterSession(router, schema="d")
            with pytest.raises(errors.TddlError):
                rsess.execute("select nope from t")
            rt = a.trace_store.get(rsess.last_trace_id)
            assert rt is not None and rt.reason == "error"
            assert "UnknownColumnError" in rt.error
            rsess.close()
        finally:
            router.close()
            sa.close()

    def _spawn(self, data_dir):
        env = dict(os.environ, JAX_PLATFORMS="cpu")
        p = subprocess.Popen(
            [sys.executable, "-m", "galaxysql_tpu.net.server", "--port",
             "0", "--sync-port", "0", "--data-dir", data_dir,
             "--platform", "cpu", "--announce"],
            cwd=os.path.dirname(os.path.dirname(os.path.abspath(__file__))),
            stdout=subprocess.PIPE, stderr=subprocess.DEVNULL, env=env,
            text=True)
        line = p.stdout.readline()
        assert line.startswith("SERVER_READY"), line
        _, mysql_port, sync_port = line.split()
        return p, int(mysql_port), int(sync_port)

    def test_subprocess_peer_graft_over_real_wires(self, tmp_path):
        """The graft over the REAL wires: statement + trace hint over the
        MySQL protocol, evidence pull over the dn sync wire."""
        data_dir = str(tmp_path / "shared")
        seed = Instance(data_dir=data_dir)
        _seed_router_schema(seed).close()
        seed.save()
        p, mp, sp = self._spawn(data_dir)
        hub = Instance(boot=False)
        router = FrontRouter(hub)
        router.local.down_until = float("inf")
        try:
            router.add_remote("127.0.0.1", mp, sp)
            hub.trace_store.configure(rate=1.0)
            rsess = RouterSession(router, schema="d")
            rs = rsess.execute("select v from t where k = 2")
            assert [tuple(map(int, r)) for r in rs.rows] == [(20,)]
            rt = hub.trace_store.get(rsess.last_trace_id)
            assert rt is not None, "router did not retain the routed trace"
            nodes = {s2["node"] for s2 in rt.spans}
            assert hub.node_id in nodes and len(nodes) == 2
            assert rt.phases and "execute" in rt.phases
            # root is the router's route span; the peer's query span (with
            # the phase breakdown) is grafted directly beneath it
            assert rt.spans[0]["name"] == "route"
            kids = [s2 for s2 in rt.spans
                    if s2["parent_id"] == rt.spans[0]["span_id"]]
            assert kids and kids[0]["name"] == "query"
            out = [r[0] for r in rsess.execute("SHOW TRACE").rows]
            assert any("query" in line and "phases=" in line
                       for line in out)
            rsess.close()
        finally:
            router.close()
            p.kill()
            p.wait()
