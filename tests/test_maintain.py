"""Maintenance surfaces: recycle bin, CHECK TABLE, index advisor, TSO batching.

Reference analogs: recycle bin (`executor/.../recycle`), corrector
(`executor/corrector/Checker.java`), index advisor
(`polardbx-optimizer/.../optimizer/index`), batched TSO fetch
(`ClusterTimestampOracle.java:109-133`).
"""

import numpy as np
import pytest

from galaxysql_tpu.server.instance import Instance
from galaxysql_tpu.server.session import Session
from galaxysql_tpu.utils import errors


@pytest.fixture()
def sess():
    inst = Instance()
    s = Session(inst)
    s.execute("CREATE DATABASE mt")
    s.execute("USE mt")
    s.execute("CREATE TABLE t (id BIGINT PRIMARY KEY, v VARCHAR(10), n INT) "
              "PARTITION BY HASH(id) PARTITIONS 4")
    s.execute("INSERT INTO t VALUES (1,'a',10), (2,'b',20), (3,'c',30)")
    return inst, s


class TestRecycleBin:
    def test_drop_flashback_roundtrip(self, sess):
        inst, s = sess
        s.execute("DROP TABLE t")
        # gone from the visible namespace
        assert s.execute("SHOW TABLES").rows == []
        with pytest.raises(errors.TddlError):
            s.execute("SELECT * FROM t")
        # listed in the bin
        bin_rows = s.execute("SHOW RECYCLEBIN").rows
        assert len(bin_rows) == 1 and bin_rows[0][1] == "t"
        # restore, data intact
        s.execute("FLASHBACK TABLE t TO BEFORE DROP")
        assert sorted(s.execute("SELECT id, v FROM t").rows) == \
            [(1, "a"), (2, "b"), (3, "c")]
        assert s.execute("SHOW RECYCLEBIN").rows == []

    def test_flashback_rename_and_name_conflict(self, sess):
        inst, s = sess
        s.execute("DROP TABLE t")
        s.execute("CREATE TABLE t (id BIGINT)")  # original name reused
        with pytest.raises(errors.TddlError, match="already exists"):
            s.execute("FLASHBACK TABLE t TO BEFORE DROP")
        s.execute("FLASHBACK TABLE t TO BEFORE DROP RENAME TO t_old")
        assert sorted(s.execute("SELECT v FROM t_old").rows) == \
            [("a",), ("b",), ("c",)]

    def test_purge(self, sess):
        inst, s = sess
        s.execute("DROP TABLE t")
        name = s.execute("SHOW RECYCLEBIN").rows[0][0]
        assert s.execute(f"PURGE TABLE {name}").affected == 1
        assert s.execute("SHOW RECYCLEBIN").rows == []
        with pytest.raises(errors.TddlError):
            s.execute("FLASHBACK TABLE t TO BEFORE DROP")
        # purge everything form
        s.execute("CREATE TABLE p2 (id BIGINT)")
        s.execute("DROP TABLE p2")
        assert s.execute("PURGE RECYCLEBIN").affected == 1

    def test_gsi_tables_drop_directly(self, sess):
        inst, s = sess
        s.execute("CREATE TABLE g (id BIGINT PRIMARY KEY, k INT) "
                  "PARTITION BY HASH(id) PARTITIONS 2")
        s.execute("CREATE GLOBAL INDEX gk ON g (k)")
        s.execute("DROP TABLE g")
        # not recyclable (backing table lifecycle), like the reference
        assert all(r[1] != "g" for r in s.execute("SHOW RECYCLEBIN").rows)

    def test_disabled_by_config(self, sess):
        inst, s = sess
        s.execute("SET ENABLE_RECYCLEBIN = false")
        s.execute("DROP TABLE t")
        assert s.execute("SHOW RECYCLEBIN").rows == []


class TestCheckTable:
    def test_ok_and_gsi_divergence(self, sess):
        inst, s = sess
        s.execute("CREATE GLOBAL INDEX gn ON t (n)")
        r = s.execute("CHECK TABLE t")
        assert any(row[3] == "OK" for row in r.rows), r.rows
        # corrupt the GSI store directly -> divergence reported
        gstore = inst.store("mt", "t$gn")
        part = next(p for p in gstore.partitions if p.num_rows)
        part.delete_rows(np.array([0]), inst.tso.next_timestamp())
        r = s.execute("CHECK TABLE t")
        assert any("diverges" in str(row[3]) for row in r.rows), r.rows


class TestAdviseIndex:
    def test_suggests_gsi_for_unserved_eq(self, sess):
        inst, s = sess
        r = s.execute("ADVISE INDEX SELECT v FROM t WHERE n = 20")
        assert len(r.rows) == 1
        tname, col, why, sugg = r.rows[0]
        assert (tname, col) == ("t", "n")
        assert sugg.startswith("CREATE GLOBAL INDEX g_n ON t (n)")
        assert "COVERING" in sugg and "v" in sugg
        # the suggestion is executable and then routes the query
        s.execute(sugg)
        plan = "\n".join(x[0] for x in
                         s.execute("EXPLAIN SELECT v FROM t WHERE n = 20").rows)
        assert "t$g_n" in plan, plan

    def test_no_suggestion_when_served(self, sess):
        inst, s = sess
        r = s.execute("ADVISE INDEX SELECT v FROM t WHERE id = 1")
        assert r.rows == []  # PK lead already serves it


class TestTsoBatch:
    def test_batch_is_monotone_and_disjoint(self):
        from galaxysql_tpu.meta.tso import TimestampOracle
        tso = TimestampOracle()
        a = tso.next_timestamp()
        batch = tso.next_timestamps(1000)
        b = tso.next_timestamp()
        assert len(set(batch)) == 1000
        assert batch == sorted(batch)
        assert a < batch[0] and batch[-1] < b
