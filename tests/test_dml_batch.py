"""Cross-session DML batching (server/dml_batch.py) + group commit + async
apply (txn/async_apply.py).

Guards the mega-batched write path: batched table state must be bit-identical
to sequential execution under heavy concurrency, a poisoned key fails only
its own session, transactional sessions bypass, reads after an async GSI
apply honor read-your-writes, replica legs apply exactly once under a
reply-leg drop, the commit point amortizes across concurrent committers, and
CDC emission coalesces per flush while replaying to identical state.  Fast
target: `make dml-smoke`.
"""

import threading

import pytest

from galaxysql_tpu.server.instance import Instance
from galaxysql_tpu.server.session import Session
from galaxysql_tpu.utils import errors
from galaxysql_tpu.utils.failpoint import (FAIL_POINTS, FP_APPLY_DELAY_MS,
                                           FP_DML_POISON_KEY, FP_RPC_DROP)

pytestmark = pytest.mark.dml_batch

DDL = """
    CREATE TABLE t (
        id BIGINT NOT NULL PRIMARY KEY,
        k  INT NOT NULL,
        v  VARCHAR(20),
        amt DECIMAL(12,2)
    ) PARTITION BY HASH(id) PARTITIONS 4
"""

INS = "INSERT INTO t (id, k, v, amt) VALUES (%d, %d, '%s', %d.25)"
UPD = "UPDATE t SET amt = %d.99, v = '%s' WHERE id = %d"
DEL = "DELETE FROM t WHERE id = %d"


@pytest.fixture(autouse=True)
def _clean_failpoints():
    FAIL_POINTS.clear()
    yield
    FAIL_POINTS.clear()


def fresh(window_us=3000):
    inst = Instance()
    # the closed-loop thread storms here push per-op latency past the TP
    # AIMD target and the overload plane (correctly) sheds; this suite tests
    # the batcher's correctness, not the shedder (tests/test_overload.py)
    inst.config.set_instance("ENABLE_ADMISSION_CONTROL", 0)
    s = Session(inst)
    s.execute("CREATE DATABASE dbx")
    s.execute("USE dbx")
    s.execute(DDL)
    # seed + register the three batch plans (first sequential run registers)
    s.execute(INS % (1, 1, "seed", 1))
    s.execute(UPD % (1, "seed", 1))
    s.execute(DEL % 1)
    if window_us:
        inst.config.set_instance("DML_BATCH_WINDOW_US", window_us)
    return inst, s


def _run_threads(n, fn):
    errs = []
    barrier = threading.Barrier(n)

    def runner(i):
        try:
            barrier.wait(timeout=30)
            fn(i)
        except Exception as e:  # pragma: no cover - assertion carrier
            errs.append(e)

    threads = [threading.Thread(target=runner, args=(i,)) for i in range(n)]
    for t in threads:
        t.start()
    for t in threads:
        t.join()
    return errs


def _workload(inst, n_sessions, per):
    """Deterministic mixed write workload: each session owns a disjoint key
    range (insert -> update -> insert+delete), so the final table state does
    not depend on interleaving."""
    def worker(i):
        sx = Session(inst, schema="dbx")
        base = 1000 + i * 100
        for j in range(per):
            k = base + j
            sx.execute(INS % (k, k % 41, f"v{k % 13}", k % 500))
            if j % 2 == 0:
                sx.execute(UPD % (k % 300, f"u{k % 7}", k))
            if j % 3 == 0:
                sx.execute(INS % (k + 50, k % 41, "tmp", 9))
                sx.execute(DEL % (k + 50))
        sx.close()
    return _run_threads(n_sessions, worker)


def _table_state(s):
    return s.execute("SELECT id, k, v, amt FROM t ORDER BY id").rows


def test_batched_bit_identical_100_sessions():
    """100+ concurrent write sessions: the batched engine's final table
    state equals the sequential engine's, bit for bit, and groups actually
    formed (not a fallback parade)."""
    inst_b, sb = fresh()
    errs = _workload(inst_b, 104, 6)
    assert not errs, errs[:3]
    assert inst_b.metrics.counter("dml_batched_queries").value > 0
    assert inst_b.metrics.counter("dml_batch_flushes").value > 0

    inst_s, ss = fresh(window_us=0)
    inst_s.config.set_instance("ENABLE_DML_BATCHING", 0)
    errs = _workload(inst_s, 104, 6)
    assert not errs, errs[:3]
    assert inst_s.metrics.counter("dml_batched_queries").value == 0
    assert _table_state(sb) == _table_state(ss)


def test_affected_counts_and_missing_keys():
    inst, s = fresh()
    s.execute(INS % (10, 1, "a", 10))
    assert s.execute(UPD % (5, "x", 10)).affected == 1
    assert s.execute(UPD % (5, "x", 999999)).affected == 0
    assert s.execute(DEL % 999999).affected == 0
    assert s.execute(DEL % 10).affected == 1

    def worker(i):
        sx = Session(inst, schema="dbx")
        assert sx.execute(UPD % (7, "y", 5000 + i)).affected == 0
        sx.close()

    errs = _run_threads(16, worker)
    assert not errs, errs[:3]


def test_poison_key_isolation():
    """A poisoned key (the duplicate-key/constraint stand-in) fails ONLY its
    own session; the rest of the group lands."""
    inst, s = fresh(window_us=5000)
    FAIL_POINTS.arm(FP_DML_POISON_KEY, 6666)
    hit = []

    def worker(i):
        sx = Session(inst, schema="dbx")
        k = 6666 if i == 7 else 2000 + i
        try:
            sx.execute(INS % (k, i, "p", i))
        except errors.TddlError:
            raise
        except Exception as e:
            hit.append((k, e))
        sx.close()

    errs = _run_threads(24, worker)
    assert not errs, errs[:3]
    assert len(hit) == 1 and hit[0][0] == 6666
    FAIL_POINTS.clear()
    n = s.execute("SELECT count(*) FROM t WHERE id >= 2000 AND id < 2024").rows
    assert n == [(23,)]
    assert s.execute("SELECT count(*) FROM t WHERE id = 6666").rows == [(0,)]


def test_not_null_violation_isolated():
    """A NOT NULL violation fails per member, mirroring the sequential
    store-level error, without poisoning the group."""
    inst, s = fresh(window_us=5000)
    tpl = "INSERT INTO t (id, k, v, amt) VALUES (%s, %s, 'n', 3.25)"
    s.execute(tpl % (300, 3))
    bad = []

    def worker(i):
        sx = Session(inst, schema="dbx")
        try:
            if i == 3:
                sx.execute(tpl % (400 + i, "NULL"))
            else:
                sx.execute(tpl % (400 + i, i))
        except errors.TddlError as e:
            bad.append(str(e))
        sx.close()

    errs = _run_threads(8, worker)
    assert not errs, errs[:3]
    assert len(bad) == 1 and "cannot be null" in bad[0]
    assert s.execute(
        "SELECT count(*) FROM t WHERE id >= 400 AND id < 408").rows == [(7,)]


def test_own_txn_bypass():
    """A transaction's writes need own-visibility + undo: they bypass the
    batcher structurally and keep exact BEGIN/ROLLBACK semantics."""
    inst, s = fresh()
    before = inst.metrics.counter("dml_batched_queries").value
    s.execute("BEGIN")
    s.execute(INS % (77, 7, "txn", 7))
    assert s.execute("SELECT v FROM t WHERE id = 77").rows == [("txn",)]
    s.execute("ROLLBACK")
    assert s.execute("SELECT count(*) FROM t WHERE id = 77").rows == [(0,)]
    s.execute("BEGIN")
    s.execute(INS % (78, 7, "txn2", 7))
    s.execute("COMMIT")
    assert s.execute("SELECT v FROM t WHERE id = 78").rows == [("txn2",)]
    assert inst.metrics.counter("dml_batched_queries").value == before


def test_duplicate_key_members_fall_back():
    """Two members writing the SAME key are order-dependent: both fall back
    and serialize on the sequential path (bit-identical contract)."""
    inst, s = fresh(window_us=8000)
    s.execute(INS % (900, 9, "dup", 1))
    f0 = inst.metrics.counter("dml_batch_fallbacks").value
    results = []

    def worker(i):
        sx = Session(inst, schema="dbx")
        results.append(sx.execute(UPD % (10 + i, f"w{i}", 900)).affected)
        sx.close()

    errs = _run_threads(2, worker)
    assert not errs, errs[:3]
    assert results == [1, 1]
    v = s.execute("SELECT v FROM t WHERE id = 900").rows[0][0]
    assert v in ("w0", "w1")
    assert inst.metrics.counter("dml_batch_fallbacks").value >= f0 + 2


def test_write_conflict_isolated_per_key():
    """A row already end-stamped by a (future) committer conflicts for ITS
    member only; the co-batched member lands."""
    import numpy as np
    from galaxysql_tpu.storage.table_store import INFINITY_TS
    inst, s = fresh(window_us=8000)
    s.execute(INS % (910, 9, "c1", 1))
    s.execute(INS % (911, 9, "c2", 1))
    store = inst.store("dbx", "t")
    # stamp 910's row as deleted by a committer AFTER any snapshot we take
    future = inst.tso.next_timestamp() + (1 << 40)
    for p in store.partitions:
        ids = p.key_candidates("id", 910)
        live = ids[p.end_ts[ids] == INFINITY_TS]
        if live.size:
            p.end_ts[live] = future
    got = {}

    def worker(i):
        sx = Session(inst, schema="dbx")
        key = 910 if i == 0 else 911
        try:
            got[key] = sx.execute(UPD % (50 + i, f"z{i}", key)).affected
        except errors.TransactionError as e:
            got[key] = e
        sx.close()

    errs = _run_threads(2, worker)
    assert not errs, errs[:3]
    assert isinstance(got[910], errors.TransactionError)
    assert got[911] == 1


class TestAsyncApply:
    def test_read_your_writes_after_async_gsi_apply(self):
        """With the applier artificially delayed, a session's batched insert
        must still be visible to its OWN next read through the GSI route
        (the fence), and the GSI store converges to the base table."""
        inst, s = fresh(window_us=5000)
        s.execute("CREATE GLOBAL INDEX g_k ON t (k) COVERING (amt)")
        # the DDL bumped schema_version: one sequential run re-registers the
        # batch plan before the storm
        s.execute(INS % (2999, 699, "warm", 99))
        FAIL_POINTS.arm(FP_APPLY_DELAY_MS, 300)
        errs = []

        def worker(i):
            sx = Session(inst, schema="dbx")
            kk = 700 + i
            sx.execute(INS % (3000 + i, kk, "g", 100 + i))
            rows = sx.execute("SELECT amt FROM t WHERE k = %d" % kk).rows
            if rows != [(float(100 + i) + 0.25,)]:
                errs.append((i, rows))
            sx.close()

        werrs = _run_threads(12, worker)
        assert not werrs, werrs[:3]
        assert not errs, errs[:3]
        FAIL_POINTS.clear()
        assert inst.metrics.counter("gsi_async_applies").value > 0
        assert inst.applier.drain(30.0)
        base = s.execute("SELECT count(*) FROM t").rows
        gsi = inst.store("dbx", "t$g_k").row_count()
        assert base == [(gsi,)]

    def test_update_delete_gsi_convergence(self):
        """Batched UPDATE/DELETE on a GSI-bearing table: async delete+insert
        tasks apply FIFO and the index converges exactly."""
        inst, s = fresh(window_us=5000)
        s.execute("CREATE GLOBAL INDEX g_k ON t (k) COVERING (amt)")
        for i in range(16):
            s.execute(INS % (4000 + i, 800 + i, "u", i))
        # re-register the update/delete plans post-DDL before the storm
        s.execute(UPD % (0, "u", 4000))
        s.execute(DEL % 3999)

        def worker(i):
            sx = Session(inst, schema="dbx")
            if i % 2 == 0:
                sx.execute(UPD % (77, "uu", 4000 + i))
            else:
                sx.execute(DEL % (4000 + i))
            sx.close()

        errs = _run_threads(16, worker)
        assert not errs, errs[:3]
        assert inst.applier.drain(30.0)
        base = s.execute("SELECT count(*) FROM t WHERE k >= 800").rows[0][0]
        assert base == 8
        gsi_store = inst.store("dbx", "t$g_k")
        assert gsi_store.row_count() == \
            s.execute("SELECT count(*) FROM t").rows[0][0]
        # the updated rows read back through the index route
        rows = s.execute(
            "SELECT amt FROM t WHERE k = 800").rows
        assert rows == [(77.99,)]

    def test_sync_apply_when_disabled(self):
        """ENABLE_ASYNC_APPLY=0: GSI maintenance stays inside the flush
        (no applier involvement), results identical."""
        inst, s = fresh(window_us=5000)
        inst.config.set_instance("ENABLE_ASYNC_APPLY", 0)
        s.execute("CREATE GLOBAL INDEX g_k ON t (k) COVERING (amt)")
        s.execute(INS % (4999, 899, "warm", 9))
        a0 = inst.metrics.counter("gsi_async_applies").value

        def worker(i):
            sx = Session(inst, schema="dbx")
            sx.execute(INS % (5000 + i, 900 + i, "s", i))
            sx.close()

        errs = _run_threads(8, worker)
        assert not errs, errs[:3]
        assert inst.metrics.counter("gsi_async_applies").value == a0
        assert inst.store("dbx", "t$g_k").row_count() == \
            s.execute("SELECT count(*) FROM t").rows[0][0]


def test_group_commit_amortizes_commit_points():
    """64 concurrent explicit txns: every commit lands durably (DONE in the
    tx log, rows visible) in FEWER metadb flush groups than txns — the
    commit-point fsync actually amortized."""
    inst, s = fresh()
    b0 = inst.metrics.counter("group_commit_batches").value
    t0 = inst.metrics.counter("group_committed_txns").value

    def worker(i):
        sx = Session(inst, schema="dbx")
        sx.execute("BEGIN")
        sx.execute(INS % (8000 + i, i, "gc", i))
        sx.execute("COMMIT")
        sx.close()

    errs = _run_threads(64, worker)
    assert not errs, errs[:3]
    txns = inst.metrics.counter("group_committed_txns").value - t0
    batches = inst.metrics.counter("group_commit_batches").value - b0
    assert txns >= 64  # every commit point + DONE marker rode the gate
    assert batches < txns, (batches, txns)
    assert s.execute(
        "SELECT count(*) FROM t WHERE id >= 8000 AND id < 8064"
    ).rows == [(64,)]


def test_cdc_coalesced_and_replays_identically():
    """Batched flushes emit coalesced CDC events (fewer binlog rows than
    statements) that replay onto a fresh instance to the exact table state."""
    from galaxysql_tpu.txn.cdc import replay
    inst, s = fresh(window_us=5000)
    seq0 = max((r[0] for r in inst.cdc.events(0)), default=0)

    def worker(i):
        sx = Session(inst, schema="dbx")
        sx.execute(INS % (9000 + i, i % 5, f"c{i}", i))
        sx.close()

    errs = _run_threads(32, worker)
    assert not errs, errs[:3]
    evs = [e for e in inst.cdc.events(0) if e[0] > seq0]
    inserts = [e for e in evs if e[4] == "insert"]
    assert inserts, "no CDC events captured"
    assert len(inserts) < 32, len(inserts)  # coalesced per flush x partition
    target = Instance()
    st = Session(target)
    st.execute("CREATE DATABASE dbx")
    st.execute("USE dbx")
    st.execute(DDL)
    replay(inst.cdc.events(0), target)
    assert _table_state(st) == _table_state(s)


class TestHatches:
    def test_param_hatch(self):
        inst, s = fresh()
        inst.config.set_instance("ENABLE_DML_BATCHING", 0)
        before = inst.metrics.counter("dml_batched_queries").value

        def worker(i):
            sx = Session(inst, schema="dbx")
            sx.execute(INS % (10000 + i, i, "h", i))
            sx.close()

        errs = _run_threads(12, worker)
        assert not errs, errs[:3]
        assert inst.metrics.counter("dml_batched_queries").value == before
        assert s.execute(
            "SELECT count(*) FROM t WHERE id >= 10000").rows == [(12,)]

    def test_env_hatch(self, monkeypatch):
        from galaxysql_tpu.server import dml_batch
        monkeypatch.setattr(dml_batch, "ENABLED", False)
        inst, s = fresh()
        before = inst.metrics.counter("dml_batched_queries").value

        def worker(i):
            sx = Session(inst, schema="dbx")
            sx.execute(INS % (10100 + i, i, "e", i))
            sx.close()

        errs = _run_threads(8, worker)
        assert not errs, errs[:3]
        assert inst.metrics.counter("dml_batched_queries").value == before

    def test_hint_hatch(self):
        """A hinted DML statement neither registers nor batches — the hint
        comment structurally pins it to the sequential path."""
        inst, s = fresh()
        tpl = ("/*+TDDL: DML_BATCH(OFF)*/ INSERT INTO t (id, k, v, amt) "
               "VALUES (%d, %d, 'hint', 1.25)")
        s.execute(tpl % (10200, 1))
        key_count = len(inst.dml_plans)
        before = inst.metrics.counter("dml_batched_queries").value

        def worker(i):
            sx = Session(inst, schema="dbx")
            sx.execute(tpl % (10201 + i, i))
            sx.close()

        errs = _run_threads(8, worker)
        assert not errs, errs[:3]
        assert len(inst.dml_plans) == key_count
        assert inst.metrics.counter("dml_batched_queries").value == before


def test_statement_summary_and_admission_attribution():
    """Batched members attribute latency/rows to their OWN digest (not the
    leader's), and the admission classifier sees the digest as TP."""
    inst, s = fresh(window_us=5000)
    # this test asserts the admission classifier's digest feed: re-enable
    # the gate (16 sessions sit far below the initial TP limit)
    inst.config.set_instance("ENABLE_ADMISSION_CONTROL", 1)

    def worker(i):
        sx = Session(inst, schema="dbx")
        sx.execute(INS % (11000 + i, i, "ss", i))
        sx.close()

    errs = _run_threads(16, worker)
    assert not errs, errs[:3]
    assert inst.metrics.counter("dml_batched_queries").value > 0
    rows = [r for r in inst.stmt_summary.rows()
            if "insert into t" in (r[-1] or "").lower()]
    assert rows, "DML digest missing from the statement summary"
    row = rows[0]
    engines = row[3]
    execs = row[4]
    assert "dml" in engines
    assert execs >= 17  # 16 batched members + the seed sequential run
    digest = row[0]
    info = inst.admission._digest_cost.get(digest)
    assert info is not None and info[0] == "TP"


def test_steady_state_retrace_and_dispatch_guard():
    """Steady-state batched flushes compile nothing, and the sequential path
    (batching off) adds zero device dispatches per DML."""
    from galaxysql_tpu.exec import operators as _ops
    inst, s = fresh(window_us=3000)

    def wave(base):
        def worker(i):
            sx = Session(inst, schema="dbx")
            sx.execute(INS % (base + i, i, "w", i))
            sx.execute(UPD % (i, "w2", base + i))
            sx.close()
        return _run_threads(16, worker)

    assert not wave(12000)
    _ops.reset_compile_stats()
    assert not wave(12100)
    assert _ops.COMPILE_STATS["retraces"] == 0
    inst.config.set_instance("ENABLE_DML_BATCHING", 0)
    _ops.reset_dispatch_stats()
    d0 = _ops.DISPATCH_STATS["dispatches"]
    s.execute(INS % (12999, 1, "d", 1))
    s.execute(UPD % (2, "d2", 12999))
    s.execute(DEL % 12999)
    assert _ops.DISPATCH_STATS["dispatches"] == d0


def test_singleton_falls_back_sequential():
    """A lone writer (group of one) runs the sequential path — batching
    never taxes unconcurrent traffic with a pointless flush."""
    inst, s = fresh(window_us=2000)
    s0 = inst.metrics.counter("dml_batch_singletons").value
    # no concurrency: the adaptive window is 0 without MIN_INFLIGHT writers,
    # but even with a pinned window a singleton group must fall back
    s.execute(INS % (13000, 1, "solo", 1))
    assert s.execute("SELECT v FROM t WHERE id = 13000").rows == [("solo",)]
    assert inst.metrics.counter("dml_batch_singletons").value >= s0


def test_show_batch_stats_and_info_schema_rows():
    inst, s = fresh(window_us=3000)

    def worker(i):
        sx = Session(inst, schema="dbx")
        sx.execute(INS % (14000 + i, i, "st", i))
        sx.close()

    assert not _run_threads(12, worker)
    rows = dict(s.execute("SHOW BATCH STATS").rows)
    assert rows.get("dml_batched_queries", 0) > 0
    assert "dml_group_size_p50" in rows
    assert "gsi_apply_backlog" in rows and "gsi_apply_lag_ms" in rows
    irows = s.execute(
        "SELECT stat_name, value FROM information_schema.batch_stats").rows
    names = {r[0] for r in irows}
    assert {"dml_batched_queries", "dml_batch_flushes",
            "gsi_apply_lag_ms"} <= names
    # typed registry + Prometheus text carry the new families
    m = dict((r[0], r[2]) for r in inst.metrics.rows())
    assert "dml_batched_queries" in m
    assert "gsi_apply_lag_ms" in m
    text = inst.metrics.prometheus_text()
    assert "dml_batched_queries" in text
    assert "gsi_apply_lag_ms" in text


class TestReplicaAsyncApply:
    def test_reply_leg_drop_applies_exactly_once(self):
        """Chaos reuse (PR 8 failpoints): the async replica leg's dml reply
        drops AFTER the replica executed it; the applier's retry re-sends
        the same uid and the dedupe window replays the recorded response —
        the replica holds the row exactly once, and the writing session's
        own read (fenced, routed to the replica) sees it."""
        from test_chaos import WorkerHarness, bounded
        prim = WorkerHarness(
            init_sql="CREATE DATABASE w; USE w; "
                     "CREATE TABLE kv (k BIGINT PRIMARY KEY, v BIGINT); "
                     "INSERT INTO kv VALUES (1, 10)")
        rep = WorkerHarness(
            init_sql="CREATE DATABASE w; USE w; "
                     "CREATE TABLE kv (k BIGINT PRIMARY KEY, v BIGINT)")
        inst = Instance()
        s = Session(inst)
        try:
            s.execute("CREATE DATABASE w")
            s.execute("USE w")
            inst.attach_remote_table("w", "kv", *prim.addr)
            # huge weight: reads deterministically route to the replica
            inst.attach_replica("w", "kv", *rep.addr, weight=10 ** 6)
            rep_client = inst.workers[rep.addr]
            st0 = rep_client.sync_action("worker_stats", {})
            # the applier sleeps first, giving a deterministic window to arm
            # the reply-leg drop AFTER the primary's synchronous dml is done
            # — the drop then hits exactly the async replica leg
            FAIL_POINTS.arm(FP_APPLY_DELAY_MS, 500)
            rs = bounded(lambda: s.execute("INSERT INTO kv VALUES (42, 420)"))
            FAIL_POINTS.arm(FP_RPC_DROP, {"op": "dml", "leg": "reply",
                                          "n": 1})
            assert rs.affected == 1
            # read-your-writes: the fenced read waits for the replica apply
            rows = bounded(
                lambda: s.execute("SELECT v FROM kv WHERE k = 42").rows)
            assert rows == [(420,)]
            FAIL_POINTS.clear()
            assert inst.applier.drain(30.0)
            # exactly once ON THE REPLICA: direct count + dedupe-hit proof
            _c, _t, data, _v = rep_client.execute(
                "SELECT count(*) FROM kv WHERE k = 42", "w")
            assert int(next(iter(data.values()))[0]) == 1
            st1 = rep_client.sync_action("worker_stats", {})
            assert st1["dedupe_hits"] >= st0["dedupe_hits"] + 1
            assert inst.metrics.counter("replica_async_applies").value >= 1
            tm = inst.catalog.table("w", "kv")
            assert not any(r.get("stale") for r in tm.replicas)
        finally:
            FAIL_POINTS.clear()
            s.close()
            prim.close()
            rep.close()

    def test_failed_replica_leg_marks_stale(self):
        """A replica that dies before its async leg applies goes STALE —
        excluded from reads until rebuilt (the synchronous contract, late)."""
        from test_chaos import WorkerHarness, bounded
        prim = WorkerHarness(
            init_sql="CREATE DATABASE w; USE w; "
                     "CREATE TABLE kv (k BIGINT PRIMARY KEY, v BIGINT); "
                     "INSERT INTO kv VALUES (1, 10)")
        rep = WorkerHarness(
            init_sql="CREATE DATABASE w; USE w; "
                     "CREATE TABLE kv (k BIGINT PRIMARY KEY, v BIGINT)")
        inst = Instance()
        s = Session(inst)
        try:
            s.execute("CREATE DATABASE w")
            s.execute("USE w")
            inst.attach_remote_table("w", "kv", *prim.addr)
            inst.attach_replica("w", "kv", *rep.addr)
            FAIL_POINTS.arm(FP_APPLY_DELAY_MS, 200)
            rep.kill()
            rs = bounded(lambda: s.execute("INSERT INTO kv VALUES (7, 70)"))
            assert rs.affected == 1
            FAIL_POINTS.clear()
            inst.applier.drain(60.0)
            tm = inst.catalog.table("w", "kv")
            entry = [r for r in tm.replicas
                     if (r["host"], r["port"]) == rep.addr][0]
            assert entry.get("stale") is True
            # primary still serves the row (reads skip the stale replica)
            rows = bounded(
                lambda: s.execute("SELECT v FROM kv WHERE k = 7").rows)
            assert rows == [(70,)]
        finally:
            FAIL_POINTS.clear()
            s.close()
            prim.close()
            rep.close()
