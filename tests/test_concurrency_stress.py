"""Multi-session concurrency stress: DML + rollback + DDL + queries in parallel.

Round-2's races (rollback-vs-concurrent-writer stamping, conflict recheck under
the partition lock) lived exactly here; this suite hammers those interleavings
with real threads instead of single-session regression tests.  Invariant: after
the storm, table contents equal the union of what each thread KNOWS it
committed (mutations applied to the oracle only after COMMIT returns).
"""

import random
import threading

import pytest

from galaxysql_tpu.server.instance import Instance
from galaxysql_tpu.server.session import Session
from galaxysql_tpu.utils import errors


N_THREADS = 4
OPS = 120


@pytest.fixture()
def inst():
    i = Instance()
    s = Session(i)
    s.execute("CREATE DATABASE cs")
    s.execute("USE cs")
    s.execute("CREATE TABLE t (id BIGINT PRIMARY KEY, v BIGINT, w BIGINT) "
              "PARTITION BY HASH(id) PARTITIONS 4")
    s.close()
    return i


def dml_worker(inst, tid, oracle, failures):
    """Each thread owns id range [tid*10^6, ...): no cross-thread write-write
    conflicts by construction, so every commit must stick exactly."""
    rng = random.Random(tid)
    s = Session(inst, schema="cs")
    base = tid * 1_000_000
    mine = {}  # id -> v (committed oracle)
    next_id = 0
    try:
        for op in range(OPS):
            kind = rng.random()
            in_txn = rng.random() < 0.5
            will_rollback = in_txn and rng.random() < 0.3
            if in_txn:
                s.execute("BEGIN")
            staged = dict(mine)
            try:
                if kind < 0.5 or not mine:
                    rid = base + next_id
                    next_id += 1
                    v = rng.randrange(1000)
                    s.execute(f"INSERT INTO t VALUES ({rid}, {v}, {tid})")
                    staged[rid] = v
                elif kind < 0.8:
                    rid = rng.choice(list(mine))
                    v = rng.randrange(1000)
                    s.execute(f"UPDATE t SET v = {v} WHERE id = {rid}")
                    staged[rid] = v
                else:
                    rid = rng.choice(list(mine))
                    s.execute(f"DELETE FROM t WHERE id = {rid}")
                    staged.pop(rid)
            except errors.TddlError:
                # a concurrent DDL may transiently reject a statement; the txn
                # (if any) is abandoned below without applying the oracle
                if in_txn:
                    s.execute("ROLLBACK")
                continue
            if in_txn:
                if will_rollback:
                    s.execute("ROLLBACK")
                    continue  # oracle unchanged
                s.execute("COMMIT")
            mine = staged
        oracle[tid] = mine
    except Exception as e:  # noqa: BLE001 - surface in the main thread
        failures.append((tid, repr(e)))
    finally:
        s.close()


def ddl_worker(inst, stop, failures):
    s = Session(inst, schema="cs")
    i = 0
    try:
        while not stop.is_set():
            i += 1
            col = f"x{i}"
            try:
                s.execute(f"ALTER TABLE t ADD COLUMN {col} BIGINT DEFAULT 7")
                s.execute("ANALYZE TABLE t")
                s.execute(f"ALTER TABLE t DROP COLUMN {col}")
            except errors.TddlError:
                pass  # contention-era refusals are fine; crashes are not
    except Exception as e:  # noqa: BLE001
        failures.append(("ddl", repr(e)))
    finally:
        s.close()


def query_worker(inst, stop, failures):
    s = Session(inst, schema="cs")
    try:
        while not stop.is_set():
            r = s.execute("SELECT count(*), sum(v) FROM t")
            assert r.rows and r.rows[0][0] >= 0
            s.execute("SELECT id, v FROM t WHERE id >= 0 ORDER BY id LIMIT 5")
    except Exception as e:  # noqa: BLE001
        failures.append(("query", repr(e)))
    finally:
        s.close()


class TestPlanDdlRace:
    def test_scan_fields_survive_concurrent_drop_column(self, inst):
        """Planning holds no MDL, so a DROP COLUMN can land between the
        binder's read of the column list and a later fields() call on the
        scan.  The bind-time ColumnMeta snapshot must keep the plan
        self-consistent — pruning drops the unreferenced lane anyway.
        (Deterministic replay of the storm's rarest interleaving.)"""
        from galaxysql_tpu.plan import logical as L
        s = Session(inst, schema="cs")
        try:
            s.execute("ALTER TABLE t ADD COLUMN x1 BIGINT DEFAULT 7")
            tm = inst.catalog.table("cs", "t")
            metas = list(tm.columns)
            scan = L.Scan(tm, "t", [(f"t.{c.name}", c.name) for c in metas],
                          col_meta={c.name: c for c in metas})
            s.execute("ALTER TABLE t DROP COLUMN x1")
            fields = scan.fields()  # must not raise UnknownColumnError
            assert "t.x1" in [f[0] for f in fields]
        finally:
            s.close()


class TestConcurrencyStress:
    def test_dml_rollback_ddl_query_storm(self, inst):
        oracle = {}
        failures: list = []
        stop = threading.Event()
        threads = [threading.Thread(target=dml_worker,
                                    args=(inst, tid, oracle, failures))
                   for tid in range(N_THREADS)]
        aux = [threading.Thread(target=ddl_worker, args=(inst, stop, failures)),
               threading.Thread(target=query_worker, args=(inst, stop, failures))]
        for t in threads + aux:
            t.start()
        for t in threads:
            t.join(timeout=300)
        stop.set()
        for t in aux:
            t.join(timeout=60)
        assert not failures, failures
        assert len(oracle) == N_THREADS  # every DML thread finished its ops

        s = Session(inst, schema="cs")
        try:
            rows = dict((r[0], r[1]) for r in
                        s.execute("SELECT id, v FROM t").rows)
        finally:
            s.close()
        want = {}
        for mine in oracle.values():
            want.update(mine)
        # exact content equality: committed == visible, rolled back == gone
        assert rows == want, (
            f"{len(rows)} visible vs {len(want)} committed; "
            f"missing={list(set(want) - set(rows))[:5]} "
            f"extra={list(set(rows) - set(want))[:5]}")


class TestCclManagerStress:
    """Concurrency-stress for the CCL admission plane (utils/ccl.py):
    rule add/drop racing in-flight admit(), bounded wait-queue overflow
    under 100 threads, and the double-release() guard on the
    Session._run_query exception paths."""

    def _mk(self):
        from galaxysql_tpu.utils.ccl import CclManager
        return CclManager()

    def test_add_drop_races_inflight_admit(self):
        """Rules churn while 100 threads admit/release: no exception other
        than CclRejectError, and after the storm every slot is free."""
        from galaxysql_tpu.utils.ccl import CclRule
        import types
        ccl = self._mk()
        sess = types.SimpleNamespace(user="root", vars={})
        stop = threading.Event()
        failures: list = []

        def churn():
            i = 0
            while not stop.is_set():
                ccl.add_rule(CclRule(f"r{i % 3}", max_concurrency=4,
                                     keyword="stress", wait_queue_size=8,
                                     wait_timeout_ms=50))
                ccl.drop_rule(f"r{(i + 1) % 3}")
                i += 1

        def admit_loop():
            for _ in range(60):
                try:
                    h = ccl.admit(sess, "select stress from t")
                    h.release()
                except errors.CclRejectError:
                    pass
                except Exception as exc:  # noqa: BLE001 — asserted below
                    failures.append(exc)

        churner = threading.Thread(target=churn, daemon=True)
        churner.start()
        threads = [threading.Thread(target=admit_loop, daemon=True)
                   for _ in range(100)]
        for t in threads:
            t.start()
        for t in threads:
            t.join(timeout=60)
            assert not t.is_alive(), "admit() hung under rule churn"
        stop.set()
        churner.join(timeout=10)
        assert not failures, failures[:3]
        for st in ccl.rules():
            assert st.running == 0 and st.waiting == 0

    def test_wait_queue_overflow_under_100_threads(self):
        """One slot, queue of 5, 100 threads: admissions + queue never
        exceed bounds, overflow rejects typed, nobody hangs."""
        from galaxysql_tpu.utils.ccl import CclRule
        import types
        ccl = self._mk()
        ccl.add_rule(CclRule("one", max_concurrency=1, keyword="hot",
                             wait_queue_size=5, wait_timeout_ms=100))
        sess = types.SimpleNamespace(user="root", vars={})
        admitted: list = []
        rejected: list = []
        failures: list = []
        lock = threading.Lock()

        def worker():
            try:
                h = ccl.admit(sess, "select hot from t")
                with lock:
                    admitted.append(1)
                h.release()
            except errors.CclRejectError:
                with lock:
                    rejected.append(1)
            except Exception as exc:  # noqa: BLE001 — asserted below
                failures.append(exc)

        # the slot is HELD for the whole storm: every thread must either
        # wait (bounded queue of 5, 100ms timeout) or reject typed — no
        # hang, no unbounded queue, no wrong exception class
        st = ccl.rules()[0]
        st.sem.acquire()
        try:
            threads = [threading.Thread(target=worker, daemon=True)
                       for _ in range(100)]
            for t in threads:
                t.start()
            for t in threads:
                t.join(timeout=60)
                assert not t.is_alive(), "admit() hung on a full wait queue"
        finally:
            st.sem.release()
        assert not failures, failures[:3]
        assert not admitted  # the slot never freed during the storm
        assert len(rejected) == 100  # all typed (queue-full or timeout)
        assert st.running == 0 and st.waiting == 0
        assert st.total_rejected == 100
        # the rule is healthy after the storm: the freed slot admits again
        h = ccl.admit(sess, "select hot from t")
        h.release()

    def test_double_release_guard(self):
        """release() is idempotent, and the Session._run_query exception
        path releases exactly once (a failing matched query never leaks or
        double-frees its slot)."""
        from galaxysql_tpu.utils.ccl import GLOBAL_CCL, CclRule
        import types
        ccl = self._mk()
        ccl.add_rule(CclRule("g", max_concurrency=1, keyword="t",
                             wait_queue_size=0))
        sess = types.SimpleNamespace(user="root", vars={})
        h = ccl.admit(sess, "select * from t")
        h.release()
        h.release()  # second release must be a no-op
        st = ccl.rules()[0]
        assert st.running == 0
        # the slot is actually free (a leaked/double-freed semaphore would
        # break the next admit or blow BoundedSemaphore)
        h2 = ccl.admit(sess, "select * from t")
        h2.release()
        # end-to-end: a matched query FAILING mid-execution releases its
        # slot on the exception ramp exactly once
        inst = Instance()
        s = Session(inst)
        s.execute("CREATE DATABASE cclx")
        s.execute("USE cclx")
        s.execute("CREATE TABLE t (a BIGINT)")
        GLOBAL_CCL.add_rule(CclRule("x", max_concurrency=1, keyword="t",
                                    wait_queue_size=0))
        try:
            for _ in range(3):
                with pytest.raises(errors.TddlError):
                    s.execute("SELECT nope FROM t")
            st = GLOBAL_CCL.rules()[0]
            assert st.running == 0 and st.waiting == 0
            # the slot survives repeated failures: a healthy query admits
            assert s.execute("SELECT count(*) FROM t").rows == [(0,)]
        finally:
            GLOBAL_CCL.clear()
            s.close()
