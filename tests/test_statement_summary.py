"""Workload insight: statement-digest summary store, time-windowed telemetry,
the instance-event journal, slow-log digest linkage, and the plan-regression
sentinel.

The `summary`-marked tests are the fast smoke target (`make summary-smoke`).
"""

import json
import threading

import numpy as np
import pytest

from galaxysql_tpu.exec import operators as ops
from galaxysql_tpu.meta import statement_summary as ssm
from galaxysql_tpu.server.instance import Instance
from galaxysql_tpu.server.session import Session
from galaxysql_tpu.utils.events import EVENTS
from galaxysql_tpu.utils.tracing import SLOW_LOG


def _mk(schema="ws"):
    inst = Instance()
    s = Session(inst)
    s.execute(f"CREATE DATABASE {schema}")
    s.execute(f"USE {schema}")
    return inst, s


def _summary_rows(s, contains=None):
    rows = s.execute("SHOW STATEMENT SUMMARY").rows
    if contains is None:
        return rows
    return [r for r in rows if contains in r[-1]]


# -- digest aggregation --------------------------------------------------------


@pytest.mark.summary
class TestDigestAggregation:
    def test_digest_stable_across_literals(self):
        inst, s = _mk()
        s.execute("CREATE TABLE t (a BIGINT PRIMARY KEY, b BIGINT)")
        for i in range(10):
            s.execute(f"INSERT INTO t VALUES ({i}, {i * 10})")
        for i in range(7):
            s.execute(f"SELECT b FROM t WHERE a = {i}")
        rows = _summary_rows(s, "SELECT b FROM t")
        digests = {r[0] for r in rows}
        assert len(digests) == 1, "literal values must share one digest"
        assert sum(r[4] for r in rows) == 7
        assert all(r[5] == 0 for r in rows)  # no errors
        # the point fast path records under its own plan fingerprint
        assert "point" in {r[2] for r in rows}
        s.close()

    def test_error_count_and_unknown_plan(self):
        inst, s = _mk("wse")
        s.execute("CREATE TABLE t (a BIGINT)")
        s.execute("INSERT INTO t VALUES (1)")
        for _ in range(3):
            with pytest.raises(Exception):
                s.execute("SELECT nope FROM t WHERE a = 1")
        rows = _summary_rows(s, "SELECT nope")
        assert rows and sum(r[5] for r in rows) == 3
        assert sum(r[4] for r in rows) == 3
        s.close()

    def test_history_buckets_and_information_schema(self):
        inst, s = _mk("wsh")
        s.execute("CREATE TABLE t (a BIGINT, b BIGINT)")
        inst.store("wsh", "t").insert_pylists(
            {"a": list(range(500)), "b": list(range(500))},
            inst.tso.next_timestamp())
        for _ in range(4):
            s.execute("SELECT count(*) FROM t WHERE a < 250")
        hist = s.execute("SHOW STATEMENT SUMMARY HISTORY")
        hrows = [r for r in hist.rows if "count" in r[-1]]
        assert hrows
        window = inst.config.get("STMT_SUMMARY_WINDOW_S")
        assert all(r[3] % window == 0 for r in hrows)  # aligned bucket starts
        assert sum(r[4] for r in hrows) == 4
        # SQL-queryable twins (exercises the whole engine over the views)
        r = s.execute("SELECT digest, exec_count FROM "
                      "information_schema.statement_summary "
                      "WHERE exec_count > 0")
        assert r.rows
        r = s.execute("SELECT digest, exec_count FROM "
                      "information_schema.statement_summary_history")
        assert r.rows
        r = s.execute("SELECT kind FROM information_schema.events")
        assert any(k == ("ddl",) for k in r.rows)
        s.close()

    def test_rows_and_counters_aggregate(self):
        inst, s = _mk("wsr")
        s.execute("CREATE TABLE t (a BIGINT)")
        inst.store("wsr", "t").insert_pylists(
            {"a": list(range(100))}, inst.tso.next_timestamp())
        for _ in range(3):
            s.execute("SELECT a FROM t WHERE a < 10")
        rows = _summary_rows(s, "SELECT a FROM t")
        assert sum(r[9] for r in rows) == 30  # rows_returned aggregated
        assert all(r[10] >= 0 for r in rows)  # rows_examined estimate
        s.close()


# -- slow log linkage ----------------------------------------------------------


@pytest.mark.summary
class TestSlowLogDigest:
    def test_slow_entry_carries_summary_digest(self):
        inst, s = _mk("wsl")
        s.execute("CREATE TABLE t (a BIGINT)")
        s.execute("INSERT INTO t VALUES (1)")
        SLOW_LOG.clear()
        s.vars["SLOW_SQL_MS"] = 0  # log every query
        s.execute("SELECT a FROM t WHERE a = 1")
        slow = s.execute("SHOW SLOW")
        assert slow.names[-1] == "Digest"
        srow = [r for r in slow.rows if "SELECT a FROM t" in r[2]][-1]
        digest = srow[-1]
        assert digest
        # the digest jumps straight to the summary row
        assert any(r[0] == digest for r in _summary_rows(s))
        s.close()


# -- event journal -------------------------------------------------------------


@pytest.mark.summary
class TestEventJournal:
    def test_ddl_events_published(self):
        EVENTS.clear()
        inst, s = _mk("wev")
        s.execute("CREATE TABLE t (a BIGINT)")
        s.execute("DROP TABLE t")
        rs = s.execute("SHOW EVENTS")
        kinds = [r[2] for r in rs.rows]
        assert "ddl" in kinds
        details = [r[5] for r in rs.rows if r[2] == "ddl"]
        assert any("CREATE TABLE wev.t" in d for d in details)
        assert any("DROP TABLE wev.t" in d for d in details)
        # newest first, seq monotonic
        seqs = [r[0] for r in rs.rows]
        assert seqs == sorted(seqs, reverse=True)
        # attrs are valid JSON
        for r in rs.rows:
            json.loads(r[6])
        s.close()

    def test_event_counters_in_prometheus(self):
        EVENTS.clear()
        inst, s = _mk("wpr")
        s.execute("CREATE TABLE t (a BIGINT)")
        from galaxysql_tpu.server.web import WebConsole
        text = WebConsole(inst).metrics_text()
        assert 'galaxysql_events_total{kind="ddl"}' in text
        s.close()


# -- Prometheus top-K + /statements -------------------------------------------


@pytest.mark.summary
class TestStatementSurfaces:
    def test_prom_topk_bounded_cardinality(self):
        inst, s = _mk("wpk")
        s.execute("CREATE TABLE t (a BIGINT)")
        inst.store("wpk", "t").insert_pylists(
            {"a": list(range(50))}, inst.tso.next_timestamp())
        for i in range(8):  # 8 distinct digests (structure, not literals)
            cols = ", ".join(["a"] * (i + 1))
            for _ in range(2):
                s.execute(f"SELECT {cols} FROM t WHERE a < 10")
        s.execute("SET GLOBAL STMT_SUMMARY_PROM_TOPK = 3")
        from galaxysql_tpu.server.web import WebConsole
        text = WebConsole(inst).metrics_text()
        labeled = {ln.split('digest="')[1].split('"')[0]
                   for ln in text.splitlines()
                   if "stmt_latency_ms{" in ln}
        assert 0 < len(labeled) <= 3  # top-K only: bounded label cardinality
        s.execute("SET GLOBAL STMT_SUMMARY_PROM_TOPK = 0")  # labels OFF
        text = WebConsole(inst).metrics_text()
        assert "stmt_latency_ms{" not in text
        s.close()

    def test_statements_json_resource(self):
        inst, s = _mk("wjs")
        s.execute("CREATE TABLE t (a BIGINT)")
        s.execute("INSERT INTO t VALUES (1)")
        s.execute("SELECT a FROM t WHERE a = 1")
        from galaxysql_tpu.server.web import WebConsole
        body = WebConsole(inst).resource("/statements")
        assert body and body["statements"]
        json.dumps(body, default=str)  # serializable
        top = body["top"]
        assert top and {"digest", "execs", "p50_ms"} <= set(top[0])
        assert any(st["digest"] == top[0]["digest"]
                   for st in body["statements"])
        s.close()


# -- hatches + equivalence -----------------------------------------------------


@pytest.mark.summary
class TestHatches:
    def test_param_off_stops_recording_and_results_identical(self):
        inst, s = _mk("wha")
        s.execute("CREATE TABLE t (a BIGINT, b BIGINT)")
        inst.store("wha", "t").insert_pylists(
            {"a": list(range(300)), "b": list(range(300))},
            inst.tso.next_timestamp())
        q = "SELECT a, b * 2 FROM t WHERE a < 100 ORDER BY a"
        on = s.execute(q)
        n0 = sum(r[4] for r in _summary_rows(s))
        s.execute("SET ENABLE_STATEMENT_SUMMARY = 0")
        off = s.execute(q)
        assert off.rows == on.rows  # bit-identical with the layer off
        assert sum(r[4] for r in _summary_rows(s)) == n0  # nothing recorded
        s.execute("SET ENABLE_STATEMENT_SUMMARY = 1")
        s.execute(q)
        assert sum(r[4] for r in _summary_rows(s)) == n0 + 1
        s.close()

    def test_env_kill_switch_gates_store(self, monkeypatch):
        inst, s = _mk("whe")
        s.execute("CREATE TABLE t (a BIGINT)")
        monkeypatch.setattr(ssm, "ENABLED", False)
        s.execute("SELECT a FROM t WHERE a = 1")
        assert not _summary_rows(s, "SELECT a FROM t")
        monkeypatch.setattr(ssm, "ENABLED", True)
        s.execute("SELECT a FROM t WHERE a = 1")
        assert _summary_rows(s, "SELECT a FROM t")
        s.close()


# -- concurrency: race-free aggregation ---------------------------------------


@pytest.mark.summary
class TestConcurrentAggregation:
    def test_multi_session_counts_exact_and_results_identical(self):
        inst, s = _mk("wcc")
        s.execute("CREATE TABLE t (a BIGINT PRIMARY KEY, b BIGINT)")
        inst.store("wcc", "t").insert_pylists(
            {"a": list(range(64)), "b": [i * 3 for i in range(64)]},
            inst.tso.next_timestamp())
        expect = s.execute("SELECT b FROM t WHERE a = 7").rows
        N_THREADS, N_QUERIES = 8, 25
        errs = []

        def worker(tid):
            sess = Session(inst, "wcc")
            try:
                for i in range(N_QUERIES):
                    key = (tid * N_QUERIES + i) % 64
                    r = sess.execute(f"SELECT b FROM t WHERE a = {key}")
                    if key == 7 and r.rows != expect:
                        errs.append((tid, i, r.rows))
            except Exception as e:  # pragma: no cover - surfaced below
                errs.append(e)
            finally:
                sess.close()

        before = sum(r[4] for r in _summary_rows(s, "SELECT b FROM t"))
        threads = [threading.Thread(target=worker, args=(t,))
                   for t in range(N_THREADS)]
        for t in threads:
            t.start()
        for t in threads:
            t.join()
        assert not errs
        rows = _summary_rows(s, "SELECT b FROM t")
        assert len({r[0] for r in rows}) == 1
        total = sum(r[4] for r in rows) - before
        assert total == N_THREADS * N_QUERIES  # no lost updates
        s.close()


# -- hot-path guard: summary on costs zero extra dispatches/syncs -------------


@pytest.mark.summary
class TestHotPathGuard:
    def test_dispatch_count_unchanged_with_summary_on(self):
        """The PR-1/PR-2 dispatch invariant survives the summary layer: the
        same query pays the same device dispatches with the layer on vs
        ENABLE_STATEMENT_SUMMARY=0 (zero extra device work, zero syncs —
        summary updates are host-side adds)."""
        inst, s = _mk("whp")
        s.execute("CREATE TABLE t (a BIGINT, b BIGINT)")
        inst.store("whp", "t").insert_pylists(
            {"a": list(range(3000)), "b": list(range(3000))},
            inst.tso.next_timestamp())
        q = "SELECT a, b * 3 FROM t WHERE a < 1500"
        s.execute(q)  # warmup: compile
        from galaxysql_tpu.exec.device_cache import TRANSFER_STATS
        ops.reset_dispatch_stats()
        x0 = TRANSFER_STATS["transfers"]
        on = s.execute(q)  # summary ON (default)
        d_on = ops.DISPATCH_STATS["dispatches"]
        x_on = TRANSFER_STATS["transfers"] - x0
        s.execute("SET ENABLE_STATEMENT_SUMMARY = 0")
        ops.reset_dispatch_stats()
        x0 = TRANSFER_STATS["transfers"]
        off = s.execute(q)
        assert ops.DISPATCH_STATS["dispatches"] == d_on
        assert TRANSFER_STATS["transfers"] - x0 == x_on
        assert on.rows == off.rows
        s.close()


# -- the plan-regression sentinel ----------------------------------------------


@pytest.mark.summary
class TestPlanRegressionSentinel:
    def test_stats_flip_regression_flagged_end_to_end(self):
        """Acceptance scenario: a stats change flips the join order for a
        known digest AND genuinely degrades latency (join-multiplicity
        explosion).  The sentinel must flag it: typed event in SHOW EVENTS,
        `plan_regressions` counter bumped, SPM PlanRecord annotated, summary
        row marked regressed — with a NEW plan fingerprint distinct from the
        baseline's."""
        EVENTS.clear()
        inst, s = _mk("wrg")
        s.execute("CREATE TABLE big (id BIGINT PRIMARY KEY, k BIGINT, "
                  "v BIGINT) PARTITION BY HASH(id) PARTITIONS 4")
        s.execute("CREATE TABLE small (sid BIGINT PRIMARY KEY, k BIGINT, "
                  "w BIGINT) PARTITION BY HASH(sid) PARTITIONS 4")
        ts = inst.tso.next_timestamp
        n = 5000
        inst.store("wrg", "big").insert_arrays(
            {"id": np.arange(n), "k": np.arange(n) % 100,
             "v": np.arange(n)}, ts())
        inst.store("wrg", "small").insert_arrays(
            {"sid": np.arange(100), "k": np.arange(100),
             "w": np.arange(100)}, ts())
        s.execute("ANALYZE TABLE big, small")
        # FRAGMENT_CACHE(OFF) keeps each run an honest execution (cached
        # replay would hide the degradation); the hint is part of the text,
        # so both phases share one digest, and it is not a plan-pinning hint
        # — the SPM baseline still captures
        q = ("/*+TDDL: FRAGMENT_CACHE(OFF)*/ SELECT count(*), "
             "sum(big.v + small.w) FROM big, small WHERE big.k = small.k")
        for _ in range(6):
            s.execute(q)
        base_rows = _summary_rows(s, "sum(big.v")
        base_fps = {r[2] for r in base_rows}
        assert len(base_fps) == 1
        # DDL/DAL invalidates the pinned baseline, then the stats change
        # (hot duplicate keys: every probe row now matches ~500 build rows)
        # flips the greedy join order at replan
        bid = s.execute("SHOW BASELINE").rows[0][0]
        s.execute(f"BASELINE DELETE {bid}")
        m = 50000
        inst.store("wrg", "small").insert_arrays(
            {"sid": np.arange(100, 100 + m), "k": np.arange(m) % 100,
             "w": np.zeros(m, np.int64)}, ts())
        inst.catalog.table("wrg", "small").bump_version()
        inst.catalog.version += 1
        s.execute("ANALYZE TABLE big, small")
        for _ in range(6):
            s.execute(q)
        rows = _summary_rows(s, "sum(big.v")
        fps = {r[2] for r in rows}
        assert len(fps) == 2, f"expected a new plan fingerprint, got {fps}"
        new_fp = (fps - base_fps).pop()
        flagged = [r for r in rows if r[2] == new_fp]
        assert flagged and flagged[0][18] == 1  # Regressed column
        # typed event
        evs = [r for r in s.execute("SHOW EVENTS").rows
               if r[2] == "plan_regression"]
        assert evs
        attrs = json.loads(evs[0][6])
        assert attrs["plan"] == new_fp and attrs["reason"] == "new_plan"
        # counter
        assert inst.metrics.counter("plan_regressions").value == 1
        # SPM record annotated
        brow = s.execute("SHOW BASELINE").rows[0]
        assert brow[8] == 1 and new_fp in brow[9]
        # information_schema twin carries the flag too
        r = s.execute("SELECT plan_fingerprint FROM "
                      "information_schema.statement_summary "
                      "WHERE regressed = 1")
        assert (new_fp,) in r.rows
        s.close()

    def test_recovered_window_rearms_and_default_path_guarded(self):
        """A window back under the threshold clears the flag (no flapping
        spam: one event per regression episode), and the uniform default
        path keeps its dispatch count with the sentinel armed."""
        inst, s = _mk("wrr")
        s.execute("CREATE TABLE t (a BIGINT, b BIGINT)")
        inst.store("wrr", "t").insert_pylists(
            {"a": list(range(2000)), "b": list(range(2000))},
            inst.tso.next_timestamp())
        ss = inst.stmt_summary
        # drive the store directly with synthetic latencies and a PINNED
        # clock (one bucket, fully deterministic sentinel stream)
        t = 1000.0

        def rec(fp, v):
            ss.record("wrr", "Q1", "Q1", fp, "", "AP", "local", v, 1, now=t)

        for v in (10.0,) * 5:  # baseline forms at median 10ms
            rec("p1", v)
        for v in (40.0,) * 5:  # regressed window
            rec("p2", v)
        assert inst.metrics.counter("plan_regressions").value == 1
        for v in (40.0,) * 3:  # still regressed: same episode, no re-fire
            rec("p2", v)
        assert inst.metrics.counter("plan_regressions").value == 1
        # flood the window with fast runs until the median recovers
        for v in (9.0,) * 20:
            rec("p2", v)
        agg = ss._entries[("wrr", "Q1")].plans["p2"]
        assert not agg.flagged  # re-armed
        for v in (50.0,) * 40:  # regresses again -> second event
            rec("p2", v)
        assert inst.metrics.counter("plan_regressions").value == 2
        # default-path dispatch guard with the sentinel armed
        q = "SELECT a, b + 1 FROM t WHERE a < 1000"
        s.execute(q)  # warmup
        ops.reset_dispatch_stats()
        s.execute(q)
        base = ops.DISPATCH_STATS["dispatches"]
        ops.reset_dispatch_stats()
        s.execute(q)
        assert ops.DISPATCH_STATS["dispatches"] == base
        s.close()


# -- parser --------------------------------------------------------------------


@pytest.mark.summary
class TestShowParsing:
    def test_show_statement_summary_forms(self):
        from galaxysql_tpu.sql.parser import parse
        st = parse("SHOW STATEMENT SUMMARY")
        assert st.kind == "statement_summary" and st.target is None
        st = parse("SHOW STATEMENT SUMMARY HISTORY")
        assert st.kind == "statement_summary" and st.target == "history"
        st = parse("SHOW EVENTS")
        assert st.kind == "events"
