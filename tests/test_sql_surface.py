"""CTEs, views, ROLLUP/CUBE/GROUPING SETS, multi-distinct, union ORDER BY."""

import numpy as np
import pandas as pd
import pytest

from galaxysql_tpu.server.instance import Instance
from galaxysql_tpu.server.session import Session
from galaxysql_tpu.utils import errors


@pytest.fixture(scope="module")
def env():
    inst = Instance()
    s = Session(inst)
    s.execute("CREATE DATABASE d; USE d")
    s.execute("CREATE TABLE t (k VARCHAR(4), g VARCHAR(4), v BIGINT)")
    rng = np.random.default_rng(7)
    inst.store("d", "t").insert_arrays(
        {"k": np.array(["a", "b", "c"])[rng.integers(0, 3, 2000)],
         "g": np.array(["p", "q"])[rng.integers(0, 2, 2000)],
         "v": rng.integers(0, 100, 2000)}, inst.tso.next_timestamp())
    s.execute("ANALYZE TABLE t")
    df = pd.DataFrame(s.execute("SELECT k, g, v FROM t").rows,
                      columns=["k", "g", "v"])
    yield inst, s, df
    s.close()


class TestCte:
    def test_basic(self, env):
        _i, s, df = env
        r = s.execute("WITH big AS (SELECT k, v FROM t WHERE v > 50) "
                      "SELECT k, count(*) FROM big GROUP BY k ORDER BY k").rows
        assert [c for _, c in r] == list(df[df.v > 50].groupby("k").size())

    def test_chained_and_double_reference(self, env):
        _i, s, df = env
        r = s.execute("WITH a AS (SELECT k, v FROM t WHERE v > 90), "
                      "b AS (SELECT k FROM a WHERE v > 95) "
                      "SELECT count(*) FROM b").rows
        assert r[0][0] == int((df.v > 95).sum())
        r = s.execute("WITH a AS (SELECT k, v FROM t WHERE v > 90) "
                      "SELECT count(*) FROM a x, a y "
                      "WHERE x.k = y.k AND x.v < y.v").rows
        m = df[df.v > 90]
        j = m.merge(m, on="k")
        assert r[0][0] == int((j.v_x < j.v_y).sum())

    def test_column_list_and_union_scope(self, env):
        _i, s, df = env
        r = s.execute("WITH c (kk) AS (SELECT k FROM t WHERE v < 5) "
                      "SELECT kk FROM c UNION SELECT kk FROM c ORDER BY kk").rows
        assert r == sorted({(k,) for k in df[df.v < 5].k})

    def test_recursion_rejected(self, env):
        _i, s, _df = env
        with pytest.raises(errors.TddlError):
            s.execute("WITH RECURSIVE r AS (SELECT 1) SELECT * FROM r")


class TestGroupingSets:
    def test_with_rollup(self, env):
        _i, s, df = env
        r = s.execute(
            "SELECT k, g, sum(v) FROM t GROUP BY k, g WITH ROLLUP").rows
        exp = [(str(k), str(g2), int(sub.v.sum()))
               for (k, g2), sub in df.groupby(["k", "g"])]
        exp += [(str(k), "None", int(sub.v.sum())) for k, sub in df.groupby("k")]
        exp.append(("None", "None", int(df.v.sum())))
        assert sorted((str(a), str(b), int(c)) for a, b, c in r) == sorted(exp)

    def test_rollup_function_form(self, env):
        _i, s, df = env
        r = s.execute("SELECT k, sum(v) FROM t GROUP BY ROLLUP(k)").rows
        assert len(r) == df.k.nunique() + 1

    def test_cube(self, env):
        _i, s, df = env
        r = s.execute("SELECT k, g, count(*) FROM t GROUP BY CUBE(k, g)").rows
        assert len(r) == (len(df.groupby(["k", "g"])) + df.k.nunique()
                          + df.g.nunique() + 1)

    def test_grouping_sets(self, env):
        _i, s, df = env
        r = s.execute("SELECT k, g, count(*) FROM t "
                      "GROUP BY GROUPING SETS ((k), (g), ())").rows
        assert len(r) == df.k.nunique() + df.g.nunique() + 1

    def test_rollup_with_having_and_order(self, env):
        _i, s, df = env
        r = s.execute("SELECT k, sum(v) AS s FROM t GROUP BY k WITH ROLLUP "
                      "HAVING sum(v) > 0 ORDER BY k").rows
        assert len(r) == df.k.nunique() + 1
        assert r[0][0] is None  # NULLs sort first ascending


class TestViews:
    def test_create_query_replace_drop(self, env):
        _i, s, df = env
        s.execute("CREATE VIEW hi AS SELECT k, v FROM t WHERE v >= 50")
        r = s.execute("SELECT k, count(*) FROM hi GROUP BY k ORDER BY k").rows
        assert [c for _, c in r] == list(df[df.v >= 50].groupby("k").size())
        s.execute("CREATE OR REPLACE VIEW hi (kk, vv) AS "
                  "SELECT k, v FROM t WHERE v < 10")
        r = s.execute("SELECT count(*) FROM hi WHERE vv < 5").rows
        assert r[0][0] == int((df.v < 5).sum())
        # views reflect base-table changes (re-expanded per reference);
        # sentinel v=-7 cannot collide with generated data (domain 0..99)
        s.execute("INSERT INTO t VALUES ('a', 'p', -7)")
        assert s.execute("SELECT count(*) FROM hi WHERE vv < 5").rows[0][0] == \
            int((df.v < 5).sum()) + 1
        s.execute("DELETE FROM t WHERE v = -7")
        s.execute("DROP VIEW hi")
        with pytest.raises(errors.TddlError):
            s.execute("SELECT * FROM hi")

    def test_view_persists_across_boot(self, tmp_path):
        d = str(tmp_path)
        inst = Instance(data_dir=d)
        s = Session(inst)
        s.execute("CREATE DATABASE vd; USE vd")
        s.execute("CREATE TABLE b (x BIGINT)")
        inst.store("vd", "b").insert_arrays({"x": np.arange(10)},
                                            inst.tso.next_timestamp())
        s.execute("CREATE VIEW evens AS SELECT x FROM b WHERE x % 2 = 0")
        inst.save()
        s.close()
        inst2 = Instance(data_dir=d)
        s2 = Session(inst2, "vd")
        assert s2.execute("SELECT count(*) FROM evens").rows == [(5,)]
        s2.close()


class TestUnionTail:
    def test_order_by_binds_to_union(self, env):
        _i, s, _df = env
        r = s.execute("SELECT k, v FROM t WHERE v < 3 UNION ALL "
                      "SELECT k, v FROM t WHERE v > 97 "
                      "ORDER BY v DESC LIMIT 5").rows
        assert len(r) == 5
        assert all(r[i][1] >= r[i + 1][1] for i in range(len(r) - 1))

    def test_order_by_ordinal(self, env):
        _i, s, df = env
        r = s.execute("SELECT k FROM t WHERE v < 3 UNION SELECT k FROM t "
                      "ORDER BY 1").rows
        assert r == sorted({(k,) for k in df.k})


class TestReviewRegressions:
    def test_union_limit_offset(self, env):
        _i, s, _df = env
        base = s.execute("SELECT v FROM t WHERE v < 3 UNION ALL "
                         "SELECT v FROM t WHERE v > 97 ORDER BY v").rows
        r = s.execute("SELECT v FROM t WHERE v < 3 UNION ALL "
                      "SELECT v FROM t WHERE v > 97 ORDER BY v "
                      "LIMIT 5 OFFSET 10").rows
        assert r == base[10:15]

    def test_view_cycle_detected(self, env):
        _i, s, _df = env
        s.execute("CREATE VIEW cyc AS SELECT v FROM t WHERE v < 5")
        s.execute("CREATE OR REPLACE VIEW cyc AS SELECT v FROM cyc")
        with pytest.raises(errors.TddlError, match="references itself"):
            s.execute("SELECT * FROM cyc")
        s.execute("DROP VIEW cyc")

    def test_view_binds_in_own_schema(self, env):
        inst, s, df = env
        s2 = Session(inst)
        s2.execute("CREATE DATABASE other; USE other")
        # unqualified 't' inside the view must resolve to d.t, not other.*
        s2.execute("CREATE VIEW d.dview AS SELECT v FROM t WHERE v < 5")
        r = s2.execute("SELECT count(*) FROM d.dview").rows
        assert r[0][0] == int((df.v < 5).sum())
        s2.execute("DROP VIEW d.dview")
        s2.close()

    def test_view_column_list_arity_checked(self, env):
        _i, s, _df = env
        with pytest.raises(errors.TddlError, match="column list"):
            s.execute("CREATE VIEW bad (a, b) AS SELECT v FROM t")

    def test_union_in_in_subquery(self, env):
        _i, s, df = env
        r = s.execute("SELECT count(*) FROM t WHERE k IN "
                      "(SELECT k FROM t WHERE v < 2 UNION "
                      "SELECT k FROM t WHERE v > 98)").rows
        keys = set(df[df.v < 2].k) | set(df[df.v > 98].k)
        assert r[0][0] == int(df.k.isin(keys).sum())


class TestMultiDistinct:
    def test_mixed_distinct_and_plain(self, env):
        _i, s, df = env
        r = s.execute("SELECT k, count(DISTINCT v), sum(v), count(*), min(v), "
                      "sum(DISTINCT v) FROM t GROUP BY k ORDER BY k").rows
        want = [(k, gr.v.nunique(), int(gr.v.sum()), len(gr), int(gr.v.min()),
                 int(gr.v.drop_duplicates().sum()))
                for k, gr in df.groupby("k", sort=True)]
        assert [tuple(x) for x in r] == want

    def test_global_mixed(self, env):
        _i, s, df = env
        r = s.execute("SELECT count(DISTINCT v), sum(v) FROM t").rows
        assert r == [(df.v.nunique(), int(df.v.sum()))]
