"""Overload harness: admission control, memory-pressure governance, and
retry-budgeted backpressure under sustained load (make overload-smoke).

The acceptance shape: under an AP flood with injected worker slow-drain and
memory pressure, concurrent TP traffic keeps bounded p99 and nonzero
goodput; every refusal is a typed ServerOverloadError / CclRejectError /
MemoryLimitExceeded (no hangs, no process OOM); admitted queries return
bit-identical results to an idle run; and total rpc_retries stays within
the configured budget (no metastable retry amplification)."""

import threading
import time

import numpy as np
import pytest

from galaxysql_tpu.server import admission as adm_mod
from galaxysql_tpu.server.instance import Instance
from galaxysql_tpu.server.session import Session
from galaxysql_tpu.utils import errors
from galaxysql_tpu.utils.events import EVENTS
from galaxysql_tpu.utils.failpoint import (FAIL_POINTS, FP_MEM_PRESSURE,
                                           FP_WORKER_SLOW_DRAIN)

pytestmark = pytest.mark.overload

RUN_BOUND_S = 90.0


def bounded(fn, timeout_s: float = RUN_BOUND_S):
    """Zero-hang enforcement: run on a daemon thread, fail on timeout."""
    result: dict = {}

    def run():
        try:
            result["v"] = fn()
        except BaseException as exc:  # noqa: BLE001 — re-raised below
            result["e"] = exc

    t = threading.Thread(target=run, daemon=True)
    t.start()
    t.join(timeout_s)
    if t.is_alive():
        raise AssertionError(f"hang: call exceeded {timeout_s}s bound")
    if "e" in result:
        raise result["e"]
    return result.get("v")


@pytest.fixture(autouse=True)
def _clean_failpoints():
    FAIL_POINTS.clear()
    yield
    FAIL_POINTS.clear()


def _mk(schema="ov", rows=0):
    inst = Instance()
    s = Session(inst)
    s.execute(f"CREATE DATABASE {schema}")
    s.execute(f"USE {schema}")
    if rows:
        s.execute("CREATE TABLE t (a BIGINT PRIMARY KEY, b BIGINT, "
                  "c BIGINT) PARTITION BY HASH(a) PARTITIONS 4")
        inst.store(schema, "t").insert_arrays(
            {"a": np.arange(rows), "b": np.arange(rows) % 97,
             "c": np.arange(rows) * 3}, inst.tso.next_timestamp())
        s.execute("ANALYZE TABLE t")  # real stats drive the AP classifier
    return inst, s


# -- classification -----------------------------------------------------------


class TestClassification:
    def test_heuristic_and_digest_truth(self):
        inst, s = _mk(rows=100)
        ctl = inst.admission
        cls, _ms, _d = ctl.classify(s, "SELECT b FROM t WHERE a = 5")
        assert cls == "TP"
        cls, _ms, _d = ctl.classify(
            s, "SELECT b, sum(c) FROM t GROUP BY b")
        assert cls == "AP"
        # after execution the digest cost map records observed truth: the
        # engine's workload classifier (scanned rows), not the keyword guess
        s.execute("SELECT count(*) FROM t")
        cls2, ms2, dig = ctl.classify(s, "SELECT count(*) FROM t")
        assert dig and dig in ctl._digest_cost
        assert ms2 is not None and ms2 > 0
        s.close()

    def test_information_schema_stays_tp(self):
        inst, s = _mk("ovis")
        cls, _ms, _d = inst.admission.classify(
            s, "SELECT * FROM information_schema.metrics")
        assert cls == "TP"  # observability must stay reachable under flood
        s.close()


# -- limits, queuing, shedding -------------------------------------------------


class TestAdmissionLimits:
    def test_queue_full_sheds_typed_with_event(self):
        inst, s = _mk("ovq", rows=200)
        EVENTS.clear()
        inst.config.set_instance("ADMISSION_AP_LIMIT", 1)
        inst.config.set_instance("ADMISSION_QUEUE_SIZE", 0)
        inst.admission._limit.clear()  # re-read the lowered limit
        inst.admission._tokens["AP"].append(None)  # hold the only AP slot
        try:
            with pytest.raises(errors.ServerOverloadError) as ei:
                s.execute("SELECT b, sum(c) FROM t GROUP BY b")
            assert ei.value.retry_after_ms > 0
            assert ei.value.errno == 9003
        finally:
            inst.admission._tokens["AP"].pop()
        assert inst.metrics.counter("admission_shed_total").value >= 1
        kinds = [e.kind for e in EVENTS.entries()]
        assert "admission_reject" in kinds
        s.close()

    def test_wait_timeout_sheds_typed(self):
        inst, s = _mk("ovt", rows=200)
        inst.config.set_instance("ADMISSION_AP_LIMIT", 1)
        inst.config.set_instance("ADMISSION_QUEUE_SIZE", 4)
        inst.config.set_instance("ADMISSION_WAIT_MS", 50)
        inst.admission._limit.clear()
        inst.admission._tokens["AP"].append(None)
        try:
            t0 = time.perf_counter()
            with pytest.raises(errors.ServerOverloadError):
                bounded(lambda: s.execute(
                    "SELECT b, sum(c) FROM t GROUP BY b"), 10.0)
            assert time.perf_counter() - t0 < 5.0  # bounded wait, no hang
        finally:
            inst.admission._tokens["AP"].pop()
        assert inst.admission.shed_timeout >= 1
        s.close()

    def test_waiter_admitted_when_slot_frees(self):
        inst, s = _mk("ovw", rows=200)
        inst.config.set_instance("ADMISSION_AP_LIMIT", 1)
        inst.config.set_instance("ADMISSION_WAIT_MS", 5000)
        inst.admission._limit.clear()
        inst.admission._tokens["AP"].append(None)
        got = []

        def waiter():
            s2 = Session(inst, schema="ovw")
            got.append(s2.execute("SELECT b, sum(c) FROM t GROUP BY b").rows)
            s2.close()

        t = threading.Thread(target=waiter, daemon=True)
        t.start()
        time.sleep(0.2)
        # free the slot: the queued query must admit and complete
        inst.admission._tokens["AP"].pop()
        with inst.admission._cond:
            inst.admission._cond.notify_all()
        t.join(20.0)
        assert not t.is_alive() and got and got[0]
        s.close()

    def test_aimd_decrease_and_increase(self):
        inst, s = _mk("ova")
        ctl = inst.admission
        lim0 = ctl.limit("AP")
        # latency blows through the AP target -> multiplicative decrease
        for _ in range(ctl.AIMD_SAMPLE):
            ctl._aimd("AP", 60_000.0)
        assert ctl.limit("AP") < lim0
        # healthy latency with the limit binding -> additive increase
        shrunk = ctl.limit("AP")
        ctl._ewma["AP"] = 1.0
        for _ in range(int(shrunk)):
            ctl._tokens["AP"].append(None)
        try:
            for _ in range(ctl.AIMD_SAMPLE):
                ctl._aimd("AP", 1.0)
        finally:
            ctl._tokens["AP"].clear()
        assert ctl.limit("AP") > shrunk
        s.close()


class TestDeadlineShed:
    def test_predicted_service_time_vs_deadline(self):
        inst, s = _mk("ovd", rows=100)
        ctl = inst.admission
        q = "SELECT b, sum(c) FROM t GROUP BY b"
        s.execute(q)  # record the digest
        dig = s._digest_of(q)
        ctl._digest_cost[dig] = ("AP", 60_000.0)  # predicted 60s service
        s.execute("SET MAX_EXECUTION_TIME = 200")  # 200ms budget
        with pytest.raises(errors.ServerOverloadError):
            s.execute(q)
        assert ctl.shed_deadline >= 1
        s.close()


# -- memory-pressure governance ------------------------------------------------


class TestMemoryPressure:
    def test_tiers_and_frag_budget(self):
        inst, s = _mk("ovm")
        gov = inst.admission.governor
        base = inst.frag_cache.budget
        assert gov.tier() == 0 and gov.spill_scale() == 1.0
        FAIL_POINTS.arm(FP_MEM_PRESSURE, "elevated")
        assert gov.tier() == 1
        assert gov.spill_scale() == 0.25
        assert inst.frag_cache.budget == base // 2
        FAIL_POINTS.arm(FP_MEM_PRESSURE, "critical")
        assert gov.tier() == 2
        FAIL_POINTS.disarm(FP_MEM_PRESSURE)
        assert gov.tier() == 0
        assert inst.frag_cache.budget == base  # restored
        kinds = [e.kind for e in EVENTS.entries()]
        assert "mem_pressure" in kinds
        s.close()

    def test_critical_refuses_ap_keeps_tp(self):
        inst, s = _mk("ovc", rows=200)
        FAIL_POINTS.arm(FP_MEM_PRESSURE, "critical")
        with pytest.raises(errors.ServerOverloadError):
            s.execute("SELECT b, sum(c) FROM t GROUP BY b")
        # TP point read still serves (goodput never zero)
        assert s.execute("SELECT b FROM t WHERE a = 5").rows == [(5,)]
        assert inst.admission.shed_memory >= 1
        s.close()

    def test_critical_revokes_largest_query(self):
        from galaxysql_tpu.exec.memory import (GLOBAL_POOL, PoolCharge,
                                               query_pool)
        inst, s = _mk("ovr")
        pool = query_pool(999_001, limit=1 << 20)
        charge = PoolCharge(pool)
        try:
            assert charge.to(512 << 10)
            assert inst.admission.governor.revoke_largest_query() > 0
            # flag-based revoke: the owning operator spills at its next
            # batch boundary
            assert charge.squeeze
        finally:
            charge.close()
            pool.close()
        assert pool not in GLOBAL_POOL.children
        s.close()

    def test_pool_exhaustion_spills_not_oom(self):
        """A tiny per-query pool forces the sort slab to spill (typed path,
        bit-identical results) instead of accumulating resident memory."""
        from galaxysql_tpu.utils.metrics import SPILL_BYTES
        inst, s = _mk("ovs", rows=20_000)
        q = "SELECT a, c FROM t ORDER BY c DESC LIMIT 7"
        expect = s.execute(q).rows
        before = SPILL_BYTES.value
        s.execute("SET QUERY_MEM_BYTES = 4096")
        assert s.execute(q).rows == expect  # spilled run, same answer
        assert SPILL_BYTES.value > before
        # per-query counter delta attributes the spill to the digest
        r = s.execute("SELECT sum(spill_bytes) FROM "
                      "information_schema.statement_summary")
        assert r.rows[0][0] and r.rows[0][0] > 0
        s.close()


# -- retry budget --------------------------------------------------------------


class TestRetryBudget:
    def test_token_bucket(self):
        from galaxysql_tpu.net.dn import RetryBudget
        b = RetryBudget(capacity=2, refill_per_s=0.0)
        assert b.try_take() and b.try_take()
        assert not b.try_take()
        assert b.exhausted == 1
        assert b.remaining() == 0.0
        b.configure(capacity=4, refill_per_s=0.0)
        assert not b.try_take()  # capacity change alone mints no tokens

    def test_empty_budget_fails_fast_typed(self):
        from galaxysql_tpu.net.dn import WorkerClient
        from galaxysql_tpu.utils.metrics import (RETRY_BUDGET_EXHAUSTED,
                                                 RPC_RETRIES)
        EVENTS.clear()
        client = WorkerClient("127.0.0.1", 1, max_retries=3,
                              failure_threshold=100)
        client.retry_budget.configure(capacity=0, refill_per_s=0.0)
        r0 = RPC_RETRIES.value
        e0 = RETRY_BUDGET_EXHAUSTED.value
        with pytest.raises(errors.WorkerUnavailableError) as ei:
            bounded(lambda: client.request({"op": "exec_plan",
                                            "fragment": {}}), 20.0)
        assert "retry budget" in str(ei.value)
        assert RPC_RETRIES.value == r0  # zero retries happened
        assert RETRY_BUDGET_EXHAUSTED.value == e0 + 1
        assert "retry_budget_exhausted" in [e.kind for e in EVENTS.entries()]

    def test_budget_caps_retry_volume(self):
        from galaxysql_tpu.net.dn import WorkerClient
        from galaxysql_tpu.utils.metrics import RPC_RETRIES
        client = WorkerClient("127.0.0.1", 1, max_retries=2,
                              failure_threshold=10_000,
                              retry_backoff_ms=1)
        client.retry_budget.configure(capacity=3, refill_per_s=0.0)
        r0 = RPC_RETRIES.value
        for _ in range(20):  # a would-be retry storm
            with pytest.raises(errors.WorkerUnavailableError):
                client.request({"op": "exec_plan", "fragment": {}})
        assert RPC_RETRIES.value - r0 <= 3  # bounded by the bucket, not 40


# -- hatches -------------------------------------------------------------------


class TestHatches:
    def test_param_off(self):
        inst, s = _mk("ovh1")
        s.execute("SET ENABLE_ADMISSION_CONTROL = 0")
        t = inst.admission.admit(s, "SELECT sum(a) FROM t GROUP BY a")
        assert t.ctl is None  # structural no-op ticket
        s.close()

    def test_env_off(self, monkeypatch):
        inst, s = _mk("ovh2")
        monkeypatch.setattr(adm_mod, "ENABLED", False)
        t = inst.admission.admit(s, "SELECT sum(a) FROM t GROUP BY a")
        assert t.ctl is None
        s.close()

    def test_hint_off(self):
        inst, s = _mk("ovh3", rows=50)
        FAIL_POINTS.arm(FP_MEM_PRESSURE, "critical")
        # CRITICAL refuses AP — unless the statement opts out of admission
        q = "/*+TDDL: ADMISSION(OFF)*/ SELECT b, sum(c) FROM t GROUP BY b"
        assert s.execute(q).rows
        s.close()

    def test_results_identical_on_vs_off(self, monkeypatch):
        inst, s = _mk("ovh4", rows=2_000)
        q = "SELECT b, sum(c) FROM t GROUP BY b ORDER BY b LIMIT 13"
        on = s.execute(q).rows
        monkeypatch.setattr(adm_mod, "ENABLED", False)
        assert s.execute(q).rows == on
        s.close()

    def test_idle_hot_path_dispatch_counts_unchanged(self, monkeypatch):
        """The no-overload guard: with limits idle, admission adds ZERO
        device dispatches — the gate is host-side token bookkeeping only."""
        from galaxysql_tpu.exec.operators import DISPATCH_STATS
        inst, s = _mk("ovh5", rows=2_000)
        q = "SELECT b, sum(c) FROM t GROUP BY b ORDER BY b LIMIT 13"
        s.execute(q)  # warm compiles on both paths

        def count(n=3):
            d0 = DISPATCH_STATS["dispatches"]
            for _ in range(n):
                s.execute(q)
            return DISPATCH_STATS["dispatches"] - d0

        with_admission = count()
        monkeypatch.setattr(adm_mod, "ENABLED", False)
        without = count()
        assert with_admission == without
        s.close()


# -- SQL surfaces --------------------------------------------------------------


class TestSqlSurfaces:
    def test_ccl_rule_ddl_round_trip(self):
        from galaxysql_tpu.utils.ccl import GLOBAL_CCL
        inst, s = _mk("ovsql", rows=10)
        try:
            s.execute("CREATE CCL_RULE throttle_t WITH MAX_CONCURRENCY = 2, "
                      "KEYWORD = 'slowq', WAIT_QUEUE_SIZE = 3, "
                      "WAIT_TIMEOUT = 500")
            rows = s.execute("SHOW CCL_RULES").rows
            assert ("throttle_t", 2, "slowq", "", 0, 0, 0, 0) in rows
            r = s.execute("SELECT rule_name, max_concurrency FROM "
                          "information_schema.ccl_rules")
            assert ("throttle_t", 2) in r.rows
            # IF NOT EXISTS keeps the existing rule
            s.execute("CREATE CCL_RULE IF NOT EXISTS throttle_t "
                      "WITH MAX_CONCURRENCY = 9")
            assert GLOBAL_CCL.rules()[0].rule.max_concurrency == 2
            s.execute("DROP CCL_RULE throttle_t")
            assert s.execute("SHOW CCL_RULES").rows == []
            with pytest.raises(errors.TddlError):
                s.execute("DROP CCL_RULE throttle_t")
            s.execute("DROP CCL_RULE IF EXISTS throttle_t")  # no error
        finally:
            GLOBAL_CCL.clear()
            s.close()

    def test_ccl_reject_publishes_event(self):
        from galaxysql_tpu.utils.ccl import GLOBAL_CCL
        inst, s = _mk("ovev", rows=10)
        EVENTS.clear()
        try:
            s.execute("CREATE CCL_RULE block WITH MAX_CONCURRENCY = 1, "
                      "KEYWORD = 't', WAIT_QUEUE_SIZE = 0")
            st = GLOBAL_CCL.rules()[0]
            st.sem.acquire()
            try:
                with pytest.raises(errors.CclRejectError):
                    s.execute("SELECT b FROM t WHERE a = 1")
            finally:
                st.sem.release()
            assert "ccl_reject" in [e.kind for e in EVENTS.entries()]
        finally:
            GLOBAL_CCL.clear()
            s.close()

    def test_show_admission_and_info_schema(self):
        inst, s = _mk("ovsh", rows=50)
        s.execute("SELECT b FROM t WHERE a = 1")  # a TP admission
        rows = dict(s.execute("SHOW ADMISSION").rows)
        assert rows["enabled"] == 1.0
        assert "tp_limit" in rows and "ap_limit" in rows
        assert rows["memory_pressure_tier"] == 0.0
        r = s.execute("SELECT stat_name, value FROM "
                      "information_schema.admission_stats "
                      "WHERE stat_name = 'tp_admitted'")
        assert r.rows and r.rows[0][1] >= 1
        # the new gauges land in the typed registry / SHOW METRICS
        names = {n for n, *_ in s.execute("SHOW METRICS").rows}
        assert {"memory_pressure_tier", "admission_queue_depth_tp",
                "admission_queue_depth_ap",
                "retry_budget_remaining"} <= names
        s.close()

    def test_spill_metrics_in_registry(self):
        inst, s = _mk("ovsp", rows=30_000)
        s.execute("SET SORT_SPILL_BYTES = 65536")
        s.execute("SELECT a, c FROM t ORDER BY c LIMIT 5")
        vals = {n: v for n, _k, v, _h in inst.metrics.rows()}
        assert vals.get("spill_bytes_total", 0) > 0
        assert vals.get("spill_files_total", 0) > 0
        assert "spill_bytes_total" in inst.metrics.prometheus_text()
        s.close()


# -- worker backpressure -------------------------------------------------------


class TestWorkerBackpressure:
    def test_slow_drain_piggyback(self):
        """A browned-out worker (slow drain, not dead) piggybacks its load in
        every reply; the client records it, results stay correct, breakers
        stay closed."""
        from test_chaos import WorkerHarness
        h = WorkerHarness(init_sql=(
            "CREATE DATABASE w; USE w; "
            "CREATE TABLE kv (k BIGINT PRIMARY KEY, v BIGINT); "
            "INSERT INTO kv VALUES (1, 10), (2, 20), (3, 30)"))
        inst, s = _mk("w")
        try:
            inst.attach_remote_table("w", "kv", *h.addr)
            client = inst.workers[h.addr]
            # no op filter: the remote scan ships as exec_plan when the
            # fragment compiles and degrades to exec_sql otherwise — the
            # drain must hit either path
            client.sync_action("failpoint",
                               {"key": FP_WORKER_SLOW_DRAIN,
                                "value": {"ms": 40}})
            t0 = time.perf_counter()
            rows = bounded(
                lambda: s.execute("SELECT v FROM kv WHERE k = 2").rows, 30.0)
            assert rows == [(20,)]
            assert time.perf_counter() - t0 >= 0.04  # the drain really hit
            assert client.load_at > 0  # piggybacked load recorded
            assert client.breaker_state() == "closed"  # slow is not dead
            client.sync_action("failpoint", {"clear": True})
        finally:
            s.close()
            h.close()

    def test_routing_deprioritizes_pressured_endpoint(self):
        """read_endpoint weights down endpoints that reported deep queues /
        memory pressure — without ever excluding them."""
        import types
        from galaxysql_tpu.net.dn import WorkerClient
        inst = Instance()
        calm = WorkerClient("127.0.0.1", 7001)
        busy = WorkerClient("127.0.0.1", 7002)
        busy.load_q, busy.load_tier, busy.load_at = 8, 1, time.time()
        inst.workers[("127.0.0.1", 7001)] = calm
        inst.workers[("127.0.0.1", 7002)] = busy
        tm = types.SimpleNamespace(
            name="kv", remote={"host": "127.0.0.1", "port": 7001},
            replicas=[{"host": "127.0.0.1", "port": 7002, "weight": 1}])
        picks = {7001: 0, 7002: 0}
        for _ in range(400):
            addr, _c = inst.read_endpoint(tm)
            picks[addr[1]] += 1
        assert picks[7002] > 0          # pressured, not excluded
        assert picks[7001] > 3 * picks[7002]  # but strongly deprioritized


# -- the end-to-end overload scenario -----------------------------------------


class TestOverloadEndToEnd:
    def test_tp_survives_ap_flood_with_pressure(self):
        """AP flood + ELEVATED memory pressure: TP keeps nonzero goodput and
        bounded p99; every AP refusal is typed; admitted results are
        bit-identical to idle; nothing hangs."""
        # 60k rows: above the planner's AP row threshold, so the flood query
        # is a GENUINE AP classification (the digest cost map records the
        # engine's workload verdict, which overrides the keyword guess after
        # the first execution)
        inst, s = _mk("ovf", rows=60_000)
        inst.config.set_instance("ADMISSION_AP_LIMIT", 2)
        inst.config.set_instance("ADMISSION_QUEUE_SIZE", 1)
        inst.config.set_instance("ADMISSION_WAIT_MS", 100)
        inst.admission._limit.clear()
        ap_q = ("SELECT b, sum(c), count(*) FROM t "
                "GROUP BY b ORDER BY 2 DESC LIMIT 5")
        tp_q = "SELECT b FROM t WHERE a = %d"
        idle_ap = s.execute(ap_q).rows          # idle-run truths
        idle_tp = {k: s.execute(tp_q % k).rows for k in (3, 77, 991)}
        FAIL_POINTS.arm(FP_MEM_PRESSURE, "elevated")
        stop = threading.Event()
        bad_failures: list = []
        ap_ok = [0]
        ap_shed = [0]
        lock = threading.Lock()

        def ap_flood():
            sx = Session(inst, schema="ovf")
            while not stop.is_set():
                try:
                    rows = sx.execute(ap_q).rows
                    with lock:
                        ap_ok[0] += 1
                    if rows != idle_ap:
                        bad_failures.append(
                            AssertionError("admitted AP result drifted"))
                except (errors.ServerOverloadError,
                        errors.CclRejectError):
                    with lock:
                        ap_shed[0] += 1
                    time.sleep(0.002)
                except Exception as exc:  # noqa: BLE001 — asserted below
                    bad_failures.append(exc)
            sx.close()

        tp_lats: list = []

        def tp_loop():
            sx = Session(inst, schema="ovf")
            mine = []
            for j in range(60):
                k = (3, 77, 991)[j % 3]
                t0 = time.perf_counter()
                try:
                    rows = sx.execute(tp_q % k).rows
                except Exception as exc:  # noqa: BLE001 — asserted below
                    bad_failures.append(exc)
                    continue
                mine.append(time.perf_counter() - t0)
                if rows != idle_tp[k]:
                    bad_failures.append(
                        AssertionError("admitted TP result drifted"))
            with lock:
                tp_lats.extend(mine)
            sx.close()

        def run():
            floods = [threading.Thread(target=ap_flood, daemon=True)
                      for _ in range(6)]
            for t in floods:
                t.start()
            time.sleep(0.2)  # flood established before TP measurement
            tps = [threading.Thread(target=tp_loop, daemon=True)
                   for _ in range(4)]
            for t in tps:
                t.start()
            for t in tps:
                t.join(RUN_BOUND_S)
                assert not t.is_alive(), "TP thread hung under flood"
            stop.set()
            for t in floods:
                t.join(RUN_BOUND_S)
                assert not t.is_alive(), "AP thread hung"

        bounded(run)
        FAIL_POINTS.clear()
        assert not bad_failures, bad_failures[:3]
        assert len(tp_lats) == 240  # full TP goodput, zero TP failures
        tp_lats.sort()
        p99 = tp_lats[min(int(0.99 * len(tp_lats)), len(tp_lats) - 1)]
        assert p99 < 5.0, f"TP p99 {p99:.3f}s unbounded under flood"
        assert ap_ok[0] > 0          # AP goodput nonzero too
        assert ap_shed[0] > 0        # the flood actually shed (typed)
        s.close()
