"""Statement hints: /*+TDDL: ... */ steering join order, engine, runtime filters.

Reference analog: `optimizer/parse/hint` + `optimizer/hint/*` — each supported
directive drives a real engine decision; unknown directives never break a query.
"""

import numpy as np
import pytest

from galaxysql_tpu.server.instance import Instance
from galaxysql_tpu.server.session import Session
from galaxysql_tpu.sql.hints import parse_hints


class TestParseHints:
    def test_directives(self):
        h = parse_hints("/*+TDDL: JOIN_ORDER(a, b.c) ENGINE(MPP) NO_BLOOM*/")
        assert h == {"join_order": ["a", "b.c"], "engine": "MPP",
                     "no_bloom": True}

    def test_non_tddl_comment_ignored(self):
        assert parse_hints("/* plain comment */") == {}
        assert parse_hints(None) == {}

    def test_unknown_directive_ignored(self):
        assert parse_hints("/*+TDDL: FROBNICATE(9) BASELINE_OFF*/") == \
            {"baseline_off": True}


@pytest.fixture()
def session():
    inst = Instance()
    s = Session(inst)
    s.execute("CREATE DATABASE h")
    s.execute("USE h")
    s.execute("CREATE TABLE big (id BIGINT, k BIGINT)")
    s.execute("CREATE TABLE small (k BIGINT, v BIGINT)")
    inst.store("h", "big").insert_pylists(
        {"id": list(range(2000)), "k": [i % 50 for i in range(2000)]},
        inst.tso.next_timestamp())
    inst.store("h", "small").insert_pylists(
        {"k": list(range(50)), "v": list(range(50))},
        inst.tso.next_timestamp())
    s.execute("ANALYZE TABLE big, small")
    yield s
    s.close()


def plan_of(s, sql):
    return s.instance.planner.plan_select(sql, "h", [], s)


class TestHintsDrivePlans:
    Q = "select count(*) from big, small where big.k = small.k"

    def test_join_order_hint_forces_order(self, session):
        default = plan_of(session, self.Q).join_orders
        assert default == [("h.small", "h.big")]  # cost picks small first
        hinted = plan_of(
            session, "/*+TDDL:JOIN_ORDER(big, small)*/ " + self.Q).join_orders
        assert hinted == [("h.big", "h.small")]
        # and the hinted query still returns the right answer
        r = session.execute("/*+TDDL:JOIN_ORDER(big, small)*/ " + self.Q)
        assert r.rows == [(2000,)]

    def test_hinted_statement_bypasses_spm(self, session):
        session.execute(self.Q)  # captures a baseline
        n = len(session.execute("SHOW BASELINE").rows)
        session.execute("/*+TDDL:JOIN_ORDER(big, small)*/ " + self.Q)
        # the hinted execution neither followed nor polluted the baseline
        rows = session.execute("SHOW BASELINE").rows
        assert len(rows) == n
        assert "h.small" in rows[0][3]  # accepted order unchanged

    def test_baseline_off(self, session):
        session.execute(self.Q)
        accepted = plan_of(session, self.Q).join_orders
        session.instance.catalog.table("h", "small").stats.row_count = 10**9
        session.instance.planner.cache.invalidate_all()
        # baseline would pin small-first; BASELINE_OFF replans by cost
        free = plan_of(session, "/*+TDDL:BASELINE_OFF*/ " + self.Q).join_orders
        assert free != accepted

    def test_engine_hint_local_and_tp(self, session):
        r = session.execute("/*+TDDL:ENGINE(TP)*/ " + self.Q)
        assert r.rows == [(2000,)]
        r = session.execute("/*+TDDL:ENGINE(LOCAL)*/ " + self.Q)
        assert r.rows == [(2000,)]

    def test_no_bloom_hint(self, session):
        r = session.execute("/*+TDDL:NO_BLOOM*/ " + self.Q)
        assert r.rows == [(2000,)]
        # trace shows no bloom was built: the join still ran correctly; the
        # observable contract is correctness + acceptance of the directive
