"""Self-healing plan management: sentinel-triggered quarantine, verified
rollback, stats-drift repair, flap damping, restart-resumed probation, and the
detect-only escape hatches.

The heal loop under test (plan/spm.py quarantine machine, driven by the
statement-summary sentinel in meta/statement_summary.py):

    HEALTHY --sentinel--> REGRESSED --bind--> PROBATION --> HEALED
                                                        --> EVOLVED
                                                        --> HEAL_FAILED

The end-to-end fixture induces a GENUINE join-order regression (no synthetic
sleeps): a 3-table star query whose m:n dim-dim edge (cust.nk = supp.nk, the
TPC-H Q5 nation-key trap the GOO planner exists to avoid) explodes when a
stats change makes the cost model merge the two dims first.

The `selfheal`-marked tests are the fast smoke target (`make heal-smoke`).
"""

import json
import time

import numpy as np
import pytest

from galaxysql_tpu.exec import operators as ops
from galaxysql_tpu.meta import statement_summary as ssm
from galaxysql_tpu.meta.statistics import analyzed_rows
from galaxysql_tpu.server.instance import Instance
from galaxysql_tpu.server.session import Session
from galaxysql_tpu.utils.events import EVENTS

Q = ("/*+TDDL: FRAGMENT_CACHE(OFF)*/ SELECT count(*), "
     "sum(fact.val + cust.cv + supp.sv) FROM fact, cust, supp "
     "WHERE fact.ck = cust.ck AND fact.sk = supp.sk AND cust.nk = supp.nk")

N_FACT, N_DIM = 40000, 2000


def _star(schema, data_dir=None, n_fact=N_FACT, n_dim=N_DIM):
    """Fact + two dims whose nk edge is the m:n trap; accurate ANALYZE stats
    make GOO route the nk edge through the fact table (fast plan)."""
    inst = Instance(data_dir=data_dir)
    s = Session(inst)
    s.execute(f"CREATE DATABASE {schema}")
    s.execute(f"USE {schema}")
    s.execute("CREATE TABLE fact (fid BIGINT PRIMARY KEY, ck BIGINT, "
              "sk BIGINT, val BIGINT) PARTITION BY HASH(fid) PARTITIONS 4")
    s.execute("CREATE TABLE cust (cid BIGINT PRIMARY KEY, ck BIGINT, "
              "nk BIGINT, cv BIGINT)")
    s.execute("CREATE TABLE supp (sid BIGINT PRIMARY KEY, sk BIGINT, "
              "nk BIGINT, sv BIGINT)")
    ts = inst.tso.next_timestamp
    rng = np.random.default_rng(7)
    inst.store(schema, "fact").insert_arrays(
        {"fid": np.arange(n_fact), "ck": rng.integers(0, n_dim, n_fact),
         "sk": rng.integers(0, n_dim, n_fact),
         "val": np.arange(n_fact) % 97}, ts())
    inst.store(schema, "cust").insert_arrays(
        {"cid": np.arange(n_dim), "ck": np.arange(n_dim),
         "nk": np.arange(n_dim) % 4, "cv": np.arange(n_dim) % 13}, ts())
    inst.store(schema, "supp").insert_arrays(
        {"sid": np.arange(n_dim), "sk": np.arange(n_dim),
         "nk": np.arange(n_dim) % 4, "sv": np.arange(n_dim) % 11}, ts())
    s.execute("ANALYZE TABLE fact, cust, supp")
    # warm the engine (cold-interpreter jax/compile inflation must not leak
    # into the frozen latency baseline), then clear the summary store so the
    # baseline re-forms from warm executions only
    s.execute(Q)
    s.execute(Q)
    inst.stmt_summary.clear()
    return inst, s


def _flip_stats(inst, s, schema, n_dim=N_DIM):
    """The stats change that flips the greedy join order: ingest distinct-nk
    dim rows (disjoint key domains — query RESULTS don't change) and ANALYZE.
    ndv(nk) jumps from 4 to ~n_dim, so the System-R estimate of the dim-dim
    merge collapses and GOO now merges the m:n edge FIRST — a genuine
    latency blow-up on the same data."""
    ts = inst.tso.next_timestamp
    inst.store(schema, "cust").insert_arrays(
        {"cid": np.arange(n_dim, 2 * n_dim),
         "ck": np.arange(n_dim, 2 * n_dim),
         "nk": np.arange(10_000, 10_000 + n_dim),
         "cv": np.zeros(n_dim, np.int64)}, ts())
    inst.store(schema, "supp").insert_arrays(
        {"sid": np.arange(n_dim, 2 * n_dim),
         "sk": np.arange(n_dim, 2 * n_dim),
         "nk": np.arange(20_000, 20_000 + n_dim),
         "sv": np.zeros(n_dim, np.int64)}, ts())
    s.execute("ANALYZE TABLE fact, cust, supp")


def _timed(s, n):
    out = []
    for _ in range(n):
        t0 = time.perf_counter()
        rs = s.execute(Q)
        out.append(((time.perf_counter() - t0) * 1000.0,
                    tuple(map(tuple, rs.rows))))
    return out


def _heal_events(kind=None):
    evs = [e for e in EVENTS.entries()
           if e.kind in ("plan_rollback", "plan_promoted",
                         "plan_heal_failed", "stats_repair")]
    return [e for e in evs if e.kind == kind] if kind else evs


def _spm_key(inst):
    return next(iter(inst.planner.spm._baselines))


# -- the acceptance scenario ---------------------------------------------------


@pytest.mark.selfheal
class TestSelfHealEndToEnd:
    def test_regression_rolls_back_verifies_and_promotes(self):
        """A stats-driven join-order regression is detected, rolled back,
        verified, and promoted with ZERO human intervention: one
        plan_rollback + one plan_promoted per episode, bit-identical results
        throughout, post-heal median back within PLAN_REGRESSION_FACTOR of
        the frozen baseline, steady-state retraces 0."""
        EVENTS.clear()
        inst, s = _star("hz")
        p1 = _timed(s, 6)  # baseline freezes on the first plan's median
        key = _spm_key(inst)
        b = inst.planner.spm._baselines[key]
        good_orders = list(b.accepted.orders)
        entry = inst.stmt_summary._entries[key]
        base_ms = entry.baseline_ms
        base_fp = entry.baseline_fp
        assert base_ms is not None and b.state == "HEALTHY"
        factor = float(inst.config.get("PLAN_REGRESSION_FACTOR"))

        # the DBA deletes the baseline (PR-9 workflow) and the stats change
        # flips the replan into the m:n-first order — a real blow-up
        bid = s.execute("SHOW BASELINE").rows[0][0]
        s.execute(f"BASELINE DELETE {bid}")
        _flip_stats(inst, s, "hz")
        # the sentinel fires once a window bucket holds PLAN_REGRESSION_MIN_
        # EXECS regressed runs (5 + a couple extra if a 60s window boundary
        # happens to split them)
        p2 = []
        for _ in range(12):
            p2 += _timed(s, 1)
            b = inst.planner.spm._baselines[key]
            if b.state != "HEALTHY":
                break
        assert b.state == "REGRESSED" and b.heal is not None
        assert b.heal.mode == "rollback"
        assert [tuple(o) for o in b.heal.rollback_orders] == good_orders
        # the regression was genuine: the flagged window median really blew up
        assert sorted(d for d, _ in p2)[len(p2) // 2] > factor * base_ms
        assert len(_heal_events("plan_rollback")) == 1

        # probation: the next bind re-plans pinned to the frozen baseline
        # plan; PLAN_HEAL_VERIFY_EXECS executions verify it
        p3 = _timed(s, int(inst.config.get("PLAN_HEAL_VERIFY_EXECS")))
        b = inst.planner.spm._baselines[key]
        assert b.state == "HEALED"
        assert b.accepted.origin == "healed"
        assert list(b.accepted.orders) == good_orders
        assert len(_heal_events("plan_promoted")) == 1
        assert not _heal_events("plan_heal_failed")
        assert inst.metrics.counter("plan_heals").value == 1
        assert inst.metrics.counter("plan_heal_failures").value == 0

        # post-heal: median back within the sentinel factor of the frozen
        # baseline, results bit-identical through every phase
        p4 = _timed(s, 5)
        assert sorted(d for d, _ in p4)[2] <= factor * base_ms
        assert len({rows for _, rows in p1 + p2 + p3 + p4}) == 1
        # the healed plan runs under the baseline fingerprint again
        rows = [r for r in s.execute("SHOW STATEMENT SUMMARY").rows
                if "fact.val" in r[-1]]
        assert base_fp in {r[2] for r in rows}

        # surfaces: SHOW BASELINE carries the heal machine columns
        brow = s.execute("SHOW BASELINE").rows[0]
        assert brow[10] == "HEALED" and brow[11] == 1 and "healed" in brow[12]

        # steady state afterwards: no retraces, unchanged dispatch counts
        from galaxysql_tpu.exec.operators import COMPILE_STATS
        s.execute(Q)
        r0 = COMPILE_STATS["retraces"]
        ops.reset_dispatch_stats()
        s.execute(Q)
        d0 = ops.DISPATCH_STATS["dispatches"]
        ops.reset_dispatch_stats()
        s.execute(Q)
        assert ops.DISPATCH_STATS["dispatches"] == d0
        assert COMPILE_STATS["retraces"] == r0
        s.close()


# -- restart: quarantine state persists, probation resumes --------------------


@pytest.mark.selfheal
class TestRestartResume:
    def test_probation_survives_coordinator_restart(self, tmp_path):
        EVENTS.clear()
        inst, s = _star("hr", data_dir=str(tmp_path / "hr"))
        _timed(s, 6)
        key = _spm_key(inst)
        bid = s.execute("SHOW BASELINE").rows[0][0]
        s.execute(f"BASELINE DELETE {bid}")
        _flip_stats(inst, s, "hr")
        for _ in range(12):  # sentinel fires -> REGRESSED
            s.execute(Q)
            if inst.planner.spm._baselines[key].state != "HEALTHY":
                break
        assert inst.planner.spm._baselines[key].state == "REGRESSED"
        _timed(s, 2)  # 2 of PLAN_HEAL_VERIFY_EXECS probation samples
        b = inst.planner.spm._baselines[key]
        assert b.state == "PROBATION" and len(b.heal.samples) == 2
        inst.save()
        s.close()

        # coordinator restart: probation resumes from the persisted record
        # instead of re-detecting and re-thrashing
        inst2 = Instance(data_dir=str(tmp_path / "hr"))
        b2 = inst2.planner.spm._baselines[key]
        assert b2.state == "PROBATION"
        assert len(b2.heal.samples) == 2
        assert b2.heal.mode == "rollback" and b2.rollbacks == 1
        s2 = Session(inst2, schema="hr")
        for _ in range(3):  # the remaining verification samples
            s2.execute(Q)
        b2 = inst2.planner.spm._baselines[key]
        assert b2.state == "HEALED" and b2.accepted.origin == "healed"
        # exactly one rollback + one promote across the whole episode,
        # restart included
        assert len(_heal_events("plan_rollback")) == 1
        assert len(_heal_events("plan_promoted")) == 1
        s2.close()


# -- same-plan drift: stats repair path ----------------------------------------


@pytest.mark.selfheal
class TestStatsDriftRepair:
    def test_drift_repairs_statistics_and_episode_concludes(self):
        """The same-fingerprint path: the dim gains many duplicate join-key
        rows per value under a pinned, cached plan (no ANALYZE — classic
        stats drift), so latency genuinely degrades with NO plan change.
        The heal loop must repair the drifted statistics from the store
        truth (targeted, not a DBA-run ANALYZE), re-enter verification
        unpinned, and close the episode with exactly one typed outcome.
        (The individual verdict branches — HEALED / HEAL_FAILED + park +
        ANALYZE re-arm — are pinned deterministically in TestFlapDamping.)"""
        EVENTS.clear()
        inst, s = _star("hd", n_fact=4000, n_dim=500)
        _timed(s, 6)
        key = _spm_key(inst)
        cust_tm = inst.catalog.table("hd", "cust")
        assert analyzed_rows(cust_tm) == 500
        # ingest 40 duplicate cust rows per ck value (a genuine
        # join-multiplicity blowup: every fact row now matches 41 cust
        # rows); the sketches still describe the 500-row dim
        ts = inst.tso.next_timestamp
        n = 20000
        inst.store("hd", "cust").insert_arrays(
            {"cid": np.arange(500, 500 + n), "ck": np.arange(n) % 500,
             "nk": (np.arange(n) % 500) % 4,
             "cv": np.zeros(n, np.int64)}, ts())
        # same cached plan: the window median crosses the threshold once
        # enough drifted samples displace the fast ones
        p2 = []
        for _ in range(20):
            p2 += _timed(s, 1)
            b = inst.planner.spm._baselines[key]
            if b.state != "HEALTHY":
                break
        assert b.state in ("REGRESSED", "PROBATION")
        assert b.heal is not None and b.heal.mode == "repair"
        assert b.heal.reason == "plan_drift"
        # the flag really was same-fingerprint drift, not a plan change
        regs = [e for e in EVENTS.entries() if e.kind == "plan_regression"]
        assert regs and regs[-1].attrs["reason"] == "plan_drift"
        # the repair corrected the drifted sketches to the live row count
        assert analyzed_rows(cust_tm) >= 500 + n
        reps = _heal_events("stats_repair")
        assert len(reps) == 1
        assert any(d["table"] == "hd.cust"
                   for d in reps[0].attrs["repaired"])

        # probation re-verifies on repaired statistics and the episode ends
        # in exactly one typed verdict
        p3 = []
        for _ in range(12):
            if inst.planner.spm._baselines[key].state not in (
                    "REGRESSED", "PROBATION"):
                break
            p3 += _timed(s, 1)
        b = inst.planner.spm._baselines[key]
        assert b.state in ("HEALED", "HEAL_FAILED")
        outcomes = _heal_events("plan_promoted") + \
            _heal_events("plan_heal_failed")
        assert len(outcomes) == 1
        assert inst.metrics.counter("plan_heals").value + \
            inst.metrics.counter("plan_heal_failures").value == 1
        assert s.execute("SHOW BASELINE").rows[0][10] == b.state
        # results stayed bit-identical through detection, repair, probation
        p4 = _timed(s, 2)
        assert len({rows for _, rows in p2 + p3 + p4}) == 1
        s.close()


# -- flap damping (breaker-style) ----------------------------------------------


@pytest.mark.selfheal
class TestFlapDamping:
    def _mk_pm(self):
        from galaxysql_tpu.plan.spm import PlanManager
        pm = PlanManager()
        key = ("s", "select ?")
        pm.capture(key, [("s.a", "s.b")], catalog_version=1,
                   followed_baseline=False)
        return pm, key

    def _episode(self, pm, key, sample_ms, n=1, now=0.0):
        action = pm.begin_quarantine(
            key, "rollback", "new_plan", [("s.b", "s.a")], baseline_ms=10.0,
            factor=1.5, verify_execs=n, max_rollbacks=3, cooldown_s=0.0,
            stats_version=7, regressed_ms=100.0, now=now)
        if action is None or action["action"] == "damped":
            return action, None
        pm.choose(key, 1)  # the bind that enters PROBATION
        verdict = None
        for _ in range(n):
            # probation samples carry the plan they ran (the pinned orders);
            # samples from other plans are rejected as stragglers
            verdict = pm.record_execution(key, sample_ms,
                                          orders=[("s.b", "s.a")],
                                          stats_version=7)
        return action, verdict

    def test_max_rollbacks_cap_parks_and_analyze_rearms(self):
        pm, key = self._mk_pm()
        for i in range(3):  # burn the episode budget (verdicts: promoted)
            action, verdict = self._episode(pm, key, 9.0, now=float(i))
            assert action["action"] == "rollback"
            assert verdict["kind"] == "promoted"
        action, _ = self._episode(pm, key, 9.0, now=99.0)
        assert action["action"] == "damped"
        b = pm._baselines[key]
        assert b.state == "HEAL_FAILED" and "flap_damped" in b.last_heal
        # parked against the SAME catalog version: nothing may start
        assert pm.begin_quarantine(
            key, "rollback", "new_plan", [("s.b", "s.a")], baseline_ms=10.0,
            factor=1.5, verify_execs=1, max_rollbacks=3, cooldown_s=0.0,
            stats_version=7, now=100.0) is None
        # ANALYZE/DDL moved the catalog version: re-armed, budget reset
        action = pm.begin_quarantine(
            key, "rollback", "new_plan", [("s.b", "s.a")], baseline_ms=10.0,
            factor=1.5, verify_execs=1, max_rollbacks=3, cooldown_s=0.0,
            stats_version=8, now=101.0)
        assert action is not None and action["action"] == "rollback"
        assert pm._baselines[key].rollbacks == 1

    def test_cooldown_blocks_back_to_back_episodes(self):
        pm, key = self._mk_pm()
        action = pm.begin_quarantine(
            key, "rollback", "new_plan", [("s.b", "s.a")], baseline_ms=10.0,
            factor=1.5, verify_execs=1, max_rollbacks=10, cooldown_s=60.0,
            stats_version=7, now=1000.0)
        assert action is not None
        pm.choose(key, 1)
        pm.record_execution(key, 9.0, orders=[("s.b", "s.a")],
                            stats_version=7)  # -> HEALED
        # within the cooldown: detect-only
        assert pm.begin_quarantine(
            key, "rollback", "new_plan", [("s.b", "s.a")], baseline_ms=10.0,
            factor=1.5, verify_execs=1, max_rollbacks=10, cooldown_s=60.0,
            stats_version=7, now=1030.0) is None
        # after it elapses: a new episode may start
        assert pm.begin_quarantine(
            key, "rollback", "new_plan", [("s.b", "s.a")], baseline_ms=10.0,
            factor=1.5, verify_execs=1, max_rollbacks=10, cooldown_s=60.0,
            stats_version=7, now=1061.0) is not None

    def test_repair_failure_parks_then_analyze_rearms(self):
        """Repair-mode probation that stays regressed parks in HEAL_FAILED
        against the CURRENT catalog version; only ANALYZE/DDL (a catalog
        version move) re-arms the digest."""
        pm, key = self._mk_pm()
        action = pm.begin_quarantine(
            key, "repair", "plan_drift", None, baseline_ms=10.0, factor=1.5,
            verify_execs=2, max_rollbacks=3, cooldown_s=0.0,
            stats_version=7, regressed_ms=30.0, now=0.0)
        assert action["action"] == "repair"
        # UNARMED (repair still running): binds keep the pinned plan
        assert pm.choose(key, 1) == [("s.a", "s.b")]
        assert pm._baselines[key].state == "REGRESSED"
        pm.arm_heal(key)  # the stats repair finished
        # now probation is UNPINNED: the corrected stats pick the plan
        assert pm.choose(key, 1) is None
        # executions BEFORE the probation bind anchors the episode are
        # unattributable stragglers — never verification samples
        assert pm.record_execution(key, 500.0, orders=[("s.z", "s.a")],
                                   stats_version=7) is None
        assert not pm._baselines[key].heal.samples
        # the probation BIND (capture) anchors the episode's plan identity
        pm.capture(key, [("s.a", "s.b")], 1, followed_baseline=False)
        assert pm.record_execution(key, 28.0, orders=[("s.a", "s.b")],
                                   stats_version=7) is None  # 1 of 2
        # a straggler of a DIFFERENT plan (bound pre-repair) is rejected
        assert pm.record_execution(key, 500.0, orders=[("s.z", "s.a")],
                                   stats_version=7) is None
        assert len(pm._baselines[key].heal.samples) == 1
        # 28ms median: misses the 15ms baseline gate and does not clearly
        # beat the 30ms regressed window either -> park
        verdict = pm.record_execution(key, 28.0, orders=[("s.a", "s.b")],
                                      stats_version=7)
        assert verdict["kind"] == "failed"
        b = pm._baselines[key]
        assert b.state == "HEAL_FAILED" and b.park_version == 7
        # parked: the same catalog version may not start another episode
        assert pm.begin_quarantine(
            key, "repair", "plan_drift", None, baseline_ms=10.0, factor=1.5,
            verify_execs=1, max_rollbacks=3, cooldown_s=0.0,
            stats_version=7, now=1.0) is None
        # after HEAL_FAILED the digest runs its accepted plan again
        assert pm.choose(key, 1) == [("s.a", "s.b")]
        # ANALYZE/DDL moved the catalog version: re-armed
        action = pm.begin_quarantine(
            key, "repair", "plan_drift", None, baseline_ms=10.0, factor=1.5,
            verify_execs=1, max_rollbacks=3, cooldown_s=0.0,
            stats_version=8, now=2.0)
        assert action is not None and action["action"] == "repair"

    def test_evolved_when_rollback_slow_but_baseline_far(self):
        """Rollback misses the baseline AND does not clearly beat the
        regressed plan: the new plan is kept as the evolved baseline and the
        latency yardstick re-freezes."""
        pm, key = self._mk_pm()
        accepted_before = list(pm._baselines[key].accepted.orders)
        _, verdict = self._episode(pm, key, sample_ms=90.0)  # regressed=100
        assert verdict["kind"] == "evolved" and verdict["refreeze"]
        b = pm._baselines[key]
        assert b.state == "EVOLVED" and b.accepted.origin == "evolved"
        assert list(b.accepted.orders) == accepted_before

    def test_promoted_with_refreeze_when_rollback_beats_regressed(self):
        """The baseline is unreachable (data grew) but the rollback still
        clearly beats the regressed plan: promote it and re-freeze."""
        pm, key = self._mk_pm()
        _, verdict = self._episode(pm, key, sample_ms=40.0)  # 40*1.5 <= 100
        assert verdict["kind"] == "promoted" and verdict["refreeze"]
        b = pm._baselines[key]
        assert b.state == "HEALED"
        assert list(b.accepted.orders) == [("s.b", "s.a")]


# -- hatches + hot path --------------------------------------------------------


@pytest.mark.selfheal
class TestHatches:
    def test_param_off_restores_detect_only(self):
        EVENTS.clear()
        inst, s = _star("hh", n_fact=4000, n_dim=500)
        inst.config.set_instance("ENABLE_PLAN_AUTOHEAL", 0)
        _timed(s, 6)
        key = _spm_key(inst)
        bid = s.execute("SHOW BASELINE").rows[0][0]
        s.execute(f"BASELINE DELETE {bid}")
        _flip_stats(inst, s, "hh", n_dim=500)
        _timed(s, 6)
        # detection stayed live, the engine never acted
        assert [e.kind for e in EVENTS.entries()
                if e.kind == "plan_regression"]
        assert not _heal_events()
        b = inst.planner.spm._baselines[key]
        assert b.state == "HEALTHY" and b.rollbacks == 0
        assert b.accepted.regressions >= 1  # PR-9 annotation still works
        s.close()

    def test_env_kill_switch(self, monkeypatch):
        EVENTS.clear()
        inst, s = _star("he", n_fact=4000, n_dim=500)
        monkeypatch.setattr(ssm, "AUTOHEAL_ENABLED", False)
        _timed(s, 6)
        key = _spm_key(inst)
        bid = s.execute("SHOW BASELINE").rows[0][0]
        s.execute(f"BASELINE DELETE {bid}")
        _flip_stats(inst, s, "he", n_dim=500)
        _timed(s, 6)
        assert not _heal_events()
        assert inst.planner.spm._baselines[key].state == "HEALTHY"
        s.close()

    def test_hot_path_dispatch_unchanged_autoheal_on_vs_off(self):
        """A healthy digest pays nothing for the armed heal loop: same
        device dispatches and zero retraces with the hatch on vs off."""
        inst, s = _star("hp", n_fact=4000, n_dim=500)
        from galaxysql_tpu.exec.operators import COMPILE_STATS
        _timed(s, 2)  # warm
        ops.reset_dispatch_stats()
        on = s.execute(Q)
        d_on = ops.DISPATCH_STATS["dispatches"]
        inst.config.set_instance("ENABLE_PLAN_AUTOHEAL", 0)
        r0 = COMPILE_STATS["retraces"]
        ops.reset_dispatch_stats()
        off = s.execute(Q)
        assert ops.DISPATCH_STATS["dispatches"] == d_on
        assert COMPILE_STATS["retraces"] == r0
        assert on.rows == off.rows
        s.close()


# -- surfaces ------------------------------------------------------------------


@pytest.mark.selfheal
class TestSurfaces:
    def test_show_baseline_web_and_information_schema_parity(self):
        inst, s = _star("hs", n_fact=4000, n_dim=500)
        s.execute(Q)
        show = s.execute("SHOW BASELINE")
        assert show.names[-3:] == ["STATE", "ROLLBACKS", "LAST_HEAL"]
        from galaxysql_tpu.server.web import WebConsole
        body = WebConsole(inst).resource("/baselines")
        jb = body["baselines"][0]
        # JSON parity: same values under the documented keys
        row = show.rows[0]
        assert jb["state"] == row[10] == "HEALTHY"
        assert jb["rollbacks"] == row[11] == 0
        assert jb["last_heal"] == row[12] == ""
        assert jb["regressions"] == row[8]
        json.dumps(body, default=str)
        # SQL-queryable twin
        r = s.execute("SELECT state, rollbacks FROM "
                      "information_schema.plan_baselines")
        assert ("HEALTHY", 0) in r.rows
        s.close()

    def test_heal_counters_in_metrics_and_prometheus(self):
        inst, s = _star("hm", n_fact=4000, n_dim=500)
        names = {r[0] for r in s.execute("SHOW METRICS").rows}
        assert {"plan_heals", "plan_heal_failures"} <= names
        from galaxysql_tpu.server.web import WebConsole
        text = WebConsole(inst).metrics_text()
        assert "galaxysql_plan_heals" in text
        assert "galaxysql_plan_heal_failures" in text
        s.close()
