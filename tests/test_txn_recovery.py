"""Rollback safety under concurrent writers + boot-time XA recovery.

Covers the round-1 advisor findings: rollback must stamp its own rows dead (never
truncate partition lanes out from under concurrent writers), boot() must resolve
orphaned provisional MVCC stamps against the durable tx log, and TTL archival must
not archive rows with pending deletes.
"""

import numpy as np
import pytest

from galaxysql_tpu.server.instance import Instance
from galaxysql_tpu.server.session import Session
from galaxysql_tpu.storage.table_store import INFINITY_TS


@pytest.fixture()
def inst():
    inst = Instance()
    s = Session(inst)
    s.execute("CREATE DATABASE x; USE x")
    s.execute("CREATE TABLE t (id BIGINT, v BIGINT) PARTITION BY HASH(id) PARTITIONS 1")
    yield inst, s
    s.close()


class TestRollbackStamping:
    def test_rollback_preserves_concurrent_committed_insert(self, inst):
        """A rolls back after B appended to the same partition: B's rows survive."""
        instance, a = inst
        b = Session(instance, "x")
        a.execute("BEGIN")
        a.execute("INSERT INTO t VALUES (1, 10), (2, 20)")
        # concurrent autocommit writer appends to the same (only) partition
        b.execute("INSERT INTO t VALUES (3, 30)")
        a.execute("ROLLBACK")
        assert b.execute("SELECT id, v FROM t").rows == [(3, 30)]
        # lanes were not shrunk: all 3 physical rows still present
        p = instance.store("x", "t").partitions[0]
        assert p.num_rows == 3
        # A's rows are dead on every visibility path (snapshot and None)
        assert not p.visible_mask(None)[:2].any()
        b.close()

    def test_rollback_then_xa_commit_of_survivor(self, inst):
        """B's open txn spanning A's rollback still commits its own rows."""
        instance, a = inst
        b = Session(instance, "x")
        b.execute("SET TRANSACTION_POLICY = 'XA'")
        a.execute("BEGIN")
        a.execute("INSERT INTO t VALUES (1, 10)")
        b.execute("BEGIN")
        b.execute("INSERT INTO t VALUES (2, 20)")
        a.execute("ROLLBACK")
        b.execute("COMMIT")  # XA prepare must still see B's stamps at B's offsets
        assert sorted(a.execute("SELECT id FROM t").rows) == [(2,)]
        b.close()

    def test_insert_then_delete_rollback_invisible_everywhere(self, inst):
        instance, a = inst
        a.execute("BEGIN")
        a.execute("INSERT INTO t VALUES (7, 70)")
        a.execute("DELETE FROM t WHERE id = 7")
        a.execute("ROLLBACK")
        p = instance.store("x", "t").partitions[0]
        assert not p.visible_mask(None).any()
        assert a.execute("SELECT count(*) FROM t").rows == [(0,)]


class TestBootRecovery:
    def _boot_cycle(self, tmp_path, mutate):
        """Create instance A with a crashed txn state, save, boot instance B."""
        d = str(tmp_path)
        ia = Instance(data_dir=d)
        s = Session(ia)
        s.execute("CREATE DATABASE x; USE x")
        s.execute("CREATE TABLE t (id BIGINT, v BIGINT) "
                  "PARTITION BY HASH(id) PARTITIONS 1")
        s.execute("INSERT INTO t VALUES (1, 10)")
        s.execute("BEGIN")
        s.execute("INSERT INTO t VALUES (2, 20)")
        txn_id = s.txn.txn_id
        mutate(ia, txn_id)  # simulate the crash point (log state written or not)
        # crash: persist partitions with the provisional stamps still in place
        ia.save()
        s.txn = None  # abandon without rollback
        s.close()
        return Instance(data_dir=d), txn_id

    def test_orphaned_uncommitted_stamps_roll_back(self, tmp_path):
        ib, txn_id = self._boot_cycle(tmp_path, lambda ia, t: None)
        s = Session(ib, "x")
        assert s.execute("SELECT id FROM t").rows == [(1,)]
        assert ib.metadb.tx_log_get(txn_id)[0] == "ABORTED"
        p = ib.store("x", "t").partitions[0]
        assert not (p.begin_ts < 0).any() and not (p.end_ts < 0).any()
        s.close()

    def test_logged_commit_point_reapplies_on_boot(self, tmp_path):
        commit_ts = {}

        def mutate(ia, txn_id):
            # coordinator logged the commit point, crashed before stamping
            commit_ts["v"] = ia.tso.next_timestamp()
            ia.metadb.tx_log_put(txn_id, "COMMITTED", commit_ts["v"])

        ib, txn_id = self._boot_cycle(tmp_path, mutate)
        s = Session(ib, "x")
        assert sorted(s.execute("SELECT id FROM t").rows) == [(1,), (2,)]
        assert ib.metadb.tx_log_get(txn_id) == ("DONE", commit_ts["v"])
        s.close()

    def test_prepared_without_commit_point_rolls_back(self, tmp_path):
        ib, txn_id = self._boot_cycle(
            tmp_path, lambda ia, t: ia.metadb.tx_log_put(t, "PREPARED"))
        s = Session(ib, "x")
        assert s.execute("SELECT id FROM t").rows == [(1,)]
        s.close()


class TestArchivePendingDeletes:
    def test_provisionally_deleted_rows_stay_hot(self, inst):
        pytest.importorskip("pyarrow")
        instance, s = inst
        s.execute("CREATE TABLE ev (id BIGINT, d DATE) "
                  "PARTITION BY HASH(id) PARTITIONS 1")
        s.execute("INSERT INTO ev VALUES (1, '1990-01-01'), (2, '1990-01-01')")
        # open txn provisionally deletes row 1; TTL job runs concurrently
        s.execute("BEGIN")
        s.execute("DELETE FROM ev WHERE id = 1")
        n = instance.archive.archive_older_than(instance, "x", "ev", "d", 20000)
        assert n == 1  # only the undeleted row was archived
        s.execute("ROLLBACK")
        # row 1 is hot exactly once; row 2 visible from the archive
        assert sorted(s.execute("SELECT id FROM ev").rows) == [(1,), (2,)]
