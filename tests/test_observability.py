"""Per-query runtime statistics: QueryProfile threading, EXPLAIN ANALYZE
actual-rows annotations (fused segments included), SHOW FULL STATS /
information_schema surfaces, the metrics registry, web endpoints, and the
no-profiling hot-path dispatch guard.

The `observability`-marked tests are the fast smoke target (`make obs-smoke`).
"""

import json
import threading
import urllib.request

import numpy as np
import pytest

import jax.numpy as jnp

from galaxysql_tpu.chunk.batch import Column, ColumnBatch
from galaxysql_tpu.exec import operators as ops
from galaxysql_tpu.exec.fusion import FusedPipelineOp, FusedSegment
from galaxysql_tpu.exec.operators import SourceOp
from galaxysql_tpu.expr import ir
from galaxysql_tpu.server.instance import Instance
from galaxysql_tpu.server.session import Session
from galaxysql_tpu.types import datatype as dt
from galaxysql_tpu.utils.metrics import MetricsRegistry
from galaxysql_tpu.utils.tracing import SEGMENT_TRACER


@pytest.fixture(scope="module")
def tpch_session():
    from galaxysql_tpu.storage import tpch
    data = tpch.generate(0.01)
    inst = Instance()
    s = Session(inst)
    s.execute("CREATE DATABASE tpch")
    s.execute("USE tpch")
    for t in tpch.TABLE_ORDER:
        s.execute(tpch.TPCH_DDL[t])
        inst.store("tpch", t).insert_pylists(data[t], inst.tso.next_timestamp())
    s.execute("ANALYZE TABLE " + ", ".join(tpch.TABLE_ORDER))
    yield s
    s.close()


def _analyze_lines(s, sql):
    return [r[0] for r in s.execute("EXPLAIN ANALYZE " + sql).rows]


def _top_actual_rows(lines):
    """actual rows= annotation of the tree's root line."""
    import re
    m = re.search(r"actual rows=(\d+)", lines[0])
    assert m, f"root line not annotated: {lines[0]!r}"
    return int(m.group(1))


# -- metrics registry ---------------------------------------------------------


class TestMetricsRegistry:
    def test_counter_gauge_rows_and_prometheus(self):
        reg = MetricsRegistry(namespace="test")
        reg.counter("hits", "cache hits").inc(3)
        reg.gauge("depth", "queue depth").set(2.5)
        rows = {n: (k, v) for n, k, v, _h in reg.rows()}
        assert rows["hits"] == ("counter", 3)
        assert rows["depth"] == ("gauge", 2.5)
        text = reg.prometheus_text()
        assert "# TYPE test_hits counter" in text
        assert "test_hits 3" in text
        assert "test_depth 2.5" in text

    def test_kind_conflict_raises(self):
        reg = MetricsRegistry()
        reg.counter("x")
        with pytest.raises(TypeError):
            reg.gauge("x")

    def test_counter_map_adapter(self):
        reg = MetricsRegistry()
        cm = reg.counter_map("engine")
        cm["mpp_queries"] += 1
        cm["mpp_queries"] += 2
        assert cm["mpp_queries"] == 3
        assert cm.get("missing", 7) == 7
        assert dict(cm) == {"mpp_queries": 3}
        assert ("engine_mpp_queries", "counter", 3) in \
            [(n, k, v) for n, k, v, _ in reg.rows()]


# -- per-query profiles -------------------------------------------------------


@pytest.mark.observability
class TestQueryProfiles:
    @pytest.fixture(scope="class")
    def session(self):
        inst = Instance()
        s = Session(inst)
        s.execute("CREATE DATABASE obs")
        s.execute("USE obs")
        s.execute("CREATE TABLE t (a BIGINT PRIMARY KEY, b BIGINT)")
        inst.store("obs", "t").insert_pylists(
            {"a": list(range(4000)), "b": [i % 11 for i in range(4000)]},
            inst.tso.next_timestamp())
        yield s
        s.close()

    def test_default_path_records_lightweight_profile(self, session):
        inst = session.instance
        r = session.execute("SELECT count(*) FROM t WHERE a < 100")
        p = inst.profiles.entries()[-1]
        assert p.sql.startswith("SELECT count(*)")
        assert not p.profiled and p.op_stats == [] and p.segments == []
        assert p.rows == len(r.rows) == 1
        assert p.elapsed_ms > 0 and p.trace_id > 0
        # trace ids are monotonic across queries
        session.execute("SELECT count(*) FROM t")
        assert inst.profiles.entries()[-1].trace_id > p.trace_id
        # and the session trace links to the profile
        assert f"trace-id {inst.profiles.entries()[-1].trace_id}" in \
            session.last_trace

    def test_profiling_collects_operators_and_segments(self, session):
        inst = session.instance
        session.execute("SET ENABLE_QUERY_PROFILING = 1")
        try:
            r = session.execute("SELECT a, b * 2 FROM t WHERE a < 500")
        finally:
            session.execute("SET ENABLE_QUERY_PROFILING = 0")
        p = inst.profiles.entries()[-1]
        assert p.profiled
        by_op = {st["operator"]: st for st in p.op_stats}
        assert by_op["Scan"]["rows_out"] == 4000
        assert by_op["Filter"]["rows_out"] == 500 and by_op["Filter"]["fused"]
        assert by_op["Project"]["rows_out"] == 500
        assert [sp.chain for sp in p.segments] == ["filter>project"]
        assert p.segments[0].rows_in == 4000 and p.segments[0].rows_out == 500
        assert p.rows == len(r.rows) == 500

    def test_point_path_profiles_and_slow_links(self, session):
        from galaxysql_tpu.utils.tracing import SLOW_LOG
        inst = session.instance
        SLOW_LOG.clear()
        session.execute("SET SLOW_SQL_MS = 0")
        try:
            session.execute("SELECT b FROM t WHERE a = 7")
            session.execute("SELECT b FROM t WHERE a = 7")  # point-plan hit
        finally:
            session.execute("SET SLOW_SQL_MS = -1")
        p = inst.profiles.entries()[-1]
        assert p.engine == "point" and p.workload == "TP"
        # SHOW SLOW rows carry the trace id + workload linking to the profile
        rows = session.execute("SHOW SLOW").rows
        assert any(row[3] == p.trace_id and row[4] == "TP" for row in rows)


# -- MPP per-stage / per-shard stats ------------------------------------------


@pytest.mark.observability
class TestMppStageStats:
    def test_profile_carries_stage_and_shard_rows(self):
        inst = Instance()
        if inst.mesh() is None:
            pytest.skip("single device: no MPP mesh")
        s = Session(inst)
        s.execute("CREATE DATABASE mob; USE mob")
        s.execute("CREATE TABLE big (k VARCHAR(4), v BIGINT)")
        rng = np.random.default_rng(0)
        inst.store("mob", "big").insert_arrays(
            {"k": np.array(["x", "y", "z"])[rng.integers(0, 3, 60_000)],
             "v": rng.integers(0, 1000, 60_000)}, inst.tso.next_timestamp())
        s.execute("ANALYZE TABLE big")
        s.vars["MPP_MIN_AP_ROWS"] = 1000
        s.vars["ENABLE_QUERY_PROFILING"] = True
        r = s.execute("SELECT k, sum(v) FROM big GROUP BY k ORDER BY k")
        assert len(r.rows) == 3
        p = inst.profiles.entries()[-1]
        assert p.engine == "mpp" and p.profiled
        mpp_stats = [st for st in p.op_stats if st.get("engine") == "mpp"]
        assert any(st["operator"] == "Scan" for st in mpp_stats)
        scan = next(st for st in mpp_stats if st["operator"] == "Scan")
        # per-shard task stats: shard-local row counts sum to the scan total
        assert "rows_per_shard" in scan
        assert sum(scan["rows_per_shard"]) == scan["rows_out"] == 60_000
        agg = next(st for st in mpp_stats if st["operator"] == "Aggregate")
        assert agg["rows_out"] == 3 and agg["replicated"]
        s.close()


# -- EXPLAIN ANALYZE ----------------------------------------------------------


@pytest.mark.observability
class TestExplainAnalyze:
    def test_q1_actual_rows_match_resultset(self, tpch_session):
        from galaxysql_tpu.storage.tpch_queries import QUERIES
        s = tpch_session
        rs = s.execute(QUERIES[1])
        lines = _analyze_lines(s, QUERIES[1])
        assert _top_actual_rows(lines) == len(rs.rows)
        # operators INSIDE the fused filter>project chain are annotated
        fused = [l for l in lines if "fused(" in l]
        assert any("Filter" in l and "actual rows=" in l for l in fused)
        assert any("Project" in l and "actual rows=" in l for l in fused)
        assert any(l.startswith("-- segment ") for l in lines)
        assert any("wall=" in l for l in lines)

    def test_q3_actual_rows_match_resultset(self, tpch_session):
        from galaxysql_tpu.storage.tpch_queries import QUERIES
        s = tpch_session
        rs = s.execute(QUERIES[3])
        lines = _analyze_lines(s, QUERIES[3])
        assert _top_actual_rows(lines) == len(rs.rows)
        assert any("Join" in l and "actual rows=" in l for l in lines)

    def test_profile_recorded_for_analyze(self, tpch_session):
        from galaxysql_tpu.storage.tpch_queries import QUERIES
        s = tpch_session
        _analyze_lines(s, QUERIES[1])
        p = s.instance.profiles.entries()[-1]
        assert p.profiled and p.op_stats


# -- SQL surfaces -------------------------------------------------------------


@pytest.mark.observability
class TestSqlSurfaces:
    @pytest.fixture(scope="class")
    def session(self):
        inst = Instance()
        s = Session(inst)
        s.execute("CREATE DATABASE surf")
        s.execute("USE surf")
        s.execute("CREATE TABLE t (a BIGINT)")
        inst.store("surf", "t").insert_pylists(
            {"a": list(range(100))}, inst.tso.next_timestamp())
        yield s
        s.close()

    def test_show_full_stats_lists_profiles(self, session):
        session.execute("SELECT count(*) FROM t")
        r = session.execute("SHOW FULL STATS")
        assert r.names[0] == "Trace_id"
        assert r.rows, "profiles should be retained"
        newest = r.rows[0]
        assert newest[0] == session.instance.profiles.entries()[-1].trace_id
        sql_col = r.names.index("SQL")
        assert newest[sql_col].lower().startswith("show full stats") or \
            "count" in newest[sql_col]
        assert "Max_shard_rows" in r.names  # per-shard skew triage column
        # SHOW STATS (without FULL) stays the instance-counter surface
        plain = session.execute("SHOW STATS")
        assert plain.names == ["Name", "Value"]

    def test_metrics_roundtrip_counter_bump(self, session):
        inst = session.instance
        before_rows = session.execute(
            "SELECT value FROM information_schema.metrics "
            "WHERE metric_name = 'engine_obs_test_bumps'").rows
        before = before_rows[0][0] if before_rows else 0
        inst.counters["obs_test_bumps"] += 3
        r = session.execute(
            "SELECT metric_kind, value FROM information_schema.metrics "
            "WHERE metric_name = 'engine_obs_test_bumps'")
        assert r.rows == [("counter", float(before) + 3.0)]
        # SHOW METRICS renders the same registry
        rows = {row[0]: row[2] for row in session.execute("SHOW METRICS").rows}
        assert rows["engine_obs_test_bumps"] == float(before) + 3.0

    def test_query_stats_virtual_table(self, session):
        session.execute("SELECT count(*) FROM t WHERE a > 5")
        r = session.execute(
            "SELECT trace_id, engine, rows_returned FROM "
            "information_schema.query_stats")
        assert len(r.rows) >= 2
        ids = [row[0] for row in r.rows]
        assert ids == sorted(ids)  # ring order: oldest -> newest


# -- query-scoped segment tracer ----------------------------------------------


@pytest.mark.observability
class TestScopedSegmentTracer:
    def test_two_sessions_do_not_interleave(self):
        """Two sessions profiling concurrently: each QueryProfile holds only
        its own segment spans (the global-ring fallback would interleave)."""
        inst = Instance()
        s0 = Session(inst)
        s0.execute("CREATE DATABASE il")
        s0.execute("USE il")
        s0.execute("CREATE TABLE big (a BIGINT, b BIGINT)")
        s0.execute("CREATE TABLE small (a BIGINT, b BIGINT)")
        inst.store("il", "big").insert_pylists(
            {"a": list(range(3000)), "b": list(range(3000))},
            inst.tso.next_timestamp())
        inst.store("il", "small").insert_pylists(
            {"a": list(range(700)), "b": list(range(700))},
            inst.tso.next_timestamp())

        ring_before = len(SEGMENT_TRACER.spans())
        results = {}
        barrier = threading.Barrier(2)

        def run(name, table, rounds=8):
            s = Session(inst, "il")
            s.vars["ENABLE_QUERY_PROFILING"] = True
            barrier.wait()
            profs = []
            for _ in range(rounds):
                s.execute(f"SELECT a, b + 1 FROM {table} WHERE a >= 0")
                tid = int(s.last_trace[0].split()[-1])  # "trace-id N"
                profs.append(inst.profiles.get(tid))
            results[name] = profs
            s.close()

        t1 = threading.Thread(target=run, args=("big", "big"))
        t2 = threading.Thread(target=run, args=("small", "small"))
        t1.start(); t2.start()
        t1.join(); t2.join()

        for name, expect in (("big", 3000), ("small", 700)):
            for p in results[name]:
                assert p is not None and p.segments, name
                # every span in this query's profile is from ITS table
                assert all(sp.rows_out == expect for sp in p.segments), (
                    name, [(sp.chain, sp.rows_out) for sp in p.segments])
        # scoped sinks bypass the module-level ring entirely
        assert len(SEGMENT_TRACER.spans()) == ring_before

    def test_global_ring_fallback_still_works(self):
        SEGMENT_TRACER.clear()
        SEGMENT_TRACER.enabled = True
        try:
            b = ColumnBatch({"a": Column(jnp.arange(2048), None,
                                         dt.BIGINT, None)}, None)
            seg = FusedSegment([("filter",
                                 ir.call("lt", ir.ColRef("a", dt.BIGINT, None),
                                         ir.lit(100)))])
            seg.run_batch(b)
        finally:
            SEGMENT_TRACER.enabled = False
        assert SEGMENT_TRACER.spans(), "unscoped spans land in the ring"
        SEGMENT_TRACER.clear()


# -- web console --------------------------------------------------------------


@pytest.mark.observability
class TestWebObservability:
    @pytest.fixture(scope="class")
    def console(self):
        from galaxysql_tpu.server.web import WebConsole
        inst = Instance()
        s = Session(inst)
        s.execute("CREATE DATABASE wob")
        s.execute("USE wob")
        s.execute("CREATE TABLE t (a BIGINT)")
        inst.store("wob", "t").insert_pylists(
            {"a": list(range(50))}, inst.tso.next_timestamp())
        s.execute("SELECT count(*) FROM t")
        web = WebConsole(inst)
        port = web.start()
        yield inst, s, port
        web.stop()
        s.close()

    def test_metrics_prometheus_format(self, console):
        _inst, _s, port = console
        req = urllib.request.urlopen(
            f"http://127.0.0.1:{port}/metrics", timeout=10)
        assert req.headers["Content-Type"].startswith("text/plain")
        text = req.read().decode()
        assert "# TYPE galaxysql_queries_total counter" in text
        assert "galaxysql_queries_total" in text
        assert "galaxysql_sessions_active" in text

    def test_query_profile_endpoint(self, console):
        inst, s, port = console
        s.vars["ENABLE_QUERY_PROFILING"] = True
        try:
            s.execute("SELECT a FROM t WHERE a < 10")
        finally:
            s.vars.pop("ENABLE_QUERY_PROFILING", None)
        tid = inst.profiles.entries()[-1].trace_id
        with urllib.request.urlopen(
                f"http://127.0.0.1:{port}/query/{tid}", timeout=10) as r:
            d = json.loads(r.read())
        assert d["trace_id"] == tid and d["profiled"]
        assert d["op_stats"] and all("node_id" not in st
                                     for st in d["op_stats"])
        with pytest.raises(urllib.error.HTTPError):
            urllib.request.urlopen(
                f"http://127.0.0.1:{port}/query/999999999", timeout=10)

    def test_query_stats_listing(self, console):
        inst, _s, port = console
        with urllib.request.urlopen(
                f"http://127.0.0.1:{port}/query-stats", timeout=10) as r:
            d = json.loads(r.read())
        assert d["queries"]
        assert d["queries"][0]["trace_id"] == \
            inst.profiles.entries()[-1].trace_id


# -- hot-path guard: profiling off costs zero extra dispatches ----------------


@pytest.mark.observability
class TestNoProfilingHotPath:
    def test_fused_chain_one_dispatch_per_batch(self):
        """The PR-1 dispatch invariant survives the observability layer: a
        fused filter→project chain still pays exactly ONE streaming dispatch
        per batch when profiling is off (the stats program variant is a
        different cache key, never the default)."""
        rng = np.random.default_rng(3)
        B, n = 8, 1 << 17  # device path (capacity > TP_HOST_ROWS)
        batches = []
        for _ in range(B):
            a = jnp.asarray(rng.integers(0, 1 << 20, n))
            batches.append(ColumnBatch(
                {"a": Column(a, None, dt.BIGINT, None)}, None))
        ca = ir.ColRef("a", dt.BIGINT, None)
        seg = FusedSegment([("filter", ir.call("lt", ca, ir.lit(1 << 19))),
                            ("project", [("c", ir.call("mul", ca,
                                                       ir.lit(2)))])])

        def drain():
            for out in FusedPipelineOp(SourceOp(batches), seg).batches():
                out.live_mask()
        drain()  # warmup: compile
        ops.reset_dispatch_stats()
        drain()
        assert ops.DISPATCH_STATS["dispatches"] == B

    def test_steady_state_dispatches_unchanged_after_profiled_run(self):
        """Profiling a query must not perturb the subsequent non-profiled
        executions (same program cache entries, same dispatch count)."""
        inst = Instance()
        s = Session(inst)
        s.execute("CREATE DATABASE hp")
        s.execute("USE hp")
        s.execute("CREATE TABLE t (a BIGINT, b BIGINT)")
        inst.store("hp", "t").insert_pylists(
            {"a": list(range(3000)), "b": list(range(3000))},
            inst.tso.next_timestamp())
        q = "SELECT a, b * 3 FROM t WHERE a < 1500"
        s.execute(q)  # warmup
        ops.reset_dispatch_stats()
        s.execute(q)
        baseline = ops.DISPATCH_STATS["dispatches"]
        s.vars["ENABLE_QUERY_PROFILING"] = True
        s.execute(q)  # profiled run (may dispatch differently — allowed)
        s.vars.pop("ENABLE_QUERY_PROFILING", None)
        ops.reset_dispatch_stats()
        s.execute(q)
        assert ops.DISPATCH_STATS["dispatches"] == baseline
        s.close()
