"""Memory pools + spill framework."""

import numpy as np
import pytest

from galaxysql_tpu.chunk.batch import batch_from_pydict
from galaxysql_tpu.exec.memory import GLOBAL_POOL, MemoryLimitExceeded, MemoryPool
from galaxysql_tpu.exec.operators import AggCall, HashAggOp, SourceOp, run_to_batch
from galaxysql_tpu.exec.spill import SPILL_MANAGER, Spiller, SpillQuotaExceeded, \
    SpillSpaceManager
from galaxysql_tpu.expr import ir
from galaxysql_tpu.types import datatype as dt


class TestMemoryPool:
    def test_hierarchy_and_limits(self):
        root = MemoryPool("r", 1000)
        q = root.child("q", 600)
        assert q.try_reserve(500)
        assert not q.try_reserve(200)   # child limit
        q2 = root.child("q2", 600)
        assert q2.try_reserve(400)
        assert not q2.try_reserve(200)  # parent limit (500+400+200 > 1000)
        q.release(500)
        assert q2.try_reserve(200)

    def test_revoke_then_raise(self):
        root = MemoryPool("r", 100)
        released = []

        def revoker(n):
            released.append(n)
            root.release(80)
            return 80
        root.add_revoker(revoker)
        root.reserve(90)
        root.reserve(50)   # triggers revoke of 80, then fits
        assert released
        with pytest.raises(MemoryLimitExceeded):
            root.reserve(200)


class TestSpill:
    def test_spiller_roundtrip_and_quota(self, tmp_path):
        mgr = SpillSpaceManager(quota_bytes=1 << 20, directory=str(tmp_path))
        sp = Spiller(mgr)
        arrays = {"a": np.arange(1000), "b": np.ones(1000)}
        sp.spill(arrays)
        got = list(sp.read_all())
        np.testing.assert_array_equal(got[0]["a"], arrays["a"])
        used = mgr.used
        assert used > 0
        sp.close()
        assert mgr.used == 0
        # quota enforcement
        sp2 = Spiller(SpillSpaceManager(quota_bytes=10, directory=str(tmp_path)))
        with pytest.raises(SpillQuotaExceeded):
            sp2.spill({"x": np.arange(100000)})

    def test_agg_spills_and_results_match(self):
        rng = np.random.default_rng(0)
        batches = []
        for i in range(6):
            batches.append(batch_from_pydict(
                {"g": rng.integers(0, 500, 2000).tolist(),
                 "v": rng.integers(0, 100, 2000).tolist()},
                {"g": dt.BIGINT, "v": dt.BIGINT}))
        g = ir.ColRef("g", dt.BIGINT)
        v = ir.ColRef("v", dt.BIGINT)
        aggs = [AggCall("sum", v, "s"), AggCall("count_star", None, "c")]
        normal = HashAggOp(SourceOp(batches), [("g", g)], aggs)
        expected = sorted(run_to_batch(normal).to_pylist())
        spilling = HashAggOp(SourceOp(batches), [("g", g)], aggs,
                             spill_threshold=1)  # force a spill per batch
        got = sorted(run_to_batch(spilling).to_pylist())
        assert spilling.spilled_partials >= 5
        assert got == expected
