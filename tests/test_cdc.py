"""CDC: ordered change log keyed by commit TSO, SHOW BINLOG EVENTS, replay.

Reference analog: `polardbx-server/.../cdc/CdcManager.java:135` — the done bar
is reproducing table state on a fresh instance by replaying the log, including
a consumer crash mid-stream (idempotent resume via the applied watermark).
"""

import pytest

from galaxysql_tpu.server.instance import Instance
from galaxysql_tpu.server.session import Session
from galaxysql_tpu.txn import cdc


DDL = ("CREATE TABLE t (id BIGINT PRIMARY KEY, grp BIGINT, val VARCHAR(16)) "
       "PARTITION BY HASH(id) PARTITIONS 4")


@pytest.fixture()
def session():
    inst = Instance()
    s = Session(inst)
    s.execute("CREATE DATABASE c")
    s.execute("USE c")
    s.execute(DDL)
    yield s
    s.close()


def fresh_target():
    inst = Instance()
    s = Session(inst)
    s.execute("CREATE DATABASE c")
    s.execute("USE c")
    s.execute(DDL)
    return inst, s


def state(s):
    return s.execute("SELECT id, grp, val FROM t ORDER BY id").rows


class TestCdc:
    def test_events_ordered_by_commit_tso(self, session):
        session.execute("INSERT INTO t VALUES (1, 1, 'a'), (2, 2, 'b')")
        session.execute("UPDATE t SET val = 'u' WHERE id = 1")
        session.execute("DELETE FROM t WHERE id = 2")
        rows = session.execute("SHOW BINLOG EVENTS").rows
        # inserts are logged per partition touched; the logical sequence is
        # insert* (first stmt), delete+insert (update), delete (delete)
        kinds = [r[4] for r in rows]
        assert kinds[-3:] == ["delete", "insert", "delete"]
        assert set(kinds[:-3]) == {"insert"}
        tsos = [r[1] for r in rows]
        assert tsos == sorted(tsos)

    def test_txn_events_flush_at_commit_with_one_tso(self, session):
        session.execute("BEGIN")
        session.execute("INSERT INTO t VALUES (10, 1, 'x')")
        session.execute("INSERT INTO t VALUES (11, 1, 'y')")
        # nothing published before commit
        assert session.execute("SHOW BINLOG EVENTS").rows == []
        session.execute("COMMIT")
        rows = session.execute("SHOW BINLOG EVENTS").rows
        assert len(rows) == 2
        assert rows[0][1] == rows[1][1]  # one commit TSO for the whole txn

    def test_rollback_publishes_nothing(self, session):
        session.execute("BEGIN")
        session.execute("INSERT INTO t VALUES (20, 1, 'gone')")
        session.execute("ROLLBACK")
        assert session.execute("SHOW BINLOG EVENTS").rows == []

    def test_replay_reproduces_state(self, session):
        session.execute("INSERT INTO t VALUES (1,1,'a'), (2,2,'b'), (3,3,'c')")
        session.execute("BEGIN")
        session.execute("UPDATE t SET val = 'upd' WHERE id = 2")
        session.execute("INSERT INTO t VALUES (4, 4, 'd')")
        session.execute("COMMIT")
        session.execute("DELETE FROM t WHERE id = 1")
        want = state(session)

        target, ts = fresh_target()
        n = cdc.replay(session.instance.cdc.events(), target)
        assert n > 0
        assert state(ts) == want
        ts.close()

    def test_replay_crash_midstream_resumes_idempotently(self, session):
        session.execute("INSERT INTO t VALUES (1,1,'a'), (2,2,'b'), (3,3,'c')")
        session.execute("UPDATE t SET val = 'u2' WHERE id = 2")
        session.execute("DELETE FROM t WHERE id = 3")
        want = state(session)
        events = session.instance.cdc.events()

        target, ts = fresh_target()
        # consumer crashes after 2 events ...
        n1 = cdc.replay(events, target, stop_after=2)
        assert n1 == 2
        # ... and the full stream is redelivered: watermark skips the applied
        # prefix, no duplicates
        n2 = cdc.replay(events, target)
        assert n2 == len(events) - 2
        assert state(ts) == want
        # a third full redelivery is a no-op
        assert cdc.replay(events, target) == 0
        assert state(ts) == want
        ts.close()

    def test_disable_via_config(self, session):
        session.execute("SET GLOBAL ENABLE_CDC = 0")
        session.execute("INSERT INTO t VALUES (30, 1, 'q')")
        assert session.execute("SHOW BINLOG EVENTS").rows == []
        session.execute("SET GLOBAL ENABLE_CDC = 1")
        session.execute("INSERT INTO t VALUES (31, 1, 'r')")
        assert len(session.execute("SHOW BINLOG EVENTS").rows) == 1
