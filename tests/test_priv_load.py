"""Privileges + LOAD DATA + metadb wire auth."""

import asyncio
import threading

import pytest

from galaxysql_tpu.net.client import MiniClient, MySQLError
from galaxysql_tpu.net.server import MySQLServer
from galaxysql_tpu.server.instance import Instance
from galaxysql_tpu.server.session import Session
from galaxysql_tpu.utils import errors


@pytest.fixture()
def inst():
    return Instance()


class TestPrivileges:
    def test_grant_revoke_enforcement(self, inst):
        root = Session(inst)
        root.execute("CREATE DATABASE shop")
        root.execute("USE shop")
        root.execute("CREATE TABLE t (a BIGINT)")
        root.execute("INSERT INTO t VALUES (1)")
        root.execute("CREATE USER 'bob' IDENTIFIED BY 'pw'")
        root.execute("GRANT SELECT ON shop.* TO 'bob'")

        bob = Session(inst, "shop")
        bob.user = "bob"
        assert bob.execute("SELECT a FROM t").rows == [(1,)]
        with pytest.raises(errors.AccessDeniedError):
            bob.execute("INSERT INTO t VALUES (2)")
        with pytest.raises(errors.AccessDeniedError):
            bob.execute("DROP TABLE t")

        root.execute("GRANT INSERT ON shop.t TO 'bob'")
        assert bob.execute("INSERT INTO t VALUES (2)").affected == 1
        root.execute("REVOKE SELECT ON shop.* FROM 'bob'")
        with pytest.raises(errors.AccessDeniedError):
            bob.execute("SELECT a FROM t")
        root.close()
        bob.close()

    def test_wire_auth_against_metadb(self, inst):
        root = Session(inst)
        root.execute("CREATE USER 'carol' IDENTIFIED BY 'secret'")
        root.close()
        srv = MySQLServer(inst, port=0, users=None)  # metadb-backed auth
        loop = asyncio.new_event_loop()
        started = threading.Event()

        def run():
            asyncio.set_event_loop(loop)
            loop.run_until_complete(srv.start())
            started.set()
            loop.run_forever()
        t = threading.Thread(target=run, daemon=True)
        t.start()
        started.wait(10)
        try:
            c = MiniClient("127.0.0.1", srv.port, user="carol", password="secret")
            assert c.ping()
            c.close()
            with pytest.raises(MySQLError):
                MiniClient("127.0.0.1", srv.port, user="carol", password="nope")
            c2 = MiniClient("127.0.0.1", srv.port)  # root, empty password
            assert c2.ping()
            c2.close()
        finally:
            loop.call_soon_threadsafe(loop.stop)


class TestLoadData:
    def test_csv_ingestion(self, inst, tmp_path):
        s = Session(inst)
        s.execute("CREATE DATABASE l; USE l")
        s.execute("CREATE TABLE t (id BIGINT, name VARCHAR(20), amt DECIMAL(10,2)) "
                  "PARTITION BY HASH(id) PARTITIONS 4")
        p = tmp_path / "data.csv"
        p.write_text("id,name,amt\n1,ann,3.50\n2,bob,4.25\n3,,\n")
        r = s.execute(f"LOAD DATA INFILE '{p}' INTO TABLE t "
                      f"FIELDS TERMINATED BY ',' IGNORE 1 LINES (id, name, amt)")
        assert r.affected == 3
        rows = s.execute("SELECT id, name, amt FROM t ORDER BY id").rows
        assert rows == [(1, "ann", 3.5), (2, "bob", 4.25), (3, None, None)]
        s.close()


class TestAuthzRegressions:
    def test_cross_schema_select_checked(self, inst):
        root = Session(inst)
        root.execute("CREATE DATABASE a; CREATE DATABASE b")
        root.execute("USE b; CREATE TABLE secret (x BIGINT)")
        root.execute("CREATE USER 'eve'")
        root.execute("GRANT SELECT ON a.* TO 'eve'")
        eve = Session(inst, "a")
        eve.user = "eve"
        with pytest.raises(errors.AccessDeniedError):
            eve.execute("SELECT x FROM b.secret")
        with pytest.raises(errors.AccessDeniedError):
            eve.execute("DROP TABLE b.secret")
        root.close(); eve.close()

    def test_table_scoped_select_grant_works(self, inst):
        root = Session(inst)
        root.execute("CREATE DATABASE a; USE a")
        root.execute("CREATE TABLE t1 (x BIGINT); CREATE TABLE t2 (x BIGINT)")
        root.execute("INSERT INTO t1 VALUES (1)")
        root.execute("CREATE USER 'tom'")
        root.execute("GRANT SELECT ON a.t1 TO 'tom'")
        tom = Session(inst, "a")
        tom.user = "tom"
        assert tom.execute("SELECT x FROM t1").rows == [(1,)]
        with pytest.raises(errors.AccessDeniedError):
            tom.execute("SELECT x FROM t2")
        root.close(); tom.close()

    def test_user_admin_requires_super(self, inst):
        root = Session(inst)
        root.execute("CREATE USER 'carl'")
        root.execute("GRANT CREATE ON *.* TO 'carl'")
        carl = Session(inst)
        carl.user = "carl"
        with pytest.raises(errors.AccessDeniedError):
            carl.execute("CREATE USER 'mallory'")
        with pytest.raises(errors.AccessDeniedError):
            carl.execute("GRANT ALL ON *.* TO 'carl'")  # escalation blocked
        with pytest.raises(errors.AccessDeniedError):
            carl.execute("DROP USER 'carl'")
        root.close(); carl.close()

    def test_user_at_host_syntax(self, inst):
        root = Session(inst)
        root.execute("CREATE USER 'hh'@'localhost' IDENTIFIED BY 'p'")
        root.execute("GRANT SELECT ON *.* TO 'hh'@'%'")
        assert inst.privileges.has_privilege("hh", "SELECT", "x")
        root.close()
