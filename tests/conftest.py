"""Test harness: force an 8-virtual-device CPU backend before JAX initializes.

Mirrors the reference's strategy of testing cluster behavior without a cluster
(SURVEY.md §4: LocalServer / mock connections): shard_map/pjit paths run on
xla_force_host_platform_device_count=8 virtual devices.
"""

import os

flags = os.environ.get("XLA_FLAGS", "")
if "xla_force_host_platform_device_count" not in flags:
    os.environ["XLA_FLAGS"] = (flags + " --xla_force_host_platform_device_count=8").strip()
os.environ["JAX_PLATFORMS"] = "cpu"

import jax

jax.config.update("jax_platforms", "cpu")
jax.config.update("jax_enable_x64", True)

import pytest


@pytest.fixture(autouse=True, scope="module")
def _clear_compiled_caches():
    """Free compiled XLA programs between test modules.

    The full suite compiles thousands of kernels; XLA:CPU's compiler has been
    observed to segfault late in the run under that accumulated state.  Dropping
    the process-wide jit caches (ours + jax's) at module boundaries keeps the
    live-executable population bounded without changing any test's behavior
    (first query of each module recompiles)."""
    yield
    from galaxysql_tpu.exec import operators as _ops
    with _ops._JIT_CACHE_LOCK:
        _ops._JIT_CACHE.clear()
    from galaxysql_tpu.exec.device_cache import GLOBAL_DEVICE_CACHE
    GLOBAL_DEVICE_CACHE.clear()
    from galaxysql_tpu.parallel.mesh import GLOBAL_MESH_CACHE
    with GLOBAL_MESH_CACHE._lock:
        GLOBAL_MESH_CACHE._map.clear()
    jax.clear_caches()
