"""Test harness: force an 8-virtual-device CPU backend before JAX initializes.

Mirrors the reference's strategy of testing cluster behavior without a cluster
(SURVEY.md §4: LocalServer / mock connections): shard_map/pjit paths run on
xla_force_host_platform_device_count=8 virtual devices.
"""

import os

flags = os.environ.get("XLA_FLAGS", "")
if "xla_force_host_platform_device_count" not in flags:
    os.environ["XLA_FLAGS"] = (flags + " --xla_force_host_platform_device_count=8").strip()
os.environ["JAX_PLATFORMS"] = "cpu"

import jax

jax.config.update("jax_platforms", "cpu")
jax.config.update("jax_enable_x64", True)
