"""Regressions for code-review findings on the core engine."""

import numpy as np

from galaxysql_tpu.chunk.batch import batch_from_pydict
from galaxysql_tpu.exec.operators import (AggCall, FilterOp, HashAggOp, HashJoinOp,
                                          ProjectOp, SourceOp, run_to_batch)
from galaxysql_tpu.expr import ir
from galaxysql_tpu.expr.compiler import ExprCompiler, batch_env
from galaxysql_tpu.types import datatype as dt


def col(batch, name):
    c = batch.columns[name]
    return ir.ColRef(name, c.dtype, c.dictionary)


def _eval(expr, batch):
    import jax.numpy as jnp
    d, v = ExprCompiler(jnp).compile(expr)(batch_env(batch))
    vm = None if v is None else np.asarray(v)
    return np.asarray(d), vm


class TestJoinResidual:
    def make(self):
        build = batch_from_pydict({"o_key": [1], "o_val": [100]},
                                  {"o_key": dt.BIGINT, "o_val": dt.BIGINT})
        probe = batch_from_pydict({"l_okey": [1, 2], "l_qty": [5, 6]},
                                  {"l_okey": dt.BIGINT, "l_qty": dt.BIGINT})
        return build, probe

    def test_left_join_residual_null_extends(self):
        build, probe = self.make()
        residual = ir.call("gt", ir.ColRef("o_val", dt.BIGINT), ir.lit(1000))
        op = HashJoinOp(SourceOp([build]), SourceOp([probe]),
                        [ir.ColRef("o_key", dt.BIGINT)], [ir.ColRef("l_okey", dt.BIGINT)],
                        "left", residual=residual)
        out = run_to_batch(op).to_pydict()
        rows = sorted(zip(out["l_qty"], out["o_val"]))
        # all matches fail the residual -> BOTH probe rows null-extended
        assert rows == [(5, None), (6, None)]

    def test_semi_join_residual(self):
        build, probe = self.make()
        residual = ir.call("gt", ir.ColRef("o_val", dt.BIGINT), ir.lit(1000))
        op = HashJoinOp(SourceOp([build]), SourceOp([probe]),
                        [ir.ColRef("o_key", dt.BIGINT)], [ir.ColRef("l_okey", dt.BIGINT)],
                        "semi", residual=residual)
        assert run_to_batch(op).to_pylist() == []

    def test_anti_join_residual(self):
        build, probe = self.make()
        residual = ir.call("gt", ir.ColRef("o_val", dt.BIGINT), ir.lit(1000))
        op = HashJoinOp(SourceOp([build]), SourceOp([probe]),
                        [ir.ColRef("o_key", dt.BIGINT)], [ir.ColRef("l_okey", dt.BIGINT)],
                        "anti", residual=residual)
        out = run_to_batch(op).to_pydict()
        assert sorted(out["l_qty"]) == [5, 6]


class TestStringOrderingBoundary:
    def test_absent_literal_le_gt(self):
        b = batch_from_pydict({"s": ["a", "c"]}, {"s": dt.VARCHAR})
        d, v = _eval(ir.call("le", col(b, "s"), ir.lit("b")), b)
        assert d.tolist() == [True, False]
        d, v = _eval(ir.call("gt", col(b, "s"), ir.lit("b")), b)
        assert d.tolist() == [False, True]
        d, v = _eval(ir.call("lt", col(b, "s"), ir.lit("b")), b)
        assert d.tolist() == [True, False]
        d, v = _eval(ir.call("ge", col(b, "s"), ir.lit("b")), b)
        assert d.tolist() == [False, True]

    def test_literal_on_left(self):
        b = batch_from_pydict({"s": ["a", "c"]}, {"s": dt.VARCHAR})
        # 'b' <= s  ==  s >= 'b'
        d, v = _eval(ir.call("le", ir.lit("b"), col(b, "s")), b)
        assert d.tolist() == [False, True]


class TestModSemantics:
    def test_mod_sign_of_dividend(self):
        b = batch_from_pydict({"a": [-5, 5, -5, 5], "b": [3, -3, -3, 3]},
                              {"a": dt.BIGINT, "b": dt.BIGINT})
        d, v = _eval(ir.call("mod", col(b, "a"), col(b, "b")), b)
        assert d.tolist() == [-2, 2, -2, 2]

    def test_decimal_mod(self):
        b = batch_from_pydict({"a": [-5.5], "b": [3.0]},
                              {"a": dt.decimal(10, 2), "b": dt.decimal(10, 2)})
        d, v = _eval(ir.call("mod", col(b, "a"), col(b, "b")), b)
        assert d.tolist() == [-250]  # -2.50


class TestDatetimeMonths:
    def test_add_months_keeps_time(self):
        b = batch_from_pydict({"t": ["2020-01-15 10:30:00"]}, {"t": dt.DATETIME})
        e = ir.call("date_add_months", col(b, "t"), ir.lit(1))
        d, v = _eval(e, b)
        from galaxysql_tpu.types import temporal
        assert temporal.format_datetime(int(d[0])) == "2020-02-15 10:30:00"


class TestNullLiteralProject:
    def test_add_null_literal(self):
        b = batch_from_pydict({"a": [1, 2, 3]}, {"a": dt.BIGINT})
        e = ir.call("add", col(b, "a"), ir.lit(None, dt.BIGINT))
        op = ProjectOp(SourceOp([b]), [("x", e)])
        out = run_to_batch(op).to_pydict()
        assert out["x"] == [None, None, None]

    def test_in_list_with_null(self):
        b = batch_from_pydict({"a": [1, 2, 3]}, {"a": dt.BIGINT})
        e = ir.InList(col(b, "a"), (1, None), False)
        d, v = _eval(e, b)
        assert d[0] and v[0]          # 1 IN (1, NULL) -> TRUE
        assert not v[1] and not v[2]  # 2 IN (1, NULL) -> NULL


class TestRound2Findings:
    def test_decimal_times_float_literal(self):
        b = batch_from_pydict({"p": [1.50, 2.25]}, {"p": dt.decimal(15, 2)})
        e = ir.call("mul", col(b, "p"), ir.lit(2.0))
        d, v = _eval(e, b)
        np.testing.assert_allclose(d, [3.0, 4.5], rtol=1e-6)

    def test_min_max_string_collation(self):
        b = batch_from_pydict({"g": [1, 1, 1], "s": ["zebra", "apple", "mango"]},
                              {"g": dt.BIGINT, "s": dt.VARCHAR})
        op = HashAggOp(SourceOp([b]), [("g", col(b, "g"))],
                       [AggCall("min", col(b, "s"), "mn"),
                        AggCall("max", col(b, "s"), "mx")])
        out = run_to_batch(op).to_pydict()
        assert out["mn"] == ["apple"] and out["mx"] == ["zebra"]

    def test_coalesce_priority(self):
        b = batch_from_pydict({"a": [None, 10], "x": [1, 2]},
                              {"a": dt.BIGINT, "x": dt.BIGINT})
        e = ir.call("coalesce", col(b, "a"), col(b, "x"), ir.lit(0))
        d, v = _eval(e, b)
        assert d.tolist() == [1, 10]

    def test_numeric_plus_datetime(self):
        b = batch_from_pydict({"t": ["2024-01-01 00:00:00"]}, {"t": dt.DATETIME})
        e = ir.call("add", ir.lit(3), col(b, "t"))
        d, v = _eval(e, b)
        from galaxysql_tpu.types import temporal
        assert temporal.format_datetime(int(d[0])) == "2024-01-04 00:00:00"

    def test_cast_float_to_int_rounds(self):
        from galaxysql_tpu.expr.ir import Cast
        b = batch_from_pydict({"f": [1.7, -1.7, 1.2]}, {"f": dt.DOUBLE})
        d, v = _eval(Cast(col(b, "f"), dt.BIGINT), b)
        assert d.tolist() == [2, -2, 1]

    def test_left_join_empty_build_keeps_schema(self):
        build = batch_from_pydict({"k": [], "v": []}, {"k": dt.BIGINT, "v": dt.BIGINT})
        probe = batch_from_pydict({"pk": [1, 2]}, {"pk": dt.BIGINT})
        op = HashJoinOp(SourceOp([build]), SourceOp([probe]),
                        [ir.ColRef("k", dt.BIGINT)], [ir.ColRef("pk", dt.BIGINT)], "left",
                        build_schema={"k": (dt.BIGINT, None), "v": (dt.BIGINT, None)})
        out = run_to_batch(op).to_pydict()
        assert sorted(out.keys()) == ["k", "pk", "v"]
        assert out["v"] == [None, None] and sorted(out["pk"]) == [1, 2]


class TestRound3Findings:
    def test_max_with_nulls_in_group(self):
        b = batch_from_pydict({"g": [1, 1, 1, 2], "x": [5, 7, None, None]},
                              {"g": dt.BIGINT, "x": dt.BIGINT})
        op = HashAggOp(SourceOp([b]), [("g", col(b, "g"))],
                       [AggCall("max", col(b, "x"), "mx"),
                        AggCall("min", col(b, "x"), "mn")])
        out = run_to_batch(op).to_pydict()
        m = dict(zip(out["g"], zip(out["mx"], out["mn"])))
        assert m[1] == (7, 5)
        assert m[2] == (None, None)  # all-NULL group

    def test_distinct_dict_transforms_not_merged(self):
        from galaxysql_tpu.plan.binder import Binder
        from galaxysql_tpu.sql import ast as A
        from galaxysql_tpu.meta.catalog import Catalog, ColumnMeta, TableMeta
        # upper(s) and lower(s) must have different expression keys
        import numpy as np
        from galaxysql_tpu.chunk.batch import Dictionary
        d = Dictionary(["Ab", "cD"])
        cref = ir.ColRef("s", dt.VARCHAR, d)
        up = ir.Call("dict_transform", [cref], dt.VARCHAR)
        up.dictionary = Dictionary(["AB", "CD"])
        up.meta = (np.array([0, 1], dtype=np.int32),)
        lo = ir.Call("dict_transform", [cref], dt.VARCHAR)
        lo.dictionary = Dictionary(["ab", "cd"])
        lo.meta = (np.array([0, 1], dtype=np.int32),)
        assert up.key() != lo.key()

    def test_source_op_accepts_generator(self):
        b = batch_from_pydict({"g": list(range(100)), "v": list(range(100))},
                              {"g": dt.BIGINT, "v": dt.BIGINT})
        gen = (x for x in [b])
        # max_groups=... power of two floor below 100 forces an overflow retry,
        # which re-iterates the (materialized) source
        op = HashAggOp(SourceOp(gen), [("g", col(b, "g"))],
                       [AggCall("count_star", None, "c")], max_groups=64)
        out = run_to_batch(op).to_pydict()
        assert len(out["g"]) == 100
