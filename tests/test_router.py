"""Serving-tier tests: the front router (server/router.py), cluster-wide
admission gossip, physical placement bindings, SHOW COORDINATORS / SHOW
CLUSTER surfaces, the hatch trio, and coordinator-kill chaos.

Covered event kinds: coordinator_joined / coordinator_left (journal
round-trips below keep galaxylint's event-untested rule green).
Covered metrics: router_routed_queries, affinity_hits, affinity_misses,
router_failovers, gossip_staleness_ms.
"""

import os
import subprocess
import sys
import time

import pytest

from galaxysql_tpu.server.instance import Instance
from galaxysql_tpu.server.router import (FrontRouter, InprocPeer,
                                         RouterSession)
from galaxysql_tpu.server.session import Session
from galaxysql_tpu.utils import errors
from galaxysql_tpu.utils.events import EVENTS

pytestmark = pytest.mark.router


def _seed(inst, tables=("t",)):
    s = Session(inst)
    s.execute("CREATE DATABASE d")
    s.execute("USE d")
    for t in tables:
        s.execute(f"CREATE TABLE {t} (k BIGINT PRIMARY KEY, v BIGINT)")
        s.execute(f"INSERT INTO {t} VALUES (1, 10), (2, 20), (3, 30)")
    return s


@pytest.fixture()
def tier():
    """A 3-peer in-process serving tier: local + two inproc peers."""
    a = Instance()
    sa = _seed(a)
    router = FrontRouter(a)
    peers = []
    for _ in range(2):
        b = Instance()
        _seed(b).close()
        p = InprocPeer(b)
        router.add_peer(p)
        peers.append(p)
    yield a, router, peers
    router.close()
    sa.close()


class TestRing:
    def test_digest_routing_is_stable_and_spreads(self, tier):
        a, router, _ = tier
        owners = {}
        for i in range(64):
            d = f"digest-{i}"
            owners[d] = router.ring_owner(d)
            # stability: the same digest always lands on the same peer
            assert router.ring_owner(d) == owners[d]
        # spread: 64 digests over 3 peers must touch every peer
        assert len(set(owners.values())) == 3

    def test_routed_statements_follow_the_ring(self, tier):
        a, router, _ = tier
        s = RouterSession(router, schema="d")
        for q in ["select 1", "select 2", "select 1 + 1", "select 9"]:
            s.execute(q)
        assert router.m_routed.value == 4
        # undisturbed tier: every statement lands on its affine peer
        assert router.m_hits.value == 4
        assert router.m_misses.value == 0
        total = sum(router.affinity_of(n)[0] for n in router.peers)
        assert total == 4
        s.close()

    def test_down_peer_is_skipped_and_counted_as_miss(self, tier):
        a, router, peers = tier
        s = RouterSession(router, schema="d")
        # suppress inline gossip: the STATEMENT must discover the death
        # (with gossip on, the map heals before any statement pays it)
        router._gossip_at = float("inf")
        peers[0].down = True
        h0, m0, f0 = (router.m_hits.value, router.m_misses.value,
                      router.m_failovers.value)
        for i in range(24):
            # distinct aliases -> distinct digests (literals parameterize
            # away, so a bare `select N` is ONE statement shape)
            assert s.execute(f"select {i} * 3 as c{i}").rows  # all succeed
        # at least one statement was owned by the dead peer and re-routed
        # WITHIN the statement (failover counter), surfacing as a miss
        assert router.m_failovers.value > f0
        assert router.m_misses.value > m0
        s.close()


class TestSessionAffinity:
    def test_begin_pins_and_commit_keeps_pin(self, tier):
        a, router, _ = tier
        s = RouterSession(router, schema="d")
        s.execute("begin")
        assert s.pinned is not None
        pinned = s.pinned
        s.execute("select k from t where k = 1")
        s.execute("commit")
        assert s.pinned == pinned  # temp/session state may outlive the txn
        s.close()

    def test_set_session_pins_but_set_global_does_not(self, tier):
        a, router, _ = tier
        s = RouterSession(router, schema="d")
        s.execute("select 1")
        assert s.pinned is None
        s2 = RouterSession(router, schema="d")
        s2.execute("SET GLOBAL SLOW_SQL_MS = 1234")  # metadb-persisted
        assert s2.pinned is None
        s2.execute("SET autocommit = 1")  # peer-resident session state
        assert s2.pinned is not None
        s.close()
        s2.close()

    def test_pinned_peer_death_fails_typed_exactly_once(self, tier):
        a, router, peers = tier
        s = RouterSession(router, schema="d")
        s.execute("begin")
        peer = router.peers[s.pinned]
        if isinstance(peer, InprocPeer):
            peer.down = True
        with pytest.raises(errors.CoordinatorUnavailableError) as ei:
            s.execute("select k from t where k = 1")
        assert ei.value.errno == 9004
        assert s.pinned is None  # unpinned: the next statement re-routes
        assert s.execute("select k from t where k = 2").rows == [(2,)]
        s.close()


class TestClusterAdmission:
    def test_gossip_exchanges_admission_snapshots(self, tier):
        a, router, peers = tier
        router.gossip_tick()
        nodes = {n for n, _snap, _age in a.admission.peer_gossip_rows()}
        assert {p.node_id for p in peers} <= nodes

    def test_peer_clamp_governs_local_admission(self, tier):
        a, router, peers = tier
        # peer B reports a flood-shed clamp: B's AIMD limit collapsed to 4
        snap = peers[0].instance.admission.cluster_snapshot()
        snap["tp"]["limit"] = 4.0
        a.admission.note_peer(peers[0].node_id, snap)
        assert a.admission.effective_limit("TP") == 4.0
        # local AIMD limit itself is untouched (recovery stays local)
        assert a.admission.limit("TP") > 4.0
        # the clamp expires with gossip freshness: a stale snapshot must
        # not throttle the tier forever
        old = (snap, time.time() - 3600.0)
        a.admission._peer_snaps[peers[0].node_id] = old
        a.admission._cluster_expire = 0.0
        assert a.admission.effective_limit("TP") == a.admission.limit("TP")

    def test_detach_forgets_peer_state(self, tier):
        a, router, peers = tier
        router.gossip_tick()
        node = peers[1].node_id
        assert any(n == node for n, _s, _a in a.admission.peer_gossip_rows())
        router.remove_peer(node)
        assert not any(n == node
                       for n, _s, _a in a.admission.peer_gossip_rows())
        assert node not in router.peers

    def test_effective_limit_hatch(self, tier):
        a, router, peers = tier
        snap = peers[0].instance.admission.cluster_snapshot()
        snap["tp"]["limit"] = 2.0
        a.admission.note_peer(peers[0].node_id, snap)
        a.config.set_instance("ENABLE_CLUSTER_ADMISSION", 0)
        try:
            assert a.admission.effective_limit("TP") == \
                a.admission.limit("TP")
        finally:
            a.config.set_instance("ENABLE_CLUSTER_ADMISSION", 1)
        assert a.admission.effective_limit("TP") == 2.0


class TestPlacement:
    def test_bind_persists_and_merges(self, tier):
        a, router, peers = tier
        a.placement.bind("g0", endpoint="127.0.0.1:9999")
        a.placement.bind("g0", coordinator=peers[0].node_id)
        ent = a.placement.binding("g0")
        assert ent["endpoint"] == "127.0.0.1:9999"  # merge kept it
        assert ent["coordinator"] == peers[0].node_id
        rows = a.placement.rows()
        assert ("g0", "127.0.0.1:9999", peers[0].node_id, "") in rows
        a.placement.unbind("g0")
        assert a.placement.binding("g0") is None

    def test_bound_coordinator_jumps_the_ring(self, tier):
        a, router, peers = tier
        sql = "select v from t where k = 1"
        a.placement.bind("g0", coordinator=peers[1].node_id)
        a.placement._cache_at = 0.0
        target = router.targets_for("any-digest", sql, "d")[0]
        assert target is peers[1]
        # routed there = an affinity HIT (placement is the preference)
        s = RouterSession(router, schema="d")
        h0 = router.m_hits.value
        s.execute(sql)
        assert router.m_hits.value == h0 + 1
        a.placement.unbind("g0")
        s.close()

    def test_preferred_endpoint_parses_addr(self, tier):
        a, _router, _peers = tier
        a.placement.bind("g0", endpoint="10.0.0.7:4406")
        tm = a.catalog.table("d", "t")
        assert a.placement.preferred_endpoint(tm) == ("10.0.0.7", 4406)
        a.placement.bind("g0", endpoint="bogus")
        a.placement._cache_at = 0.0
        assert a.placement.preferred_endpoint(tm) is None
        a.placement.unbind("g0")


class TestShowSurfaces:
    def test_show_coordinators(self, tier):
        a, router, peers = tier
        s = Session(a, schema="d")
        rs = s.execute("SHOW COORDINATORS")
        assert rs.names[0] == "Node"
        by_node = {r[0]: r for r in rs.rows}
        assert by_node[a.node_id][1] == "local"
        for p in peers:
            assert by_node[p.node_id][1] == "peer"
            assert by_node[p.node_id][2] == "OK"
        peers[0].down = True
        rs = s.execute("SHOW COORDINATORS")
        by_node = {r[0]: r for r in rs.rows}
        assert by_node[peers[0].node_id][2] == "UNREACHABLE"
        peers[0].down = False
        s.close()

    def test_show_cluster_statement_summary_merges_peers(self, tier):
        a, router, peers = tier
        rsess = RouterSession(router, schema="d")
        for q in ["select k from t where k = 1", "select v from t",
                  "select 41 + 1"]:
            rsess.execute(q)
        s = Session(a, schema="d")
        rs = s.execute("SHOW CLUSTER STATEMENT SUMMARY")
        assert rs.names[0] == "Node"
        nodes = {r[0] for r in rs.rows}
        assert len(nodes) >= 2  # local + at least one peer answered
        rsess.close()
        s.close()

    def test_show_cluster_metrics_and_unreachable_rows(self, tier):
        a, router, peers = tier
        s = Session(a, schema="d")
        rs = s.execute("SHOW CLUSTER METRICS")
        names = {(r[0], r[1]) for r in rs.rows}
        assert (a.node_id, "router_routed_queries") in names
        assert (a.node_id, "affinity_hits") in names
        assert (a.node_id, "affinity_misses") in names
        assert (a.node_id, "gossip_staleness_ms") in names
        assert (a.node_id, "router_failovers") in names
        peers[0].down = True
        rs = s.execute("SHOW CLUSTER METRICS")
        dead = [r for r in rs.rows if r[0] == peers[0].node_id]
        assert dead and dead[0][1] == "UNREACHABLE"  # a row, not an error
        rs = s.execute("SHOW CLUSTER STATEMENT SUMMARY")
        dead = [r for r in rs.rows if r[0] == peers[0].node_id]
        assert dead and dead[0][1] == "UNREACHABLE"
        peers[0].down = False
        s.close()

    def test_information_schema_coordinators(self, tier):
        a, router, peers = tier
        router.gossip_tick()
        s = Session(a, schema="d")
        rs = s.execute("SELECT node_id, role, state FROM "
                       "information_schema.coordinators ORDER BY role")
        nodes = {r[0] for r in rs.rows}
        assert a.node_id in nodes
        for p in peers:
            assert p.node_id in nodes
        s.close()

    def test_join_and_leave_events_journal(self, tier):
        a, router, peers = tier
        kinds = [e.kind for e in EVENTS.entries()]
        assert "coordinator_joined" in kinds
        router.remove_peer(peers[1].node_id, reason="test detach")
        kinds = [e.kind for e in EVENTS.entries()]
        assert "coordinator_left" in kinds


class TestHatchTrio:
    def test_param_hatch_is_structurally_off_path(self, tier):
        """ENABLE_ROUTER=0: bit-identical local execution with ZERO routed
        statements (the dispatch-count guard)."""
        a, router, _ = tier
        a.config.set_instance("ENABLE_ROUTER", 0)
        try:
            routed0 = router.m_routed.value
            s = RouterSession(router, schema="d")
            plain = Session(a, schema="d")
            for q in ["select k, v from t order by k",
                      "select v from t where k = 2"]:
                assert s.execute(q).rows == plain.execute(q).rows
            assert router.m_routed.value == routed0  # structurally off-path
            s.close()
            plain.close()
        finally:
            a.config.set_instance("ENABLE_ROUTER", 1)

    def test_env_hatch(self, tier, monkeypatch):
        from galaxysql_tpu.server import router as router_mod
        a, router, _ = tier
        monkeypatch.setattr(router_mod, "ENABLED", False)  # GALAXYSQL_ROUTER=0
        routed0 = router.m_routed.value
        s = RouterSession(router, schema="d")
        assert s.execute("select k from t where k = 3").rows == [(3,)]
        assert router.m_routed.value == routed0
        s.close()

    def test_env_hatch_reads_environment(self):
        """The module-level hatch mirrors GALAXYSQL_ROUTER=0 at import."""
        out = subprocess.run(
            [sys.executable, "-c",
             "from galaxysql_tpu.server import router; print(router.ENABLED)"],
            env=dict(os.environ, GALAXYSQL_ROUTER="0", JAX_PLATFORMS="cpu"),
            capture_output=True, text=True, timeout=120)
        assert out.stdout.strip() == "False"


class TestGossipTransport:
    def test_rpc_failpoint_marks_peer_down_and_gossip_revives(self, tier):
        """FP_RPC_* rides the coordinator gossip plane: a dropped sync
        reply marks the peer down; the next clean tick revives it."""
        a, router, peers = tier

        orig = peers[0].sync_action

        class _Flaky:
            fail = True

            def sync_action(self, action, payload):
                if self.fail:
                    raise ConnectionError("injected drop")
                return orig(action, payload)

        flaky = _Flaky()
        peers[0].sync_action = flaky.sync_action
        try:
            router.gossip_tick()
            assert peers[0].down_until > time.time()
            flaky.fail = False
            router.gossip_tick()
            assert peers[0].down_until == 0.0  # revived
        finally:
            peers[0].sync_action = orig


@pytest.mark.slow
class TestCoordinatorKillChaos:
    """The failover chaos proof over REAL subprocess coordinators: kill one
    mid-load — sticky sessions fail typed exactly once, stateless
    statements re-route within the statement, the affinity map heals, and
    every acked write on the shared store survives."""

    def _spawn(self, data_dir):
        env = dict(os.environ, JAX_PLATFORMS="cpu")
        p = subprocess.Popen(
            [sys.executable, "-m", "galaxysql_tpu.net.server", "--port",
             "0", "--sync-port", "0", "--data-dir", data_dir,
             "--platform", "cpu", "--announce"],
            cwd=os.path.dirname(os.path.dirname(os.path.abspath(__file__))),
            stdout=subprocess.PIPE, stderr=subprocess.DEVNULL, env=env,
            text=True)
        line = p.stdout.readline()
        assert line.startswith("SERVER_READY"), line
        _, mysql_port, sync_port = line.split()
        return p, int(mysql_port), int(sync_port)

    def test_kill_coordinator_mid_load(self, tmp_path):
        data_dir = str(tmp_path / "shared")
        seed = Instance(data_dir=data_dir)
        s = _seed(seed)
        s.execute("CREATE TABLE acked (k BIGINT PRIMARY KEY, v BIGINT)")
        seed.save()
        s.close()

        procs = [self._spawn(data_dir) for _ in range(2)]
        hub = Instance(boot=False)
        router = FrontRouter(hub)
        router.local.down_until = float("inf")  # hub routes, never serves
        try:
            remotes = [router.add_remote("127.0.0.1", mp, sp)
                       for _p, mp, sp in procs]
            rsess = RouterSession(router, schema="d")
            # the acked inserts share ONE digest (literals strip), so one
            # ring owner serves them all; the doomed peer is the OTHER one
            # -- acked writes must outlive the kill on the surviving owner
            from galaxysql_tpu.sql.parameterize import parameterize
            from galaxysql_tpu.meta.statement_summary import digest_key
            ins_digest = digest_key(
                "d", parameterize("insert into acked values (1, 1)").cache_key)
            owner_node = router.targets_for(
                ins_digest, "insert into acked values (1, 1)", "d")[0].node_id
            victim_idx = next(i for i, r in enumerate(remotes)
                              if r.node_id != owner_node)
            victim_node = remotes[victim_idx].node_id
            # sticky session pinned to the doomed peer: pin statements carry
            # distinct digests (var names survive parameterize), so one of
            # them lands on the victim through the REAL pin path
            sticky = None
            for i in range(16):
                cand = RouterSession(router, schema="d")
                cand.execute("begin" if i == 0 else f"set @pin{i} = 1")
                if cand.pinned == victim_node:
                    sticky = cand
                    break
                cand.close()
            assert sticky is not None, "no pin statement landed on the victim"
            # acked writes BEFORE the kill, through the router, onto the
            # surviving digest owner
            for k in range(1, 6):
                rsess.execute(f"insert into acked values ({k}, {k})")
            procs[victim_idx][0].kill()
            procs[victim_idx][0].wait()
            # sticky statement: typed failure EXACTLY ONCE...
            with pytest.raises(errors.CoordinatorUnavailableError):
                sticky.execute("select k from t where k = 1")
            # ...then the session unpins and serves again
            assert sticky.execute("select k from t where k = 1").rows
            # stateless statements re-route WITHIN the statement: no
            # client-visible error even when the ring prefers the corpse
            for i in range(12):
                assert rsess.execute(f"select v from t where k = "
                                     f"{1 + i % 3}").rows
            # affinity map healed: the dead peer serves nothing now
            routed_dead = router.affinity_of(victim_node)[0]
            for i in range(6):
                rsess.execute(f"select k + {i} from t where k = 1")
            assert router.affinity_of(victim_node)[0] == routed_dead
            # zero lost acked writes: every acked row is still readable
            survivor = remotes[1 - victim_idx]
            sess = survivor.open_session("d")
            rs = survivor.execute(sess, "select count(*) from acked")
            assert [tuple(map(int, r)) for r in rs.rows] == [(5,)]
            survivor.close_session(sess)
            sticky.close()
            rsess.close()
        finally:
            router.close()
            for p, _mp, _sp in procs:
                if p.poll() is None:
                    p.kill()
                    p.wait()
