"""Parser corpus tests — the MysqlTest analog (SURVEY.md §4 parser corpus)."""

import pytest

from galaxysql_tpu.sql import ast
from galaxysql_tpu.sql.lexer import split_statements, tokenize
from galaxysql_tpu.sql.parameterize import parameterize
from galaxysql_tpu.sql.parser import parse
from galaxysql_tpu.storage.tpch_queries import QUERIES
from galaxysql_tpu.utils.errors import SqlSyntaxError


class TestTpchCorpus:
    @pytest.mark.parametrize("qid", sorted(QUERIES))
    def test_parses(self, qid):
        stmt = parse(QUERIES[qid])
        assert isinstance(stmt, ast.Select)

    def test_q1_shape(self):
        s = parse(QUERIES[1])
        assert len(s.items) == 10
        assert s.items[2].alias == "sum_qty"
        assert len(s.group_by) == 2
        assert len(s.order_by) == 2
        assert isinstance(s.where, ast.Binary)

    def test_q3_joins_and_limit(self):
        s = parse(QUERIES[3])
        assert isinstance(s.from_, ast.Join)
        assert s.limit.value == 10

    def test_q7_derived_table_and_alias(self):
        s = parse(QUERIES[7])
        assert isinstance(s.from_, ast.SubqueryRef)
        assert s.from_.alias == "shipping"

    def test_q13_left_join_with_extra_on(self):
        s = parse(QUERIES[13])
        inner = s.from_.select.from_
        assert isinstance(inner, ast.Join)
        assert inner.kind == "left"

    def test_q16_not_in_subquery(self):
        s = parse(QUERIES[16])
        # find the NOT IN subquery in the where conjunction
        found = []
        def walk(e):
            if isinstance(e, ast.InExpr):
                found.append(e)
            for f in e.__dataclass_fields__:
                v = getattr(e, f)
                if isinstance(v, ast.ExprNode):
                    walk(v)
                elif isinstance(v, list):
                    for x in v:
                        if isinstance(x, ast.ExprNode):
                            walk(x)
        walk(s.where)
        assert any(e.negated and e.select is not None for e in found)
        assert any(e.items is not None and len(e.items) == 8 for e in found)

    def test_q21_exists_not_exists(self):
        s = parse(QUERIES[21])
        assert isinstance(s, ast.Select)


class TestStatements:
    def test_create_table_partitioned(self):
        s = parse("""
            CREATE TABLE IF NOT EXISTS t1 (
                id BIGINT NOT NULL AUTO_INCREMENT,
                name VARCHAR(30) DEFAULT 'x' COMMENT 'the name',
                amount DECIMAL(15,2) NOT NULL,
                created DATE,
                PRIMARY KEY (id),
                KEY idx_name (name),
                GLOBAL INDEX g_i (amount) COVERING (name) PARTITION BY HASH(amount) PARTITIONS 4
            ) ENGINE=InnoDB DEFAULT CHARSET=utf8mb4 COMMENT='demo'
              PARTITION BY HASH(id) PARTITIONS 16
        """)
        assert isinstance(s, ast.CreateTable)
        assert s.if_not_exists
        assert [c.name for c in s.columns] == ["id", "name", "amount", "created"]
        assert s.columns[0].auto_increment and not s.columns[0].nullable
        assert s.columns[2].type_name == "DECIMAL" and s.columns[2].scale == 2
        assert s.primary_key == ["id"]
        assert s.partition.method == "hash" and s.partition.count == 16
        gsi = [i for i in s.indexes if i.global_index]
        assert gsi and gsi[0].covering == ["name"] and gsi[0].partition.count == 4
        assert s.comment == "demo"

    def test_create_table_range_partitions(self):
        s = parse("""
            CREATE TABLE t (a INT, b DATE) PARTITION BY RANGE COLUMNS(b) (
                PARTITION p0 VALUES LESS THAN ('2000-01-01'),
                PARTITION p1 VALUES LESS THAN (MAXVALUE)
            )
        """)
        assert s.partition.method == "range_columns"
        assert len(s.partition.boundaries) == 2
        assert s.partition.boundaries[1][1][0].parts == ["MAXVALUE"]

    def test_broadcast_single(self):
        assert parse("CREATE TABLE r (a INT) BROADCAST").broadcast
        assert parse("CREATE TABLE r (a INT) SINGLE").single

    def test_insert_forms(self):
        s = parse("INSERT INTO t (a, b) VALUES (1, 'x'), (2, 'y')")
        assert len(s.rows) == 2
        s = parse("INSERT INTO t SELECT a, b FROM u WHERE a > 3")
        assert s.select is not None
        s = parse("INSERT INTO t SET a = 1, b = 'z'")
        assert s.columns == ["a", "b"]
        s = parse("INSERT INTO t (a) VALUES (1) ON DUPLICATE KEY UPDATE a = a + 1")
        assert s.on_dup_update is not None

    def test_update_delete(self):
        s = parse("UPDATE t SET a = a + 1, b = 2 WHERE c < 5 LIMIT 10")
        assert len(s.sets) == 2 and s.limit is not None
        s = parse("DELETE FROM t WHERE a IN (1,2,3)")
        assert isinstance(s.where, ast.InExpr)

    def test_set_show_use(self):
        s = parse("SET autocommit = 1, @@session.sql_mode = 'STRICT', @u = 5")
        assert [a[0] for a in s.assignments] == ["session", "session", "user"]
        s = parse("SET GLOBAL max_connections = 100")
        assert s.assignments[0][0] == "global"
        s = parse("SHOW FULL COLUMNS FROM t1")
        assert s.kind == "columns" and s.full
        s = parse("SHOW TABLES LIKE 'li%'")
        assert s.like == "li%"
        assert isinstance(parse("USE mydb"), ast.UseDb)

    def test_explain_txn(self):
        s = parse("EXPLAIN ANALYZE SELECT 1")
        assert s.analyze and isinstance(s.stmt, ast.Select)
        assert isinstance(parse("BEGIN"), ast.Begin)
        assert isinstance(parse("START TRANSACTION"), ast.Begin)
        assert isinstance(parse("COMMIT"), ast.Commit)

    def test_union(self):
        s = parse("SELECT a FROM t UNION ALL SELECT b FROM u ORDER BY 1 LIMIT 5")
        assert isinstance(s, ast.SetOpSelect) and s.op == "union_all"

    def test_errors(self):
        with pytest.raises(SqlSyntaxError):
            parse("SELECT FROM t")
        with pytest.raises(SqlSyntaxError):
            parse("SELEC 1")
        with pytest.raises(SqlSyntaxError):
            parse("SELECT 1 FROM t WHERE")

    def test_multi_statement_split(self):
        parts = split_statements("SELECT 1; SELECT 'a;b'; -- c;\nSELECT 2")
        assert len(parts) == 3

    def test_prepared_params(self):
        s = parse("SELECT * FROM t WHERE a = ? AND b > ?")
        # two ParamRef with increasing indexes
        w = s.where
        assert isinstance(w.left.right, ast.ParamRef) and w.left.right.index == 0
        assert w.right.right.index == 1


class TestParameterize:
    def test_basic(self):
        p = parameterize("SELECT * FROM t WHERE a = 5 AND s = 'x' LIMIT 10")
        assert p.parameterized == "SELECT * FROM t WHERE a = ? AND s = ? LIMIT 10"
        assert p.params == [5, "x"]

    def test_same_key_different_values(self):
        a = parameterize("SELECT * FROM t WHERE a = 5")
        b = parameterize("SELECT * FROM t WHERE a = 99")
        assert a.cache_key == b.cache_key

    def test_interval_and_date_kept(self):
        p = parameterize("SELECT * FROM t WHERE d < date '1994-01-01' + interval '1' year")
        assert "interval '1' year" in p.parameterized
        assert "date '1994-01-01'" in p.parameterized  # typed literal stays inline
        assert p.params == []

    def test_client_param_slots(self):
        p = parameterize("SELECT * FROM t WHERE a = ? AND b = 5")
        assert p.parameterized == "SELECT * FROM t WHERE a = ? AND b = ?"
        assert p.slots == [("client", 0), ("lit", 5)]
        assert p.resolve([42]) == [42, 5]

    def test_ddl_untouched(self):
        sql = "CREATE TABLE t (a INT DEFAULT 5)"
        assert parameterize(sql).parameterized == sql
