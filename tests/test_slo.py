"""SLO plane: windowed metric history, burn-rate alerting, cluster health.

Covers the round-16 plane end to end and deterministically:

- burn/recover e2e: `FP_SLO_LATENCY_MS`-injected latency trips the fast
  window, `slo_burn` fires (critical at >= 2x the fast threshold), SHOW SLO
  shows BURNING and web `/health` goes degraded; disarm + a flush of good
  queries re-arms the objective and `slo_recovered` lands
- robust-EWMA anomaly detector: an injected compile-retrace storm fires
  `metric_anomaly` naming `compile_retraces`
- hatch equivalence + hot-path guards: history on vs off is bit-identical
  with identical dispatch counts, and a sample() itself costs zero device
  dispatches and zero host<->device transfers
- CREATE/DROP SLO SQL (IF NOT EXISTS / IF EXISTS, typed duplicate/unknown
  errors, kv persistence across a coordinator restart)
- SHOW METRIC HISTORY [LIKE] / SHOW CLUSTER HEALTH / SHOW EVENTS severity +
  kind-LIKE filtering, the three information_schema tables, web
  `/timeseries/<metric>` + `/events`
- delta-encoded ring eviction: trimming folds into the base so replay stays
  exact at the retention bound
- the worker-side `health` sync action and the cluster view's UNREACHABLE /
  piggyback rendering
- journal round-trip naming every published event kind, and the dynamic
  histogram coverage check: every registry histogram's `<name>_p99`
  expansion must land in a history sample (`segment_wall_ms`, `rpc_rtt_ms`,
  `batch_group_size`, `batch_wait_ms`, `dml_group_size`, `dml_wait_ms`,
  `query_latency_ms`)

The `slo`-marked tests are the fast smoke target (`make slo-smoke`).
"""

import time

import numpy as np
import pytest

from galaxysql_tpu.exec import operators as ops
from galaxysql_tpu.server.instance import Instance
from galaxysql_tpu.server.session import Session
from galaxysql_tpu.server.web import WebConsole
from galaxysql_tpu.utils import errors, events
from galaxysql_tpu.utils.events import EVENTS
from galaxysql_tpu.utils.failpoint import FAIL_POINTS, FP_SLO_LATENCY_MS


@pytest.fixture(autouse=True)
def _clean_failpoints():
    FAIL_POINTS.clear()
    yield
    FAIL_POINTS.clear()


def _mk(schema="slo", rows=200, data_dir=None):
    inst = Instance(data_dir=data_dir)
    s = Session(inst)
    s.execute(f"CREATE DATABASE IF NOT EXISTS {schema}")
    s.execute(f"USE {schema}")
    if rows:
        s.execute("CREATE TABLE t (a BIGINT PRIMARY KEY, b BIGINT)")
        inst.store(schema, "t").insert_arrays(
            {"a": np.arange(rows), "b": np.arange(rows) % 17},
            inst.tso.next_timestamp())
        s.execute("ANALYZE TABLE t")
    return inst, s


class _Ticker:
    """Synthetic 5s-spaced sample ticks: real back-to-back wall-clock ticks
    would make every counter rate astronomical, so tests stamp time."""

    def __init__(self, inst):
        self.inst = inst
        self.t0 = time.time()
        self.n = 0

    def __call__(self, k=1):
        for _ in range(k):
            self.n += 1
            assert self.inst.slo_tick(now=self.t0 + 5.0 * self.n, force=True)


def _state(inst, name):
    return {r[0]: r[8] for r in inst.slo.rows()}[name]


# -- metric history: sampling, replay, eviction -------------------------------


@pytest.mark.slo
class TestMetricHistory:
    def test_sample_replay_rate(self):
        inst, s = _mk("mh1")
        T = _Ticker(inst)
        c = inst.metrics.counter("mh_probe", "test probe")
        for i in range(5):
            c.inc(10)
            T()
        mh = inst.metric_history
        pts = mh.series("mh_probe")
        assert [v for _t, v in pts] == [10.0, 20.0, 30.0, 40.0, 50.0]
        # 10 per 5s tick -> 2/s average, exact under synthetic stamps
        assert mh.rate("mh_probe") == pytest.approx(2.0)
        assert mh.latest("mh_probe") == 50.0
        assert [round(dv, 6) for _t, dv in mh.derivative("mh_probe")] \
            == [2.0, 2.0, 2.0, 2.0]
        assert "mh_probe" in mh.counter_names()
        s.close()

    def test_eviction_folds_into_base_replay_exact(self):
        """Trimming past METRIC_HISTORY_SAMPLES folds the evicted delta into
        the base snapshot — replay over the retained window stays exact."""
        inst, s = _mk("mh2", rows=0)
        inst.config.set_instance("METRIC_HISTORY_SAMPLES", 4)
        T = _Ticker(inst)
        c = inst.metrics.counter("evict_probe", "test probe")
        for i in range(10):
            c.inc()
            T()
        mh = inst.metric_history
        assert mh.samples_count == 4
        pts = mh.series("evict_probe")
        assert [v for _t, v in pts] == [7.0, 8.0, 9.0, 10.0]
        assert mh.latest("evict_probe") == 10.0
        assert mh.mean("evict_probe") == pytest.approx(8.5)
        s.close()

    def test_hatch_off_no_samples(self):
        inst, s = _mk("mh3", rows=0)
        inst.config.set_instance("ENABLE_METRIC_HISTORY", 0)
        assert inst.metric_history.sample() is None
        assert not inst.slo_tick(force=True)
        assert inst.metric_history.samples_count == 0
        s.close()

    def test_every_registry_histogram_lands_in_a_sample(self):
        """Dynamic leg of the galaxylint histogram-unsampled rule: every
        histogram the registry knows (process-shared adopted ones —
        segment_wall_ms, rpc_rtt_ms, batch_group_size, batch_wait_ms,
        dml_group_size, dml_wait_ms — and registry-created ones like
        query_latency_ms) must expand into the history sample."""
        inst, s = _mk("mh4")
        s.execute("SELECT b FROM t WHERE a = 7")  # populate latency histo
        vals = inst.metric_history.sample()
        histos = sorted({n for n, k, _v, _h in inst.metrics.rows()
                         if k == "histogram" and n.endswith("_p99")})
        assert histos, "registry exposes no histograms?"
        for n in histos:
            assert n in vals, f"histogram expansion {n} missing from sample"
        assert "query_latency_ms_p99" in vals
        s.close()


# -- hot-path guards: zero device work, on/off equivalence --------------------


@pytest.mark.slo
class TestHotPathGuards:
    def test_sample_costs_zero_dispatches_zero_transfers(self):
        from galaxysql_tpu.exec.device_cache import TRANSFER_STATS
        inst, s = _mk("hp1")
        s.execute("SELECT b FROM t WHERE a < 50")  # warm + populate metrics
        ops.reset_dispatch_stats()
        x0 = TRANSFER_STATS["transfers"]
        for _ in range(5):
            assert inst.metric_history.sample() is not None
            inst.slo.evaluate()
        assert ops.DISPATCH_STATS["dispatches"] == 0
        assert TRANSFER_STATS["transfers"] == x0
        s.close()

    def test_history_on_off_bit_identical_same_dispatches(self):
        from galaxysql_tpu.exec.device_cache import TRANSFER_STATS
        inst, s = _mk("hp2", rows=3000)
        q = "SELECT a, b * 3 FROM t WHERE a < 1500"
        s.execute(q)  # warmup: compile
        ops.reset_dispatch_stats()
        x0 = TRANSFER_STATS["transfers"]
        on = s.execute(q)  # history ON (default), sampler constructed
        inst.slo_tick(force=True)
        d_on = ops.DISPATCH_STATS["dispatches"]
        x_on = TRANSFER_STATS["transfers"] - x0
        inst.config.set_instance("ENABLE_METRIC_HISTORY", 0)
        ops.reset_dispatch_stats()
        x0 = TRANSFER_STATS["transfers"]
        off = s.execute(q)
        inst.slo_tick(force=True)  # no-op while the hatch is off
        assert ops.DISPATCH_STATS["dispatches"] == d_on
        assert TRANSFER_STATS["transfers"] - x0 == x_on
        assert on.rows == off.rows
        s.close()


# -- the burn/recover e2e (the acceptance scenario) ---------------------------


@pytest.mark.slo
class TestBurnRecover:
    def test_injected_latency_trips_fast_window_then_recovers(self):
        EVENTS.clear()
        inst, s = _mk("burn")
        inst.config.set_instance("SLO_FAST_WINDOW_SAMPLES", 2)
        inst.config.set_instance("SLO_SLOW_WINDOW_SAMPLES", 4)
        T = _Ticker(inst)

        def run(n):
            for i in range(n):
                s.execute(f"SELECT b FROM t WHERE a = {i % 200}")

        # steady state: enough samples to judge, nothing burns
        run(10)
        T(4)
        assert _state(inst, "tp_latency_p99") == "OK"
        assert inst.slo.burning_names() == []

        # inject a 10s pad on every TP query: recent_p99 blows 40x past the
        # 250ms default target — fast AND slow windows burn
        FAIL_POINTS.arm(FP_SLO_LATENCY_MS, {"ms": 10000, "workload": "TP"})
        run(20)
        T(3)
        assert _state(inst, "tp_latency_p99") == "BURNING"
        assert "tp_latency_p99" in inst.slo.burning_names()
        burn = EVENTS.entries(kind="slo_burn")
        assert burn and burn[-1].severity == "critical"  # >= 2x fast thresh
        assert burn[-1].attrs["slo"] == "tp_latency_p99"
        assert float(burn[-1].attrs["fast_burn"]) >= 2.0
        # the gauge tracks the burn set
        reg = {n: v for n, _k, v, _h in inst.metrics.rows()}
        assert reg["slo_burn_active"] >= 1

        # web /health degrades while burning (readiness for load balancers)
        h = WebConsole(inst).resource("/health")
        assert h["status"] == "degraded" and not h["ready"]
        assert "tp_latency_p99" in h["burning_slos"]

        # recovery: disarm, flush the 128-deep class ring with good queries
        FAIL_POINTS.disarm(FP_SLO_LATENCY_MS)
        run(140)
        T(3)
        assert _state(inst, "tp_latency_p99") == "OK"
        rec = EVENTS.entries(kind="slo_recovered")
        assert rec and rec[-1].severity == "info"
        assert rec[-1].attrs["slo"] == "tp_latency_p99"
        h = WebConsole(inst).resource("/health")
        assert h["status"] == "ok" and h["ready"]
        s.close()

    def test_scoped_slo_burns_only_its_tenant(self):
        """A CREATE SLO scoped to one schema judges that tenant's digest
        class only: padding a different schema leaves it OK."""
        EVENTS.clear()
        inst, s = _mk("ten_a")
        s2 = Session(inst)
        s2.execute("CREATE DATABASE ten_b")
        s2.execute("USE ten_b")
        s2.execute("CREATE TABLE t (a BIGINT PRIMARY KEY, b BIGINT)")
        inst.store("ten_b", "t").insert_arrays(
            {"a": np.arange(50), "b": np.arange(50)},
            inst.tso.next_timestamp())
        inst.config.set_instance("SLO_FAST_WINDOW_SAMPLES", 2)
        inst.config.set_instance("SLO_SLOW_WINDOW_SAMPLES", 4)
        s.execute("CREATE SLO tenant_a_p99 WITH TARGET_P99_MS = 250, "
                  "SCHEMA = 'ten_a', CLASS = 'TP'")
        T = _Ticker(inst)
        for i in range(10):
            s.execute(f"SELECT b FROM t WHERE a = {i}")
            s2.execute(f"SELECT b FROM t WHERE a = {i}")
        T(4)
        assert _state(inst, "tenant_a_p99") == "OK"
        # pad ONLY schema ten_b: the ten_a-scoped objective must stay OK
        FAIL_POINTS.arm(FP_SLO_LATENCY_MS,
                        {"ms": 10000, "workload": "TP", "schema": "ten_b"})
        for i in range(20):
            s2.execute(f"SELECT b FROM t WHERE a = {i % 50}")
        T(3)
        assert _state(inst, "tenant_a_p99") == "OK"
        # now pad ten_a too: the scoped objective trips
        FAIL_POINTS.arm(FP_SLO_LATENCY_MS,
                        {"ms": 10000, "workload": "TP", "schema": "ten_a"})
        for i in range(20):
            s.execute(f"SELECT b FROM t WHERE a = {i % 200}")
        T(3)
        assert _state(inst, "tenant_a_p99") == "BURNING"
        s2.close()
        s.close()


# -- the anomaly detector -----------------------------------------------------


@pytest.mark.slo
class TestAnomalyDetector:
    def test_retrace_storm_fires_metric_anomaly(self):
        EVENTS.clear()
        inst, s = _mk("anom")
        T = _Ticker(inst)
        before = ops.COMPILE_STATS["retraces"]
        try:
            # warm-up: stable rates establish the EWMA baseline
            for i in range(6):
                s.execute(f"SELECT b FROM t WHERE a = {i}")
                T()
            assert not EVENTS.entries(kind="metric_anomaly")
            # storm: a retrace burst far past mean + sigma * dev
            ops.COMPILE_STATS["retraces"] += 5000
            T()
            anom = EVENTS.entries(kind="metric_anomaly")
            assert any(e.attrs.get("metric") == "compile_retraces"
                       for e in anom)
            hit = [e for e in anom
                   if e.attrs.get("metric") == "compile_retraces"][-1]
            assert hit.severity == "warn"
            assert float(hit.attrs["rate"]) > float(hit.attrs["baseline"])
            # transition-edged: a second storm tick while still firing does
            # not re-publish for the same metric
            n0 = len(EVENTS.entries(kind="metric_anomaly"))
            ops.COMPILE_STATS["retraces"] += 5000
            T()
            again = [e for e in EVENTS.entries(kind="metric_anomaly")[n0:]
                     if e.attrs.get("metric") == "compile_retraces"]
            assert not again
        finally:
            ops.COMPILE_STATS["retraces"] = before
        s.close()


# -- CREATE / DROP SLO SQL ----------------------------------------------------


@pytest.mark.slo
class TestSloSql:
    def test_create_show_drop_round_trip(self):
        inst, s = _mk("sql1", rows=0)
        s.execute("CREATE SLO gold_tp WITH TARGET_P99_MS = 100, "
                  "SCHEMA = 'sql1', CLASS = 'TP'")
        rows = {r[0]: r for r in s.execute("SHOW SLO").rows}
        assert "gold_tp" in rows
        assert rows["gold_tp"][1] == "latency_p99"
        assert rows["gold_tp"][2] == "sql1" and rows["gold_tp"][3] == "TP"
        assert rows["gold_tp"][4] == 100.0
        assert rows["gold_tp"][10] == "sql"
        # built-ins present with live config-backed targets
        assert rows["tp_latency_p99"][10] == "default"
        assert rows["typed_error_ratio"][1] == "error_ratio"
        # typed errors: duplicate create, unknown drop
        with pytest.raises(errors.TddlError):
            s.execute("CREATE SLO gold_tp WITH TARGET_P99_MS = 50")
        s.execute("CREATE SLO IF NOT EXISTS gold_tp WITH TARGET_P99_MS = 50")
        assert {r[0]: r for r in s.execute("SHOW SLO").rows}[
            "gold_tp"][4] == 100.0  # unchanged
        with pytest.raises(errors.TddlError):
            s.execute("CREATE SLO bad WITH TARGET_P99_MS = 1, "
                      "ERROR_RATIO = 0.1")  # exactly-one-of
        with pytest.raises(errors.TddlError):
            s.execute("CREATE SLO bad WITH ERROR_RATIO = -1")
        s.execute("DROP SLO gold_tp")
        assert "gold_tp" not in {r[0] for r in s.execute("SHOW SLO").rows}
        with pytest.raises(errors.TddlError):
            s.execute("DROP SLO gold_tp")
        s.execute("DROP SLO IF EXISTS gold_tp")
        s.close()

    def test_persists_across_coordinator_restart(self, tmp_path):
        d = str(tmp_path / "slokv")
        inst, s = _mk("sql2", rows=0, data_dir=d)
        s.execute("CREATE SLO durable_err WITH ERROR_RATIO = 0.05, "
                  "SCHEMA = 'sql2'")
        s.close()
        inst2 = Instance(data_dir=d)
        names = {d_.name: d_ for d_ in inst2.slo.defs()}
        assert "durable_err" in names
        assert names["durable_err"].kind == "error_ratio"
        assert names["durable_err"].target == 0.05
        assert names["durable_err"].schema == "sql2"
        # DROP unpersists: gone after another restart
        Session(inst2).execute("DROP SLO durable_err")
        inst3 = Instance(data_dir=d)
        assert "durable_err" not in {d_.name for d_ in inst3.slo.defs()}


# -- surfaces: SHOW / information_schema / web --------------------------------


@pytest.mark.slo
class TestSurfaces:
    def test_show_metric_history_like(self):
        inst, s = _mk("surf1")
        s.execute("SELECT b FROM t WHERE a = 1")
        _Ticker(inst)(2)
        rows = s.execute("SHOW METRIC HISTORY LIKE 'queries%'").rows
        assert rows and all(r[0].startswith("queries") for r in rows)
        by_name = {r[0]: r for r in rows}
        assert by_name["queries_total"][2] >= 1  # latest
        assert by_name["queries_total"][1] == 2  # points
        all_rows = s.execute("SHOW METRIC HISTORY").rows
        assert len(all_rows) > len(rows)
        assert any(r[0] == "stmt_class_tp_recent_p99_ms" for r in all_rows)
        assert any(r[0] == "admission_tp_limit" for r in all_rows)
        s.close()

    def test_show_cluster_health_and_unreachable_worker(self):
        inst, s = _mk("surf2")
        s.execute("SELECT b FROM t WHERE a = 1")
        _Ticker(inst)(2)
        rows = s.execute("SHOW CLUSTER HEALTH").rows
        assert len(rows) == 1
        node, role, addr, state, leader = rows[0][:5]
        assert role == "coordinator" and state == "OK" and leader == 1
        assert rows[0][11] >= 2  # samples

        # a dead worker renders an UNREACHABLE row, never an exception
        class _DeadClient:
            def sync_action(self, *a, **kw):
                raise ConnectionError("down")
        inst.workers[("127.0.0.1", 1)] = _DeadClient()
        rows = s.execute("SHOW CLUSTER HEALTH").rows
        assert [r[3] for r in rows if r[1] == "worker"] == ["UNREACHABLE"]

        # piggyback rendering (pull=False: info_schema path) uses the
        # telemetry fields the reply legs maintain — no sync round-trip
        class _IdleClient:
            load_q, load_tier, load_up, load_samples = 3, 1, 42.0, 7
        inst.workers[("127.0.0.1", 1)] = _IdleClient()
        wrow = [r for r in inst.cluster_health(pull=False)
                if r[1] == "worker"][0]
        assert wrow[3] == "OK" and wrow[5] == 42.0 and wrow[6] == 3.0
        assert wrow[9] == 1 and wrow[11] == 7
        s.close()

    def test_information_schema_tables(self):
        inst, s = _mk("surf3")
        s.execute("SELECT b FROM t WHERE a = 1")
        _Ticker(inst)(2)
        slo = s.execute("SELECT slo_name, state FROM "
                        "information_schema.slo_status").rows
        assert ("tp_latency_p99", "OK") in slo
        mh = s.execute("SELECT metric_name, points FROM "
                       "information_schema.metric_history "
                       "WHERE metric_name = 'queries_total'").rows
        assert mh == [("queries_total", 2)]
        ch = s.execute("SELECT role, state FROM "
                       "information_schema.cluster_health").rows
        assert ("coordinator", "OK") in ch
        s.close()

    def test_web_timeseries_and_events(self):
        inst, s = _mk("surf4")
        s.execute("SELECT b FROM t WHERE a = 1")
        _Ticker(inst)(3)
        web = WebConsole(inst)
        ts = web.resource("/timeseries/queries_total")
        assert ts["metric"] == "queries_total" and len(ts["points"]) == 3
        assert web.resource("/timeseries/no_such_metric") is None  # 404
        EVENTS.clear()
        EVENTS.publish("slo_burn", detail="drill", severity="critical")
        EVENTS.publish("ddl", detail="drill")
        evs = web.resource("/events?kind=slo_burn")
        assert [e["kind"] for e in evs["events"]] == ["slo_burn"]
        evs = web.resource("/events?severity=critical")
        assert evs["events"] and all(e["severity"] == "critical"
                                     for e in evs["events"])
        evs = web.resource("/events?like=slo%")
        assert [e["kind"] for e in evs["events"]] == ["slo_burn"]
        s.close()

    def test_show_events_severity_and_like(self):
        inst, s = _mk("surf5", rows=0)
        EVENTS.clear()
        EVENTS.publish("slo_burn", detail="d1", severity="critical")
        EVENTS.publish("slo_recovered", detail="d2")
        EVENTS.publish("breaker_open", detail="d3")
        rows = s.execute("SHOW EVENTS").rows
        assert len(rows) >= 3
        rows = s.execute("SHOW EVENTS CRITICAL").rows
        assert {r[2] for r in rows} == {"slo_burn"}
        rows = s.execute("SHOW EVENTS LIKE 'slo%'").rows
        assert {r[2] for r in rows} == {"slo_burn", "slo_recovered"}
        rows = s.execute("SHOW EVENTS INFO LIKE 'slo%'").rows
        assert {r[2] for r in rows} == {"slo_recovered"}
        with pytest.raises(errors.NotSupportedError):
            s.execute("SHOW EVENTS LOUD")
        s.close()


# -- worker-side sampler + health sync action ---------------------------------


@pytest.mark.slo
class TestWorkerHealth:
    def test_health_sync_action(self, tmp_path):
        from galaxysql_tpu.net.worker import Worker
        w = Worker(data_dir=str(tmp_path / "whealth"))
        resp, arrays = w._sync({"action": "health"})
        assert resp["ok"] and resp["action"] == "health"
        assert resp["node"] == w.instance.node_id
        assert resp["samples"] >= 1  # the pull itself sampled
        assert resp["burning"] == [] and resp["mem_tier"] == 0
        assert resp["uptime_s"] >= 0.0 and arrays == {}


# -- journal round-trip: every published kind, filtered retrieval -------------


# Every event kind the package publishes (galaxylint's event-untested rule
# keeps this honest: a kind published anywhere must be named by a test).
ALL_EVENT_KINDS = (
    # core + distributed plane
    "ddl", "breaker_open", "breaker_close", "worker_failover",
    "sync_failure", "sync_heal", "worker_telemetry_failed",
    "session_close_failed", "replica_cleanup_failed", "async_apply_failed",
    # execution tiers
    "skew_activate", "skew_deactivate", "batch_fallback",
    # self-heal loop
    "plan_regression", "plan_rollback", "stats_repair", "plan_promoted",
    "plan_heal_failed",
    # resource governance
    "admission_reject", "ccl_reject", "mem_pressure",
    "retry_budget_exhausted",
    # SLO plane
    "slo_burn", "slo_recovered", "metric_anomaly",
    # serving tier
    "coordinator_joined", "coordinator_left",
)


@pytest.mark.slo
class TestJournalRoundTrip:
    def test_all_kinds_publish_default_severity_and_filter(self):
        assert set(ALL_EVENT_KINDS) >= set(events.KINDS)
        EVENTS.clear()
        for k in ALL_EVENT_KINDS:
            EVENTS.publish(k, detail=f"drill {k}")
        got = EVENTS.entries()
        assert {e.kind for e in got} >= set(ALL_EVENT_KINDS)
        # failure-shaped kinds default to warn severity, the rest to info
        by_kind = {e.kind: e for e in got}
        assert by_kind["slo_burn"].severity == "warn"
        assert by_kind["metric_anomaly"].severity == "warn"
        assert by_kind["slo_recovered"].severity == "info"
        assert by_kind["breaker_open"].severity == "warn"
        assert by_kind["sync_heal"].severity == "info"
        # filtered retrieval composes: severity AND kind_like
        warn_slo = EVENTS.entries(severity="warn", kind_like="slo%")
        assert {e.kind for e in warn_slo} == {"slo_burn"}


# -- parser coverage ----------------------------------------------------------


@pytest.mark.slo
class TestParser:
    def test_create_drop_slo_and_show_forms(self):
        from galaxysql_tpu.sql import ast as A
        from galaxysql_tpu.sql.parser import parse
        st = parse("CREATE SLO IF NOT EXISTS x WITH TARGET_P99_MS = 10.5, "
                   "SCHEMA = 'd', CLASS = 'AP'")
        assert isinstance(st, A.CreateSlo)
        assert st.if_not_exists and st.name == "x"
        assert st.p99_ms == 10.5 and st.error_ratio is None
        assert st.schema == "d" and st.workload == "AP"
        st = parse("CREATE SLO y WITH ERROR_RATIO = 0.01")
        assert st.error_ratio == 0.01 and st.p99_ms is None
        st = parse("DROP SLO IF EXISTS y")
        assert isinstance(st, A.DropSlo) and st.if_exists
        assert parse("SHOW SLO").kind == "slo"
        assert parse("SHOW METRIC HISTORY LIKE 'q%'").kind == "metric_history"
        assert parse("SHOW CLUSTER HEALTH").kind == "cluster_health"
        assert parse("SHOW EVENTS WARN").target.upper() == "WARN"
