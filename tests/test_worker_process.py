"""The CN<->worker plane: a REAL second OS process serving shipped plan SQL,
the sync-action bus, and HA liveness acting on it.

Reference analogs: `repo/mysql/spi/MyJdbcHandler.java:691` (plan shipping to
the shard's storage process), `executor/sync/SyncManagerHelper.java:36`
(inter-node sync actions), `gms/ha/impl/StorageHaManager.java:1203` (liveness
driving behavior).  The done bar: one query whose fragments span both
processes.
"""

import os
import signal
import subprocess
import sys
import time

import pytest

from galaxysql_tpu.server.instance import Instance
from galaxysql_tpu.server.session import Session
from galaxysql_tpu.utils import errors

INIT_SQL = (
    "CREATE DATABASE w; USE w; "
    "CREATE TABLE dim (k BIGINT PRIMARY KEY, label VARCHAR(16), price DECIMAL(10,2)); "
    "INSERT INTO dim VALUES (1,'alpha',1.50), (2,'beta',2.25), (3,'gamma',0.75), "
    "(4,'delta',9.99), (5, NULL, 5.00)"
)


@pytest.fixture(scope="module")
def worker_proc():
    env = dict(os.environ, JAX_PLATFORMS="cpu")
    p = subprocess.Popen(
        [sys.executable, "-m", "galaxysql_tpu.net.worker", "--port", "0",
         "--platform", "cpu", "--init-sql", INIT_SQL],
        cwd=os.path.dirname(os.path.dirname(os.path.abspath(__file__))),
        stdout=subprocess.PIPE, stderr=subprocess.PIPE, env=env, text=True)
    line = p.stdout.readline()
    if not line.startswith("WORKER_READY"):
        err = p.stderr.read()[-3000:] if p.stderr else ""
        raise AssertionError(f"worker failed to start: {line!r}\n{err}")
    port = int(line.split()[1])
    yield p, port
    if p.poll() is None:
        p.kill()
        p.wait()


@pytest.fixture()
def session(worker_proc):
    _, port = worker_proc
    inst = Instance()
    s = Session(inst)
    s.execute("CREATE DATABASE w")
    s.execute("USE w")
    inst.attach_remote_table("w", "dim", "127.0.0.1", port)
    yield s, port
    s.close()


class TestPlanShipping:
    def test_remote_scan(self, session):
        s, port = session
        r = s.execute("SELECT k, label, price FROM dim ORDER BY k")
        assert r.rows == [(1, "alpha", 1.5), (2, "beta", 2.25),
                          (3, "gamma", 0.75), (4, "delta", 9.99), (5, None, 5.0)]

    def test_query_fragments_span_both_processes(self, session):
        """Local fact table joined with the worker-resident dim table: the
        probe/agg fragment runs here, the dim scan runs in the worker."""
        s, port = session
        s.execute("CREATE TABLE fact (id BIGINT, k BIGINT, qty BIGINT)")
        s.instance.store("w", "fact").insert_pylists(
            {"id": list(range(100)), "k": [(i % 5) + 1 for i in range(100)],
             "qty": [i for i in range(100)]},
            s.instance.tso.next_timestamp())
        r = s.execute(
            "SELECT dim.label, sum(fact.qty) FROM fact, dim "
            "WHERE fact.k = dim.k AND dim.k <= 2 "
            "GROUP BY dim.label ORDER BY dim.label")
        # k=1 rows: ids 0,5,..,95 qty sum = 950; k=2: 970
        assert r.rows == [("alpha", 950), ("beta", 970)]
        assert any("remote-scan" in t for t in s.last_trace)

    def test_shipped_sql_is_column_pruned(self, session):
        s, port = session
        s.execute("SELECT k FROM dim")
        log = s.instance.workers[("127.0.0.1", port)].sync_action(
            "query_log", {})["queries"]
        pruned = [q for q in log if q.startswith("SELECT k FROM")]
        assert pruned, log  # only the referenced column was shipped

    def test_remote_dml_refused(self, session):
        s, _ = session
        with pytest.raises(errors.NotSupportedError, match="worker"):
            s.execute("INSERT INTO dim VALUES (9, 'x', 1.0)")
        with pytest.raises(errors.NotSupportedError, match="worker"):
            s.execute("DELETE FROM dim WHERE k = 1")

    def test_sync_bus_broadcast(self, session):
        s, port = session
        acks = s.instance.sync_bus.broadcast(
            "set_config", {"name": "SLOW_SQL_MS", "value": 1234})
        assert acks and acks[0]["ok"]
        acks = s.instance.sync_bus.broadcast("invalidate_plan_cache", {})
        assert acks[0]["ok"]


class TestHaActs:
    def test_fenced_worker_refuses_fast(self, session):
        s, port = session
        addr = ("127.0.0.1", port)
        s.instance.ha.fence_worker(addr, True)
        try:
            t0 = time.time()
            with pytest.raises(errors.TddlError, match="fenced"):
                s.execute("SELECT k FROM dim")
            assert time.time() - t0 < 1.0  # refusal, not a socket hang
        finally:
            s.instance.ha.fence_worker(addr, False)
        assert len(s.execute("SELECT k FROM dim").rows) == 5

    def test_probe_fences_dead_worker_and_recovers(self, session):
        s, port = session
        addr = ("127.0.0.1", port)
        fenced = s.instance.ha.probe_workers()
        assert fenced.get(addr) is False  # alive
        # dead endpoint: a worker nobody listens on
        from galaxysql_tpu.net.dn import WorkerClient
        dead = WorkerClient("127.0.0.1", 1)  # port 1: nothing listens
        s.instance.workers[("127.0.0.1", 1)] = dead
        try:
            fenced = s.instance.ha.probe_workers()
            assert fenced[("127.0.0.1", 1)] is True
            assert fenced[addr] is False
        finally:
            del s.instance.workers[("127.0.0.1", 1)]


class TestLeaderElection:
    def test_smallest_alive_coordinator_leads(self):
        inst = Instance()
        db = inst.metadb
        # "!" sorts before every hex digit, so this rival beats the
        # instance's own cn-<hex> heartbeat deterministically
        db.heartbeat("cn-!first", "coordinator", "h1", 0)
        db.heartbeat("cn-zzz", "coordinator", "h2", 0)
        inst.ha.check()
        assert inst.ha.leader() == "cn-!first"
        # the leader's heartbeat goes stale -> leadership moves
        from galaxysql_tpu.utils.failpoint import FAIL_POINTS
        from galaxysql_tpu.meta.ha import FP_HB_STALE
        FAIL_POINTS.arm(FP_HB_STALE, "cn-!first")
        try:
            trans = inst.ha.check()
            assert ("cn-!first", "ALIVE", "DEAD") in trans
            assert inst.ha.leader() != "cn-!first"
        finally:
            FAIL_POINTS.clear()

    def test_scheduler_fires_only_on_leader(self):
        inst = Instance()
        # another coordinator with a smaller id is alive: we are NOT leader
        db = inst.metadb
        db.heartbeat("cn-!rival", "coordinator", "h1", 0)
        inst.ha.check()
        assert not inst.ha.is_leader()
        inst.scheduler.register("j1", "analyze", "x", "y", {}, interval_s=0)
        assert inst.scheduler.run_due() == []  # gated
        # the rival dies -> leadership falls to us -> jobs fire
        from galaxysql_tpu.utils.failpoint import FAIL_POINTS
        from galaxysql_tpu.meta.ha import FP_HB_STALE
        FAIL_POINTS.arm(FP_HB_STALE, "cn-!rival")
        try:
            assert inst.ha.is_leader()
            fired = inst.scheduler.run_due()
            assert fired == ["j1"]  # job ran (FAILED status is fine: fake table)
        finally:
            FAIL_POINTS.clear()
