"""The CN<->worker plane: a REAL second OS process serving shipped plan SQL,
the sync-action bus, and HA liveness acting on it.

Reference analogs: `repo/mysql/spi/MyJdbcHandler.java:691` (plan shipping to
the shard's storage process), `executor/sync/SyncManagerHelper.java:36`
(inter-node sync actions), `gms/ha/impl/StorageHaManager.java:1203` (liveness
driving behavior).  The done bar: one query whose fragments span both
processes.
"""

import os
import signal
import subprocess
import sys
import time

import pytest

from galaxysql_tpu.server.instance import Instance
from galaxysql_tpu.server.session import Session
from galaxysql_tpu.utils import errors

INIT_SQL = (
    "CREATE DATABASE w; USE w; "
    "CREATE TABLE dim (k BIGINT PRIMARY KEY, label VARCHAR(16), price DECIMAL(10,2)); "
    "INSERT INTO dim VALUES (1,'alpha',1.50), (2,'beta',2.25), (3,'gamma',0.75), "
    "(4,'delta',9.99), (5, NULL, 5.00)"
)


@pytest.fixture(scope="module")
def worker_proc():
    env = dict(os.environ, JAX_PLATFORMS="cpu")
    p = subprocess.Popen(
        [sys.executable, "-m", "galaxysql_tpu.net.worker", "--port", "0",
         "--platform", "cpu", "--init-sql", INIT_SQL],
        cwd=os.path.dirname(os.path.dirname(os.path.abspath(__file__))),
        stdout=subprocess.PIPE, stderr=subprocess.PIPE, env=env, text=True)
    line = p.stdout.readline()
    if not line.startswith("WORKER_READY"):
        err = p.stderr.read()[-3000:] if p.stderr else ""
        raise AssertionError(f"worker failed to start: {line!r}\n{err}")
    port = int(line.split()[1])
    yield p, port
    if p.poll() is None:
        p.kill()
        p.wait()


@pytest.fixture()
def session(worker_proc):
    _, port = worker_proc
    inst = Instance()
    s = Session(inst)
    s.execute("CREATE DATABASE w")
    s.execute("USE w")
    inst.attach_remote_table("w", "dim", "127.0.0.1", port)
    yield s, port
    s.close()


class TestPlanShipping:
    def test_remote_scan(self, session):
        s, port = session
        r = s.execute("SELECT k, label, price FROM dim ORDER BY k")
        assert r.rows == [(1, "alpha", 1.5), (2, "beta", 2.25),
                          (3, "gamma", 0.75), (4, "delta", 9.99), (5, None, 5.0)]

    def test_query_fragments_span_both_processes(self, session):
        """Local fact table joined with the worker-resident dim table: the
        probe/agg fragment runs here, the dim scan runs in the worker."""
        s, port = session
        s.execute("CREATE TABLE fact (id BIGINT, k BIGINT, qty BIGINT)")
        s.instance.store("w", "fact").insert_pylists(
            {"id": list(range(100)), "k": [(i % 5) + 1 for i in range(100)],
             "qty": [i for i in range(100)]},
            s.instance.tso.next_timestamp())
        r = s.execute(
            "SELECT dim.label, sum(fact.qty) FROM fact, dim "
            "WHERE fact.k = dim.k AND dim.k <= 2 "
            "GROUP BY dim.label ORDER BY dim.label")
        # k=1 rows: ids 0,5,..,95 qty sum = 950; k=2: 970
        assert r.rows == [("alpha", 950), ("beta", 970)]
        assert any("remote-plan" in t or "remote-scan" in t
                   for t in s.last_trace)

    def test_shipped_fragment_is_column_pruned(self, session):
        """Scans ship as serialized plan fragments (XPlan analog) carrying only
        the referenced columns; SQL text is the degrade path."""
        s, port = session
        s.execute("SELECT k FROM dim")
        log = s.instance.workers[("127.0.0.1", port)].sync_action(
            "query_log", {})["queries"]
        frags = [q for q in log if q.startswith("PLAN:w.dim:")]
        assert frags, log  # fragment execution, not SQL re-parse
        assert frags[-1] == "PLAN:w.dim:k"  # column pruning rode the fragment

    def test_fragment_sarg_pushdown(self, session):
        """Range predicates ride the fragment: the worker filters before
        shipping rows back (runtime SARGs on the DN, not just the CN)."""
        s, port = session
        r = s.execute("SELECT k FROM dim WHERE k >= 3 ORDER BY k")
        assert [x[0] for x in r.rows] == [3, 4, 5]

    def test_remote_dml_autocommit(self, session):
        s, _ = session
        s.execute("INSERT INTO dim VALUES (9, 'iota', 1.10)")
        try:
            r = s.execute("SELECT label, price FROM dim WHERE k = 9")
            assert r.rows == [("iota", 1.1)]
        finally:
            s.execute("DELETE FROM dim WHERE k = 9")
        assert s.execute("SELECT label FROM dim WHERE k = 9").rows == []

    def test_read_your_own_remote_writes(self, session):
        """A txn's remote writes are visible to its own SELECTs before COMMIT
        (the scan ships the branch xid; the worker reads through the branch)."""
        s, _ = session
        s.execute("BEGIN")
        s.execute("INSERT INTO dim VALUES (31, 'rw', 3.33)")
        r = s.execute("SELECT label FROM dim WHERE k = 31")
        assert r.rows == [("rw",)]
        # other sessions do NOT see the uncommitted branch row
        s2 = Session(s.instance, schema="w")
        assert s2.execute("SELECT label FROM dim WHERE k = 31").rows == []
        s2.close()
        s.execute("ROLLBACK")
        assert s.execute("SELECT label FROM dim WHERE k = 31").rows == []

    def test_remote_dml_atomic_with_local(self, session):
        """One txn spanning a local store and the worker: COMMIT lands both,
        ROLLBACK lands neither (XA 2PC with the worker as a branch)."""
        s, _ = session
        s.execute("CREATE TABLE localtab (id BIGINT PRIMARY KEY, v BIGINT)")
        s.execute("BEGIN")
        s.execute("INSERT INTO localtab VALUES (1, 10)")
        s.execute("INSERT INTO dim VALUES (21, 'txn', 0.10)")
        # uncommitted: another session sees neither side
        s2 = Session(s.instance, schema="w")
        assert s2.execute("SELECT v FROM localtab").rows == []
        assert s2.execute("SELECT label FROM dim WHERE k = 21").rows == []
        s.execute("COMMIT")
        assert s2.execute("SELECT v FROM localtab").rows == [(10,)]
        assert s2.execute("SELECT label FROM dim WHERE k = 21").rows == [("txn",)]
        s2.close()
        s.execute("DELETE FROM dim WHERE k = 21")
        # rollback side
        s.execute("BEGIN")
        s.execute("INSERT INTO localtab VALUES (2, 20)")
        s.execute("INSERT INTO dim VALUES (22, 'gone', 0.20)")
        s.execute("ROLLBACK")
        assert s.execute("SELECT v FROM localtab WHERE id = 2").rows == []
        assert s.execute("SELECT label FROM dim WHERE k = 22").rows == []

    def test_sync_bus_broadcast(self, session):
        s, port = session
        acks = s.instance.sync_bus.broadcast(
            "set_config", {"name": "SLOW_SQL_MS", "value": 1234})
        assert acks and acks[0]["ok"]
        acks = s.instance.sync_bus.broadcast("invalidate_plan_cache", {})
        assert acks[0]["ok"]


def _spawn_worker(data_dir, init_sql=None):
    env = dict(os.environ, JAX_PLATFORMS="cpu")
    argv = [sys.executable, "-m", "galaxysql_tpu.net.worker", "--port", "0",
            "--platform", "cpu", "--data-dir", data_dir]
    if init_sql:
        argv += ["--init-sql", init_sql]
    p = subprocess.Popen(argv,
                         cwd=os.path.dirname(os.path.dirname(
                             os.path.abspath(__file__))),
                         stdout=subprocess.PIPE, stderr=subprocess.PIPE,
                         env=env, text=True)
    line = p.stdout.readline()
    if not line.startswith("WORKER_READY"):
        err = p.stderr.read()[-3000:] if p.stderr else ""
        raise AssertionError(f"worker failed to start: {line!r}\n{err}")
    return p, int(line.split()[1])


class TestCrashRecovery:
    """2PC crash tests across REAL process boundaries: the worker is
    SIGKILLed between its XA PREPARE and the branch commit, restarted from
    its data dir, and the coordinator resolves the in-doubt branch from its
    durable commit-point log (XARecoverTask analog, SURVEY.md §3.4)."""

    def _setup(self, tmp_path):
        data_dir = str(tmp_path / "wdata")
        os.makedirs(data_dir, exist_ok=True)
        p, port = _spawn_worker(
            data_dir,
            "CREATE DATABASE cw; USE cw; "
            "CREATE TABLE acct (id BIGINT PRIMARY KEY, bal BIGINT); "
            "INSERT INTO acct VALUES (1, 100)")
        inst = Instance()
        s = Session(inst)
        s.execute("CREATE DATABASE cw")
        s.execute("USE cw")
        inst.attach_remote_table("cw", "acct", "127.0.0.1", port)
        return data_dir, p, port, inst, s

    def _prepare_branch(self, inst, s, sql):
        """Open a txn, ship DML, drive the branch to PREPARED; returns txn."""
        from galaxysql_tpu.txn.xa import remote_participants_of
        s.execute("BEGIN")
        s.execute(sql)
        txn = s.txn
        parts = remote_participants_of(inst, txn)
        assert len(parts) == 1 and parts[0].prepare()
        return txn

    def test_commit_point_wins_after_worker_crash(self, tmp_path):
        data_dir, p, port, inst, s = self._setup(tmp_path)
        try:
            txn = self._prepare_branch(
                inst, s, "INSERT INTO acct VALUES (2, 555)")
            # crash the worker AFTER prepare, then log the commit point: the
            # outcome is decided even though the branch never saw the commit
            p.kill()
            p.wait()
            cts = inst.tso.next_timestamp()
            inst.metadb.tx_log_put(txn.txn_id, "COMMITTED", cts)
            s.txn = None  # the session's txn is resolved by recovery below
            # restart from the same data dir; reattach at the new port
            p, port = _spawn_worker(data_dir)
            # reattachment auto-resolves in-doubt branches (XARecoverTask on
            # reconnect); a later explicit call then finds nothing left
            inst.attach_remote_table("cw", "acct", "127.0.0.1", port)
            out = inst.xa_coordinator.recover_remote()
            assert out in ({}, {f"g{txn.txn_id}": "committed"}), out
            r = s.execute("SELECT bal FROM acct WHERE id = 2")
            assert r.rows == [(555,)]
        finally:
            if p.poll() is None:
                p.kill()
                p.wait()

    def test_no_commit_point_presumes_abort(self, tmp_path):
        data_dir, p, port, inst, s = self._setup(tmp_path)
        try:
            txn = self._prepare_branch(
                inst, s, "INSERT INTO acct VALUES (3, 777)")
            p.kill()
            p.wait()
            s.txn = None  # coordinator never logged a commit point
            p, port = _spawn_worker(data_dir)
            # in doubt until resolved: the restarted worker must HOLD the
            # prepared rows (not roll them back at boot); resolution runs at
            # reattach or on the explicit call, whichever comes first
            inst.attach_remote_table("cw", "acct", "127.0.0.1", port)
            out = inst.xa_coordinator.recover_remote()
            assert out in ({}, {f"g{txn.txn_id}": "rolled_back"}), out
            assert s.execute("SELECT bal FROM acct WHERE id = 3").rows == []
            # the surviving committed data is intact
            assert s.execute("SELECT bal FROM acct WHERE id = 1").rows == [(100,)]
        finally:
            if p.poll() is None:
                p.kill()
                p.wait()


class TestReplicaAndMove:
    """Read-write splitting + fence-triggered failover + online table move
    between workers (TGroupDataSource / Balancer.java analogs)."""

    def _two_workers(self, tmp_path):
        init = ("CREATE DATABASE rp; USE rp; "
                "CREATE TABLE inv (id BIGINT PRIMARY KEY, qty BIGINT); "
                "INSERT INTO inv VALUES (1, 5), (2, 7)")
        p1, port1 = _spawn_worker(str(tmp_path / "w1"), init)
        p2, port2 = _spawn_worker(str(tmp_path / "w2"), init)
        inst = Instance()
        s = Session(inst)
        s.execute("CREATE DATABASE rp")
        s.execute("USE rp")
        inst.attach_remote_table("rp", "inv", "127.0.0.1", port1)
        return p1, port1, p2, port2, inst, s

    def test_replica_failover_keeps_reads_serving(self, tmp_path):
        p1, port1, p2, port2, inst, s = self._two_workers(tmp_path)
        try:
            inst.attach_replica("rp", "inv", "127.0.0.1", port2)
            # writes replicate synchronously to both endpoints
            s.execute("INSERT INTO inv VALUES (3, 9)")
            base = sorted(s.execute("SELECT id, qty FROM inv").rows)
            assert base == [(1, 5), (2, 7), (3, 9)]
            # kill the PRIMARY: probe fences it, reads fail over to the replica
            p1.kill()
            p1.wait()
            fenced = inst.ha.probe_workers()
            assert fenced[("127.0.0.1", port1)] is True
            for _ in range(5):
                r = sorted(s.execute("SELECT id, qty FROM inv").rows)
                assert r == base
        finally:
            for p in (p1, p2):
                if p.poll() is None:
                    p.kill()
                    p.wait()

    def test_fresh_replica_is_backfilled_before_serving(self, tmp_path):
        """A replica attached EMPTY must not serve reads until it holds the
        table's data: attach_replica snapshot-copies from the primary."""
        init = ("CREATE DATABASE rb; USE rb; "
                "CREATE TABLE r (id BIGINT PRIMARY KEY, v BIGINT); "
                "INSERT INTO r VALUES (1, 10), (2, 20)")
        p1, port1 = _spawn_worker(str(tmp_path / "b1"), init)
        p2, port2 = _spawn_worker(str(tmp_path / "b2"))  # EMPTY worker
        inst = Instance()
        s = Session(inst)
        s.execute("CREATE DATABASE rb")
        s.execute("USE rb")
        inst.attach_remote_table("rb", "r", "127.0.0.1", port1)
        try:
            inst.attach_replica("rb", "r", "127.0.0.1", port2)
            # force reads onto the replica by fencing the primary
            inst.ha.fence_worker(("127.0.0.1", port1), True)
            assert sorted(s.execute("SELECT id, v FROM r").rows) == \
                [(1, 10), (2, 20)]
        finally:
            for p in (p1, p2):
                if p.poll() is None:
                    p.kill()
                    p.wait()

    def test_move_table_between_workers(self, tmp_path):
        init = ("CREATE DATABASE mv; USE mv; "
                "CREATE TABLE t (id BIGINT PRIMARY KEY, v VARCHAR(12), "
                "amt DECIMAL(10,2)); "
                "INSERT INTO t VALUES (1,'a',1.25), (2,'b',2.50), (3,NULL,0.75)")
        p1, port1 = _spawn_worker(str(tmp_path / "m1"), init)
        p2, port2 = _spawn_worker(str(tmp_path / "m2"))  # empty target
        inst = Instance()
        s = Session(inst)
        s.execute("CREATE DATABASE mv")
        s.execute("USE mv")
        inst.attach_remote_table("mv", "t", "127.0.0.1", port1)
        try:
            # concurrent-ish write before the move cutover
            s.execute("INSERT INTO t VALUES (4, 'd', 4.00)")
            s.execute("DELETE FROM t WHERE id = 2")
            inst.move_remote_table("mv", "t", "127.0.0.1", port2)
            tm = inst.catalog.table("mv", "t")
            assert (tm.remote["host"], tm.remote["port"]) == ("127.0.0.1", port2)
            got = sorted(s.execute("SELECT id, v, amt FROM t").rows)
            assert got == [(1, "a", 1.25), (3, None, 0.75), (4, "d", 4.0)]
            # the moved table serves reads even with the OLD worker dead
            p1.kill()
            p1.wait()
            got = sorted(s.execute("SELECT id, v, amt FROM t").rows)
            assert got == [(1, "a", 1.25), (3, None, 0.75), (4, "d", 4.0)]
            # and accepts writes on the new primary
            s.execute("INSERT INTO t VALUES (9, 'z', 9.99)")
            assert s.execute("SELECT v FROM t WHERE id = 9").rows == [("z",)]
        finally:
            for p in (p1, p2):
                if p.poll() is None:
                    p.kill()
                    p.wait()


class TestHaActs:
    def test_fenced_worker_refuses_fast(self, session):
        s, port = session
        addr = ("127.0.0.1", port)
        # fencing a LIVE worker self-heals: the next read's lazy revival
        # ping proves it alive and unfences (no background prober exists
        # in production, so fencing must not be forever)
        s.instance.ha.fence_worker(addr, True)
        assert len(s.execute("SELECT k FROM dim").rows) == 5
        assert not s.instance.ha.worker_fenced(addr)
        # a fenced DEAD endpoint refuses FAST and typed: the revival ping
        # fails immediately (nothing listens), no socket hang
        from galaxysql_tpu.net.dn import WorkerClient
        dead_addr = ("127.0.0.1", 1)
        tm = s.instance.catalog.table("w", "dim")
        old_remote = dict(tm.remote)
        s.instance.workers[dead_addr] = WorkerClient("127.0.0.1", 1,
                                                     timeout=0.5)
        s.instance.ha.fence_worker(dead_addr, True)
        tm.remote = {"host": dead_addr[0], "port": dead_addr[1]}
        try:
            t0 = time.time()
            with pytest.raises(errors.TddlError, match="fenced"):
                s.execute("SELECT k FROM dim")
            assert time.time() - t0 < 2.0  # refusal, not a socket hang
        finally:
            tm.remote = old_remote
            del s.instance.workers[dead_addr]
            s.instance.ha.fence_worker(dead_addr, False)
        assert len(s.execute("SELECT k FROM dim").rows) == 5

    def test_probe_fences_dead_worker_and_recovers(self, session):
        s, port = session
        addr = ("127.0.0.1", port)
        fenced = s.instance.ha.probe_workers()
        assert fenced.get(addr) is False  # alive
        # dead endpoint: a worker nobody listens on
        from galaxysql_tpu.net.dn import WorkerClient
        dead = WorkerClient("127.0.0.1", 1)  # port 1: nothing listens
        s.instance.workers[("127.0.0.1", 1)] = dead
        try:
            fenced = s.instance.ha.probe_workers()
            assert fenced[("127.0.0.1", 1)] is True
            assert fenced[addr] is False
        finally:
            del s.instance.workers[("127.0.0.1", 1)]


class TestLeaderElection:
    def test_smallest_alive_coordinator_leads(self):
        inst = Instance()
        db = inst.metadb
        # "!" sorts before every hex digit, so this rival beats the
        # instance's own cn-<hex> heartbeat deterministically
        db.heartbeat("cn-!first", "coordinator", "h1", 0)
        db.heartbeat("cn-zzz", "coordinator", "h2", 0)
        inst.ha.check()
        assert inst.ha.leader() == "cn-!first"
        # the leader's heartbeat goes stale -> leadership moves
        from galaxysql_tpu.utils.failpoint import FAIL_POINTS
        from galaxysql_tpu.meta.ha import FP_HB_STALE
        FAIL_POINTS.arm(FP_HB_STALE, "cn-!first")
        try:
            trans = inst.ha.check()
            assert ("cn-!first", "ALIVE", "DEAD") in trans
            assert inst.ha.leader() != "cn-!first"
        finally:
            FAIL_POINTS.clear()

    def test_scheduler_fires_only_on_leader(self):
        inst = Instance()
        # another coordinator with a smaller id is alive: we are NOT leader
        db = inst.metadb
        db.heartbeat("cn-!rival", "coordinator", "h1", 0)
        inst.ha.check()
        assert not inst.ha.is_leader()
        inst.scheduler.register("j1", "analyze", "x", "y", {}, interval_s=0)
        assert inst.scheduler.run_due() == []  # gated
        # the rival dies -> leadership falls to us -> jobs fire
        from galaxysql_tpu.utils.failpoint import FAIL_POINTS
        from galaxysql_tpu.meta.ha import FP_HB_STALE
        FAIL_POINTS.arm(FP_HB_STALE, "cn-!rival")
        try:
            assert inst.ha.is_leader()
            fired = inst.scheduler.run_due()
            assert fired == ["j1"]  # job ran (FAILED status is fine: fake table)
        finally:
            FAIL_POINTS.clear()
