"""Online elastic rebalancing: SPLIT/MERGE/MOVE PARTITION + the Balancer.

Covers the ddl/rebalance.py job family (bucket-map conversion identity,
shadow backfill + CDC catchup + FastChecker verify + TSO-fenced cutover),
crash-resume from every checkpoint, the verify-mismatch rollback restoring
the source byte-identically, the open-transaction cutover drain, the
heat-driven balancer policy (server/balancer.py) with its admission-pressure
yield, and the SHOW REBALANCE / information_schema surfaces.

`make rebalance-smoke` runs this file with GALAXYSQL_LOCKDEP=1 so the move
path's router/partition-lock choreography doubles as a lock-order proof.
"""

import threading
import time

import numpy as np
import pytest

from galaxysql_tpu.ddl import rebalance as rb
from galaxysql_tpu.meta.catalog import PartitionInfo, PartitionRouter
from galaxysql_tpu.server.instance import Instance
from galaxysql_tpu.server.session import Session
from galaxysql_tpu.utils import errors
from galaxysql_tpu.utils.failpoint import (FAIL_POINTS, FP_MEM_PRESSURE,
                                           FP_REBALANCE_AFTER_SWAP,
                                           FP_REBALANCE_BEFORE_SWAP,
                                           FP_REBALANCE_CATCHUP,
                                           FP_REBALANCE_CHUNK,
                                           FP_REBALANCE_VERIFY_MISMATCH,
                                           FailPointError)
from galaxysql_tpu.utils.fastchecker import partitions_checksum

pytestmark = pytest.mark.rebalance


@pytest.fixture()
def session():
    inst = Instance()
    s = Session(inst)
    s.execute("CREATE DATABASE rb")
    s.execute("USE rb")
    yield s
    FAIL_POINTS.clear()
    s.close()


def load(session, n=2000, parts=4, table="t"):
    session.execute(
        f"CREATE TABLE {table} (id BIGINT PRIMARY KEY, grp BIGINT, "
        f"val VARCHAR(16)) PARTITION BY HASH(id) PARTITIONS {parts}")
    store = session.instance.store("rb", table)
    store.insert_pylists(
        {"id": list(range(n)), "grp": [i % 37 for i in range(n)],
         "val": [f"v{i % 11}" for i in range(n)]},
        session.instance.tso.next_timestamp())
    return store


def snapshot(session, table="t"):
    return session.execute(
        f"SELECT id, grp, val FROM {table} ORDER BY id").rows


def routing_invariant(store):
    """Every physical row lives where the live router would place it."""
    tm = store.table
    cols = [tm.column(c).name for c in tm.partition.columns]
    for pid, p in enumerate(store.partitions):
        if not p.num_rows:
            continue
        got = store.router.route_rows([p.lanes[c] for c in cols])
        assert (got == pid).all(), f"partition {pid} holds foreign rows"


class TestSplitMergeMove:
    def test_bucket_conversion_is_routing_identical(self, session):
        load(session, n=10, parts=4)
        tm = session.instance.catalog.table("rb", "t")
        keys = [np.arange(200_000, dtype=np.int64)]
        before = PartitionRouter(tm).route_rows(keys)
        info2 = PartitionInfo("hash", ["id"], 4, [],
                              [b % 4 for b in range(4 * rb.BUCKETS_PER)])
        after = PartitionRouter(tm, info2).route_rows(keys)
        assert (before == after).all()

    def test_split_end_to_end(self, session):
        store = load(session, n=2000, parts=4)
        before = snapshot(session)
        epoch0 = store.router.epoch
        session.execute("ALTER TABLE t SPLIT PARTITION p1 INTO 3")
        tm = session.instance.catalog.table("rb", "t")
        assert tm.partition.num_partitions == 6
        assert len(store.partitions) == 6
        assert store.router.epoch > epoch0  # versioned router swapped
        assert snapshot(session) == before
        routing_invariant(store)
        # new DML routes by the NEW map
        session.execute("INSERT INTO t VALUES (777777, 3, 'nv')")
        assert session.execute(
            "SELECT grp FROM t WHERE id = 777777").rows == [(3,)]
        # shadow + kv state fully cleaned
        assert not session.instance.rebalance_shadows
        assert not [k for k, _ in session.instance.metadb.kv_scan("rebal.")
                    if ".hist." not in k]

    def test_merge_end_to_end(self, session):
        store = load(session, n=2000, parts=4)
        before = snapshot(session)
        session.execute("ALTER TABLE t MERGE PARTITIONS p0, p2")
        tm = session.instance.catalog.table("rb", "t")
        assert tm.partition.num_partitions == 3
        assert len(store.partitions) == 3
        assert snapshot(session) == before
        routing_invariant(store)
        session.execute("DELETE FROM t WHERE id = 7")
        assert session.execute(
            "SELECT count(*) FROM t").rows == [(1999,)]

    def test_move_rebuilds_and_places(self, session):
        store = load(session, n=1500, parts=4)
        before = snapshot(session)
        # dead versions compact away: delete some rows first so the source
        # partition holds garbage the rebuilt copy drops
        session.execute("DELETE FROM t WHERE id % 10 = 3")
        expect = session.execute("SELECT count(*) FROM t").rows
        physical_before = store.partitions[2].num_rows
        session.execute("ALTER TABLE t MOVE PARTITION p2 TO 'g1'")
        tm = session.instance.catalog.table("rb", "t")
        assert tm.partition.group_of(2) == "g1"
        assert tm.partition.group_of(1) == PartitionInfo.DEFAULT_GROUP
        assert session.execute("SELECT count(*) FROM t").rows == expect
        # the rebuilt partition dropped the dead MVCC versions
        assert store.partitions[2].num_rows < physical_before
        routing_invariant(store)
        assert snapshot(session) == [r for r in before if r[0] % 10 != 3]

    def test_range_split_at_and_merge(self, session):
        session.execute(
            "CREATE TABLE r (id BIGINT PRIMARY KEY, d BIGINT) "
            "PARTITION BY RANGE(d) (PARTITION r0 VALUES LESS THAN (100), "
            "PARTITION r1 VALUES LESS THAN (MAXVALUE))")
        store = session.instance.store("rb", "r")
        store.insert_pylists(
            {"id": list(range(600)), "d": [i % 200 for i in range(600)]},
            session.instance.tso.next_timestamp())
        before = session.execute("SELECT id, d FROM r ORDER BY id").rows
        session.execute("ALTER TABLE r SPLIT PARTITION p0 AT (50)")
        tm = session.instance.catalog.table("rb", "r")
        assert tm.partition.num_partitions == 3
        assert [b[1][0] for b in tm.partition.boundaries] == [50, 100, None]
        assert session.execute("SELECT id, d FROM r ORDER BY id").rows == before
        # partition p0 now holds exactly d < 50
        assert int(store.partitions[0].num_rows) == \
            sum(1 for _, d in before if d < 50)
        session.execute("ALTER TABLE r MERGE PARTITIONS p1, p2")
        tm = session.instance.catalog.table("rb", "r")
        assert tm.partition.num_partitions == 2
        assert session.execute("SELECT id, d FROM r ORDER BY id").rows == before

    def test_split_preserves_gsi_consistency(self, session):
        from galaxysql_tpu.utils.fastchecker import check_gsi
        load(session, n=1200, parts=4)
        session.execute("CREATE GLOBAL INDEX g_grp ON t (grp) COVERING (val)")
        session.execute("ALTER TABLE t SPLIT PARTITION p0 INTO 2")
        res = check_gsi(session.instance, "rb", "t", "g_grp")
        assert res["consistent"], res
        # the GSI route still serves
        assert session.execute(
            "SELECT count(*) FROM t WHERE grp = 5").rows[0][0] > 0

    def test_rejects_unsupported_shapes(self, session):
        session.execute("CREATE TABLE s1 (id BIGINT PRIMARY KEY) SINGLE")
        with pytest.raises(errors.TddlError):
            session.execute("ALTER TABLE s1 MOVE PARTITION p0 TO 'g1'")
        session.execute("CREATE TABLE nk (id BIGINT, v BIGINT) "
                        "PARTITION BY HASH(id) PARTITIONS 2")
        with pytest.raises(errors.TddlError):  # no primary key
            session.execute("ALTER TABLE nk SPLIT PARTITION p0")
        load(session, n=10, parts=2, table="cdcoff")
        session.execute("SET GLOBAL ENABLE_CDC = 0")
        try:
            with pytest.raises(errors.TddlError):
                session.execute("ALTER TABLE cdcoff SPLIT PARTITION p0")
        finally:
            session.execute("SET GLOBAL ENABLE_CDC = 1")

    def test_split_argument_validation_typed(self, session):
        load(session, n=200, parts=2)
        # INTO < 2 must fail typed, not divide by zero with the job wedged
        for n in (0, 1):
            with pytest.raises(errors.TddlError):
                session.execute(f"ALTER TABLE t SPLIT PARTITION p0 INTO {n}")
        # AT (value) on a hash table would be silently ignored -> typed
        with pytest.raises(errors.TddlError):
            session.execute("ALTER TABLE t SPLIT PARTITION p0 AT (5)")
        # INTO n != 2 on a range table would be silently ignored -> typed
        session.execute(
            "CREATE TABLE rv (id BIGINT PRIMARY KEY, d BIGINT) "
            "PARTITION BY RANGE(d) (PARTITION r0 VALUES LESS THAN (100), "
            "PARTITION r1 VALUES LESS THAN (MAXVALUE))")
        with pytest.raises(errors.TddlError):
            session.execute("ALTER TABLE rv SPLIT PARTITION p0 AT (50) INTO 3")
        # nothing wedged: the legal split on the same table still runs
        session.execute("ALTER TABLE t SPLIT PARTITION p0 INTO 2")
        assert len(session.instance.store("rb", "t").partitions) == 3
        routing_invariant(session.instance.store("rb", "t"))


class TestCrashResume:
    def test_crash_mid_backfill_resumes_from_checkpoint(self, session):
        store = load(session, n=3000, parts=2)
        before = snapshot(session)
        old_chunk = rb.RebalanceBackfillTask.CHUNK
        rb.RebalanceBackfillTask.CHUNK = 128
        try:
            FAIL_POINTS.arm(FP_REBALANCE_CHUNK, 4)
            with pytest.raises(FailPointError):
                session.execute("ALTER TABLE t SPLIT PARTITION p0 INTO 2")
            # job parked RUNNING; shadows hold a partial copy
            assert session.instance.rebalance_shadows
            # serving continues off the OLD map meanwhile (plus a write the
            # catchup must pick up)
            assert snapshot(session) == before
            session.execute("INSERT INTO t VALUES (888888, 1, 'mid')")
            FAIL_POINTS.clear()
            resumed = session.instance.ddl_engine.recover()
            assert resumed
        finally:
            rb.RebalanceBackfillTask.CHUNK = old_chunk
            FAIL_POINTS.clear()
        tm = session.instance.catalog.table("rb", "t")
        assert tm.partition.num_partitions == 3
        assert snapshot(session) == sorted(
            before + [(888888, 1, "mid")])
        routing_invariant(store)

    def test_crash_mid_catchup_is_idempotent(self, session):
        store = load(session, n=1000, parts=2)
        # park the job mid-backfill so the churn lands AFTER the snapshot —
        # the catchup then has real post-snapshot events to replay (updates
        # and deletes, so the delete-by-PK path runs too)
        FAIL_POINTS.arm(FP_REBALANCE_CHUNK, 1)
        with pytest.raises(FailPointError):
            session.execute("ALTER TABLE t SPLIT PARTITION p0 INTO 2")
        FAIL_POINTS.clear()
        session.execute("UPDATE t SET val = 'x' WHERE id < 50")
        session.execute("DELETE FROM t WHERE id BETWEEN 100 AND 120")
        session.execute("INSERT INTO t VALUES (555555, 5, 'late')")
        before = snapshot(session)
        # crash in the catchup loop after the first (only) event page — the
        # persisted seq watermark makes the resumed re-apply idempotent
        FAIL_POINTS.arm(FP_REBALANCE_CATCHUP, 1)
        with pytest.raises(FailPointError):
            session.instance.ddl_engine.recover()
        FAIL_POINTS.clear()
        assert session.instance.ddl_engine.recover()
        assert session.instance.catalog.table(
            "rb", "t").partition.num_partitions == 3
        assert snapshot(session) == before
        routing_invariant(store)

    def test_crash_before_swap_resumes(self, session):
        store = load(session, n=800, parts=2)
        before = snapshot(session)
        FAIL_POINTS.arm(FP_REBALANCE_BEFORE_SWAP, True)
        with pytest.raises(FailPointError):
            session.execute("ALTER TABLE t MERGE PARTITIONS p0, p1")
        # swap did NOT happen: old map still serves
        assert len(store.partitions) == 2
        assert snapshot(session) == before
        FAIL_POINTS.clear()
        assert session.instance.ddl_engine.recover()
        assert len(store.partitions) == 1
        assert snapshot(session) == before
        routing_invariant(store)

    def test_crash_after_swap_does_not_reswap(self, session):
        store = load(session, n=800, parts=2)
        before = snapshot(session)
        FAIL_POINTS.arm(FP_REBALANCE_AFTER_SWAP, True)
        with pytest.raises(FailPointError):
            session.execute("ALTER TABLE t SPLIT PARTITION p1 INTO 2")
        # swap already durable + live
        assert len(store.partitions) == 3
        FAIL_POINTS.clear()
        parts_snapshot = store.partitions
        assert session.instance.ddl_engine.recover()
        # resume published/cleaned up WITHOUT swapping again
        assert store.partitions is parts_snapshot
        assert snapshot(session) == before
        assert not [k for k, _ in session.instance.metadb.kv_scan("rebal.")
                    if ".hist." not in k]

    def test_verify_mismatch_rolls_back_source_byte_identical(self, session):
        store = load(session, n=1000, parts=2)
        tm = session.instance.catalog.table("rb", "t")
        cols = tm.column_names()
        ts0 = session.instance.tso.next_timestamp()
        chk0 = partitions_checksum(store.partitions, cols, ts0)
        FAIL_POINTS.arm(FP_REBALANCE_VERIFY_MISMATCH, True)
        with pytest.raises(errors.TddlError, match="verify failed"):
            session.execute("ALTER TABLE t SPLIT PARTITION p0 INTO 2")
        FAIL_POINTS.clear()
        # reverse-order undo dropped the shadows + kv and never touched the
        # source: FastChecker proves byte-identity at the same snapshot
        assert partitions_checksum(store.partitions, cols, ts0) == chk0
        assert tm.partition.num_partitions == 2
        assert not session.instance.rebalance_shadows
        assert not [k for k, _ in session.instance.metadb.kv_scan("rebal.")
                    if ".hist." not in k]
        # and the table is not wedged: a clean retry succeeds
        session.execute("ALTER TABLE t SPLIT PARTITION p0 INTO 2")
        assert tm.partition.num_partitions == 3

    def test_cutover_drains_open_transactions(self, session):
        load(session, n=400, parts=2)
        inst = session.instance
        s2 = Session(inst, "rb")
        try:
            s2.execute("BEGIN")
            s2.execute("INSERT INTO t VALUES (999001, 1, 'txn')")
            inst.config.set_instance("REBALANCE_DRAIN_TIMEOUT_S", 0.3)
            with pytest.raises(errors.TddlError, match="pin the table"):
                session.execute("ALTER TABLE t MOVE PARTITION p0 TO 'g1'")
            # rollback left the source serving and un-wedged
            s2.execute("COMMIT")
            inst.config.set_instance("REBALANCE_DRAIN_TIMEOUT_S", 30.0)
            session.execute("ALTER TABLE t MOVE PARTITION p0 TO 'g1'")
            assert inst.catalog.table("rb", "t").partition.group_of(0) == "g1"
            assert session.execute(
                "SELECT count(*) FROM t").rows == [(401,)]
        finally:
            inst.config.set_instance("REBALANCE_DRAIN_TIMEOUT_S", 30.0)
            s2.close()

    def test_cutover_drain_covers_midflight_commits(self, session):
        """Session._commit clears sess.txn BEFORE applying the commit, so
        the drain must ALSO refuse to swap while provisional MVCC stamps sit
        in the source partitions (the mid-flight-commit window)."""
        store = load(session, n=400, parts=2)
        inst = session.instance
        # the scan covers the partitions being DETACHED — pick an id that
        # routes to the moved partition p0 (a stamp elsewhere is untouched
        # by the swap and must NOT block it)
        wid = next(i for i in range(999002, 999400)
                   if int(store.router.route_rows(
                       [np.asarray([i], dtype=np.int64)])[0]) == 0)
        s2 = Session(inst, "rb")
        try:
            s2.execute("BEGIN")
            s2.execute(f"INSERT INTO t VALUES ({wid}, 1, 'mid')")
            txn = s2.txn
            s2.txn = None  # the commit ramp's state at the drain's window
            inst.config.set_instance("REBALANCE_DRAIN_TIMEOUT_S", 0.3)
            with pytest.raises(errors.TddlError, match="pin the table"):
                session.execute("ALTER TABLE t MOVE PARTITION p0 TO 'g1'")
            # finish the commit the way _commit would, then the move goes
            s2.txn = txn
            s2.execute("COMMIT")
            inst.config.set_instance("REBALANCE_DRAIN_TIMEOUT_S", 30.0)
            session.execute("ALTER TABLE t MOVE PARTITION p0 TO 'g1'")
            assert session.execute(
                f"SELECT val FROM t WHERE id = {wid}").rows == [("mid",)]
        finally:
            inst.config.set_instance("REBALANCE_DRAIN_TIMEOUT_S", 30.0)
            s2.close()

    def test_rebalance_does_not_leak_binlog_events(self, session):
        load(session, n=500, parts=2)
        n0 = len(session.instance.cdc.events(0, limit=100000))
        session.execute("ALTER TABLE t SPLIT PARTITION p0 INTO 2")
        # data movement is physical, not logical: no CDC events emitted
        assert len(session.instance.cdc.events(0, limit=100000)) == n0


class TestConcurrentDml:
    def test_split_under_concurrent_writes_loses_nothing(self, session):
        store = load(session, n=4000, parts=2)
        inst = session.instance
        old_chunk = rb.RebalanceBackfillTask.CHUNK
        rb.RebalanceBackfillTask.CHUNK = 256
        acked = {"ins": [], "del": [], "errs": []}
        stop = threading.Event()

        def writer(base):
            s = Session(inst, "rb")
            try:
                i = 0
                while not stop.is_set() and i < 400:
                    wid = base + i
                    try:
                        s.execute(
                            f"INSERT INTO t VALUES ({wid}, {wid % 37}, 'w')")
                        acked["ins"].append(wid)
                        if i % 7 == 3:
                            s.execute(f"DELETE FROM t WHERE id = {wid}")
                            acked["del"].append(wid)
                    except errors.TddlError as e:
                        acked["errs"].append(str(e))
                    i += 1
            finally:
                s.close()

        threads = [threading.Thread(target=writer, args=(1_000_000 * (k + 1),))
                   for k in range(3)]
        for t in threads:
            t.start()
        try:
            session.execute("ALTER TABLE t SPLIT PARTITION p1 INTO 3")
        finally:
            stop.set()
            for t in threads:
                t.join()
            rb.RebalanceBackfillTask.CHUNK = old_chunk
        rows = session.execute("SELECT id FROM t WHERE id >= 1000000").rows
        got = [r[0] for r in rows]
        expect = sorted(set(acked["ins"]) - set(acked["del"]))
        # zero lost writes, zero duplicated writes
        assert sorted(got) == expect
        assert len(got) == len(set(got))
        assert session.execute(
            "SELECT count(*) FROM t WHERE id < 1000000").rows == [(4000,)]
        routing_invariant(store)


class TestBalancer:
    def _hot_table(self, session, hot_part_rows=6000, cold_rows=200):
        session.execute(
            "CREATE TABLE h (id BIGINT PRIMARY KEY, k BIGINT, v BIGINT) "
            "PARTITION BY HASH(k) PARTITIONS 4")
        store = session.instance.store("rb", "h")
        tm = session.instance.catalog.table("rb", "h")
        # find a key per partition, then overload ONE partition
        router = store.router
        keys_by_pid = {}
        for k in range(200):
            pid = int(router.route_rows([np.asarray([k], dtype=np.int64)])[0])
            keys_by_pid.setdefault(pid, k)
            if len(keys_by_pid) == 4:
                break
        hot_key = keys_by_pid[0]
        ids = iter(range(10_000_000))
        data = {"id": [], "k": [], "v": []}
        for _ in range(hot_part_rows):
            data["id"].append(next(ids))
            data["k"].append(hot_key)
            data["v"].append(1)
        for pid in (1, 2, 3):
            for _ in range(cold_rows):
                data["id"].append(next(ids))
                data["k"].append(keys_by_pid[pid])
                data["v"].append(1)
        store.insert_pylists(data, session.instance.tso.next_timestamp())
        session.execute("ANALYZE TABLE h")  # builds the heavy sketches
        return store, tm

    def test_proposes_split_of_hot_partition(self, session):
        self._hot_table(session)
        props = session.instance.balancer.propose("rb", "h")
        assert props and props[0]["op"] == "split"
        assert props[0]["pids"] == [0]

    def test_rebalance_table_applies(self, session):
        store, tm = self._hot_table(session)
        rows = session.execute("REBALANCE TABLE h").rows
        assert rows and rows[0][1] == "split" and rows[0][5] == "applied"
        assert tm.partition.num_partitions == 5
        routing_invariant(store)

    def test_split_damping_after_no_progress(self, session):
        # one indivisible hot KEY: the first split is proposed and applied,
        # but it cannot divide the key's mass — the follow-up tick must not
        # chase it with another full backfill+cutover (runaway to
        # REBALANCE_MAX_PARTITIONS)
        store, tm = self._hot_table(session)
        inst = session.instance
        rows = session.execute("REBALANCE TABLE h").rows
        assert rows and rows[0][1] == "split" and rows[0][5] == "applied"
        assert tm.partition.num_partitions == 5
        props = inst.balancer.propose("rb", "h")
        assert not any(p["op"] == "split" for p in props), props
        # dry re-proposals without a landed split stay un-parked (covered by
        # the traffic-gate test calling propose twice) — and the park clears
        # if the table shrinks back below the parked partition count
        inst.balancer._split_outcome["rb.h"] = (9, 1.0, 0)
        props = inst.balancer.propose("rb", "h")
        assert any(p["op"] == "split" for p in props), props

    def test_traffic_match_is_word_bounded(self, session):
        # a table named `t` must not collect the traffic of every statement
        # containing the letter t ("select", "count", ...)
        session.execute("CREATE TABLE t (id BIGINT PRIMARY KEY) "
                        "PARTITION BY HASH(id) PARTITIONS 2")
        session.execute("CREATE TABLE h (id BIGINT PRIMARY KEY, k BIGINT) "
                        "PARTITION BY HASH(k) PARTITIONS 2")
        inst = session.instance
        base = inst.balancer.table_traffic().get("rb.t", 0.0)
        for _ in range(5):
            session.execute("SELECT count(*) FROM h")
        traffic = inst.balancer.table_traffic()
        assert traffic.get("rb.t", 0.0) == base, "h's traffic leaked onto t"
        assert traffic.get("rb.h", 0.0) > 0

    def test_proposes_merge_of_cold_pair(self, session):
        session.execute(
            "CREATE TABLE c (id BIGINT PRIMARY KEY, v BIGINT) "
            "PARTITION BY HASH(id) PARTITIONS 6")
        store = session.instance.store("rb", "c")
        # two partitions nearly empty, the rest loaded
        ids = [i for i in range(20000)
               if int(store.router.route_rows(
                   [np.asarray([i], dtype=np.int64)])[0]) not in (2, 5)]
        store.insert_pylists({"id": ids, "v": [0] * len(ids)},
                             session.instance.tso.next_timestamp())
        props = session.instance.balancer.propose("rb", "c")
        assert props and props[0]["op"] == "merge"
        assert props[0]["pids"] == [2, 5]

    def test_proposes_cross_group_move(self, session):
        load(session, n=3000, parts=4)
        inst = session.instance
        inst.config.set_instance("REBALANCE_GROUPS", "g0,g1")
        # damp split/merge proposals so the move policy is what fires
        inst.config.set_instance("REBALANCE_SPLIT_FACTOR", 100.0)
        inst.config.set_instance("REBALANCE_MERGE_FACTOR", 0.0)
        try:
            props = inst.balancer.propose("rb", "t")
            assert props and props[0]["op"] == "move"
            assert props[0]["group"] == "g1"
        finally:
            for k, v in (("REBALANCE_GROUPS", ""),
                         ("REBALANCE_SPLIT_FACTOR", 2.0),
                         ("REBALANCE_MERGE_FACTOR", 0.25)):
                inst.config.set_instance(k, v)

    def test_yields_under_memory_pressure(self, session):
        self._hot_table(session)
        FAIL_POINTS.arm(FP_MEM_PRESSURE, "critical")
        try:
            assert session.instance.balancer.run_once("rb", "h") == []
        finally:
            FAIL_POINTS.clear()
        # and the hatch: ENABLE_REBALANCE=0 proposes nothing
        session.instance.config.set_instance("ENABLE_REBALANCE", False)
        try:
            assert session.instance.balancer.run_once("rb", "h") == []
        finally:
            session.instance.config.set_instance("ENABLE_REBALANCE", True)

    def test_traffic_gate_skips_cold_tables(self, session):
        self._hot_table(session)
        inst = session.instance
        inst.config.set_instance("REBALANCE_MIN_TRAFFIC_MS", 1e12)
        try:
            assert inst.balancer.propose("rb", "h") == []
        finally:
            inst.config.set_instance("REBALANCE_MIN_TRAFFIC_MS", 0.0)
        # drive real traffic through the statement summary: the digest text
        # names the table, so it clears a modest gate
        for _ in range(3):
            session.execute("SELECT count(*) FROM h WHERE k = 1")
        inst.config.set_instance("REBALANCE_MIN_TRAFFIC_MS", 1e-6)
        try:
            assert inst.balancer.propose("rb", "h")
        finally:
            inst.config.set_instance("REBALANCE_MIN_TRAFFIC_MS", 0.0)

    def test_maintain_loop_job_kind(self, session):
        self._hot_table(session)
        inst = session.instance
        inst.scheduler.register("auto_rb", "rebalance", "rb", "h",
                                {"apply": False}, interval_s=0.0)
        fired = inst.scheduler.run_due()
        assert "auto_rb" in fired
        hist = inst.scheduler.history("auto_rb")
        assert hist and hist[-1][2] == "SUCCESS"
        assert "proposal" in hist[-1][3]


class TestSurfaces:
    def test_show_rebalance_and_info_schema(self, session):
        load(session, n=1500, parts=2)
        session.execute("ALTER TABLE t SPLIT PARTITION p0 INTO 2")
        rows = session.execute("SHOW REBALANCE").rows
        assert rows
        job = rows[-1]
        assert job[2] == "split" and job[3] == "DONE"
        assert job[7] > 0  # rows copied
        assert job[11] > 0  # router epoch recorded at cutover
        irows = session.execute(
            "SELECT kind, state, phase FROM information_schema.rebalance_jobs"
        ).rows
        assert ("split", "DONE", "cutover") in irows

    def test_live_progress_mid_job(self, session):
        load(session, n=3000, parts=2)
        old_chunk = rb.RebalanceBackfillTask.CHUNK
        rb.RebalanceBackfillTask.CHUNK = 128
        try:
            FAIL_POINTS.arm(FP_REBALANCE_CHUNK, 6)
            with pytest.raises(FailPointError):
                session.execute("ALTER TABLE t SPLIT PARTITION p0 INTO 2")
            FAIL_POINTS.clear()
            rows = session.execute("SHOW REBALANCE").rows
            live = [r for r in rows if r[3] == "RUNNING"]
            assert live and live[0][4] == "backfill"
            assert live[0][7] > 0  # rows copied so far
            assert live[0][10] != "[]"  # checkpoint recorded
            assert session.instance.ddl_engine.recover()
        finally:
            rb.RebalanceBackfillTask.CHUNK = old_chunk
            FAIL_POINTS.clear()

    def test_counters(self, session):
        load(session, n=500, parts=2)
        c0 = session.instance.counters["rebalance_jobs"]
        session.execute("ALTER TABLE t MERGE PARTITIONS p0, p1")
        assert session.instance.counters["rebalance_jobs"] == c0 + 1
