"""Native runtime (libgalaxystore): bindings correctness vs the numpy fallbacks,
hash consistency with the device kernels, and bloom runtime-filter semantics."""

import numpy as np
import pytest

from galaxysql_tpu import native


class TestBindings:
    def test_library_loaded(self):
        # the image ships g++, so the native path must actually be live in CI
        assert native.AVAILABLE

    def test_hash_partition_matches_fallback_and_device(self):
        import jax.numpy as jnp
        from galaxysql_tpu.kernels import relational as K
        keys = np.random.default_rng(0).integers(-2**62, 2**62, 4096)
        nat = native.hash_partition(keys, 16)
        # numpy fallback
        with np.errstate(over="ignore"):
            h = native._mix_np(keys.astype(np.uint64))
        ref = (h % np.uint64(16)).astype(np.int32)
        np.testing.assert_array_equal(nat, ref)
        # device kernel mix
        dev = np.asarray(K._mix64(jnp.asarray(keys).astype(jnp.uint64)))
        np.testing.assert_array_equal(np.asarray(dev % 16, dtype=np.int32), nat)

    def test_visible_mask_matches_fallback(self):
        INF = (1 << 63) - 1
        begin = np.array([100, 200, -7, 300, -9], dtype=np.int64)
        end = np.array([INF, 150, INF, -7, INF], dtype=np.int64)
        for ts, txn in [(250, 0), (250, 7), (120, 0), (250, 9)]:
            nat = native.visible_mask(begin, end, ts, txn)
            b, e = begin, end
            ins = (b >= 0) & (b <= ts)
            dele = (e >= 0) & (e <= ts)
            if txn:
                ins = ins | (b == -txn)
                dele = dele | (e == -txn)
            np.testing.assert_array_equal(nat, ins & ~dele, err_msg=f"ts={ts} txn={txn}")

    def test_bloom_no_false_negatives(self):
        rng = np.random.default_rng(1)
        keys = rng.integers(0, 10**12, 5000)
        words = native.bloom_build(keys, 2048)
        assert native.bloom_query(keys, words).all()  # bloom property
        other = rng.integers(10**13, 10**14, 5000)
        fp = native.bloom_query(other, words).mean()
        assert fp < 0.05  # ~16 bits/key, 2 probes

    def test_bloom_device_matches_native(self):
        import jax.numpy as jnp
        from galaxysql_tpu.kernels import relational as K
        rng = np.random.default_rng(2)
        keys = rng.integers(0, 10**9, 1000)
        words = native.bloom_build(keys[:500], 512)
        host = native.bloom_query(keys, words)
        dev = np.asarray(K.bloom_query_device(jnp.asarray(keys), jnp.asarray(words)))
        np.testing.assert_array_equal(host, dev)

    def test_varint_codec_roundtrip(self):
        rng = np.random.default_rng(3)
        vals = rng.integers(-10**9, 10**9, 10000).cumsum()  # delta-friendly
        enc = native.encode_i64(vals)
        assert len(enc) < vals.nbytes  # actually compresses sorted-ish data
        dec = native.decode_i64(enc, vals.size)
        np.testing.assert_array_equal(dec, vals)

    def test_crc32c(self):
        # RFC 3720 test vector: crc32c of 32 zero bytes
        if native.AVAILABLE:
            assert native.crc32c(b"\x00" * 32) == 0x8A9136AA


class TestBloomRuntimeFilter:
    def test_join_results_unchanged(self):
        from galaxysql_tpu.chunk.batch import batch_from_pydict
        from galaxysql_tpu.exec.operators import HashJoinOp, SourceOp, run_to_batch
        from galaxysql_tpu.expr import ir
        from galaxysql_tpu.types import datatype as dt
        rng = np.random.default_rng(4)
        build = batch_from_pydict({"k": rng.integers(0, 100, 50).tolist(),
                                   "v": list(range(50))},
                                  {"k": dt.BIGINT, "v": dt.BIGINT})
        probe = batch_from_pydict({"k": rng.integers(0, 10000, 5000).tolist(),
                                   "q": list(range(5000))},
                                  {"k": dt.BIGINT, "q": dt.BIGINT})
        kd = ir.ColRef("k", dt.BIGINT)
        for jt in ("inner", "semi"):
            op = HashJoinOp(SourceOp([build]), SourceOp([probe]), [kd], [kd], jt)
            with_bloom = sorted(run_to_batch(op).to_pylist())
            op2 = HashJoinOp(SourceOp([build]), SourceOp([probe]), [kd], [kd], jt)
            op2.BLOOM_MAX_BUILD = 0  # disable
            without = sorted(run_to_batch(op2).to_pylist())
            assert with_bloom == without, jt
