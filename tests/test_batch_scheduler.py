"""Cross-session point-query batching (server/batch_scheduler.py).

Guards the mega-batched TP serving path: batched results must be
bit-identical to sequential execution (rows AND order) under heavy
concurrency, a poisoned key fails only its own session, transactional
sessions keep exact snapshot semantics, and the static batch buckets never
retrace in steady state.  Fast target: `make batch-smoke`.
"""

import threading
import time

import numpy as np
import pytest

from galaxysql_tpu.server.instance import Instance
from galaxysql_tpu.server.session import Session

pytestmark = pytest.mark.batching


@pytest.fixture()
def sess():
    inst = Instance()
    s = Session(inst)
    s.execute("CREATE DATABASE bsx")
    s.execute("USE bsx")
    s.execute("""
        CREATE TABLE t (
            id BIGINT NOT NULL PRIMARY KEY,
            k  INT NOT NULL,
            v  VARCHAR(20),
            amt DECIMAL(12,2)
        ) PARTITION BY HASH(id) PARTITIONS 4
    """)
    rows = ", ".join(f"({i}, {i % 41}, 'v{i % 13}', {i}.25)"
                     for i in range(1, 2001))
    s.execute(f"INSERT INTO t (id, k, v, amt) VALUES {rows}")
    return inst, s


def _register(s, sql_tpl, key):
    """Two executions register + warm the PointPlan for the template."""
    s.execute(sql_tpl % key)
    s.execute(sql_tpl % key)


def _run_threads(n, fn):
    errors = []
    barrier = threading.Barrier(n)

    def runner(i):
        try:
            barrier.wait(timeout=30)
            fn(i)
        except Exception as e:  # pragma: no cover - assertion carrier
            errors.append(e)

    threads = [threading.Thread(target=runner, args=(i,)) for i in range(n)]
    for t in threads:
        t.start()
    for t in threads:
        t.join()
    return errors


def test_batched_bit_identical_100_sessions(sess):
    """100+ concurrent sessions: every batched result equals the sequential
    (batching-off) execution of the same statement, and groups actually
    formed (the run was not a fallback parade)."""
    inst, s = sess
    tpl = "SELECT v, amt FROM t WHERE id = %d"
    _register(s, tpl, 1)
    keys = list(range(1, 2001, 7)) + [999999, 1000001]
    inst.config.set_instance("ENABLE_BATCH_SCHEDULER", 0)
    expected = {k: s.execute(tpl % k).rows for k in keys}
    inst.config.set_instance("ENABLE_BATCH_SCHEDULER", 1)
    inst.config.set_instance("BATCH_WINDOW_US", 3000)

    def worker(i):
        sx = Session(inst, schema="bsx")
        for j in range(8):
            k = keys[(i * 13 + j * 29) % len(keys)]
            got = sx.execute(tpl % k).rows
            assert got == expected[k], (k, got, expected[k])
        sx.close()

    errors = _run_threads(104, worker)
    assert not errors, errors[:3]
    assert inst.metrics.counter("batched_queries").value > 0
    assert inst.metrics.counter("batch_flushes").value > 0


def test_multi_row_non_unique_key_row_order(sess):
    """A non-unique indexed key returns MULTIPLE rows; the batched gather
    must reproduce the sequential path's row ORDER exactly (partition order,
    then ascending row ids)."""
    inst, s = sess
    s.execute("CREATE INDEX i_k ON t (k)")
    s.execute("ANALYZE TABLE t")
    tpl = "SELECT id, amt FROM t WHERE k = %d"
    _register(s, tpl, 5)
    keys = list(range(41))
    inst.config.set_instance("ENABLE_BATCH_SCHEDULER", 0)
    expected = {k: s.execute(tpl % k).rows for k in keys}
    assert any(len(r) > 10 for r in expected.values())
    inst.config.set_instance("ENABLE_BATCH_SCHEDULER", 1)
    inst.config.set_instance("BATCH_WINDOW_US", 3000)

    def worker(i):
        sx = Session(inst, schema="bsx")
        for j in range(4):
            k = keys[(i * 7 + j) % len(keys)]
            got = sx.execute(tpl % k).rows
            assert got == expected[k], (k, len(got), len(expected[k]))
        sx.close()

    errors = _run_threads(24, worker)
    assert not errors, errors[:3]


def test_error_isolation_poisoned_key(sess):
    """A poisoned key inside a group fails ONLY its own session; every other
    member of the same flush gets its correct rows."""
    from galaxysql_tpu.utils.failpoint import (FAIL_POINTS,
                                               FP_BATCH_POISON_KEY,
                                               FailPointError)
    inst, s = sess
    tpl = "SELECT amt FROM t WHERE id = %d"
    _register(s, tpl, 1)
    inst.config.set_instance("BATCH_WINDOW_US", 20000)
    poisoned_key = 777
    FAIL_POINTS.arm(FP_BATCH_POISON_KEY, poisoned_key)
    outcomes = {}
    lock = threading.Lock()
    try:
        def worker(i):
            sx = Session(inst, schema="bsx")
            key = poisoned_key if i == 3 else 100 + i
            try:
                rows = sx.execute(tpl % key).rows
                with lock:
                    outcomes[i] = rows
            except FailPointError:
                with lock:
                    outcomes[i] = "poisoned"
            finally:
                sx.close()

        errors = _run_threads(8, worker)
        assert not errors, errors[:3]
    finally:
        FAIL_POINTS.disarm(FP_BATCH_POISON_KEY)
    assert outcomes[3] == "poisoned"
    for i in range(8):
        if i == 3:
            continue
        assert outcomes[i] == [(100 + i + 0.25,)], (i, outcomes[i])
    # the error surfaced through the normal error ramp (profile + counter)
    assert inst.metrics.counter("query_errors").value >= 1


def test_txn_write_bypass_and_snapshot_semantics(sess):
    """Sessions inside a writing transaction bypass batching (own provisional
    stamps stay own-visible); read-only transactions keep their pinned
    snapshot; autocommit sessions see committed writes through the batched
    path."""
    inst, s = sess
    tpl = "SELECT amt FROM t WHERE id = %d"
    _register(s, tpl, 42)
    inst.config.set_instance("BATCH_WINDOW_US", 2000)
    # writing txn: sees its own uncommitted write, bypassing the group path
    s.execute("BEGIN")
    s.execute("UPDATE t SET amt = 777.77 WHERE id = 42")
    assert s.execute(tpl % 42).rows == [(777.77,)]
    prof = inst.profiles.entries()[-1]
    assert prof.engine != "batch"
    # a concurrent autocommit session must NOT see it (even batched)
    s2 = Session(inst, schema="bsx")
    assert s2.execute(tpl % 42).rows == [(42.25,)]
    s.execute("COMMIT")
    # read-only txn pinned BEFORE an update commits keeps the old snapshot
    s3 = Session(inst, schema="bsx")
    s3.execute("BEGIN")
    assert s3.execute(tpl % 42).rows == [(777.77,)]  # pin snapshot
    s2.execute("UPDATE t SET amt = 888.88 WHERE id = 42")
    assert s3.execute(tpl % 42).rows == [(777.77,)]
    s3.execute("ROLLBACK")
    # autocommit group sees the committed value: run a real batched group
    results = {}
    lock = threading.Lock()

    def worker(i):
        sx = Session(inst, schema="bsx")
        with lock:
            results[i] = sx.execute(tpl % 42).rows
        sx.close()

    errors = _run_threads(8, worker)
    assert not errors, errors[:3]
    for i, rows in results.items():
        assert rows == [(888.88,)], (i, rows)
    s2.close()
    s3.close()


def test_append_tail_visible_in_batched_lookup(sess):
    """Rows appended after the sorted index was built (the unsorted tail)
    must surface through the batched path's host-side tail probe."""
    inst, s = sess
    tpl = "SELECT amt FROM t WHERE id = %d"
    _register(s, tpl, 1)  # builds the sorted key index
    s.execute("INSERT INTO t (id, k, v, amt) VALUES (5001, 1, 'x', 9.99)")
    inst.config.set_instance("BATCH_WINDOW_US", 3000)
    results = {}
    lock = threading.Lock()

    def worker(i):
        sx = Session(inst, schema="bsx")
        key = 5001 if i % 2 == 0 else 1 + i
        with lock:
            results[i] = (key, sx.execute(tpl % key).rows)
        sx.close()

    errors = _run_threads(8, worker)
    assert not errors, errors[:3]
    for i, (key, rows) in results.items():
        want = [(9.99,)] if key == 5001 else [(key + 0.25,)]
        assert rows == want, (key, rows)


def test_batch_buckets_never_retrace_in_steady_state(sess):
    """The vectorized lookup keys on static (bucket, capacity) shapes: after
    one warm pass over the bucket ladder, re-running every shape — including
    different key counts within one bucket — compiles NOTHING."""
    from galaxysql_tpu.exec import operators as ops
    inst, s = sess
    store = inst.store("bsx", "t")
    part = next(p for p in store.partitions if p.num_rows > 0)
    snap = inst.tso.next_timestamp()
    tm = inst.catalog.table("bsx", "t")

    def sweep(force_device):
        out = []
        for nkeys in (1, 3, 4, 9, 16, 40, 64):
            vals = [1 + 3 * i for i in range(nkeys)]
            ids, offs = ops.batched_point_lookup(
                store, part.pid, part, "id", tm.version, vals, snap, 0,
                force_device=force_device)
            out.append((ids.tolist(), offs.tolist()))
        return out

    first = sweep(True)
    ops.reset_compile_stats()
    second = sweep(True)
    assert ops.COMPILE_STATS["retraces"] == 0, ops.COMPILE_STATS
    assert first == second
    # the backend-adaptive host formulation (XLA:CPU) is bit-identical to
    # the device program path
    assert sweep(False) == first
    # and the results agree with the sequential per-key probe
    from galaxysql_tpu import native
    vals = [1 + 3 * i for i in range(40)]
    ids, offs = ops.batched_point_lookup(
        store, part.pid, part, "id", tm.version, vals, snap, 0)
    for j, v in enumerate(vals):
        ref = part.key_candidates("id", v)
        keep = part.valid["id"][ref] & native.visible_mask(
            part.begin_ts[ref], part.end_ts[ref], snap, 0)
        assert ids[offs[j]:offs[j + 1]].tolist() == ref[keep].tolist()


def test_surfaces_and_metrics(sess):
    """SHOW BATCH STATS, information_schema.batch_stats, and the metrics
    registry all expose the batching counters/histograms."""
    inst, s = sess
    tpl = "SELECT amt FROM t WHERE id = %d"
    _register(s, tpl, 1)
    inst.config.set_instance("BATCH_WINDOW_US", 3000)

    def worker(i):
        sx = Session(inst, schema="bsx")
        for j in range(4):
            sx.execute(tpl % (1 + (i * 5 + j) % 2000))
        sx.close()

    errors = _run_threads(16, worker)
    assert not errors, errors[:3]
    stats = dict(s.execute("SHOW BATCH STATS").rows)
    assert stats["batched_queries"] > 0
    assert stats["batch_flushes"] > 0
    assert stats["group_size_p50"] >= 1
    assert 0.0 <= stats["hit_ratio"] <= 1.0
    r = s.execute("SELECT stat_name, value FROM information_schema.batch_stats")
    names = {n for n, _ in r.rows}
    assert {"batched_queries", "batch_flushes", "group_size_p50",
            "window_occupancy"} <= names
    metric_names = {n for n, _k, _v, _h in inst.metrics.rows()}
    assert "batched_queries" in metric_names
    assert "batch_group_size_p50" in metric_names
    assert "batch_wait_ms_p95" in metric_names
    # Prometheus exposition carries the summaries
    text = inst.metrics.prometheus_text()
    assert "galaxysql_batch_group_size" in text


def test_escape_hatches(sess):
    """ENABLE_BATCH_SCHEDULER=0 keeps every query on the sequential path;
    the BATCH(OFF) hint parses and structurally avoids the batched plan."""
    inst, s = sess
    tpl = "SELECT amt FROM t WHERE id = %d"
    _register(s, tpl, 7)
    inst.config.set_instance("ENABLE_BATCH_SCHEDULER", 0)
    inst.config.set_instance("BATCH_WINDOW_US", 3000)
    before = inst.metrics.counter("batched_queries").value

    def worker(i):
        sx = Session(inst, schema="bsx")
        assert sx.execute(tpl % (10 + i)).rows == [(10 + i + 0.25,)]
        sx.close()

    errors = _run_threads(8, worker)
    assert not errors, errors[:3]
    assert inst.metrics.counter("batched_queries").value == before
    inst.config.set_instance("ENABLE_BATCH_SCHEDULER", 1)
    # the hint parses...
    from galaxysql_tpu.sql.hints import parse_hints
    assert parse_hints("/*+TDDL: BATCH(OFF)*/")["batch"] == "off"
    # ...and a hinted statement stays correct on the planned path
    r = s.execute("/*+TDDL: BATCH(OFF)*/ SELECT amt FROM t WHERE id = 7")
    assert r.rows == [(7.25,)]
    prof = inst.profiles.entries()[-1]
    assert prof.engine != "batch"
