"""XA two-phase commit + in-doubt recovery."""

import pytest

from galaxysql_tpu.server.instance import Instance
from galaxysql_tpu.server.session import Session
from galaxysql_tpu.utils.failpoint import FAIL_POINTS, FP_BEFORE_COMMIT, \
    FailPointError


@pytest.fixture()
def session():
    inst = Instance()
    s = Session(inst)
    s.execute("CREATE DATABASE x; USE x")
    s.execute("SET TRANSACTION_POLICY = 'XA'")
    s.execute("CREATE TABLE a (id BIGINT, v BIGINT) PARTITION BY HASH(id) PARTITIONS 2")
    s.execute("CREATE TABLE b (id BIGINT, v BIGINT) PARTITION BY HASH(id) PARTITIONS 2")
    s.execute("INSERT INTO a VALUES (1, 10); INSERT INTO b VALUES (1, 100)")
    yield s
    FAIL_POINTS.clear()
    s.close()


class TestXa:
    def test_two_store_commit(self, session):
        s = session
        s.execute("BEGIN")
        s.execute("UPDATE a SET v = 11 WHERE id = 1")
        s.execute("INSERT INTO b VALUES (2, 200)")
        s.execute("COMMIT")
        s2 = Session(s.instance, "x")
        assert s2.execute("SELECT v FROM a WHERE id = 1").rows == [(11,)]
        assert s2.execute("SELECT count(*) FROM b").rows == [(2,)]
        # commit point logged as DONE
        logs = s.instance.metadb.query(
            "SELECT state FROM global_tx_log ORDER BY txn_id DESC LIMIT 1")
        assert logs[0][0] == "DONE"
        s2.close()

    def test_crash_before_commit_point_rolls_back(self, session):
        s = session
        s.execute("BEGIN")
        s.execute("INSERT INTO a VALUES (5, 50)")
        s.execute("DELETE FROM b WHERE id = 1")
        FAIL_POINTS.arm(FP_BEFORE_COMMIT)
        with pytest.raises(FailPointError):
            s.execute("COMMIT")
        FAIL_POINTS.clear()
        # in-doubt: PREPARED logged, no commit point -> recovery rolls back
        resolved = s.instance.xa_coordinator.recover()
        assert list(resolved.values()) == ["rolled_back"]
        s2 = Session(s.instance, "x")
        assert s2.execute("SELECT count(*) FROM a").rows == [(1,)]
        assert s2.execute("SELECT count(*) FROM b").rows == [(1,)]
        s2.close()

    def test_recovery_after_commit_point_commits(self, session):
        s = session
        inst = s.instance
        s.execute("BEGIN")
        s.execute("INSERT INTO a VALUES (7, 70)")
        txn = s.txn
        from galaxysql_tpu.txn.xa import participants_of
        parts = participants_of(txn)
        for sp in parts:
            assert sp.prepare()
        inst.metadb.tx_log_put(txn.txn_id, "PREPARED")
        commit_ts = inst.tso.next_timestamp()
        inst.metadb.tx_log_put(txn.txn_id, "COMMITTED", commit_ts)
        # simulate coordinator death here: register in-doubt + recover
        inst.xa_coordinator._in_doubt[txn.txn_id] = parts
        s.txn = None  # session forgets; recovery owns resolution
        resolved = inst.xa_coordinator.recover()
        assert resolved[txn.txn_id] == "committed"
        s2 = Session(inst, "x")
        assert s2.execute("SELECT count(*) FROM a").rows == [(2,)]
        s2.close()
