"""Benchmark driver: TPC-H on the TPU engine vs a measured pandas host baseline.

Prints ONE JSON line: {"metric", "value", "unit", "vs_baseline"}.

The reference publishes no numbers (BASELINE.md), so the baseline is measured on the
same machine and data: pandas (C-vectorized host columnar execution) standing in for
the reference's vectorized Java executor.  Metric: TPC-H Q1 rows/sec/chip, steady
state (plan cache + HBM-resident columns), best of N runs.

Env knobs: BENCH_SF (scale factor, default 0.2), BENCH_RUNS (default 3),
BENCH_QUERY (default 1).
"""

import json
import os
import sys
import time

import numpy as np

sys.path.insert(0, os.path.dirname(os.path.abspath(__file__)))

import jax

if os.environ.get("BENCH_PLATFORM"):
    # explicit platform override (e.g. BENCH_PLATFORM=cpu when no accelerator)
    jax.config.update("jax_platforms", os.environ["BENCH_PLATFORM"])
else:
    # Probe the default backend with a bounded timeout (subprocess — an in-process
    # hang in backend init is unkillable) and fall back to cpu if it is dead.  The
    # sitecustomize clobbers JAX_PLATFORMS, so the fallback must be in-process.
    import __graft_entry__ as _ge
    if not _ge._default_backend_alive():
        jax.config.update("jax_platforms", "cpu")
jax.config.update("jax_enable_x64", True)
# Scope the cache by host CPU identity: XLA:CPU AOT artifacts are machine-specific,
# and reusing a cache written on a different host risks SIGILL.
try:
    import hashlib
    import platform as _plat
    _STABLE = ("flags", "Features", "model name", "vendor_id", "cpu family",
               "model\t", "stepping", "CPU implementer", "CPU part")
    try:
        with open("/proc/cpuinfo") as f:
            # only ISA-identifying lines — fields like "cpu MHz" vary per read
            cpu_desc = _plat.machine() + "".join(
                sorted({l for l in f if l.startswith(_STABLE)}))
    except OSError:
        cpu_desc = _plat.machine() + _plat.processor()
    host_id = hashlib.md5(cpu_desc.encode()).hexdigest()[:8]
    jax.config.update("jax_compilation_cache_dir",
                      os.path.expanduser(f"~/.galaxysql_tpu_jax_cache/{host_id}"))
except Exception:
    pass

from galaxysql_tpu.server.instance import Instance
from galaxysql_tpu.server.session import Session
from galaxysql_tpu.storage import tpch
from galaxysql_tpu.storage.tpch_queries import QUERIES
from galaxysql_tpu.types import temporal


def load(sf: float):
    data = tpch.generate(sf)
    inst = Instance()
    s = Session(inst)
    s.execute("CREATE DATABASE tpch")
    s.execute("USE tpch")
    for t in tpch.TABLE_ORDER:
        s.execute(tpch.TPCH_DDL[t])
        inst.store("tpch", t).insert_arrays(data[t], inst.tso.next_timestamp())
    s.execute("ANALYZE TABLE " + ", ".join(tpch.TABLE_ORDER))
    return inst, s, data


def pandas_q1(data):
    """Host baseline: pandas implementation of Q1 (vectorized C loops)."""
    import pandas as pd
    li = data["lineitem"]
    cutoff = temporal.parse_date("1998-12-01") - 90
    df = pd.DataFrame({
        "flag": li["l_returnflag"], "status": li["l_linestatus"],
        "qty": li["l_quantity"], "price": li["l_extendedprice"],
        "disc": li["l_discount"], "tax": li["l_tax"], "ship": li["l_shipdate"],
    })
    t0 = time.perf_counter()
    f = df[df.ship <= cutoff]
    disc_price = f.price * (1 - f.disc)
    charge = disc_price * (1 + f.tax)
    g = f.assign(disc_price=disc_price, charge=charge).groupby(
        ["flag", "status"], sort=True).agg(
        sum_qty=("qty", "sum"), sum_base=("price", "sum"),
        sum_disc=("disc_price", "sum"), sum_charge=("charge", "sum"),
        avg_qty=("qty", "mean"), avg_price=("price", "mean"),
        avg_disc=("disc", "mean"), cnt=("qty", "size"))
    g = g.reset_index()
    return time.perf_counter() - t0, g


def pandas_q3(data):
    """Host baseline: pandas implementation of Q3 (3-way join + high-NDV agg)."""
    import pandas as pd
    cutoff = temporal.parse_date("1995-03-15")
    cust = pd.DataFrame({"ck": data["customer"]["c_custkey"],
                         "seg": data["customer"]["c_mktsegment"]})
    orders = pd.DataFrame({"ok": data["orders"]["o_orderkey"],
                           "ck": data["orders"]["o_custkey"],
                           "od": data["orders"]["o_orderdate"],
                           "sp": data["orders"]["o_shippriority"]})
    li = pd.DataFrame({"ok": data["lineitem"]["l_orderkey"],
                       "price": data["lineitem"]["l_extendedprice"],
                       "disc": data["lineitem"]["l_discount"],
                       "ship": data["lineitem"]["l_shipdate"]})
    t0 = time.perf_counter()
    c = cust[cust.seg == "BUILDING"][["ck"]]
    o = orders[orders.od < cutoff].merge(c, on="ck")
    l = li[li.ship > cutoff].merge(o[["ok", "od", "sp"]], on="ok")
    rev = l.price * (1 - l.disc)
    g = l.assign(rev=rev).groupby(["ok", "od", "sp"], sort=False).rev.sum()
    g = g.reset_index().sort_values(["rev", "od"],
                                    ascending=[False, True]).head(10)
    return time.perf_counter() - t0, g


def _bench_query(s, q, runs):
    s.execute(q)  # warmup: compile + populate device cache
    times = []
    for _ in range(runs):
        t0 = time.perf_counter()
        s.execute(q)
        times.append(time.perf_counter() - t0)
    return min(times)


def main():
    sf = float(os.environ.get("BENCH_SF", "0.2"))
    runs = int(os.environ.get("BENCH_RUNS", "3"))
    platform = jax.devices()[0].platform

    inst, s, data = load(sf)
    n_rows = len(data["lineitem"]["l_orderkey"])
    results = []

    # -- TP point-query latency (BASELINE.md config 1's latency floor) --------
    import pandas as pd
    okeys = data["orders"]["o_orderkey"]
    probe_keys = [int(okeys[i]) for i in
                  np.linspace(0, len(okeys) - 1, 21).astype(int)]
    odf = pd.DataFrame({"ok": okeys, "tp": data["orders"]["o_totalprice"]})
    point = "select o_totalprice from orders where o_orderkey = %d"
    _bench_query(s, point % probe_keys[0], 1)  # compile once
    lats, base_lats = [], []
    for k in probe_keys:
        t0 = time.perf_counter()
        s.execute(point % k)
        lats.append(time.perf_counter() - t0)
        t0 = time.perf_counter()
        _ = odf.tp.values[odf.ok.values == k]
        base_lats.append(time.perf_counter() - t0)
    lat = sorted(lats)[len(lats) // 2]
    base_lat = sorted(base_lats)[len(base_lats) // 2]
    results.append({
        "metric": f"tp_point_select_p50_latency_sf{sf:g}",
        "value": round(lat * 1000, 3), "unit": "ms",
        "vs_baseline": round(base_lat / lat, 3), "platform": platform,
    })

    # -- TPC-H Q3: 3-way join + high-NDV agg + top-n ---------------------------
    q3_best = _bench_query(s, QUERIES[3], runs)
    q3_base = min(pandas_q3(data)[0] for _ in range(runs))
    results.append({
        "metric": f"tpch_q3_sf{sf:g}_rows_per_sec_per_chip",
        "value": round(n_rows / q3_best, 1), "unit": "rows/s",
        "vs_baseline": round(q3_base / q3_best, 3), "platform": platform,
    })

    # -- SSB Q1.1: fact scan + date-dim join + filtered agg (config 4) ----------
    if os.environ.get("BENCH_SSB", "1") != "0":
        from galaxysql_tpu.storage import ssb
        sdata = ssb.generate(sf / 2)
        s.execute("CREATE DATABASE ssb")
        s.execute("USE ssb")
        for t in ssb.TABLE_ORDER:
            s.execute(ssb.SSB_DDL[t])
            inst.store("ssb", t).insert_arrays(sdata[t],
                                               inst.tso.next_timestamp())
        s.execute("ANALYZE TABLE " + ", ".join(ssb.TABLE_ORDER))
        ssb_best = _bench_query(s, ssb.QUERIES["1.1"], runs)

        def pandas_ssb(d):
            lo, da = d["lineorder"], d["dates"]
            # frames build OUTSIDE the timer (the engine's lanes preload too)
            dd = pd.DataFrame({"dk": da["d_datekey"], "y": da["d_year"]})
            lf = pd.DataFrame({"od": lo["lo_orderdate"],
                               "p": lo["lo_extendedprice"],
                               "disc": lo["lo_discount"], "q": lo["lo_quantity"]})
            t0 = time.perf_counter()
            f = lf[(lf.disc >= 1) & (lf.disc <= 3) & (lf.q < 25)]
            j = f.merge(dd[dd.y == 1993], left_on="od", right_on="dk")
            _ = (j.p * j.disc).sum()
            return time.perf_counter() - t0

        ssb_base = min(pandas_ssb(sdata) for _ in range(runs))
        n_lo = len(sdata["lineorder"]["lo_orderdate"])
        results.append({
            "metric": f"ssb_q1.1_sf{sf / 2:g}_rows_per_sec_per_chip",
            "value": round(n_lo / ssb_best, 1), "unit": "rows/s",
            "vs_baseline": round(ssb_base / ssb_best, 3), "platform": platform,
        })
        s.execute("USE tpch")

    # -- TPC-H Q1 (headline; LAST so a single-line parse of the tail sees it) --
    q1_best = _bench_query(s, QUERIES[1], runs)
    q1_base = min(pandas_q1(data)[0] for _ in range(runs))
    results.append({
        "metric": f"tpch_q1_sf{sf:g}_rows_per_sec_per_chip",
        "value": round(n_rows / q1_best, 1), "unit": "rows/s",
        "vs_baseline": round(q1_base / q1_best, 3), "platform": platform,
    })

    for out in results:
        print(json.dumps(out))


if __name__ == "__main__":
    main()
