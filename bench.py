"""Benchmark driver: TPC-H on the TPU engine vs a measured pandas host baseline.

Prints ONE JSON line: {"metric", "value", "unit", "vs_baseline"}.

The reference publishes no numbers (BASELINE.md), so the baseline is measured on the
same machine and data: pandas (C-vectorized host columnar execution) standing in for
the reference's vectorized Java executor.  Metric: TPC-H Q1 rows/sec/chip, steady
state (plan cache + HBM-resident columns), best of N runs.

Env knobs: BENCH_SF (scale factor, default 0.2), BENCH_RUNS (default 3),
BENCH_QUERY (default 1).
"""

import json
import os
import sys
import time

import numpy as np

sys.path.insert(0, os.path.dirname(os.path.abspath(__file__)))

import jax

if os.environ.get("BENCH_PLATFORM"):
    # explicit platform override (e.g. BENCH_PLATFORM=cpu when no accelerator)
    jax.config.update("jax_platforms", os.environ["BENCH_PLATFORM"])
else:
    # Probe the default backend with a bounded timeout (subprocess — an in-process
    # hang in backend init is unkillable) and fall back to cpu if it is dead.  The
    # sitecustomize clobbers JAX_PLATFORMS, so the fallback must be in-process.
    import __graft_entry__ as _ge
    if not _ge._default_backend_alive():
        jax.config.update("jax_platforms", "cpu")
jax.config.update("jax_enable_x64", True)
# Scope the cache by host CPU identity: XLA:CPU AOT artifacts are machine-specific,
# and reusing a cache written on a different host risks SIGILL.
try:
    import hashlib
    import platform as _plat
    _STABLE = ("flags", "Features", "model name", "vendor_id", "cpu family",
               "model\t", "stepping", "CPU implementer", "CPU part")
    try:
        with open("/proc/cpuinfo") as f:
            # only ISA-identifying lines — fields like "cpu MHz" vary per read
            cpu_desc = _plat.machine() + "".join(
                sorted({l for l in f if l.startswith(_STABLE)}))
    except OSError:
        cpu_desc = _plat.machine() + _plat.processor()
    host_id = hashlib.md5(cpu_desc.encode()).hexdigest()[:8]
    jax.config.update("jax_compilation_cache_dir",
                      os.path.expanduser(f"~/.galaxysql_tpu_jax_cache/{host_id}"))
except Exception:
    pass

from galaxysql_tpu.server.instance import Instance
from galaxysql_tpu.server.session import Session
from galaxysql_tpu.storage import tpch
from galaxysql_tpu.storage.tpch_queries import QUERIES
from galaxysql_tpu.types import temporal


def load(sf: float):
    data = tpch.generate(sf)
    inst = Instance()
    s = Session(inst)
    s.execute("CREATE DATABASE tpch")
    s.execute("USE tpch")
    for t in tpch.TABLE_ORDER:
        s.execute(tpch.TPCH_DDL[t])
        inst.store("tpch", t).insert_arrays(data[t], inst.tso.next_timestamp())
    s.execute("ANALYZE TABLE " + ", ".join(tpch.TABLE_ORDER))
    return inst, s, data


def pandas_q1(data):
    """Host baseline: pandas implementation of Q1 (vectorized C loops)."""
    import pandas as pd
    li = data["lineitem"]
    cutoff = temporal.parse_date("1998-12-01") - 90
    df = pd.DataFrame({
        "flag": li["l_returnflag"], "status": li["l_linestatus"],
        "qty": li["l_quantity"], "price": li["l_extendedprice"],
        "disc": li["l_discount"], "tax": li["l_tax"], "ship": li["l_shipdate"],
    })
    t0 = time.perf_counter()
    f = df[df.ship <= cutoff]
    disc_price = f.price * (1 - f.disc)
    charge = disc_price * (1 + f.tax)
    g = f.assign(disc_price=disc_price, charge=charge).groupby(
        ["flag", "status"], sort=True).agg(
        sum_qty=("qty", "sum"), sum_base=("price", "sum"),
        sum_disc=("disc_price", "sum"), sum_charge=("charge", "sum"),
        avg_qty=("qty", "mean"), avg_price=("price", "mean"),
        avg_disc=("disc", "mean"), cnt=("qty", "size"))
    g = g.reset_index()
    return time.perf_counter() - t0, g


def main():
    sf = float(os.environ.get("BENCH_SF", "0.2"))
    runs = int(os.environ.get("BENCH_RUNS", "3"))
    qid = int(os.environ.get("BENCH_QUERY", "1"))

    inst, s, data = load(sf)
    n_rows = len(data["lineitem"]["l_orderkey"])
    q = QUERIES[qid]

    # warmup: compile + populate device cache
    s.execute(q)
    times = []
    for _ in range(runs):
        t0 = time.perf_counter()
        s.execute(q)
        times.append(time.perf_counter() - t0)
    best = min(times)

    # measured host baseline (pandas, same data, best of same run count)
    base_times = []
    for _ in range(runs):
        bt, _g = pandas_q1(data)
        base_times.append(bt)
    base_best = min(base_times)

    rows_per_sec = n_rows / best
    base_rows_per_sec = n_rows / base_best
    out = {
        "metric": f"tpch_q{qid}_sf{sf:g}_rows_per_sec_per_chip",
        "value": round(rows_per_sec, 1),
        "unit": "rows/s",
        "vs_baseline": round(rows_per_sec / base_rows_per_sec, 3),
        "platform": jax.devices()[0].platform,
    }
    print(json.dumps(out))


if __name__ == "__main__":
    main()
