"""Benchmark driver: TPC-H on the TPU engine vs a measured pandas host baseline.

Prints ONE JSON line: {"metric", "value", "unit", "vs_baseline"}.

The reference publishes no numbers (BASELINE.md), so the baseline is measured on the
same machine and data: pandas (C-vectorized host columnar execution) standing in for
the reference's vectorized Java executor.  Metric: TPC-H Q1 rows/sec/chip, steady
state (plan cache + HBM-resident columns), best of N runs.

Env knobs: BENCH_SF (scale factor, default 0.2), BENCH_RUNS (default 3),
BENCH_QUERY (default 1).
"""

import json
import os
import sys
import time

import numpy as np

sys.path.insert(0, os.path.dirname(os.path.abspath(__file__)))

if "--skew-only" in sys.argv and \
        "xla_force_host_platform_device_count" not in \
        os.environ.get("XLA_FLAGS", ""):
    # the skew family needs the 8-virtual-device mesh; XLA reads this at
    # backend init, which has not happened yet at import time
    os.environ["XLA_FLAGS"] = (os.environ.get("XLA_FLAGS", "") +
                               " --xla_force_host_platform_device_count=8"
                               ).strip()

import jax

if os.environ.get("BENCH_PLATFORM"):
    # explicit platform override (e.g. BENCH_PLATFORM=cpu when no accelerator)
    jax.config.update("jax_platforms", os.environ["BENCH_PLATFORM"])
else:
    # Probe the default backend with a bounded timeout (subprocess — an in-process
    # hang in backend init is unkillable) and fall back to cpu if it is dead.  The
    # sitecustomize clobbers JAX_PLATFORMS, so the fallback must be in-process.
    import __graft_entry__ as _ge
    if not _ge._default_backend_alive():
        jax.config.update("jax_platforms", "cpu")
jax.config.update("jax_enable_x64", True)
# Scope the cache by host CPU identity: XLA:CPU AOT artifacts are machine-specific,
# and reusing a cache written on a different host risks SIGILL.
try:
    import hashlib
    import platform as _plat
    _STABLE = ("flags", "Features", "model name", "vendor_id", "cpu family",
               "model\t", "stepping", "CPU implementer", "CPU part")
    try:
        with open("/proc/cpuinfo") as f:
            # only ISA-identifying lines — fields like "cpu MHz" vary per read
            cpu_desc = _plat.machine() + "".join(
                sorted({l for l in f if l.startswith(_STABLE)}))
    except OSError:
        cpu_desc = _plat.machine() + _plat.processor()
    host_id = hashlib.md5(cpu_desc.encode()).hexdigest()[:8]
    jax.config.update("jax_compilation_cache_dir",
                      os.path.expanduser(f"~/.galaxysql_tpu_jax_cache/{host_id}"))
except Exception:
    pass

from galaxysql_tpu.server.instance import Instance
from galaxysql_tpu.server.session import Session
from galaxysql_tpu.storage import tpch
from galaxysql_tpu.storage.tpch_queries import QUERIES
from galaxysql_tpu.types import temporal


def load(sf: float):
    data = tpch.generate(sf)
    inst = Instance()
    s = Session(inst)
    s.execute("CREATE DATABASE tpch")
    s.execute("USE tpch")
    for t in tpch.TABLE_ORDER:
        s.execute(tpch.TPCH_DDL[t])
        inst.store("tpch", t).insert_arrays(data[t], inst.tso.next_timestamp())
    s.execute("ANALYZE TABLE " + ", ".join(tpch.TABLE_ORDER))
    return inst, s, data


def pandas_q1(data):
    """Host baseline: pandas implementation of Q1 (vectorized C loops)."""
    import pandas as pd
    li = data["lineitem"]
    cutoff = temporal.parse_date("1998-12-01") - 90
    df = pd.DataFrame({
        "flag": li["l_returnflag"], "status": li["l_linestatus"],
        "qty": li["l_quantity"], "price": li["l_extendedprice"],
        "disc": li["l_discount"], "tax": li["l_tax"], "ship": li["l_shipdate"],
    })
    t0 = time.perf_counter()
    f = df[df.ship <= cutoff]
    disc_price = f.price * (1 - f.disc)
    charge = disc_price * (1 + f.tax)
    g = f.assign(disc_price=disc_price, charge=charge).groupby(
        ["flag", "status"], sort=True).agg(
        sum_qty=("qty", "sum"), sum_base=("price", "sum"),
        sum_disc=("disc_price", "sum"), sum_charge=("charge", "sum"),
        avg_qty=("qty", "mean"), avg_price=("price", "mean"),
        avg_disc=("disc", "mean"), cnt=("qty", "size"))
    g = g.reset_index()
    return time.perf_counter() - t0, g


def pandas_q3(data):
    """Host baseline: pandas implementation of Q3 (3-way join + high-NDV agg)."""
    import pandas as pd
    cutoff = temporal.parse_date("1995-03-15")
    cust = pd.DataFrame({"ck": data["customer"]["c_custkey"],
                         "seg": data["customer"]["c_mktsegment"]})
    orders = pd.DataFrame({"ok": data["orders"]["o_orderkey"],
                           "ck": data["orders"]["o_custkey"],
                           "od": data["orders"]["o_orderdate"],
                           "sp": data["orders"]["o_shippriority"]})
    li = pd.DataFrame({"ok": data["lineitem"]["l_orderkey"],
                       "price": data["lineitem"]["l_extendedprice"],
                       "disc": data["lineitem"]["l_discount"],
                       "ship": data["lineitem"]["l_shipdate"]})
    t0 = time.perf_counter()
    c = cust[cust.seg == "BUILDING"][["ck"]]
    o = orders[orders.od < cutoff].merge(c, on="ck")
    l = li[li.ship > cutoff].merge(o[["ok", "od", "sp"]], on="ok")
    rev = l.price * (1 - l.disc)
    g = l.assign(rev=rev).groupby(["ok", "od", "sp"], sort=False).rev.sum()
    g = g.reset_index().sort_values(["rev", "od"],
                                    ascending=[False, True]).head(10)
    return time.perf_counter() - t0, g


def pandas_q5(data):
    """Host baseline: pandas Q5 (6-way shuffle join, BASELINE.md config 3)."""
    import pandas as pd
    lo = temporal.parse_date("1994-01-01")
    hi = temporal.parse_date("1995-01-01")
    region = pd.DataFrame({"rk": data["region"]["r_regionkey"],
                           "rn": data["region"]["r_name"]})
    nation = pd.DataFrame({"nk": data["nation"]["n_nationkey"],
                           "rk": data["nation"]["n_regionkey"],
                           "nn": data["nation"]["n_name"]})
    supp = pd.DataFrame({"sk": data["supplier"]["s_suppkey"],
                         "nk": data["supplier"]["s_nationkey"]})
    cust = pd.DataFrame({"ck": data["customer"]["c_custkey"],
                         "nk": data["customer"]["c_nationkey"]})
    orders = pd.DataFrame({"ok": data["orders"]["o_orderkey"],
                           "ck": data["orders"]["o_custkey"],
                           "od": data["orders"]["o_orderdate"]})
    li = pd.DataFrame({"ok": data["lineitem"]["l_orderkey"],
                       "sk": data["lineitem"]["l_suppkey"],
                       "price": data["lineitem"]["l_extendedprice"],
                       "disc": data["lineitem"]["l_discount"]})
    t0 = time.perf_counter()
    n = nation.merge(region[region.rn == "ASIA"][["rk"]], on="rk")
    s = supp.merge(n[["nk", "nn"]], on="nk")
    o = orders[(orders.od >= lo) & (orders.od < hi)]
    oc = o.merge(cust, on="ck")
    j = li.merge(oc[["ok", "nk"]], on="ok").merge(
        s, on="sk", suffixes=("_c", "_s"))
    j = j[j.nk_c == j.nk_s]
    rev = j.price * (1 - j.disc)
    g = j.assign(rev=rev).groupby("nn", sort=False).rev.sum()
    g = g.reset_index().sort_values("rev", ascending=False)
    return time.perf_counter() - t0, g


def pandas_q9(data):
    """Host baseline: pandas Q9 (product-type profit: 6-table join over
    high-NDV part/supplier keys — the runtime-filter probe-pruning shape)."""
    import pandas as pd
    part = pd.DataFrame({"pk": data["part"]["p_partkey"],
                         "pn": data["part"]["p_name"]})
    sup = pd.DataFrame({"sk": data["supplier"]["s_suppkey"],
                        "nk": data["supplier"]["s_nationkey"]})
    li = pd.DataFrame({"ok": data["lineitem"]["l_orderkey"],
                       "pk": data["lineitem"]["l_partkey"],
                       "sk": data["lineitem"]["l_suppkey"],
                       "qty": data["lineitem"]["l_quantity"],
                       "price": data["lineitem"]["l_extendedprice"],
                       "disc": data["lineitem"]["l_discount"]})
    ps = pd.DataFrame({"pk": data["partsupp"]["ps_partkey"],
                       "sk": data["partsupp"]["ps_suppkey"],
                       "cost": data["partsupp"]["ps_supplycost"]})
    orders = pd.DataFrame({"ok": data["orders"]["o_orderkey"],
                           "od": data["orders"]["o_orderdate"]})
    nation = pd.DataFrame({"nk": data["nation"]["n_nationkey"],
                           "nn": data["nation"]["n_name"]})
    t0 = time.perf_counter()
    pf = part[part.pn.str.contains("green")][["pk"]]
    j = li.merge(pf, on="pk").merge(sup, on="sk") \
          .merge(ps, on=["pk", "sk"]).merge(orders, on="ok") \
          .merge(nation, on="nk")
    amount = j.price * (1 - j.disc) - j.cost * j.qty
    year = pd.to_datetime(j.od, unit="D", origin="unix").dt.year
    g = j.assign(a=amount, y=year).groupby(["nn", "y"], sort=False).a.sum()
    g = g.reset_index().sort_values(["nn", "y"], ascending=[True, False])
    return time.perf_counter() - t0, g


def rf_probe_rows_delta(s, q):
    """Probe rows reaching join probe stages, runtime filters ON vs OFF —
    the pruning win the planned-filter pass buys, measured outside the timed
    loops (the counter adds a pre-bloom device sync per probe batch)."""
    from galaxysql_tpu.exec import runtime_filter as rfmod
    # fragment-cache cleared: a cached agg/build replay skips the probe
    # stages this delta exists to measure
    s.instance.frag_cache.clear()
    rfmod.reset_rf_stats(enabled=True)
    s.execute(q)
    on_rows = rfmod.RF_STATS["probe_rows"]
    built = rfmod.RF_STATS["filters_built"]
    s.instance.frag_cache.clear()
    rfmod.reset_rf_stats(enabled=True)
    s.execute("/*+TDDL:RUNTIME_FILTER(OFF)*/ " + q)
    off_rows = rfmod.RF_STATS["probe_rows"]
    rfmod.reset_rf_stats(enabled=False)
    return on_rows, off_rows, built


def pandas_ds_q7(d):
    """Host baseline: pandas TPC-DS q7 (5-way join + 4 avgs, config 5)."""
    import pandas as pd
    ss = pd.DataFrame({"sold": d["store_sales"]["ss_sold_date_sk"],
                       "item": d["store_sales"]["ss_item_sk"],
                       "cdemo": d["store_sales"]["ss_cdemo_sk"],
                       "promo": d["store_sales"]["ss_promo_sk"],
                       "qty": d["store_sales"]["ss_quantity"],
                       "lp": d["store_sales"]["ss_list_price"],
                       "coup": d["store_sales"]["ss_coupon_amt"],
                       "sp": d["store_sales"]["ss_sales_price"]})
    cd = pd.DataFrame({"cd": d["customer_demographics"]["cd_demo_sk"],
                       "g": d["customer_demographics"]["cd_gender"],
                       "m": d["customer_demographics"]["cd_marital_status"],
                       "e": d["customer_demographics"]["cd_education_status"]})
    dd = pd.DataFrame({"dk": d["date_dim"]["d_date_sk"],
                       "y": d["date_dim"]["d_year"]})
    it = pd.DataFrame({"ik": d["item"]["i_item_sk"],
                       "iid": d["item"]["i_item_id"]})
    pr = pd.DataFrame({"pk": d["promotion"]["p_promo_sk"],
                       "em": d["promotion"]["p_channel_email"],
                       "ev": d["promotion"]["p_channel_event"]})
    t0 = time.perf_counter()
    cdf = cd[(cd.g == "M") & (cd.m == "S") & (cd.e == "College")][["cd"]]
    prf = pr[(pr.em == "N") | (pr.ev == "N")][["pk"]]
    ddf = dd[dd.y == 2000][["dk"]]
    j = ss.merge(ddf, left_on="sold", right_on="dk") \
          .merge(it, left_on="item", right_on="ik") \
          .merge(cdf, left_on="cdemo", right_on="cd") \
          .merge(prf, left_on="promo", right_on="pk")
    g = j.groupby("iid", sort=True).agg(a1=("qty", "mean"), a2=("lp", "mean"),
                                        a3=("coup", "mean"), a4=("sp", "mean"))
    g = g.reset_index().head(100)
    return time.perf_counter() - t0, g


def kernel_microbench(data, platform: str, runs: int):
    """Device-kernel roofline datapoint: the Q1 aggregation kernel alone over
    device-resident lanes — rows/s and GB/s (lanes actually touched), so the
    first round where the TPU backend answers yields an MFU/roofline number,
    not just end-to-end times."""
    import jax
    import jax.numpy as jnp
    li = data["lineitem"]
    cutoff = temporal.parse_date("1998-12-01") - 90
    lanes = {
        "ship": jnp.asarray(np.asarray(li["l_shipdate"])),
        "qty": jnp.asarray(np.asarray(li["l_quantity"])),
        "price": jnp.asarray(np.asarray(li["l_extendedprice"])),
        "disc": jnp.asarray(np.asarray(li["l_discount"])),
        "tax": jnp.asarray(np.asarray(li["l_tax"])),
        "flag": jnp.asarray(np.unique(np.asarray(li["l_returnflag"]),
                                      return_inverse=True)[1].astype(np.int32)),
    }

    @jax.jit
    def q1_kernel(ship, qty, price, disc, tax, flag):
        live = ship <= cutoff
        disc_price = price * (1 - disc)
        charge = disc_price * (1 + tax)
        seg = jnp.where(live, flag.astype(jnp.int32), 8)
        out = []
        for lane in (qty, price, disc_price, charge, disc,
                     jnp.ones_like(qty)):
            out.append(jax.ops.segment_sum(jnp.where(live, lane, 0), seg,
                                           num_segments=9))
        return out

    args = (lanes["ship"], lanes["qty"], lanes["price"], lanes["disc"],
            lanes["tax"], lanes["flag"])
    jax.block_until_ready(q1_kernel(*args))  # compile
    best = None
    for _ in range(max(runs, 3)):
        t0 = time.perf_counter()
        jax.block_until_ready(q1_kernel(*args))
        el = time.perf_counter() - t0
        best = el if best is None or el < best else best
    n = int(lanes["qty"].shape[0])
    nbytes = sum(int(a.nbytes) for a in args)
    return {
        "metric": f"q1_kernel_{platform}_bandwidth",
        "value": round(nbytes / best / 1e9, 2), "unit": "GB/s",
        "vs_baseline": round(n / best / 1e6, 1),  # Mrows/s alongside
        "platform": platform,
    }


def dispatch_microbench(runs: int):
    """Per-batch dispatch overhead: a filter→project chain over B device
    batches, stacked per-operator programs vs ONE fused segment program.

    Reports fused ms/batch; vs_baseline = unfused/fused wall ratio; plus the
    measured streaming-program dispatch counts per batch for both shapes (the
    number the fusion PR moves: 2 dispatches/batch -> 1)."""
    import jax.numpy as jnp
    from galaxysql_tpu.chunk.batch import Column, ColumnBatch
    from galaxysql_tpu.exec import operators as ops
    from galaxysql_tpu.exec.fusion import FusedPipelineOp, FusedSegment
    from galaxysql_tpu.exec.operators import FilterOp, ProjectOp, SourceOp
    from galaxysql_tpu.expr import ir
    from galaxysql_tpu.types import datatype as dt

    B, n = 32, 1 << 17  # device path (capacity > TP_HOST_ROWS)
    rng = np.random.default_rng(7)
    batches = []
    for _ in range(B):
        a = jnp.asarray(rng.integers(0, 1 << 20, n))
        b = jnp.asarray(rng.random(n))
        batches.append(ColumnBatch({"a": Column(a, None, dt.BIGINT, None),
                                    "b": Column(b, None, dt.DOUBLE, None)}, None))
    ca = ir.ColRef("a", dt.BIGINT, None)
    cb = ir.ColRef("b", dt.DOUBLE, None)
    pred = ir.call("lt", ca, ir.lit(1 << 19))
    projs = [("c", ir.call("mul", cb, ir.lit(2.0))), ("a", ca)]

    def drain(op):
        last = None
        for out in op.batches():
            last = out.live_mask()
        jax.block_until_ready(last)

    def timed(make):
        drain(make())  # warmup: compile
        ops.reset_dispatch_stats()
        drain(make())
        d_per_batch = ops.DISPATCH_STATS["dispatches"] / B
        best = None
        for _ in range(max(runs, 3)):
            t0 = time.perf_counter()
            drain(make())
            el = time.perf_counter() - t0
            best = el if best is None or el < best else best
        return best / B, d_per_batch

    # both shapes construct their operators inside the timed drain, so each
    # side pays its own per-execution setup (expression walks, cache-key
    # resolution) and the ratio isolates the per-batch dispatch difference
    unfused_ms, unfused_d = timed(
        lambda: ProjectOp(FilterOp(SourceOp(batches), pred), projs))
    fused_ms, fused_d = timed(lambda: FusedPipelineOp(
        SourceOp(batches),
        FusedSegment([("filter", pred), ("project", list(projs))])))
    return {
        "metric": "pipeline_fused_dispatch_ms_per_batch",
        "value": round(fused_ms * 1000, 4), "unit": "ms/batch",
        "vs_baseline": round(unfused_ms / fused_ms, 3),
        "fused_dispatches_per_batch": fused_d,
        "unfused_dispatches_per_batch": unfused_d,
        "platform": jax.devices()[0].platform,
    }


def _closed_loop_point(inst, tpl, keys, n_sessions, per_session):
    """Closed-loop multi-session point-select driver (thin wrapper over the
    generic `_closed_loop_ops` scaffolding).  Returns (qps, p99_ms, errors)."""
    nkeys = len(keys)
    return _closed_loop_ops(
        inst, "tpch", n_sessions, per_session,
        lambda sx, i, j: sx.execute(tpl % keys[(i * 31 + j * 7) % nkeys]))


def batch_serving_bench(inst, s, data, platform):
    """Mega-batched TP serving: closed-loop QPS/chip + p99 at increasing
    concurrent-session counts, batching on (adaptive window) vs off (the
    PR-5 sequential fast path) on the SAME engine + data.  vs_baseline is
    the batching-on/off QPS ratio — the launch-amortization win this PR
    claims — and retraces_steady guards the static batch shapes (steady
    state must compile NOTHING).

    Methodology: best of BENCH_BATCH_RUNS (default 3) closed-loop passes per
    mode per level, matching the suite's best-of-runs convention — the
    closed loop is scheduler-sensitive, and a single pass mostly measures
    the ramp while the group-commit pipeline converges.  The default top
    level is 4000 sessions: 10k CPython threads exceed what small
    containers allow (set BENCH_BATCH_SESSIONS=100,1000,10000 on a real
    host — the driver itself is ready for it)."""
    from galaxysql_tpu.exec import operators as _ops
    from galaxysql_tpu.utils.metrics import BATCH_GROUP_SIZE

    okeys = data["orders"]["o_orderkey"]
    keys = [int(k) for k in okeys[:: max(1, len(okeys) // 4096)]]
    tpl = "select o_totalprice from orders where o_orderkey = %d"
    s.execute(tpl % keys[0])  # register + warm the PointPlan
    s.execute(tpl % keys[0])
    levels = [int(x) for x in os.environ.get(
        "BENCH_BATCH_SESSIONS", "100,1000,4000").split(",") if x]
    reps = max(1, int(os.environ.get("BENCH_BATCH_RUNS", "3")))
    out = []
    # warm both paths + the group-commit pipeline before any timed pass
    inst.config.set_instance("ENABLE_BATCH_SCHEDULER", 1)
    _closed_loop_point(inst, tpl, keys, 64, 4)
    inst.config.set_instance("ENABLE_BATCH_SCHEDULER", 0)
    _closed_loop_point(inst, tpl, keys, 64, 4)
    for n in levels:
        per = max(4, min(16, 16000 // n))
        inst.config.set_instance("ENABLE_BATCH_SCHEDULER", 0)
        off_runs = []
        for _ in range(reps):
            qps, p99, errs = _closed_loop_point(inst, tpl, keys, n, per)
            if errs:
                raise errs[0]
            off_runs.append((qps, p99))
        qps_off, p99_off = max(off_runs)
        inst.config.set_instance("ENABLE_BATCH_SCHEDULER", 1)
        _closed_loop_point(inst, tpl, keys, n, 2)  # ramp the pipeline
        _ops.reset_compile_stats()
        BATCH_GROUP_SIZE.reset()  # per-level quantiles: no warmup/prior-level blend
        on_runs = []
        for _ in range(reps):
            qps, p99, errs = _closed_loop_point(inst, tpl, keys, n, per)
            if errs:
                raise errs[0]
            on_runs.append((qps, p99))
        qps_on, p99_on = max(on_runs)
        gs = BATCH_GROUP_SIZE.quantiles()
        out.append({
            "metric": f"tp_point_select_qps_per_chip_{n}_sessions",
            "value": round(qps_on, 1), "unit": "qps",
            "vs_baseline": round(qps_on / max(qps_off, 1e-9), 3),
            "p99_ms": round(p99_on, 3),
            "unbatched_qps": round(qps_off, 1),
            "unbatched_p99_ms": round(p99_off, 3),
            "batch_flushes": BATCH_GROUP_SIZE.count,
            "batch_group_p50": gs[0.5],
            "retraces_steady": _ops.COMPILE_STATS["retraces"],
            "platform": platform,
        })
    return out


def _closed_loop_ops(inst, schema, n_sessions, per_session, op):
    """Closed-loop multi-session driver over an arbitrary per-op callable
    `op(sx, i, j)` — THE scaffolding (`_closed_loop_point` wraps it):
    sessions + threads built before the clock starts, shrunken stacks,
    bounded ready-wait.  Returns (qps, p99_ms, errors)."""
    import threading
    lats: list = []
    errors: list = []
    lock = threading.Lock()
    start = threading.Event()
    all_ready = threading.Event()
    ready = [0]

    def run(i):
        counted = False
        try:
            sx = Session(inst, schema=schema)
            mine = []
            with lock:
                ready[0] += 1
                counted = True
                if ready[0] == n_sessions:
                    all_ready.set()
            start.wait()
            for j in range(per_session):
                t0 = time.perf_counter()
                op(sx, i, j)
                mine.append(time.perf_counter() - t0)
            sx.close()
            with lock:
                lats.extend(mine)
        except Exception as e:  # pragma: no cover - surfaced to the caller
            with lock:
                errors.append(e)
                if not counted:
                    ready[0] += 1
                    if ready[0] == n_sessions:
                        all_ready.set()

    old_stack = threading.stack_size(512 << 10)
    try:
        threads = [threading.Thread(target=run, args=(i,), daemon=True)
                   for i in range(n_sessions)]
        for t in threads:
            t.start()
    finally:
        threading.stack_size(old_stack)
    all_ready.wait(timeout=120.0)
    t0 = time.perf_counter()
    start.set()
    for t in threads:
        t.join()
    wall = time.perf_counter() - t0
    if errors or not lats:
        return 0.0, 0.0, errors
    lats.sort()
    p99 = lats[min(int(0.99 * len(lats)), len(lats) - 1)]
    return len(lats) / wall, p99 * 1000.0, errors


def dml_serving_bench(inst, s, platform):
    """Mega-batched write serving: closed-loop DML QPS/chip + p99 at
    increasing session counts, DML batching on (adaptive window, group
    commit, coalesced CDC) vs off (the sequential per-statement path) on the
    SAME engine.  vs_baseline is the on/off QPS ratio — the write-path
    amortization win this PR claims.  A mixed 50/50 read+write closed loop
    rides along (`tp_mixed_rw_qps_...`): real TP traffic is never
    write-only, and the two batchers must compose.

    Methodology matches batch_serving_bench: best of BENCH_DML_RUNS
    (default 3) passes per mode per level; every INSERT id is globally
    unique so no pass ever conflicts with another."""
    from galaxysql_tpu.exec import operators as _ops
    from galaxysql_tpu.utils.metrics import DML_GROUP_SIZE

    schema = "dmlbench"
    # measure the batcher, not the shedder: the closed loop intentionally
    # saturates, and AIMD shedding typed errors would abort the pass.
    # Both knobs restore on exit — later bench sections (and operator
    # settings) must not inherit this section's configuration.
    prev_adm = inst.config.get("ENABLE_ADMISSION_CONTROL")
    prev_batch = inst.config.get("ENABLE_DML_BATCHING")
    inst.config.set_instance("ENABLE_ADMISSION_CONTROL", 0)
    try:
        return _dml_serving_passes(inst, s, schema, platform)
    finally:
        inst.config.set_instance("ENABLE_DML_BATCHING", prev_batch)
        inst.config.set_instance("ENABLE_ADMISSION_CONTROL", prev_adm)


def _dml_serving_passes(inst, s, schema, platform):
    from galaxysql_tpu.exec import operators as _ops
    from galaxysql_tpu.utils.metrics import DML_GROUP_SIZE
    try:
        s.execute(f"CREATE DATABASE {schema}")
    except Exception:
        pass
    sb = Session(inst, schema=schema)
    sb.execute("CREATE TABLE wb (id BIGINT NOT NULL PRIMARY KEY, "
               "grp INT NOT NULL, amt DECIMAL(12,2)) "
               "PARTITION BY HASH(id) PARTITIONS 4")
    ins = "INSERT INTO wb (id, grp, amt) VALUES (%d, %d, %d.25)"
    sel = "SELECT amt FROM wb WHERE id = %d"
    # register + warm the DML batch plan and the read PointPlan
    sb.execute(ins % (1, 1, 1))
    sb.execute(ins % (2, 2, 2))
    sb.execute(sel % 1)
    sb.execute(sel % 1)
    next_id = [1000]

    def make_insert_op(base):
        def op(sx, i, j):
            k = base + i * 1000 + j
            sx.execute(ins % (k, k % 97, k % 1000))
        return op

    def make_mixed_op(base):
        def op(sx, i, j):
            k = base + i * 1000 + j
            if j % 2 == 0:
                sx.execute(ins % (k, k % 97, k % 1000))
            else:
                sx.execute(sel % (base + i * 1000 + j - 1))
        return op

    levels = [int(x) for x in os.environ.get(
        "BENCH_DML_SESSIONS", "64,256").split(",") if x]
    reps = max(1, int(os.environ.get("BENCH_DML_RUNS", "3")))
    out = []

    def passes(mode_on, mk_op, n, per):
        inst.config.set_instance("ENABLE_DML_BATCHING", 1 if mode_on else 0)
        best = (0.0, 0.0)
        for _ in range(reps):
            base = next_id[0]
            next_id[0] += n * 1000 + 1000
            qps, p99, errs = _closed_loop_ops(inst, schema, n, per,
                                              mk_op(base))
            if errs:
                raise errs[0]
            if qps > best[0]:
                best = (qps, p99)
        return best

    # warm both paths + the group-commit pipeline before any timed pass
    passes(True, make_insert_op, 32, 4)
    passes(False, make_insert_op, 32, 4)
    for n in levels:
        per = max(4, min(16, 8000 // n))
        qps_off, p99_off = passes(False, make_insert_op, n, per)
        _ops.reset_compile_stats()
        DML_GROUP_SIZE.reset()
        qps_on, p99_on = passes(True, make_insert_op, n, per)
        gs = DML_GROUP_SIZE.quantiles()
        out.append({
            "metric": f"tp_dml_qps_per_chip_{n}_sessions",
            "value": round(qps_on, 1), "unit": "qps",
            "vs_baseline": round(qps_on / max(qps_off, 1e-9), 3),
            "p99_ms": round(p99_on, 3),
            "unbatched_qps": round(qps_off, 1),
            "unbatched_p99_ms": round(p99_off, 3),
            "dml_flushes": DML_GROUP_SIZE.count,
            "dml_group_p50": gs[0.5],
            "retraces_steady": _ops.COMPILE_STATS["retraces"],
            "platform": platform,
        })
        mq_off, mp_off = passes(False, make_mixed_op, n, per)
        mq_on, mp_on = passes(True, make_mixed_op, n, per)
        out.append({
            "metric": f"tp_mixed_rw_qps_per_chip_{n}_sessions",
            "value": round(mq_on, 1), "unit": "qps",
            "vs_baseline": round(mq_on / max(mq_off, 1e-9), 3),
            "p99_ms": round(mp_on, 3),
            "unbatched_qps": round(mq_off, 1),
            "unbatched_p99_ms": round(mp_off, 3),
            "platform": platform,
        })
    sb.close()
    return out


def _bench_query(s, q, runs):
    best, _d, _c = _bench_query_d(s, q, runs)
    return best


def _profile_summary(s, q):
    """One profiled execution -> {operator: rows/ms} summary attached to the
    BENCH json, so the perf trajectory records WHERE time went (per-operator,
    per-segment), not just end-to-end totals.  Runs OUTSIDE the timed loops:
    profiling forces device syncs the benchmark numbers must not contain."""
    try:
        s.execute("SET ENABLE_QUERY_PROFILING = 1")
        s.execute(q)
        prof = s.instance.profiles.entries()[-1]
        return {
            "trace_id": prof.trace_id,
            "engine": prof.engine,
            "elapsed_ms": prof.elapsed_ms,
            "operators": [
                {"op": st["operator"], "rows": st["rows_out"],
                 "ms": st["wall_ms"],
                 **({"fused": st["segment"]} if st.get("fused") else {})}
                for st in prof.op_stats],
            "segments": [
                {"chain": sp.chain, "rows_in": sp.rows_in,
                 "rows_out": sp.rows_out, "ms": sp.wall_ms}
                for sp in prof.segments],
        }
    except Exception as e:  # profile datapoint is best-effort
        return {"error": str(e)}
    finally:
        s.execute("SET ENABLE_QUERY_PROFILING = 0")


def _bench_query_d(s, q, runs):
    """(best wall seconds, steady-state streaming dispatches per execution,
    compile stats).

    The dispatch count is the number the fusion pass moves (deterministic,
    unlike wall time on a shared host): one streaming-program invocation per
    batch per segment — an XLA dispatch on the device path, a host-np program
    call on the TP path.  Compile stats bracket the warmup (cold trace+compile
    cost of the query's program set) and the timed loop (steady-state
    retraces, which a healthy lifted-key cache keeps at ZERO — a regression
    here means some program's key became value-sensitive)."""
    from galaxysql_tpu.exec import operators as _ops

    def _frag_clear():
        # these metrics track ENGINE throughput across PRs: clear the
        # fragment cache per run so a cached replay doesn't masquerade as a
        # faster pipeline (the *_warm_* metrics measure the cached state)
        fcache = getattr(s.instance, "frag_cache", None)
        if fcache is not None:
            fcache.clear()
    _ops.reset_compile_stats()
    s.execute(q)  # warmup: compile + populate device cache
    compile_stats = {
        "compile_ms": round(_ops.COMPILE_STATS["compile_ms"], 3),
        "retrace_count": _ops.COMPILE_STATS["retraces"],
    }
    times = []
    _frag_clear()
    _ops.reset_dispatch_stats()
    _ops.reset_compile_stats()
    t0 = time.perf_counter()
    s.execute(q)
    times.append(time.perf_counter() - t0)
    dispatches = _ops.DISPATCH_STATS["dispatches"]
    for _ in range(runs - 1):
        _frag_clear()
        t0 = time.perf_counter()
        s.execute(q)
        times.append(time.perf_counter() - t0)
    compile_stats["retraces_steady"] = _ops.COMPILE_STATS["retraces"]
    return min(times), dispatches, compile_stats


def skew_bench(platform):
    """Zipf theta sweep on a Q9-like join family over the 8-device mesh:
    skew-aware execution on vs SKEW(OFF), per-theta rows/sec/chip plus the
    observed shard-skew ratio (max/mean live rows per shard of the join
    stage) and steady-state retrace counts.

    The Q9 shape: a Zipf-keyed fact joining two dimension tables sized above
    the broadcast threshold (so both joins hash-shuffle — the skew-sensitive
    plan), feeding a grouped aggregate.  rows/sec/chip divides by the mesh
    size: the 8 virtual devices share this host's cores."""
    from galaxysql_tpu.exec import operators as _ops
    from galaxysql_tpu.parallel.mesh import make_mesh
    from galaxysql_tpu.parallel.mpp import MppExecutor
    from galaxysql_tpu.plan.physical import ExecContext
    from galaxysql_tpu.server.instance import Instance
    from galaxysql_tpu.server.session import Session

    S = 8
    n = int(os.environ.get("BENCH_SKEW_ROWS", str(2_000_000)))
    k = int(os.environ.get("BENCH_SKEW_KEYS", str(600_000)))
    reps = max(1, int(os.environ.get("BENCH_SKEW_RUNS", "3")))
    rng = np.random.default_rng(17)
    mesh = make_mesh(S)
    out = []
    q = ("SELECT d.attr, d2.attr, COUNT(*), SUM(f.v) "
         "FROM fact f, dim d, dim2 d2 "
         "WHERE f.k = d.k AND f.k2 = d2.k GROUP BY d.attr, d2.attr")

    # theta sweep per the Zipf literature (top-key mass ~19% at theta=1.2)
    # plus the production hot-key-incident shape: ONE key holding 35% — the
    # case the off path's overflow ladder hurts most
    for theta, label in ((0.0, "theta0"), (0.8, "theta08"),
                         (1.2, "theta12"), ("hot", "hotkey35")):
        inst = Instance()
        s = Session(inst)
        s.execute("CREATE DATABASE skb; USE skb")
        s.execute("CREATE TABLE fact (id BIGINT PRIMARY KEY, k BIGINT, "
                  "k2 BIGINT, v BIGINT) PARTITION BY HASH(id) PARTITIONS 8")
        if theta == "hot":
            p = np.full(k, 0.65 / (k - 1))
            p[7] = 0.35
            keys = rng.choice(k, size=n, p=p)
            keys2 = rng.choice(k, size=n, p=p)
        elif theta > 0:
            p = np.arange(1, k + 1, dtype=np.float64) ** -theta
            p /= p.sum()
            keys = rng.choice(k, size=n, p=p)
            keys2 = rng.choice(k, size=n, p=p)
        else:
            keys = rng.integers(0, k, size=n)
            keys2 = rng.integers(0, k, size=n)
        inst.store("skb", "fact").insert_arrays(
            {"id": np.arange(n, dtype=np.int64),
             "k": keys.astype(np.int64), "k2": keys2.astype(np.int64),
             "v": rng.integers(0, 1000, size=n).astype(np.int64)},
            inst.tso.next_timestamp())
        for dim, mul in (("dim", 7919), ("dim2", 104729)):
            s.execute(f"CREATE TABLE {dim} (did BIGINT PRIMARY KEY, "
                      "k BIGINT, attr BIGINT) "
                      "PARTITION BY HASH(did) PARTITIONS 8")
            inst.store("skb", dim).insert_arrays(
                {"did": (np.arange(k, dtype=np.int64) * mul) % (1 << 30),
                 "k": np.arange(k, dtype=np.int64),
                 "attr": np.arange(k, dtype=np.int64) % 11},
                inst.tso.next_timestamp())
        s.execute("ANALYZE TABLE fact, dim, dim2")

        def once(sql, collect=False):
            plan = inst.planner.plan_select(sql, "skb")
            ctx = ExecContext(inst.stores, inst.tso.next_timestamp(), [],
                              archive=inst.archive, archive_instance=inst,
                              hints=plan.hints)
            ctx.collect_stats = collect
            t0 = time.perf_counter()
            MppExecutor(ctx, mesh).execute(plan.rel)
            return time.perf_counter() - t0, ctx

        def best(sql):
            once(sql)  # compile warmup
            _ops.reset_compile_stats()
            ts = []
            for _ in range(reps):
                inst.frag_cache.clear()
                ts.append(once(sql)[0])
            return min(ts), _ops.COMPILE_STATS["retraces"]

        t_on, retraces = best(q)
        t_off, _ = best("/*+TDDL: SKEW(OFF)*/ " + q)
        # shard-skew ratio of the join stages, measured on the OFF path (the
        # imbalance the hybrid removes); profiled run, excluded from timing
        inst.frag_cache.clear()
        _, ctx = once("/*+TDDL: SKEW(OFF)*/ " + q, collect=True)
        ratios = [st["shard_skew"] for st in ctx.op_stats
                  if st.get("shard_skew")]
        _, ctx_on = once(q)
        out.append({
            "metric": f"tpch_q9_skew_{label}_rows_per_sec_per_chip",
            "value": round(n / t_on / S, 1), "unit": "rows/s",
            "vs_skew_off": round(t_off / t_on, 3),
            "skew_off_rows_per_sec_per_chip": round(n / t_off / S, 1),
            "shard_skew_ratio_off": max(ratios) if ratios else None,
            "hybrid_engaged": any("mpp-hybrid-join" in t
                                  for t in ctx_on.trace),
            "salted": any("mpp-salted-agg" in t for t in ctx_on.trace),
            "retraces_steady": retraces, "theta": theta,
            "platform": platform, "mesh": S,
        })
        s.close()
    return out


def skew_only_main():
    """`bench.py --skew-only` (make bench-skew): the Zipf theta sweep on the
    8-virtual-device mesh."""
    for line in skew_bench(jax.devices()[0].platform):
        print(json.dumps(line))


def main():
    sf = float(os.environ.get("BENCH_SF", "0.2"))
    runs = int(os.environ.get("BENCH_RUNS", "3"))
    platform = jax.devices()[0].platform

    inst, s, data = load(sf)
    n_rows = len(data["lineitem"]["l_orderkey"])
    results = []

    # -- TP point-query latency (BASELINE.md config 1's latency floor) --------
    import pandas as pd
    okeys = data["orders"]["o_orderkey"]
    probe_keys = [int(okeys[i]) for i in
                  np.linspace(0, len(okeys) - 1, 21).astype(int)]
    odf = pd.DataFrame({"ok": okeys, "tp": data["orders"]["o_totalprice"]})
    point = "select o_totalprice from orders where o_orderkey = %d"
    _bench_query(s, point % probe_keys[0], 1)  # compile once
    lats, base_lats = [], []
    for k in probe_keys:
        t0 = time.perf_counter()
        s.execute(point % k)
        lats.append(time.perf_counter() - t0)
        t0 = time.perf_counter()
        _ = odf.tp.values[odf.ok.values == k]
        base_lats.append(time.perf_counter() - t0)
    lat = sorted(lats)[len(lats) // 2]
    base_lat = sorted(base_lats)[len(base_lats) // 2]
    from galaxysql_tpu.exec import operators as _ops
    _ops.reset_dispatch_stats()
    s.execute(point % probe_keys[0])
    results.append({
        "metric": f"tp_point_select_p50_latency_sf{sf:g}",
        "value": round(lat * 1000, 3), "unit": "ms",
        "vs_baseline": round(base_lat / lat, 3), "platform": platform,
        "dispatches_per_exec": _ops.DISPATCH_STATS["dispatches"],
    })

    # -- mega-batched TP serving: closed-loop multi-session QPS ---------------
    if os.environ.get("BENCH_BATCH", "1") != "0":
        results.extend(batch_serving_bench(inst, s, data, platform))

    # -- mega-batched write serving: closed-loop DML + mixed r/w QPS ----------
    if os.environ.get("BENCH_DML", "1") != "0":
        results.extend(dml_serving_bench(inst, s, platform))

    # -- skew-aware execution: Zipf theta sweep on Q9-like joins --------------
    # needs the 8-device mesh; single-device runs use `bench.py --skew-only`
    # (which forces 8 virtual CPU devices) / `make bench-skew`
    if os.environ.get("BENCH_SKEW", "1") != "0" and len(jax.devices()) >= 8:
        try:
            results.extend(skew_bench(platform))
        except Exception as e:
            # best-effort (headline lines still print) but never silent: a
            # dashboard must see WHY the tpch_q9_skew_* lines disappeared
            print(f"skew bench failed: {e!r}", file=sys.stderr)

    # -- TPC-H Q3: 3-way join + high-NDV agg + top-n ---------------------------
    q3_best, q3_d, q3_c = _bench_query_d(s, QUERIES[3], runs)
    q3_base = min(pandas_q3(data)[0] for _ in range(runs))
    results.append({
        "metric": f"tpch_q3_sf{sf:g}_rows_per_sec_per_chip",
        "value": round(n_rows / q3_best, 1), "unit": "rows/s",
        "vs_baseline": round(q3_base / q3_best, 3), "platform": platform,
        "dispatches_per_exec": q3_d, "compile": q3_c,
        "profile": _profile_summary(s, QUERIES[3]),
    })

    # -- TPC-H Q5: 6-way shuffle join (config 3) -------------------------------
    q5_best, q5_d, q5_c = _bench_query_d(s, QUERIES[5], runs)
    q5_base = min(pandas_q5(data)[0] for _ in range(runs))
    results.append({
        "metric": f"tpch_q5_sf{sf:g}_rows_per_sec_per_chip",
        "value": round(n_rows / q5_best, 1), "unit": "rows/s",
        "vs_baseline": round(q5_base / q5_best, 3), "platform": platform,
        "dispatches_per_exec": q5_d, "compile": q5_c,
        "profile": _profile_summary(s, QUERIES[5]),
    })

    # -- runtime-filter pruning win: probe rows scanned, filters on vs off ----
    on_rows, off_rows, built = rf_probe_rows_delta(s, QUERIES[5])
    results.append({
        "metric": f"tpch_q5_sf{sf:g}_rf_probe_rows_delta",
        "value": round(off_rows / max(on_rows, 1), 3), "unit": "x",
        "vs_baseline": round(off_rows / max(on_rows, 1), 3),
        "probe_rows_filters_on": on_rows,
        "probe_rows_filters_off": off_rows,
        "filters_built": built, "platform": platform,
    })

    # -- TPC-H Q9: 6-table product-profit join (runtime-filter headline) -------
    q9_best, q9_d, q9_c = _bench_query_d(s, QUERIES[9], runs)
    q9_base = min(pandas_q9(data)[0] for _ in range(runs))
    results.append({
        "metric": f"tpch_q9_sf{sf:g}_rows_per_sec_per_chip",
        "value": round(n_rows / q9_best, 1), "unit": "rows/s",
        "vs_baseline": round(q9_base / q9_best, 3), "platform": platform,
        "dispatches_per_exec": q9_d, "compile": q9_c,
        "profile": _profile_summary(s, QUERIES[9]),
    })

    # -- fragment cache: warm (second-execution) steady state ------------------
    # cold = fragment cache cleared before each run (kernels compiled, device
    # cache warm — isolates the build-side work the cache removes); warm =
    # repeated executions hitting the cached build artifacts + filters.  The
    # steady-state number a CN serving parameterized traffic actually sees.
    fcache = inst.frag_cache
    for qid in (5, 9):
        q = QUERIES[qid]
        s.execute(q)  # compile + device-cache warmup (cache cleared below)
        cold_times = []
        for _ in range(runs):
            fcache.clear()
            t0 = time.perf_counter()
            s.execute(q)
            cold_times.append(time.perf_counter() - t0)
        cold = min(cold_times)
        s.execute(q)  # populate the fragment cache
        h0, m0 = fcache.hits, fcache.misses
        warm_times = []
        for _ in range(runs):
            t0 = time.perf_counter()
            s.execute(q)
            warm_times.append(time.perf_counter() - t0)
        warm = min(warm_times)
        hits = fcache.hits - h0
        lookups = hits + (fcache.misses - m0)
        results.append({
            "metric": f"tpch_q{qid}_sf{sf:g}_warm_rows_per_sec_per_chip",
            "value": round(n_rows / warm, 1), "unit": "rows/s",
            # vs_baseline here = warm speedup over the cold (cache-cleared)
            # run of the SAME engine: the build + filter reuse win
            "vs_baseline": round(cold / warm, 3),
            "cold_rows_per_sec": round(n_rows / cold, 1),
            "cache_hit_rate": round(hits / max(lookups, 1), 3),
            "cache_bytes": fcache.bytes, "platform": platform,
        })

    # -- TPC-DS q7: 5-way star join + 4 avgs (config 5) ------------------------
    if os.environ.get("BENCH_TPCDS", "1") != "0":
        from galaxysql_tpu.storage import tpcds
        ddata = tpcds.generate(sf / 2)
        s.execute("CREATE DATABASE tpcds")
        s.execute("USE tpcds")
        for t in tpcds.TABLE_ORDER:
            s.execute(tpcds.TPCDS_DDL[t])
            inst.store("tpcds", t).insert_pylists(ddata[t],
                                                  inst.tso.next_timestamp())
        s.execute("ANALYZE TABLE " + ", ".join(tpcds.TABLE_ORDER))
        ds_best, ds_d, ds_c = _bench_query_d(s, tpcds.QUERIES["q7"], runs)
        ds_base = min(pandas_ds_q7(ddata)[0] for _ in range(runs))
        n_ss = len(ddata["store_sales"]["ss_item_sk"])
        results.append({
            "metric": f"tpcds_q7_sf{sf / 2:g}_rows_per_sec_per_chip",
            "value": round(n_ss / ds_best, 1), "unit": "rows/s",
            "vs_baseline": round(ds_base / ds_best, 3), "platform": platform,
            "dispatches_per_exec": ds_d, "compile": ds_c,
            "profile": _profile_summary(s, tpcds.QUERIES["q7"]),
        })
        s.execute("USE tpch")

    # -- SSB Q1.1: fact scan + date-dim join + filtered agg (config 4) ----------
    if os.environ.get("BENCH_SSB", "1") != "0":
        from galaxysql_tpu.storage import ssb
        sdata = ssb.generate(sf / 2)
        s.execute("CREATE DATABASE ssb")
        s.execute("USE ssb")
        for t in ssb.TABLE_ORDER:
            s.execute(ssb.SSB_DDL[t])
            inst.store("ssb", t).insert_arrays(sdata[t],
                                               inst.tso.next_timestamp())
        s.execute("ANALYZE TABLE " + ", ".join(ssb.TABLE_ORDER))
        ssb_best, ssb_d, ssb_c = _bench_query_d(s, ssb.QUERIES["1.1"], runs)

        def pandas_ssb(d):
            lo, da = d["lineorder"], d["dates"]
            # frames build OUTSIDE the timer (the engine's lanes preload too)
            dd = pd.DataFrame({"dk": da["d_datekey"], "y": da["d_year"]})
            lf = pd.DataFrame({"od": lo["lo_orderdate"],
                               "p": lo["lo_extendedprice"],
                               "disc": lo["lo_discount"], "q": lo["lo_quantity"]})
            t0 = time.perf_counter()
            f = lf[(lf.disc >= 1) & (lf.disc <= 3) & (lf.q < 25)]
            j = f.merge(dd[dd.y == 1993], left_on="od", right_on="dk")
            _ = (j.p * j.disc).sum()
            return time.perf_counter() - t0

        ssb_base = min(pandas_ssb(sdata) for _ in range(runs))
        n_lo = len(sdata["lineorder"]["lo_orderdate"])
        results.append({
            "metric": f"ssb_q1.1_sf{sf / 2:g}_rows_per_sec_per_chip",
            "value": round(n_lo / ssb_best, 1), "unit": "rows/s",
            "vs_baseline": round(ssb_base / ssb_best, 3), "platform": platform,
            "dispatches_per_exec": ssb_d, "compile": ssb_c,
            "profile": _profile_summary(s, ssb.QUERIES["1.1"]),
        })
        s.execute("USE tpch")

    # -- SF>=1 config (BASELINE.md intent: the baselines target SF1-100): Q1 +
    # Q3 on a 6M-row lineitem, loaded fresh so the small-SF frames can be GC'd
    big_sf = float(os.environ.get("BENCH_SF_BIG", "1"))
    if big_sf > 0:
        del data
        inst, s, data = load(big_sf)  # headline Q1 below runs at this scale
        nb = len(data["lineitem"]["l_orderkey"])
        q3b_best = _bench_query(s, QUERIES[3], runs)
        q3b_base = min(pandas_q3(data)[0] for _ in range(runs))
        results.append({
            "metric": f"tpch_q3_sf{big_sf:g}_rows_per_sec_per_chip",
            "value": round(nb / q3b_best, 1), "unit": "rows/s",
            "vs_baseline": round(q3b_base / q3b_best, 3), "platform": platform,
        })

    # -- TPC-H Q1 (headline; LAST so a single-line parse of the tail sees it) --
    q1_best, q1_d, q1_c = _bench_query_d(s, QUERIES[1], runs)
    q1_base = min(pandas_q1(data)[0] for _ in range(runs))
    results.append({
        "metric": f"tpch_q1_sf{(big_sf if big_sf > 0 else sf):g}"
                  f"_rows_per_sec_per_chip",
        "value": round((len(data['lineitem']['l_orderkey'])) / q1_best, 1),
        "unit": "rows/s",
        "vs_baseline": round(q1_base / q1_best, 3), "platform": platform,
        "dispatches_per_exec": q1_d, "compile": q1_c,
        "profile": _profile_summary(s, QUERIES[1]),
    })

    # statement-summary snapshot: per-digest aggregates of everything this
    # bench run executed, so future runs can diff per-digest latency across
    # PRs (meta/statement_summary.py)
    ss = getattr(inst, "stmt_summary", None)
    if ss is not None:
        results.append({"metric": "statement_summary_snapshot",
                        "unit": "digests", "platform": platform,
                        "value": len(ss.rows()),
                        "statements": ss.top_digests(10)})

    try:
        results.insert(0, kernel_microbench(data, platform, runs))
    except Exception:
        pass  # roofline datapoint is best-effort; end-to-end lines still print
    try:
        results.insert(1, dispatch_microbench(runs))
    except Exception:
        pass  # dispatch datapoint is best-effort too

    for out in results:
        print(json.dumps(out))


def overload_bench(inst, s, data, platform):
    """Overload driver (PR 12 admission-control plane): closed-loop TP point
    serving measured alone, then again with a concurrent AP flood hammering
    a heavy aggregation while admission limits bite.  Reports TP QPS/p99
    with and without the flood, AP goodput, and the typed shed rate — the
    numbers that show the box degrading instead of collapsing."""
    import threading
    from galaxysql_tpu.utils import errors as _errors

    okeys = data["orders"]["o_orderkey"]
    keys = [int(k) for k in okeys[:: max(1, len(okeys) // 2048)]]
    tpl = "select o_totalprice from orders where o_orderkey = %d"
    ap_q = ("select l_orderkey, sum(l_extendedprice * (1 - l_discount)) "
            "from lineitem group by l_orderkey order by 2 desc limit 10")
    s.execute(tpl % keys[0])  # register + warm the PointPlan
    s.execute(ap_q)           # warm the AP plan + classify the digest
    n_tp = int(os.environ.get("BENCH_OVERLOAD_TP_SESSIONS", "32"))
    per = int(os.environ.get("BENCH_OVERLOAD_PER_SESSION", "40"))
    n_ap = int(os.environ.get("BENCH_OVERLOAD_AP_THREADS", "8"))
    inst.config.set_instance("ADMISSION_AP_LIMIT", 2)
    inst.config.set_instance("ADMISSION_QUEUE_SIZE", 1)
    inst.config.set_instance("ADMISSION_WAIT_MS", 100)
    inst.admission._limit.clear()

    qps0, p99_0, errs = _closed_loop_point(inst, tpl, keys, n_tp, per)
    if errs:
        raise errs[0]

    stop = threading.Event()
    counts = {"ok": 0, "shed": 0, "other": 0}
    lock = threading.Lock()

    def flood():
        sx = Session(inst, schema="tpch")
        while not stop.is_set():
            try:
                sx.execute(ap_q)
                with lock:
                    counts["ok"] += 1
            except (_errors.ServerOverloadError, _errors.CclRejectError):
                with lock:
                    counts["shed"] += 1
                time.sleep(0.001)
            except Exception:
                with lock:
                    counts["other"] += 1
        sx.close()

    floods = [threading.Thread(target=flood, daemon=True)
              for _ in range(n_ap)]
    for t in floods:
        t.start()
    time.sleep(0.3)  # flood established before the measured TP pass
    qps1, p99_1, errs = _closed_loop_point(inst, tpl, keys, n_tp, per)
    stop.set()
    for t in floods:
        t.join(timeout=60)
    if errs:
        raise errs[0]
    total_ap = counts["ok"] + counts["shed"] + counts["other"]
    return [{
        "metric": f"tp_point_qps_under_ap_flood_{n_tp}_sessions",
        "value": round(qps1, 1), "unit": "qps",
        "vs_baseline": round(qps1 / max(qps0, 1e-9), 3),
        "p99_ms": round(p99_1, 3),
        "no_flood_qps": round(qps0, 1),
        "no_flood_p99_ms": round(p99_0, 3),
        "ap_flood_threads": n_ap,
        "ap_completed": counts["ok"],
        "ap_shed_typed": counts["shed"],
        "ap_untyped_failures": counts["other"],
        "ap_shed_rate": round(counts["shed"] / max(total_ap, 1), 3),
        "platform": platform,
    }]


def rebalance_bench(inst, s, platform):
    """`bench.py --rebalance-only` (make bench-rebalance): point serving
    measured quiesced, then DURING a live SPLIT PARTITION job — the
    rebalance-while-serving QPS dip and p99 inflation the elasticity plane
    promises to bound, plus the data-movement throughput itself.

    The split is slowed to bench scale (small chunks) so the measured
    closed-loop window genuinely overlaps the backfill+catchup+cutover
    pipeline rather than sampling an already-finished job."""
    import threading
    from galaxysql_tpu.ddl import rebalance as rb

    n_rows = int(os.environ.get("BENCH_REBALANCE_ROWS", "200000"))
    n_sessions = int(os.environ.get("BENCH_REBALANCE_SESSIONS", "32"))
    s.execute("CREATE DATABASE IF NOT EXISTS rbench")
    s.execute("USE rbench")
    s.execute("CREATE TABLE rt (id BIGINT PRIMARY KEY, grp BIGINT, "
              "v BIGINT) PARTITION BY HASH(id) PARTITIONS 4")
    store = inst.store("rbench", "rt")
    store.insert_pylists(
        {"id": list(range(n_rows)), "grp": [i % 97 for i in range(n_rows)],
         "v": list(range(n_rows))}, inst.tso.next_timestamp())
    tpl = "select v from rt where id = %d"
    keys = list(range(0, n_rows, max(1, n_rows // 4096)))
    nkeys = len(keys)
    s.execute(tpl % keys[0])  # register + warm the PointPlan
    s.execute(tpl % keys[0])

    def _loop(n, per):
        return _closed_loop_ops(
            inst, "rbench", n, per,
            lambda sx, i, j: sx.execute(tpl % keys[(i * 31 + j * 7) % nkeys]))

    _loop(n_sessions, 4)  # ramp
    per = max(4, int(os.environ.get("BENCH_REBALANCE_PER_SESSION", "24")))
    qps0, p99_0, errs0 = _loop(n_sessions, per)

    old_chunk = rb.RebalanceBackfillTask.CHUNK
    rb.RebalanceBackfillTask.CHUNK = max(
        256, n_rows // (4 * 64))  # ~64 checkpointed chunks per partition
    job_wall = [0.0]
    job_err: list = []

    def _run_split():
        sx = Session(inst, schema="rbench")
        t0 = time.perf_counter()
        try:
            sx.execute("ALTER TABLE rt SPLIT PARTITION p1 INTO 2")
        except Exception as e:  # pragma: no cover - surfaced in the json
            job_err.append(repr(e))
        finally:
            job_wall[0] = time.perf_counter() - t0
            sx.close()

    mover = threading.Thread(target=_run_split)
    mover.start()
    lats_qps = []
    try:
        # keep the closed loop running until the job finishes so the
        # measurement covers backfill, catchup, AND the fenced cutover
        while mover.is_alive():
            lats_qps.append(_loop(n_sessions, per))
    finally:
        mover.join()
        rb.RebalanceBackfillTask.CHUNK = old_chunk
    if not lats_qps:
        # split finished before the first overlap window (tiny table / fast
        # box): report the quiesced numbers as a degenerate 1.0x overlap
        lats_qps = [(qps0, p99_0, [])]
    qps1 = min(q for q, _, _ in lats_qps)
    p99_1 = max(p for _, p, _ in lats_qps)
    errs1 = sum(len(e) for _, _, e in lats_qps)
    moved = sum(p.num_rows for p in store.partitions[1:2]) + \
        store.partitions[-1].num_rows
    return [{
        "metric": "rebalance_while_serving_qps_per_chip",
        "value": round(qps1, 1), "unit": "qps",
        "vs_baseline": round(qps1 / max(qps0, 1e-9), 3),
        "quiesced_qps": round(qps0, 1),
        "quiesced_p99_ms": round(p99_0, 3),
        "during_p99_ms": round(p99_1, 3),
        "p99_inflation": round(p99_1 / max(p99_0, 1e-9), 2),
        "sessions": n_sessions,
        "rebalance_wall_s": round(job_wall[0], 2),
        "rows_moved": int(moved),
        "move_rows_per_sec": round(moved / max(job_wall[0], 1e-9), 1),
        "job_errors": job_err, "serve_errors": len(errs0) + errs1,
        "windows_during": len(lats_qps),
        "platform": platform,
    }]


def rebalance_only_main():
    """`bench.py --rebalance-only` (make bench-rebalance): fresh instance,
    no TPC-H load needed — the driver builds its own serving table."""
    inst = Instance()
    s = Session(inst)
    for out in rebalance_bench(inst, s, jax.devices()[0].platform):
        print(json.dumps(out))


def overload_only_main():
    """`bench.py --overload-only` (make bench-overload): TP serving under an
    AP flood with admission control engaged, on a small TPC-H load."""
    sf = float(os.environ.get("BENCH_SF", "0.1"))
    inst, s, data = load(sf)
    for out in overload_bench(inst, s, data, jax.devices()[0].platform):
        print(json.dumps(out))


def batch_only_main():
    """`bench.py --batch-only` (make batch-smoke): just the closed-loop
    multi-session serving bench, on a small TPC-H load."""
    sf = float(os.environ.get("BENCH_SF", "0.1"))
    inst, s, data = load(sf)
    for out in batch_serving_bench(inst, s, data, jax.devices()[0].platform):
        print(json.dumps(out))


def kernels_bench(platform: str):
    """Kernel tier: Pallas join/agg formulations vs the reference ones
    (direct steady-state kernel calls), plus the persistent AOT compile
    cache measured as a cold-vs-warm restart of the same query.  On CPU the
    Pallas kernels run in INTERPRET mode (the TPU compiled path has no chip
    to answer here) — reported as pallas_mode so the number is honest."""
    import tempfile

    import jax.numpy as jnp

    from galaxysql_tpu.exec import operators as ops
    from galaxysql_tpu.kernels import relational as R

    runs = max(int(os.environ.get("BENCH_RUNS", "3")), 3)
    pallas_mode = "compiled" if jax.default_backend() == "tpu" \
        else "interpret"

    def best_of(fn):
        fn()  # compile
        best = None
        for _ in range(runs):
            t0 = time.perf_counter()
            jax.block_until_ready(fn())
            el = time.perf_counter() - t0
            best = el if best is None or el < best else best
        return best

    # -- grouped aggregation ------------------------------------------------
    n = 1 << 17
    rng = np.random.default_rng(42)
    g = jnp.asarray(rng.integers(0, 1024, n).astype(np.int64))
    v = jnp.asarray(rng.integers(0, 1000, n).astype(np.int64))
    live = jnp.ones(n, bool)
    specs = [R.AggSpec("sum", 0), R.AggSpec("count_star", -1)]

    def gb(mode):
        def run():
            with R.kernel_scope(mode):
                return R.hash_groupby([(g, None)], [(v, None)], specs, live,
                                      2048)
        return run

    agg = {label: n / best_of(gb(mode))
           for mode, label in (("off", "reference"), ("pallas", "pallas"))}
    yield {"metric": "kernel_groupby_rows_per_sec_per_chip",
           "value": round(agg["pallas"], 1), "unit": "rows/s",
           "vs_baseline": round(agg["pallas"] / agg["reference"], 3),
           "reference_rows_per_sec": round(agg["reference"], 1),
           "pallas_mode": pallas_mode, "rows": n, "platform": platform}

    # -- hash join ----------------------------------------------------------
    nb, npr = 1 << 15, 1 << 17
    bk = jnp.asarray(rng.integers(0, 1 << 14, nb).astype(np.int64))
    pk = jnp.asarray(rng.integers(0, 1 << 14, npr).astype(np.int64))
    b_live = jnp.ones(nb, bool)
    p_live = jnp.ones(npr, bool)
    cap = 1 << 19

    def jn(mode):
        def run():
            with R.kernel_scope(mode):
                return R.hash_join_pairs([(bk, None)], [(pk, None)], b_live,
                                         p_live, cap)
        return run

    join = {label: npr / best_of(jn(mode))
            for mode, label in (("off", "reference"), ("pallas", "pallas"))}
    yield {"metric": "kernel_join_probe_rows_per_sec_per_chip",
           "value": round(join["pallas"], 1), "unit": "rows/s",
           "vs_baseline": round(join["pallas"] / join["reference"], 3),
           "reference_rows_per_sec": round(join["reference"], 1),
           "pallas_mode": pallas_mode, "build_rows": nb, "probe_rows": npr,
           "platform": platform}

    # -- persistent AOT compile cache: cold vs warm restart -----------------
    def fresh_process():
        with ops._JIT_CACHE_LOCK:
            ops._JIT_CACHE.clear()
        jax.clear_caches()
        ops.reset_compile_stats()

    d = os.path.join(tempfile.mkdtemp(prefix="gx_bench_cc_"), "db")
    q = "SELECT g, SUM(v), COUNT(*) FROM t GROUP BY g ORDER BY g"
    fresh_process()
    inst = Instance(data_dir=d)
    s = Session(inst)
    s.execute("CREATE DATABASE cc; USE cc")
    s.execute("CREATE TABLE t (g BIGINT, v BIGINT) "
              "PARTITION BY HASH(g) PARTITIONS 4")
    inst.store("cc", "t").insert_arrays(
        {"g": rng.integers(0, 64, 1 << 16).astype(np.int64),
         "v": rng.integers(0, 1000, 1 << 16).astype(np.int64)},
        inst.tso.next_timestamp())
    ops.reset_compile_stats()
    s.execute(q)
    cold_ms = ops.COMPILE_STATS["compile_ms"]
    cold_retraces = ops.COMPILE_STATS["retraces"]
    s.execute(q)  # steady: everything the next process should replay
    inst.save()
    s.close()

    fresh_process()
    inst2 = Instance(data_dir=d)
    s2 = Session(inst2)
    s2.execute("USE cc")
    s2.execute(q)
    warm_ms = ops.COMPILE_STATS["compile_ms"]
    hits = ops.COMPILE_STATS["cache_hits"]
    retr = ops.COMPILE_STATS["retraces"]
    s2.close()
    yield {"metric": "compile_cache_restart_compile_ms_speedup",
           "value": round(cold_ms / max(warm_ms, 1e-9), 1), "unit": "x",
           "cold_compile_ms": round(cold_ms, 1),
           "warm_compile_ms": round(warm_ms, 1),
           "cold_retraces": cold_retraces,
           "retraces_after_restart": retr,
           "cache_hits_after_restart": hits,
           "replay_fraction": round(hits / max(1, hits + retr), 3),
           "platform": platform}


def kernels_only_main():
    """`bench.py --kernels-only` (make bench-kernels): the kernel-tier
    microbench + the AOT compile-cache restart comparison (no TPC-H load)."""
    for out in kernels_bench(jax.devices()[0].platform):
        print(json.dumps(out))


def dml_only_main():
    """`bench.py --dml-only` (make bench-dml): the closed-loop DML + mixed
    read/write serving bench on a fresh instance (no TPC-H load needed —
    the driver builds its own write table)."""
    inst = Instance()
    s = Session(inst)
    for out in dml_serving_bench(inst, s, jax.devices()[0].platform):
        print(json.dumps(out))


def slo_bench(inst, s, data, platform):
    """SLO plane (PR 17): two numbers.  `slo_snapshot` reads the measured
    steady state BACK through the metric history — history-derived qps and
    the per-class recent p99 the burn-rate windows judge, plus every
    objective's state — proving the windows see what the bench measured.
    `slo_sampler_overhead` is the honest cost claim: closed-loop TP point
    serving with the history/SLO tick exercised around every pass vs
    hatched off entirely (sampling is off the query path by construction,
    so the target is <= 3% — noise, not a tax)."""
    okeys = data["orders"]["o_orderkey"]
    keys = [int(k) for k in okeys[:: max(1, len(okeys) // 2048)]]
    tpl = "select o_totalprice from orders where o_orderkey = %d"
    s.execute(tpl % keys[0])  # register + warm the PointPlan
    n_s = int(os.environ.get("BENCH_SLO_SESSIONS", "16"))
    per = int(os.environ.get("BENCH_SLO_PER_SESSION", "60"))
    reps = int(os.environ.get("BENCH_SLO_RUNS", "3"))
    _closed_loop_point(inst, tpl, keys, n_s, 4)  # ramp

    def best_pass(history_on):
        inst.config.set_instance("ENABLE_METRIC_HISTORY",
                                 1 if history_on else 0)
        best_qps, best_p99 = 0.0, 0.0
        for _ in range(reps):
            if history_on:
                inst.slo_tick(force=True)
            qps, p99, errs = _closed_loop_point(inst, tpl, keys, n_s, per)
            if history_on:
                inst.slo_tick(force=True)
            if errs:
                raise errs[0]
            if qps > best_qps:
                best_qps, best_p99 = qps, p99
        return best_qps, best_p99

    qps_on, p99_on = best_pass(True)
    qps_off, p99_off = best_pass(False)
    inst.config.set_instance("ENABLE_METRIC_HISTORY", 1)

    # pure sampler cost: a full registry+admission+summary snapshot, timed
    t0 = time.perf_counter()
    n_samp = 50
    for _ in range(n_samp):
        inst.metric_history.sample()
        inst.slo.evaluate()
    sample_ms = (time.perf_counter() - t0) * 1000.0 / n_samp

    mh = inst.metric_history
    snapshot = {
        "metric": "slo_snapshot", "platform": platform,
        "history_qps": round(mh.rate("queries_total"), 1),
        "recent_tp_p99_ms": round(
            mh.latest("stmt_class_tp_recent_p99_ms") or 0.0, 3),
        "error_rate_per_s": round(mh.rate("query_errors"), 6),
        "samples": int(mh.summary()["samples"]),
        "sample_plus_evaluate_ms": round(sample_ms, 3),
        "objectives": {r[0]: r[8] for r in inst.slo.rows()},
        "burning": inst.slo.burning_names(),
    }
    overhead_pct = round((qps_off - qps_on) / qps_off * 100.0, 2) \
        if qps_off > 0 else 0.0
    overhead = {
        "metric": "slo_sampler_overhead", "platform": platform,
        "sessions": n_s, "per_session": per, "runs": reps,
        "qps_on": round(qps_on, 1), "p99_on_ms": round(p99_on, 3),
        "qps_off": round(qps_off, 1), "p99_off_ms": round(p99_off, 3),
        "overhead_pct": overhead_pct, "target_pct": 3.0,
    }
    return [snapshot, overhead]


def slo_only_main():
    """`bench.py --slo-only` (make bench-slo): the SLO-plane snapshot +
    sampler-overhead bench on a small TPC-H load."""
    sf = float(os.environ.get("BENCH_SF", "0.1"))
    inst, s, data = load(sf)
    for out in slo_bench(inst, s, data, jax.devices()[0].platform):
        print(json.dumps(out))


def tracing_bench(inst, s, data, platform):
    """Always-on tail-sampled tracing (ISSUE 20): the honest overhead
    claim.  Closed-loop TP point serving on the 32-session batched-serving
    loop with always-on collection at the DEFAULT head-sample rate (every
    query builds its span skeleton + phase ramp timestamps; the sampler's
    per-query cost is one dict probe + one compare) vs ENABLE_QUERY_TRACING
    off entirely.  Target <= 3%: collection is host-side perf_counter reads
    only — no device syncs, no extra dispatches (asserted here, not
    assumed), steady-state retraces 0."""
    from galaxysql_tpu.exec import operators as _ops

    okeys = data["orders"]["o_orderkey"]
    keys = [int(k) for k in okeys[:: max(1, len(okeys) // 2048)]]
    tpl = "select o_totalprice from orders where o_orderkey = %d"
    s.execute(tpl % keys[0])  # register + warm the PointPlan
    n_s = int(os.environ.get("BENCH_TRACING_SESSIONS", "32"))
    per = int(os.environ.get("BENCH_TRACING_PER_SESSION", "60"))
    reps = int(os.environ.get("BENCH_TRACING_RUNS", "3"))
    _closed_loop_point(inst, tpl, keys, n_s, 4)  # ramp both code paths

    def best_pass(tracing_on):
        inst.config.set_instance("ENABLE_QUERY_TRACING",
                                 1 if tracing_on else 0)
        _closed_loop_point(inst, tpl, keys, n_s, 4)  # re-warm under config
        best_qps, best_p99 = 0.0, 0.0
        for _ in range(reps):
            qps, p99, errs = _closed_loop_point(inst, tpl, keys, n_s, per)
            if errs:
                raise errs[0]
            if qps > best_qps:
                best_qps, best_p99 = qps, p99
        return best_qps, best_p99

    # hot-path guard measured inline: dispatch counts per pass must be
    # IDENTICAL on vs off, and a warm loop compiles nothing new
    inst.config.set_instance("ENABLE_QUERY_TRACING", 1)
    _closed_loop_point(inst, tpl, keys, n_s, 4)
    _ops.reset_dispatch_stats()
    r0 = _ops.COMPILE_STATS["retraces"]
    _closed_loop_point(inst, tpl, keys, n_s, 8)
    d_on = _ops.DISPATCH_STATS["dispatches"]
    retraces_on = _ops.COMPILE_STATS["retraces"] - r0
    inst.config.set_instance("ENABLE_QUERY_TRACING", 0)
    _closed_loop_point(inst, tpl, keys, n_s, 4)
    _ops.reset_dispatch_stats()
    _closed_loop_point(inst, tpl, keys, n_s, 8)
    d_off = _ops.DISPATCH_STATS["dispatches"]

    qps_on, p99_on = best_pass(True)
    qps_off, p99_off = best_pass(False)
    inst.config.set_instance("ENABLE_QUERY_TRACING", 1)
    overhead_pct = round((qps_off - qps_on) / qps_off * 100.0, 2) \
        if qps_off > 0 else 0.0
    st = inst.trace_store.stats()
    return [{
        "metric": "tracing_always_on_overhead", "platform": platform,
        "sessions": n_s, "per_session": per, "runs": reps,
        "qps_on": round(qps_on, 1), "p99_on_ms": round(p99_on, 3),
        "qps_off": round(qps_off, 1), "p99_off_ms": round(p99_off, 3),
        "overhead_pct": overhead_pct, "target_pct": 3.0,
        "dispatches_on": d_on, "dispatches_off": d_off,
        "dispatches_equal": d_on == d_off,
        "retraces_steady": retraces_on,
        "sample_rate": st["rate"],
        "store_count": st["count"], "store_bytes": st["bytes"],
        "store_budget": st["budget"],
    }]


def tracing_only_main():
    """`bench.py --tracing-only` (make bench-tracing): the always-on
    tracing overhead proof on a small TPC-H load; commits BENCH_r14.json."""
    sf = float(os.environ.get("BENCH_SF", "0.1"))
    inst, s, data = load(sf)
    results = list(tracing_bench(inst, s, data, jax.devices()[0].platform))
    for out in results:
        print(json.dumps(out))
    envelope = {"n": 14, "cmd": "python bench.py --tracing-only", "rc": 0,
                "tail": json.dumps(results[-1]), "parsed": results}
    path = os.path.join(os.path.dirname(os.path.abspath(__file__)),
                        "BENCH_r14.json")
    with open(path, "w") as f:
        json.dump(envelope, f, indent=1)
        f.write("\n")


def htap_bench(platform):
    """`bench.py --htap-only` (make bench-htap): the columnar HTAP replica
    (PR 18) measured as its actual claim — scan-heavy AP queries on the
    CDC-fed columnar tier vs the SAME queries on the row store, BOTH under
    one sustained DML stream mutating lineitem (the row store re-derives
    visibility + lane concat per version bump; the replica serves immutable
    pre-encoded stripes at its watermark).  Then the stream stops, the
    tailer drains, and a quiesced phase asserts bit-identical results at
    the drained watermark plus zero steady-state retraces.  The freshness
    lag of every replica is sampled throughout — the SLA the router
    enforces must stay bounded while the writer hammers."""
    import threading

    from galaxysql_tpu.exec import operators as _ops

    sf = float(os.environ.get("BENCH_HTAP_SF",
                              os.environ.get("BENCH_SF", "0.2")))
    runs = int(os.environ.get("BENCH_RUNS", "3"))
    inst, s, data = load(sf)
    n_rows = len(data["lineitem"]["l_orderkey"])
    inst.config.set_instance("ENABLE_COLUMNAR_REPLICA", 1)
    inst.config.set_instance("COLUMNAR_POLL_MS", 20)
    inst.config.set_instance("COLUMNAR_WATERMARK_LAG_MS", 20)
    # cluster the fact table on ship date: Q6/Q3's date sargs then prune
    # whole stripes via the zone maps instead of filtering every row
    inst.config.set_instance("COLUMNAR_CLUSTER_BY", "lineitem:l_shipdate")
    mgr = inst.columnar
    seed_t0 = time.perf_counter()
    for t in tpch.TABLE_ORDER:
        mgr.ensure_ready("tpch", t, timeout_s=300.0)
    seed_wall = time.perf_counter() - seed_t0

    qids = [int(x) for x in
            os.environ.get("BENCH_HTAP_QUERIES", "1,6,3,5").split(",") if x]
    on_q = {q: "/*+TDDL:COLUMNAR(ON)*/ " + QUERIES[q] for q in qids}
    off_q = {q: "/*+TDDL:COLUMNAR(OFF)*/ " + QUERIES[q] for q in qids}
    # dedicated reader session: it never writes, so the read-your-writes
    # fence stays open and routing is decided purely by the watermark
    sr = Session(inst, schema="tpch")
    for q in qids:  # compile warmup for both paths, outside any timing
        sr.execute(off_q[q])
        routed0 = mgr.routed.value
        sr.execute(on_q[q])
        if mgr.routed.value == routed0:
            raise RuntimeError(f"COLUMNAR(ON) Q{q} did not route to the "
                               "replica — bench preconditions broken")

    # -- sustained DML stream + freshness-lag sampler -------------------------
    okeys = data["orders"]["o_orderkey"]
    wkeys = [int(k) for k in okeys[:: max(1, len(okeys) // 2048)]]
    upd = ("UPDATE lineitem SET l_suppkey = l_suppkey + 1 "
           "WHERE l_orderkey = %d")
    # prime the delete path: the first delete event the tailer sees builds
    # the pk map (one-time, proportional to table size); pay it here so the
    # measured lag window reflects steady-state tailing, not the build
    sp = Session(inst, schema="tpch")
    sp.execute(upd % wkeys[0])
    sp.close()
    ts_p = inst.tso.next_timestamp()
    deadline = time.time() + 120.0
    while any(rep.watermark < ts_p for rep in mgr.replicas.values()):
        mgr.tail_once()
        if time.time() > deadline:
            raise RuntimeError("pk-prime drain did not complete")
        time.sleep(0.02)
    stop = threading.Event()
    dml_n = [0]
    lags: list = []

    def writer():
        sw = Session(inst, schema="tpch")
        i = 0
        while not stop.is_set():
            sw.execute(upd % wkeys[i % len(wkeys)])
            dml_n[0] += 1
            i += 1
        sw.close()

    def sampler():
        while not stop.is_set():
            cur = max((rep.lag_ms() for rep in mgr.replicas.values()),
                      default=0.0)
            if cur >= 0:
                lags.append(cur)
            time.sleep(0.05)

    threads = [threading.Thread(target=writer, daemon=True),
               threading.Thread(target=sampler, daemon=True)]
    dml_t0 = time.perf_counter()
    for t in threads:
        t.start()
    time.sleep(1.0)  # stream + tailer established before the timed passes

    results = []
    timings = {}
    for q in qids:
        off_best = min(_timed_exec(sr, off_q[q]) for _ in range(runs))
        routed0 = mgr.routed.value
        on_best = min(_timed_exec(sr, on_q[q]) for _ in range(runs))
        timings[q] = (on_best, off_best, mgr.routed.value - routed0)

    stop.set()
    for t in threads:
        t.join(timeout=60)
    dml_wall = time.perf_counter() - dml_t0

    # -- quiesce: drain the tailer past the last write, then assert identity --
    ts_q = inst.tso.next_timestamp()
    deadline = time.time() + 120.0
    while any(rep.watermark < ts_q for rep in mgr.replicas.values()):
        mgr.tail_once()
        if time.time() > deadline:
            raise RuntimeError("tailer failed to drain past the DML stream")
        time.sleep(0.02)
    equal = {}
    for q in qids:
        on_rows = sr.execute(on_q[q]).rows
        off_rows = sr.execute(off_q[q]).rows
        equal[q] = on_rows == off_rows
        if not equal[q]:
            raise RuntimeError(f"quiesced Q{q}: columnar result diverged "
                               "from the row store")
    for q in qids:  # steady-state warmup at the drained watermark
        sr.execute(on_q[q])
    _ops.reset_compile_stats()
    for q in qids:
        sr.execute(on_q[q])
    retraces = _ops.COMPILE_STATS["retraces"]

    lags.sort()
    for q in qids:
        on_best, off_best, routed = timings[q]
        results.append({
            "metric": f"htap_q{q}_sf{sf:g}_columnar_rows_per_sec_per_chip",
            "value": round(n_rows / on_best, 1), "unit": "rows/s",
            "vs_baseline": round(off_best / on_best, 3),
            "row_store_rows_per_sec": round(n_rows / off_best, 1),
            "routed_executions": routed,
            "quiesced_equal": equal[q],
            "platform": platform,
        })
    results.append({
        "metric": f"htap_freshness_lag_sf{sf:g}",
        "value": round(lags[len(lags) // 2], 1) if lags else -1.0,
        "unit": "ms",
        "vs_baseline": round(
            (lags[-1] if lags else 0.0) /
            float(inst.config.get("COLUMNAR_MAX_LAG_MS") or 10_000), 3),
        "lag_p95_ms": round(lags[int(len(lags) * 0.95)], 1) if lags else -1.0,
        "lag_max_ms": round(lags[-1], 1) if lags else -1.0,
        "lag_samples": len(lags),
        "dml_statements": dml_n[0],
        "dml_statements_per_sec": round(dml_n[0] / dml_wall, 1),
        "seed_wall_s": round(seed_wall, 2),
        "retraces_steady": retraces,
        "platform": platform,
    })
    sr.close()
    return results


def _timed_exec(s, q):
    t0 = time.perf_counter()
    s.execute(q)
    return time.perf_counter() - t0


def htap_only_main():
    """`bench.py --htap-only` (make bench-htap): run the columnar-vs-row
    HTAP bench and commit it to BENCH_r13.json."""
    results = htap_bench(jax.devices()[0].platform)
    for out in results:
        print(json.dumps(out), flush=True)
    envelope = {"n": 13, "cmd": "python bench.py --htap-only", "rc": 0,
                "tail": json.dumps(results[-1]), "parsed": results}
    path = os.path.join(os.path.dirname(os.path.abspath(__file__)),
                        "BENCH_r13.json")
    with open(path, "w") as f:
        json.dump(envelope, f, indent=1)
        f.write("\n")


def _spawn_coordinator(data_dir):
    """One coordinator subprocess over the shared metadb; returns
    (popen, mysql_port, sync_port) after the SERVER_READY handshake."""
    import subprocess
    env = dict(os.environ, JAX_PLATFORMS="cpu")
    p = subprocess.Popen(
        [sys.executable, "-m", "galaxysql_tpu.net.server", "--port", "0",
         "--sync-port", "0", "--data-dir", data_dir, "--platform", "cpu",
         "--announce"],
        stdout=subprocess.PIPE, stderr=subprocess.DEVNULL, env=env,
        text=True)
    line = p.stdout.readline()
    if not line.startswith("SERVER_READY"):
        p.kill()
        raise RuntimeError(f"coordinator failed to boot: {line!r}")
    _, mysql_port, sync_port = line.split()
    return p, int(mysql_port), int(sync_port)


def _scaleout_level(data_dir, n_coord, n_tables, sessions_per_peer,
                    per_session, ramp_ops):
    """One point on the curve: N coordinator subprocesses behind a front
    router, closed-loop point SELECTs spread by digest affinity."""
    import threading

    from galaxysql_tpu.server.instance import Instance
    from galaxysql_tpu.server.router import FrontRouter, RouterSession

    procs = [_spawn_coordinator(data_dir) for _ in range(n_coord)]
    hub = Instance(boot=False)  # front-of-tier process: routes, never serves
    router = FrontRouter(hub)
    router.local.down_until = float("inf")  # hub serves nothing itself
    try:
        for _p, mysql_port, sync_port in procs:
            router.add_remote("127.0.0.1", mysql_port, sync_port)

        # session -> table assignment BALANCED per peer: each peer serves
        # `sessions_per_peer` sessions over the tables the ring hands it,
        # so the curve measures tier capacity, not sha1 luck
        shapes = [f"select v from pt{t} where k = %d"
                  for t in range(n_tables)]
        by_peer = {}
        for t, tpl in enumerate(shapes):
            peer = router.targets_for(
                _scaleout_digest(tpl, "sb"), tpl % 1, "sb")[0]
            by_peer.setdefault(peer.node_id, []).append(tpl)
        plans = []  # one template per session
        for node_id, tpls in by_peer.items():
            for i in range(sessions_per_peer):
                plans.append(tpls[i % len(tpls)])
        uncovered = n_coord - len(by_peer)

        lat_lock = threading.Lock()
        lats, errors_seen = [], []

        def run(idx, tpl, n_ops, record):
            sess = RouterSession(router, schema="sb")
            try:
                for j in range(n_ops):
                    t0 = time.perf_counter()
                    sess.execute(tpl % (1 + (idx * 7 + j) % 64))
                    dt_ms = (time.perf_counter() - t0) * 1000.0
                    if record:
                        with lat_lock:
                            lats.append(dt_ms)
            except Exception as e:  # surfaced, never swallowed
                errors_seen.append(e)
            finally:
                sess.close()

        def pass_over(n_ops, record):
            ts = [threading.Thread(target=run, args=(i, tpl, n_ops, record))
                  for i, tpl in enumerate(plans)]
            t0 = time.perf_counter()
            for t in ts:
                t.start()
            for t in ts:
                t.join()
            return time.perf_counter() - t0

        pass_over(ramp_ops, record=False)  # warm plan caches + compiles
        routed0, hits0 = router.m_routed.value, router.m_hits.value
        retr0 = {p.node_id: p.sync_action("health", {}).get("retraces", 0)
                 for p in router.peers.values() if p is not router.local}
        wall = pass_over(per_session, record=True)
        if errors_seen:
            raise errors_seen[0]
        retr1 = {p.node_id: p.sync_action("health", {}).get("retraces", 0)
                 for p in router.peers.values() if p is not router.local}
        router.gossip_tick()
        routed = router.m_routed.value - routed0
        hits = router.m_hits.value - hits0
        lats.sort()
        return {
            "coordinators": n_coord,
            "sessions": len(plans),
            "qps": round(len(lats) / wall, 1),
            "p99_ms": round(lats[int(len(lats) * 0.99) - 1], 3),
            "p50_ms": round(lats[len(lats) // 2], 3),
            "affinity_hit_rate": round(hits / routed, 4) if routed else 1.0,
            "gossip_staleness_ms": round(router.staleness_ms(), 1),
            "steady_retraces": sum(retr1[n] - retr0[n] for n in retr1),
            "uncovered_peers": uncovered,
        }
    finally:
        router.close()
        for p, _, _ in procs:
            p.kill()
        for p, _, _ in procs:
            p.wait()


def _scaleout_digest(tpl, schema):
    from galaxysql_tpu.meta.statement_summary import digest_key
    from galaxysql_tpu.sql.parameterize import parameterize
    return digest_key(schema, parameterize(tpl % 1).cache_key)


def scaleout_bench():
    """`bench.py --scaleout-only` (make bench-scaleout): the serving-tier
    curve.  1/2/4 coordinator subprocesses over ONE shared metadb file,
    closed-loop point SELECTs through the front router with digest
    affinity; offered load scales with the tier (sessions-per-peer fixed).

    The workload is window-paced: a fixed BATCH_WINDOW_US pins the PR 6
    batch collection window, so each coordinator's ceiling is its batch
    cadence x in-flight sessions — a genuine per-process serialization
    point that scale-out removes.  (On this container `os.cpu_count()`
    cores; a CPU-saturated curve cannot show process scaling on one core,
    so the regime and core count ride the JSON for honesty.)"""
    import tempfile

    from galaxysql_tpu.server.instance import Instance
    from galaxysql_tpu.server.session import Session

    n_tables = int(os.environ.get("BENCH_SCALEOUT_TABLES", "16"))
    spp = int(os.environ.get("BENCH_SCALEOUT_SESSIONS_PER_PEER", "8"))
    per = int(os.environ.get("BENCH_SCALEOUT_PER_SESSION", "40"))
    ramp = int(os.environ.get("BENCH_SCALEOUT_RAMP", "6"))
    window_us = int(os.environ.get("BENCH_SCALEOUT_WINDOW_US", "60000"))
    levels = [int(x) for x in
              os.environ.get("BENCH_SCALEOUT_LEVELS", "1,2,4").split(",")]

    data_dir = tempfile.mkdtemp(prefix="scaleout_")
    seed = Instance(data_dir=data_dir)
    s = Session(seed)
    s.execute("CREATE DATABASE sb")
    s.execute("USE sb")
    for t in range(n_tables):
        s.execute(f"CREATE TABLE pt{t} (k BIGINT PRIMARY KEY, v BIGINT)")
        rows = ",".join(f"({k}, {k * 10})" for k in range(1, 65))
        s.execute(f"INSERT INTO pt{t} VALUES {rows}")
    # fixed batch window: the per-coordinator pacing the curve scales out
    # (persisted in the shared metadb -> every peer boots with it)
    s.execute(f"SET GLOBAL BATCH_WINDOW_US = {window_us}")
    seed.save()
    s.close()

    results = []
    for n in levels:
        out = _scaleout_level(data_dir, n, n_tables, spp, per, ramp)
        out.update({"metric": "scaleout_point_qps", "platform": "cpu",
                    "batch_window_us": window_us,
                    "cores": os.cpu_count()})
        if results:
            out["vs_baseline"] = round(out["qps"] / results[0]["qps"], 2)
            out["p99_vs_baseline"] = round(
                out["p99_ms"] / results[0]["p99_ms"], 2)
        results.append(out)
        print(json.dumps(out), flush=True)
    return results


def scaleout_only_main():
    """`bench.py --scaleout-only` (make bench-scaleout): run the serving
    tier curve and commit it to BENCH_r12.json."""
    results = scaleout_bench()
    envelope = {"n": 12, "cmd": "python bench.py --scaleout-only", "rc": 0,
                "tail": json.dumps(results[-1]), "parsed": results}
    path = os.path.join(os.path.dirname(os.path.abspath(__file__)),
                        "BENCH_r12.json")
    with open(path, "w") as f:
        json.dump(envelope, f, indent=1)
        f.write("\n")


if __name__ == "__main__":
    if "--batch-only" in sys.argv:
        batch_only_main()
    elif "--dml-only" in sys.argv:
        dml_only_main()
    elif "--skew-only" in sys.argv:
        skew_only_main()
    elif "--overload-only" in sys.argv:
        overload_only_main()
    elif "--rebalance-only" in sys.argv:
        rebalance_only_main()
    elif "--kernels-only" in sys.argv:
        kernels_only_main()
    elif "--slo-only" in sys.argv:
        slo_only_main()
    elif "--tracing-only" in sys.argv:
        tracing_only_main()
    elif "--scaleout-only" in sys.argv:
        scaleout_only_main()
    elif "--htap-only" in sys.argv:
        htap_only_main()
    else:
        main()
